#!/bin/sh
# Regenerate tempo_tpu/tempopb from protos/. Run from repo root.
set -e
protoc -I protos --python_out=tempo_tpu/tempopb protos/trace.proto protos/tempo.proto protos/remote_write.proto protos/opencensus.proto
# protoc emits a flat sibling import; rewrite to package-relative so the
# generated module never collides with a foreign top-level trace_pb2.
sed -i 's/^import trace_pb2 as trace__pb2$/from . import trace_pb2 as trace__pb2/' \
    tempo_tpu/tempopb/tempo_pb2.py
