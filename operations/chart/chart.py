#!/usr/bin/env python3
"""tempo-tpu chart: values-driven renderer for operations/kube.

Role-equivalent to the reference's helm chart + jsonnet library
(/root/reference/operations/helm/, /root/reference/operations/jsonnet/
rendering its kube-manifests/): a single values surface (values.yaml)
that deterministically generates the full manifest set, so the
checked-in operations/kube/ is provably a render of this chart, not
hand-drifted YAML. Pure python + pyyaml — no helm/jsonnet binary in the
loop, and the render-diff test (tests/test_operations.py) keeps chart
and manifests in lockstep.

Usage:
  python operations/chart/chart.py                      # render to stdout paths
  python operations/chart/chart.py --out operations/kube
  python operations/chart/chart.py --values prod.yaml --out ./rendered
  python operations/chart/chart.py --check              # diff vs --out, exit 1 on drift
"""

from __future__ import annotations

import argparse
import os
import sys

import yaml

CHART_DIR = os.path.dirname(os.path.abspath(__file__))


def deep_merge(base: dict, over: dict) -> dict:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def load_values(path: str | None = None) -> dict:
    with open(os.path.join(CHART_DIR, "values.yaml")) as f:
        vals = yaml.safe_load(f)
    if path:
        with open(path) as f:
            vals = deep_merge(vals, yaml.safe_load(f) or {})
    return vals


# ---------------------------------------------------------------------------
# building blocks


def _labels(v, component: str) -> str:
    return ("{app.kubernetes.io/part-of: %s, app.kubernetes.io/component: %s}"
            % (v["name_prefix"], component))


def _container(v, component: str, extra_ports=(), extra="", grpc=True) -> str:
    p = v["ports"]
    ports = [f'- {{containerPort: {p["http"]}, name: http}}']
    if grpc:
        ports.append(f'- {{containerPort: {p["grpc"]}, name: grpc}}')
    ports.append(f'- {{containerPort: {p["gossip"]}, name: gossip}}')
    ports += list(extra_ports)
    ports_yaml = "\n            ".join(ports)
    return f"""        - name: {component}
          image: {v["image"]}
          args: ["-config.file=/etc/tempo/tempo.yaml", "-target={component}"]
{extra}          ports:
            {ports_yaml}
          readinessProbe:
            httpGet: {{path: /ready, port: http}}
          volumeMounts:
            - {{name: config, mountPath: /etc/tempo}}"""


def _deployment(v, component: str, replicas: int, *, comment: str = "",
                grpc: bool = True, container_extra: str = "") -> str:
    """Stateless-Deployment skeleton. The ingester (StatefulSet + PVC +
    preStop drain) and querier (TPU nodeSelector + device resources)
    keep hand-rolled templates below on purpose: their shapes diverge
    enough that threading them through here would mean more hook
    parameters than shared lines."""
    name = f'{v["name_prefix"]}-{component}'
    return f"""{comment}apiVersion: apps/v1
kind: Deployment
metadata:
  name: {name}
  namespace: {v["namespace"]}
  labels: {_labels(v, component)}
spec:
  replicas: {replicas}
  selector:
    matchLabels: {{app.kubernetes.io/component: {component}}}
  template:
    metadata:
      labels: {_labels(v, component)}
    spec:
      containers:
{_container(v, component, extra="", grpc=grpc) if not container_extra else container_extra}
      volumes:
        - name: config
          configMap: {{name: {v["name_prefix"]}-config}}"""


# ---------------------------------------------------------------------------
# manifests


def configmap(v) -> str:
    st = v["storage"]
    # render only the ACTIVE backend's section: a local/gcs/azure values
    # overlay must not ship dead s3 placeholders into the ConfigMap
    if st["backend"] == "s3":
        s3 = st["s3"]
        backend_yaml = f"""      s3:
        endpoint: {s3["endpoint"]}
        bucket: {s3["bucket"]}
        region: {s3["region"]}
        access_key: {s3["access_key"]}
        secret_key: {s3["secret_key"]}"""
    elif st["backend"] == "local":
        local = st.get("local") or {}  # bare `local:` key = defaults
        backend_yaml = f"""      local:
        path: {local.get("path", "/var/tempo/blocks")}"""
    else:
        # dump the whole section as one YAML mapping: null/lists/nested
        # maps/multi-line credentials all render as valid YAML (a
        # hand-rolled per-value f-string cannot — str(None) is the
        # string "None", and newline-bearing scalars need block quoting)
        import textwrap

        section = dict(st.get(st["backend"]) or {})
        if section:
            body = textwrap.indent(
                yaml.safe_dump(section, default_flow_style=False,
                               sort_keys=False), "        ").rstrip()
            backend_yaml = f"      {st['backend']}:\n{body}"
        else:
            backend_yaml = ""
    cache_addrs = ", ".join(f'"{a}"' for a in v["cache"]["addresses"])
    return f"""apiVersion: v1
kind: ConfigMap
metadata:
  name: {v["name_prefix"]}-config
  namespace: {v["namespace"]}
data:
  tempo.yaml: |
    server:
      http_port: {v["ports"]["http"]}
      grpc_port: {v["ports"]["grpc"]}
    multitenancy_enabled: {str(v["multitenancy"]).lower()}
    storage:
      backend: {v["storage"]["backend"]}
{backend_yaml}
      wal_dir: {v["storage"]["wal_dir"]}
      block_encoding: {v["storage"]["block_encoding"]}
      search_encoding: {v["storage"]["search_encoding"]}
      blocklist_poll_s: {v["storage"]["blocklist_poll_s"]}
      cache:
        cache: {v["cache"]["cache"]}
        addresses: [{cache_addrs}]
    ingester:
      replication_factor: {v["ingester"]["replication_factor"]}
      write_quorum: {v["ingester"]["write_quorum"]}
    compactor:
      window_s: {v["compactor"]["window_s"]}
      max_inputs: {v["compactor"]["max_inputs"]}
    retention:
      block_s: {v["retention"]["block_s"]}
      compacted_s: {v["retention"]["compacted_s"]}
    memberlist:
      bind: "0.0.0.0:{v["ports"]["gossip"]}"
      join:
        - "dnssrv+_gossip._tcp.{v["name_prefix"]}-gossip.{v["namespace"]}.svc.cluster.local"
    distributor:
      receivers: {{}}
    overrides:
      defaults:
        ingestion_rate_bytes: {v["overrides"]["ingestion_rate_bytes"]}
        max_live_traces: {v["overrides"]["max_live_traces"]}
"""


def gossip_service(v) -> str:
    return f"""# Headless service publishing SRV records for gossip seed discovery —
# consumed by the dnssrv+ join spec in the ConfigMap (utils/dns.py).
apiVersion: v1
kind: Service
metadata:
  name: {v["name_prefix"]}-gossip
  namespace: {v["namespace"]}
spec:
  clusterIP: None
  publishNotReadyAddresses: true
  ports:
    - name: gossip
      port: {v["ports"]["gossip"]}
      targetPort: {v["ports"]["gossip"]}
  selector:
    app.kubernetes.io/part-of: {v["name_prefix"]}
"""


def frontend_service(v) -> str:
    p = v["ports"]
    return f"""apiVersion: v1
kind: Service
metadata:
  name: {v["name_prefix"]}-query-frontend
  namespace: {v["namespace"]}
spec:
  ports:
    - name: http
      port: {p["http"]}
      targetPort: {p["http"]}
  selector:
    app.kubernetes.io/component: query-frontend
---
apiVersion: v1
kind: Service
metadata:
  name: {v["name_prefix"]}-distributor
  namespace: {v["namespace"]}
spec:
  ports:
    - name: otlp-grpc
      port: {p["otlp_grpc"]}
      targetPort: {p["grpc"]}
    - name: http
      port: {p["http"]}
      targetPort: {p["http"]}
  selector:
    app.kubernetes.io/component: distributor
"""


def workloads(v) -> str:
    r = v["replicas"]
    distributor = _deployment(
        v, "distributor", r["distributor"],
        comment=("# Stateless workloads. IMAGE must contain this repo; "
                 "entrypoint runs the\n# CLI with the per-target flag "
                 "(cli/main.py -target, reference\n# cmd/tempo -target "
                 "microservice split).\n"),
        container_extra=_container(
            v, "distributor", grpc=True,
            extra=("          # OTLP/gRPC ingest is served on the main "
                   "gRPC port; the\n          # distributor Service maps "
                   f"the conventional {v['ports']['otlp_grpc']} onto it\n")))
    frontend = _deployment(
        v, "query-frontend", r["query_frontend"],
        comment=("# The query-frontend serves gRPC too: queriers dial it "
                 "and PULL jobs over\n# the Frontend/Process stream "
                 "(modules/worker.py).\n"),
        grpc=True)
    compactor = _deployment(v, "compactor", r["compactor"], grpc=False)
    generator = _deployment(
        v, "metrics-generator", r["metrics_generator"],
        comment=("# Standalone metrics-generator: the distributor ships "
                 "span batches to it\n# over the MetricsGenerator/"
                 "PushSpans gRPC service, routed per trace over\n# the "
                 "generator ring (service-graph pairing is instance-"
                 "local).\n"),
        grpc=True)
    # compactor has no readiness dependency on peers; keep probe anyway
    return "\n---\n".join([distributor, frontend, compactor,
                           generator]) + "\n"


def ingester(v) -> str:
    ing = v["ingester"]
    return f"""# Ingesters keep WAL state — StatefulSet with a PVC per replica so crash
# replay (wal/replay_all) finds its files after reschedule.
apiVersion: apps/v1
kind: StatefulSet
metadata:
  name: {v["name_prefix"]}-ingester
  namespace: {v["namespace"]}
  labels: {_labels(v, "ingester")}
spec:
  serviceName: {v["name_prefix"]}-gossip
  replicas: {v["replicas"]["ingester"]}
  selector:
    matchLabels: {{app.kubernetes.io/component: ingester}}
  template:
    metadata:
      labels: {_labels(v, "ingester")}
    spec:
      terminationGracePeriodSeconds: {ing["termination_grace_s"]}  # /shutdown flushes all blocks
      containers:
        - name: ingester
          image: {v["image"]}
          args: ["-config.file=/etc/tempo/tempo.yaml", "-target=ingester"]
          ports:
            - {{containerPort: {v["ports"]["http"]}, name: http}}
            - {{containerPort: {v["ports"]["grpc"]}, name: grpc}}
            - {{containerPort: {v["ports"]["gossip"]}, name: gossip}}
          readinessProbe:
            httpGet: {{path: /ready, port: http}}
          lifecycle:
            preStop:
              httpGet: {{path: /shutdown, port: http}}
          volumeMounts:
            - {{name: config, mountPath: /etc/tempo}}
            - {{name: wal, mountPath: {v["storage"]["wal_dir"]}}}
      volumes:
        - name: config
          configMap: {{name: {v["name_prefix"]}-config}}
  volumeClaimTemplates:
    - metadata:
        name: wal
      spec:
        accessModes: ["ReadWriteOnce"]
        resources:
          requests:
            storage: {ing["wal_storage"]}
"""


def querier(v) -> str:
    tpu = v["querier"]["tpu"]
    sched = ""
    resources = ""
    if tpu.get("enabled"):
        sched = f"""      nodeSelector:
        cloud.google.com/gke-tpu-accelerator: {tpu["accelerator"]}
        cloud.google.com/gke-tpu-topology: {tpu["topology"]}
"""
        resources = f"""          resources:
            limits:
              google.com/tpu: "{tpu["chips"]}"
"""
    p = v["ports"]
    return f"""# Queriers run the TPU scan engine — schedule onto TPU node pools.
apiVersion: apps/v1
kind: Deployment
metadata:
  name: {v["name_prefix"]}-querier
  namespace: {v["namespace"]}
  labels: {_labels(v, "querier")}
spec:
  replicas: {v["replicas"]["querier"]}
  selector:
    matchLabels: {{app.kubernetes.io/component: querier}}
  template:
    metadata:
      labels: {_labels(v, "querier")}
    spec:
{sched}      containers:
        - name: querier
          image: {v["image"]}
          args: ["-config.file=/etc/tempo/tempo.yaml", "-target=querier"]
{resources}          ports:
            - {{containerPort: {p["http"]}, name: http}}
            - {{containerPort: {p["grpc"]}, name: grpc}}
            - {{containerPort: {p["gossip"]}, name: gossip}}
          readinessProbe:
            httpGet: {{path: /ready, port: http}}
          volumeMounts:
            - {{name: config, mountPath: /etc/tempo}}
      volumes:
        - name: config
          configMap: {{name: {v["name_prefix"]}-config}}
"""


def render_all(values: dict) -> dict[str, str]:
    """filename → content; the chart's full output set."""
    return {
        "configmap.yaml": configmap(values),
        "gossip-service.yaml": gossip_service(values),
        "frontend-service.yaml": frontend_service(values),
        "workloads.yaml": workloads(values),
        "ingester.yaml": ingester(values),
        "querier.yaml": querier(values),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--values", help="values overlay (deep-merged)")
    ap.add_argument("--out", default=os.path.join(CHART_DIR, "..", "kube"))
    ap.add_argument("--check", action="store_true",
                    help="diff rendered output against --out; exit 1 on drift")
    args = ap.parse_args(argv)

    rendered = render_all(load_values(args.values))
    out = os.path.abspath(args.out)
    if args.check:
        drift = []
        for name, content in rendered.items():
            path = os.path.join(out, name)
            on_disk = open(path).read() if os.path.exists(path) else None
            if on_disk != content:
                drift.append(name)
        # hand-written manifests OUTSIDE the chart's output set are
        # drift too — same contract the render-diff test enforces
        if os.path.isdir(out):
            drift.extend(sorted(
                f for f in os.listdir(out)
                if f.endswith((".yaml", ".yml")) and f not in rendered))
        if drift:
            print(f"DRIFT: {', '.join(drift)} — re-render with "
                  f"`python operations/chart/chart.py --out {args.out}`")
            return 1
        print(f"ok: {len(rendered)} manifests match {out}")
        return 0
    os.makedirs(out, exist_ok=True)
    for name, content in rendered.items():
        with open(os.path.join(out, name), "w") as f:
            f.write(content)
        print(os.path.join(out, name))
    return 0


if __name__ == "__main__":
    sys.exit(main())
