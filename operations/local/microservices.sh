#!/bin/sh
# Local microservices playground — role-equivalent to the reference's
# integration/microservices docker-compose: one process per target,
# gossiping over loopback, shared local-disk object storage.
#
#   sh operations/local/microservices.sh /tmp/tempo-playground
#
# Then: curl -X POST localhost:3200/v1/traces ... ; curl "localhost:3203/api/search?..."
set -e
ROOT=${1:-/tmp/tempo-tpu-playground}
REPO=$(cd "$(dirname "$0")/../.." && pwd)
mkdir -p "$ROOT"

# each process needs its own gossip bind; all join the first three seeds
mkconfig() { # target gossip_port
  cat > "$ROOT/$1.yaml" <<EOF
server:
  http_port: 0
  grpc_port: 0
storage:
  backend: local
  local: {path: $ROOT/blocks}
  wal_dir: $ROOT/wal-$1
ingester:
  replication_factor: 1
memberlist:
  bind: "127.0.0.1:$2"
  join: ["127.0.0.1:7946", "127.0.0.1:7947", "127.0.0.1:7948"]
EOF
}

run() { # target http grpc gossip
  mkconfig "$1" "$4"
  PYTHONPATH="$REPO:$PYTHONPATH" python -m tempo_tpu.cli.main \
    -config.file "$ROOT/$1.yaml" -target "$1" \
    -http-port "$2" -grpc-port "$3" -instance-id "$1-local" \
    > "$ROOT/$1.log" 2>&1 &
  echo "$1 pid $! (http :$2, gossip :$4, logs $ROOT/$1.log)"
}

run distributor 3200 9095 7946
run ingester 3201 9096 7947
run querier 3202 9097 7948
run query-frontend 3203 9098 7949
run compactor 3204 9099 7950
run metrics-generator 3205 9100 7951
echo "frontend API: http://127.0.0.1:3203  (OTLP gRPC ingest: 127.0.0.1:9095)"
echo "stop: pkill -f tempo_tpu.cli.main"
wait
