// Native host runtime for tempo-tpu: block codecs + hashing.
//
// Wraps the system libzstd / liblz4 / libsnappy — the role the reference
// fills with vendored Go asm codec libraries (SURVEY.md §7 native mapping).
// Exposed as a C ABI consumed via ctypes (tempo_tpu/ops/native.py).
// All functions return the produced byte count, or a negative error code.

#ifndef _GNU_SOURCE
#define _GNU_SOURCE  // memmem
#endif
#include <cstddef>
#include <cstdint>
#include <cstring>

#include <zstd.h>
#include <zstd_errors.h>

// liblz4 / libsnappy ship no dev headers in this image; declare the stable
// C ABIs directly and link against the versioned runtime libraries.
extern "C" {
int LZ4_compress_default(const char* src, char* dst, int srcSize, int dstCapacity);
int LZ4_decompress_safe(const char* src, char* dst, int compressedSize, int dstCapacity);

typedef enum {
  SNAPPY_OK = 0,
  SNAPPY_INVALID_INPUT = 1,
  SNAPPY_BUFFER_TOO_SMALL = 2,
} snappy_status;
snappy_status snappy_compress(const char* input, size_t input_length,
                              char* compressed, size_t* compressed_length);
snappy_status snappy_uncompress(const char* compressed, size_t compressed_length,
                                char* uncompressed, size_t* uncompressed_length);
}

extern "C" {

long long tt_zstd_compress(const char* src, size_t src_len,
                           char* dst, size_t dst_cap, int level) {
  size_t n = ZSTD_compress(dst, dst_cap, src, src_len, level);
  if (ZSTD_isError(n)) return -1;
  return (long long)n;
}

long long tt_zstd_content_size(const char* src, size_t src_len) {
  // exact decompressed size from the frame header, so the caller can
  // allocate once instead of a 32x guess (a 1 MB page was paying a
  // 32 MB zeroed-buffer alloc per decompress). -2 = frame does not
  // declare a size (streamed writer); -1 = not a zstd frame.
  unsigned long long c = ZSTD_getFrameContentSize(src, src_len);
  if (c == ZSTD_CONTENTSIZE_ERROR) return -1;
  if (c == ZSTD_CONTENTSIZE_UNKNOWN) return -2;
  return (long long)c;
}

long long tt_zstd_decompress(const char* src, size_t src_len,
                             char* dst, size_t dst_cap) {
  unsigned long long content = ZSTD_getFrameContentSize(src, src_len);
  if (content != ZSTD_CONTENTSIZE_UNKNOWN &&
      content != ZSTD_CONTENTSIZE_ERROR && content > dst_cap) {
    return -2;  // caller must grow dst
  }
  size_t n = ZSTD_decompress(dst, dst_cap, src, src_len);
  if (ZSTD_isError(n)) {
    // streaming encoders omit the frame content size; a too-small dst then
    // surfaces here rather than in the precheck — keep it retryable
    return ZSTD_getErrorCode(n) == ZSTD_error_dstSize_tooSmall ? -2 : -1;
  }
  return (long long)n;
}

long long tt_lz4_compress(const char* src, size_t src_len,
                          char* dst, size_t dst_cap) {
  int n = LZ4_compress_default(src, dst, (int)src_len, (int)dst_cap);
  if (n <= 0) return -1;
  return (long long)n;
}

long long tt_lz4_decompress(const char* src, size_t src_len,
                            char* dst, size_t dst_cap) {
  int n = LZ4_decompress_safe(src, dst, (int)src_len, (int)dst_cap);
  if (n < 0) return -1;
  return (long long)n;
}

long long tt_snappy_compress(const char* src, size_t src_len,
                             char* dst, size_t dst_cap) {
  size_t out_len = dst_cap;
  if (snappy_compress(src, src_len, dst, &out_len) != SNAPPY_OK) return -1;
  return (long long)out_len;
}

long long tt_snappy_decompress(const char* src, size_t src_len,
                               char* dst, size_t dst_cap) {
  size_t out_len = dst_cap;
  if (snappy_uncompress(src, src_len, dst, &out_len) != SNAPPY_OK) return -1;
  return (long long)out_len;
}

// Dictionary substring scan: find all strings in a packed dictionary
// containing `needle`. Packed layout: concatenated utf-8 bytes + an
// (n+1)-entry offset table. This is the 10M-distinct-values answer for
// substring (bytes.Contains) semantics — the host-side prefilter of the
// TPU search engine — where python-level scanning is too slow.
long long tt_substr_scan(const char* buf, const long long* offsets,
                         long long n_strs, const char* needle,
                         long long needle_len, int* out_ids,
                         long long out_cap) {
  long long found = 0;
  if (needle_len == 0) {
    if (n_strs > out_cap) return -2;  // grow, never truncate silently
    for (long long i = 0; i < n_strs; i++)
      out_ids[found++] = (int)i;
    return found;
  }
  // ONE memmem pass over the whole packed buffer instead of one call
  // per string: at 10M short values the per-call overhead dominates
  // (~500ms vs ~100ms measured). Strings are concatenated WITHOUT
  // separators, so a raw hit can straddle a boundary — validate that
  // the match lies inside a single string before accepting, else resume
  // one byte past the false hit.
  const char* end = buf + offsets[n_strs];
  const char* p = buf;
  long long cur = 0;       // monotone string cursor (offsets ascend)
  while (p < end) {
    const char* hit =
        (const char*)memmem(p, (size_t)(end - p), needle, (size_t)needle_len);
    if (hit == nullptr) break;
    long long pos = hit - buf;
    while (offsets[cur + 1] <= pos) cur++;
    if (pos + needle_len <= offsets[cur + 1]) {
      if (found >= out_cap) return -2;  // caller must grow out buffer
      out_ids[found++] = (int)cur;
      p = buf + offsets[cur + 1];  // further hits in this string are dupes
      cur++;
    } else {
      p = hit + 1;  // boundary-straddling false hit
    }
  }
  return found;
}

// xxhash64 (XXH64) — self-contained implementation so we do not depend on
// a system libxxhash being present.
static const uint64_t P1 = 11400714785074694791ULL;
static const uint64_t P2 = 14029467366897019727ULL;
static const uint64_t P3 = 1609587929392839161ULL;
static const uint64_t P4 = 9650029242287828579ULL;
static const uint64_t P5 = 2870177450012600261ULL;

static inline uint64_t rotl64(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}
static inline uint64_t read64(const char* p) {
  uint64_t v;
  memcpy(&v, p, 8);
  return v;
}
static inline uint32_t read32(const char* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}
static inline uint64_t round1(uint64_t acc, uint64_t input) {
  acc += input * P2;
  acc = rotl64(acc, 31);
  acc *= P1;
  return acc;
}
static inline uint64_t merge_round(uint64_t acc, uint64_t val) {
  val = round1(0, val);
  acc ^= val;
  acc = acc * P1 + P4;
  return acc;
}

unsigned long long tt_xxhash64(const char* data, size_t len,
                               unsigned long long seed) {
  const char* p = data;
  const char* end = data + len;
  uint64_t h;
  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2, v2 = seed + P2, v3 = seed, v4 = seed - P1;
    const char* limit = end - 32;
    do {
      v1 = round1(v1, read64(p)); p += 8;
      v2 = round1(v2, read64(p)); p += 8;
      v3 = round1(v3, read64(p)); p += 8;
      v4 = round1(v4, read64(p)); p += 8;
    } while (p <= limit);
    h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
    h = merge_round(h, v1);
    h = merge_round(h, v2);
    h = merge_round(h, v3);
    h = merge_round(h, v4);
  } else {
    h = seed + P5;
  }
  h += (uint64_t)len;
  while (p + 8 <= end) {
    h ^= round1(0, read64(p));
    h = rotl64(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    h ^= (uint64_t)read32(p) * P1;
    h = rotl64(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= (uint64_t)(uint8_t)(*p) * P5;
    h = rotl64(h, 11) * P1;
    p++;
  }
  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

// CRC32C (Castagnoli), slice-by-8 — RecordBatch v2 integrity on the
// kafka ingest path (pure-python table CRC is ~5 MB/s; this is ~1 GB/s).
static uint32_t crc32c_tbl[8][256];

// built at library load (single-threaded) — ctypes callers drop the GIL,
// so lazy init here would be a data race
static bool crc32c_tables_built = [] {
  for (uint32_t n = 0; n < 256; n++) {
    uint32_t c = n;
    for (int k = 0; k < 8; k++) c = c & 1 ? 0x82f63b78u ^ (c >> 1) : c >> 1;
    crc32c_tbl[0][n] = c;
  }
  for (uint32_t n = 0; n < 256; n++) {
    uint32_t c = crc32c_tbl[0][n];
    for (int s = 1; s < 8; s++) {
      c = crc32c_tbl[0][c & 0xff] ^ (c >> 8);
      crc32c_tbl[s][n] = c;
    }
  }
  return true;
}();

unsigned int tt_crc32c(const char* data, size_t len, unsigned int crc) {
  (void)crc32c_tables_built;
  const unsigned char* p = (const unsigned char*)data;
  uint32_t c = crc ^ 0xffffffffu;
  while (len && ((uintptr_t)p & 7)) {
    c = crc32c_tbl[0][(c ^ *p++) & 0xff] ^ (c >> 8);
    len--;
  }
  while (len >= 8) {
    uint64_t x;
    memcpy(&x, p, 8);
    x ^= c;
    c = crc32c_tbl[7][x & 0xff] ^ crc32c_tbl[6][(x >> 8) & 0xff] ^
        crc32c_tbl[5][(x >> 16) & 0xff] ^ crc32c_tbl[4][(x >> 24) & 0xff] ^
        crc32c_tbl[3][(x >> 32) & 0xff] ^ crc32c_tbl[2][(x >> 40) & 0xff] ^
        crc32c_tbl[1][(x >> 48) & 0xff] ^ crc32c_tbl[0][(x >> 56) & 0xff];
    p += 8;
    len -= 8;
  }
  while (len--) c = crc32c_tbl[0][(c ^ *p++) & 0xff] ^ (c >> 8);
  return c ^ 0xffffffffu;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// OTLP ingest fast path: regroup-by-trace + search-data extraction + time
// range in ONE native pass over SERIALIZED ResourceSpans — the role the
// reference's Go distributor hot loop fills (distributor.go:442-516 +
// requestsByTraceID), where our Python per-span object walk was the
// measured ingest ceiling (VERDICT r4 #4).
//
// Input:  concatenated [u32le len][ResourceSpans bytes] records.
// Output: u32 n_traces, u32 n_spans, then per trace:
//           16B padded trace id, u32 start_s, u32 end_s,
//           u32 seg_len  + seg   (8B v2 header + Trace proto bytes),
//           u32 sd_len   + sd    (search-data wire format, data.py:191)
// Returns bytes written; -2 malformed proto; -3 output too small (caller
// grows and retries); -4 invalid trace id (caller falls back to the
// Python path so the user-visible error is identical).

#include <algorithm>
#include <array>
#include <charconv>
#include <cstdio>
#include <map>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Range { size_t off, len; };  // into the input buffer

static bool rd_varint(const uint8_t* p, size_t n, size_t& off, uint64_t& v) {
  v = 0;
  int shift = 0;
  while (off < n && shift < 64) {
    uint8_t b = p[off++];
    v |= (uint64_t)(b & 0x7f) << shift;
    if (!(b & 0x80)) return true;
    shift += 7;
  }
  return false;
}

// skip one field's value given its wire type; LEN returns the payload range
static bool rd_skip(const uint8_t* p, size_t n, size_t& off, uint32_t wt,
                    Range* payload) {
  uint64_t v;
  switch (wt) {
    case 0: return rd_varint(p, n, off, v);
    case 1: if (off + 8 > n) return false; off += 8; return true;
    case 5: if (off + 4 > n) return false; off += 4; return true;
    case 2: {
      // v can be a full 64-bit value from a hostile 10-byte varint:
      // compare against the REMAINING bytes so `off + v` cannot wrap
      if (!rd_varint(p, n, off, v) || v > n - off) return false;
      if (payload) *payload = {off, (size_t)v};
      off += v;
      return true;
    }
    default: return false;
  }
}

// python repr() of a double, byte-for-byte: shortest round-trip digits
// (std::to_chars scientific), re-formatted by CPython's rule — FIXED
// notation when the decimal exponent is in [-4, 16), scientific with a
// 2-digit signed exponent otherwise. to_chars alone picks scientific
// whenever strictly shorter (2e5 → "2e+05" where Python says
// "200000.0"), which broke search-data parity (code-review r5).
static std::string py_double_repr(double d) {
  if (d != d) return "nan";
  if (d == __builtin_inf()) return "inf";
  if (d == -__builtin_inf()) return "-inf";
  char buf[64];
  auto res = std::to_chars(buf, buf + sizeof(buf), d,
                           std::chars_format::scientific);
  std::string s(buf, res.ptr);  // [-]D[.DDDD]e±EE — shortest digits
  bool neg = s[0] == '-';
  size_t i = neg ? 1 : 0;
  size_t epos = s.find('e', i);
  std::string digits;
  for (size_t j = i; j < epos; j++)
    if (s[j] != '.') digits += s[j];
  int exp = atoi(s.c_str() + epos + 1);
  std::string out = neg ? "-" : "";
  if (exp >= -4 && exp < 16) {
    if (exp >= (int)digits.size() - 1) {        // integral: pad + ".0"
      out += digits;
      out.append(exp - (digits.size() - 1), '0');
      out += ".0";
    } else if (exp >= 0) {                      // point inside digits
      out += digits.substr(0, exp + 1) + "." + digits.substr(exp + 1);
    } else {                                    // leading zeros
      out += "0.";
      out.append(-exp - 1, '0');
      out += digits;
    }
  } else {                                      // python scientific
    out += digits.substr(0, 1);
    if (digits.size() > 1) out += "." + digits.substr(1);
    char e[8];
    snprintf(e, sizeof(e), "e%+03d", exp);
    out += e;
  }
  return out;
}

// AnyValue → string per data.py _any_value_str (empty = unindexed type)
static bool anyvalue_str(const uint8_t* p, Range r, std::string& out) {
  size_t off = r.off, end = r.off + r.len;
  out.clear();
  // last occurrence wins (proto3 oneof semantics on the wire)
  while (off < end) {
    uint64_t tag;
    if (!rd_varint(p, end, off, tag)) return false;
    uint32_t f = (uint32_t)(tag >> 3), wt = (uint32_t)(tag & 7);
    Range pay{0, 0};
    size_t before = off;
    if (f == 1 && wt == 2) {           // string_value
      if (!rd_skip(p, end, off, wt, &pay)) return false;
      out.assign((const char*)p + pay.off, pay.len);
    } else if (f == 2 && wt == 0) {    // bool_value
      uint64_t v; if (!rd_varint(p, end, off, v)) return false;
      out = v ? "true" : "false";
    } else if (f == 3 && wt == 0) {    // int_value (zigzag? no — int64)
      uint64_t v; if (!rd_varint(p, end, off, v)) return false;
      char b[24];
      auto res = std::to_chars(b, b + sizeof(b), (long long)v);
      out.assign(b, res.ptr);
    } else if (f == 4 && wt == 1) {    // double_value
      if (off + 8 > end) return false;
      double d; memcpy(&d, p + off, 8); off += 8;
      out = py_double_repr(d);
    } else {
      if (!rd_skip(p, end, off, wt, nullptr)) return false;
      out.clear();                     // array/kvlist/bytes → unindexed
    }
    (void)before;
  }
  return true;
}

// AnyValue → its string_value field ONLY (python `kv.value.string_value`
// semantics — collect_span_rows derives the per-span service name this
// way, so an int-typed service.name yields "" here while the trace-level
// rollup stringifies it). Last occurrence wins; only a RECOGNIZED later
// oneof arm (fields 2-7 at their declared wire types) clears a set
// string_value — protobuf parsers treat unknown fields and wire-type
// mismatches as unknown, which never clear a oneof, and the Python
// fallback path must read the same value.
static bool anyvalue_string_only(const uint8_t* p, Range r,
                                 std::string& out) {
  size_t off = r.off, end = r.off + r.len;
  out.clear();
  while (off < end) {
    uint64_t tag;
    if (!rd_varint(p, end, off, tag)) return false;
    uint32_t f = (uint32_t)(tag >> 3), wt = (uint32_t)(tag & 7);
    Range pay{0, 0};
    if (!rd_skip(p, end, off, wt, &pay)) return false;
    if (f == 1 && wt == 2)
      out.assign((const char*)p + pay.off, pay.len);
    else if ((f == 2 && wt == 0) ||   // bool_value
             (f == 3 && wt == 0) ||   // int_value
             (f == 4 && wt == 1) ||   // double_value
             (f >= 5 && f <= 7 && wt == 2))  // array/kvlist/bytes
      out.clear();
  }
  return true;
}

// utf-8 character count (python len(str)) — budget accounting must match
static size_t u8len(const std::string& s) {
  size_t n = 0;
  for (unsigned char c : s) n += (c & 0xC0) != 0x80;
  return n;
}

static size_t varint_size(uint64_t v) {
  size_t n = 1;
  while (v >= 0x80) { v >>= 7; n++; }
  return n;
}

// per-span summary row for the metrics-generator feed: the generator
// thread consumes these (56B fixed records + a string table) instead of
// re-walking the proto objects — both the second walk and most of its
// GIL steal from the ingest ack path disappear (VERDICT r4 #4)
struct RowTmp {
  uint32_t trace_idx, svc_idx, name_idx, kind, status, flags;
  uint64_t start_ns, end_ns;
  uint8_t span_id[8], parent_id[8];
};

// per-span summary captured for the search-data SPAN SECTION (the
// structural engine's ingest substrate, data.py collect_span_rows) —
// only populated when the caller asked for span rows (flags bit 0), so
// the legacy path allocates nothing extra
struct SpanSum {
  uint64_t start_ns = 0, end_ns = 0;
  uint32_t kind = 0, status = 0;
  std::string name;
  std::string span_id, parent_id;  // RAW bytes (python keys idx_of raw)
  std::vector<std::pair<std::string, std::string>> attrs;
};

struct ScopeOut {
  std::vector<Range> passthrough;  // scope + schema_url fields, verbatim
  std::vector<Range> spans;        // span payloads (field 2 LEN values)
  std::vector<SpanSum> sums;       // parallel to `spans` (span section)
  size_t body_size = 0;            // computed at emit
};

struct BatchOut {
  std::vector<Range> passthrough;  // resource + schema_url, verbatim
  std::vector<ScopeOut> scopes;
  // resource service.name with STRING_VALUE-only semantics (python
  // collect_span_rows reads kv.value.string_value, not the any-value
  // stringification the trace-level rollup uses) — last key wins
  std::string svc_str;
  size_t body_size = 0;
};

struct TraceOut {
  std::array<uint8_t, 16> tid{};
  std::vector<BatchOut> batches;
  std::map<std::string, std::set<std::string>> kvs;
  long long budget = 0;
  uint64_t min_start = ~0ull, max_end = 0;
  bool have_root = false;
  uint64_t root_start = 0, first_start = 0;
  std::string root_svc, root_name, first_svc, first_name;
  bool have_first = false;
};

static void kv_add(TraceOut& t, const std::string& k, const std::string& v) {
  if (v.empty()) return;
  long long cost = (long long)(u8len(k) + u8len(v));
  if (t.budget < cost) return;
  auto& s = t.kvs[k];
  if (s.insert(v).second) t.budget -= cost;
  else if (s.size() == 0) t.kvs.erase(k);  // unreachable; keep -Wall quiet
}

static void put_u32(std::string& out, uint32_t v) {
  char b[4];
  memcpy(b, &v, 4);
  out.append(b, 4);
}

static void put_u16s(std::string& out, const std::string& s) {
  size_t n = std::min(s.size(), (size_t)0xFFFF);
  uint16_t len = (uint16_t)n;
  char b[2];
  memcpy(b, &len, 2);
  out.append(b, 2);
  out.append(s.data(), n);
}

}  // namespace

// full regroup implementation; `flags` bit 0 asks for the search-data
// SPAN SECTION (data.py optional trailing section) capped at
// `max_spans` rows / `max_span_kvs` kv pairs per span — byte-identical
// to the Python walk (collect_span_rows + encode_search_data)
static long long ingest_regroup_impl(const char* src_c, size_t src_len,
                                     long long max_search_bytes,
                                     long long flags, long long max_spans,
                                     long long max_span_kvs,
                                     char* dst, size_t dst_cap) {
  const bool want_spans = (flags & 1) != 0;
  const uint8_t* p = (const uint8_t*)src_c;
  std::vector<TraceOut> traces;
  std::unordered_map<std::string, int> tid_idx;  // padded tid → index
  uint64_t n_spans = 0;
  std::vector<RowTmp> rows;                      // generator summaries
  std::vector<std::string> strtab;
  std::unordered_map<std::string, uint32_t> str_idx;
  auto intern = [&](const std::string& s) -> uint32_t {
    auto it = str_idx.find(s);
    if (it != str_idx.end()) return it->second;
    uint32_t i = (uint32_t)strtab.size();
    strtab.push_back(s);
    str_idx.emplace(s, i);
    return i;
  };

  size_t off = 0;
  while (off < src_len) {
    if (off + 4 > src_len) return -2;
    uint32_t blen;
    memcpy(&blen, p + off, 4);
    off += 4;
    if (off + blen > src_len) return -2;
    size_t bend = off + blen;

    // ---- one ResourceSpans ----
    std::vector<Range> rs_passthrough;
    std::string svc;                       // resource service.name
    std::string svc_sv;                    // ...string_value-only form
    std::vector<std::pair<std::string, std::string>> res_kvs;
    std::vector<Range> scope_payloads;
    {
      size_t o = off;
      while (o < bend) {
        size_t field_start = o;
        uint64_t tag;
        if (!rd_varint(p, bend, o, tag)) return -2;
        uint32_t f = (uint32_t)(tag >> 3), wt = (uint32_t)(tag & 7);
        Range pay{0, 0};
        if (!rd_skip(p, bend, o, wt, &pay)) return -2;
        if (f == 2 && wt == 2) {           // scope_spans
          scope_payloads.push_back(pay);
        } else {
          rs_passthrough.push_back({field_start, o - field_start});
          if (f == 1 && wt == 2) {         // resource → attributes
            size_t ro = pay.off, rend = pay.off + pay.len;
            while (ro < rend) {
              uint64_t rtag;
              if (!rd_varint(p, rend, ro, rtag)) return -2;
              Range rpay{0, 0};
              if (!rd_skip(p, rend, ro, (uint32_t)(rtag & 7), &rpay)) return -2;
              if ((rtag >> 3) == 1 && (rtag & 7) == 2) {  // KeyValue
                size_t ko = rpay.off, kend = rpay.off + rpay.len;
                std::string key, val;
                Range val_r{0, 0};
                while (ko < kend) {
                  uint64_t ktag;
                  if (!rd_varint(p, kend, ko, ktag)) return -2;
                  Range kpay{0, 0};
                  if (!rd_skip(p, kend, ko, (uint32_t)(ktag & 7), &kpay))
                    return -2;
                  if ((ktag >> 3) == 1 && (ktag & 7) == 2)
                    key.assign((const char*)p + kpay.off, kpay.len);
                  else if ((ktag >> 3) == 2 && (ktag & 7) == 2) {
                    if (!anyvalue_str(p, kpay, val)) return -2;
                    val_r = kpay;
                  }
                }
                res_kvs.emplace_back(key, val);
                if (key == "service.name") {
                  svc = val;  // last wins (py parity)
                  // span rows read string_value ONLY (py parity:
                  // collect_span_rows vs extract_search_data)
                  if (!anyvalue_string_only(p, val_r, svc_sv)) return -2;
                }
              }
            }
          }
        }
      }
    }

    // per-batch dest map: tid index → BatchOut index (id()-keyed regroup)
    std::unordered_map<int, int> batch_dest;

    for (const Range& sp : scope_payloads) {
      // ---- one ScopeSpans ----
      std::vector<Range> sc_passthrough;
      std::vector<Range> span_payloads;
      size_t o = sp.off, send = sp.off + sp.len;
      while (o < send) {
        size_t field_start = o;
        uint64_t tag;
        if (!rd_varint(p, send, o, tag)) return -2;
        uint32_t f = (uint32_t)(tag >> 3), wt = (uint32_t)(tag & 7);
        Range pay{0, 0};
        if (!rd_skip(p, send, o, wt, &pay)) return -2;
        if (f == 2 && wt == 2) span_payloads.push_back(pay);
        else sc_passthrough.push_back({field_start, o - field_start});
      }

      // tid idx → (batch idx, scope idx); a packed-int encoding here
      // overflowed at ~2148 scopes and crashed on valid input
      // (code-review r5) — pay for the pair
      std::unordered_map<int, std::pair<int, int>> scope_dest;

      for (const Range& spn : span_payloads) {
        // ---- one Span ----
        size_t so = spn.off, ssend = spn.off + spn.len;
        Range tid_r{0, 0}, name_r{0, 0};
        Range span_id_r{0, 0}, parent_r{0, 0};
        bool have_parent = false;
        uint64_t start_ns = 0, end_ns = 0, kind = 0;
        uint32_t status_code = 0;
        std::vector<std::pair<std::string, std::string>> span_kvs;
        while (so < ssend) {
          uint64_t tag;
          if (!rd_varint(p, ssend, so, tag)) return -2;
          uint32_t f = (uint32_t)(tag >> 3), wt = (uint32_t)(tag & 7);
          Range pay{0, 0};
          if (f == 7 && wt == 1) {
            if (so + 8 > ssend) return -2;
            memcpy(&start_ns, p + so, 8); so += 8;
            continue;
          }
          if (f == 8 && wt == 1) {
            if (so + 8 > ssend) return -2;
            memcpy(&end_ns, p + so, 8); so += 8;
            continue;
          }
          if (f == 6 && wt == 0) {                 // kind
            if (!rd_varint(p, ssend, so, kind)) return -2;
            continue;
          }
          if (!rd_skip(p, ssend, so, wt, &pay)) return -2;
          if (f == 1 && wt == 2) tid_r = pay;
          else if (f == 2 && wt == 2) span_id_r = pay;
          else if (f == 4 && wt == 2 && pay.len > 0) {
            have_parent = true;
            parent_r = pay;
          }
          else if (f == 5 && wt == 2) name_r = pay;
          else if (f == 9 && wt == 2) {            // attributes KeyValue
            size_t ko = pay.off, kend = pay.off + pay.len;
            std::string key, val;
            while (ko < kend) {
              uint64_t ktag;
              if (!rd_varint(p, kend, ko, ktag)) return -2;
              Range kpay{0, 0};
              if (!rd_skip(p, kend, ko, (uint32_t)(ktag & 7), &kpay))
                return -2;
              if ((ktag >> 3) == 1 && (ktag & 7) == 2)
                key.assign((const char*)p + kpay.off, kpay.len);
              else if ((ktag >> 3) == 2 && (ktag & 7) == 2) {
                if (!anyvalue_str(p, kpay, val)) return -2;
              }
            }
            span_kvs.emplace_back(key, val);
          } else if (f == 15 && wt == 2) {         // status → code
            size_t to = pay.off, tend = pay.off + pay.len;
            while (to < tend) {
              uint64_t ttag;
              if (!rd_varint(p, tend, to, ttag)) return -2;
              if ((ttag >> 3) == 3 && (ttag & 7) == 0) {
                uint64_t v;
                if (!rd_varint(p, tend, to, v)) return -2;
                status_code = (uint32_t)v;
              } else {
                Range tpay{0, 0};
                if (!rd_skip(p, tend, to, (uint32_t)(ttag & 7), &tpay))
                  return -2;
              }
            }
          }
        }
        if (tid_r.len == 0 || tid_r.len > 16) return -4;

        std::string padded(16, '\0');
        memcpy(&padded[16 - tid_r.len], p + tid_r.off, tid_r.len);
        auto it = tid_idx.find(padded);
        int ti;
        if (it == tid_idx.end()) {
          ti = (int)traces.size();
          tid_idx.emplace(padded, ti);
          traces.emplace_back();
          memcpy(traces[ti].tid.data(), padded.data(), 16);
          traces[ti].budget = max_search_bytes;
        } else {
          ti = it->second;
        }
        n_spans++;
        // NOTE: `traces` may reallocate on emplace above — take the
        // reference AFTER any potential growth
        TraceOut& T = traces[ti];

        auto sd_it = scope_dest.find(ti);
        ScopeOut* SO;
        if (sd_it == scope_dest.end()) {
          auto bd_it = batch_dest.find(ti);
          int bi;
          if (bd_it == batch_dest.end()) {
            bi = (int)T.batches.size();
            T.batches.emplace_back();
            T.batches[bi].passthrough = rs_passthrough;
            T.batches[bi].svc_str = svc_sv;
            batch_dest.emplace(ti, bi);
            for (auto& kv : res_kvs) kv_add(T, kv.first, kv.second);
          } else {
            bi = bd_it->second;
          }
          BatchOut& B = T.batches[bi];
          int si = (int)B.scopes.size();
          B.scopes.emplace_back();
          B.scopes[si].passthrough = sc_passthrough;
          scope_dest.emplace(ti, std::make_pair(bi, si));
          SO = &B.scopes[si];
        } else {
          SO = &T.batches[sd_it->second.first].scopes[sd_it->second.second];
        }
        SO->spans.push_back(spn);

        if (start_ns < T.min_start) T.min_start = start_ns;
        if (end_ns > T.max_end) T.max_end = end_ns;

        std::string name((const char*)p + name_r.off, name_r.len);
        if (!name.empty()) {
          long long cost = 4 + (long long)u8len(name);
          if (T.budget >= cost) {
            auto& s = T.kvs["name"];
            if (s.insert(name).second) T.budget -= cost;
          }
        }
        if (status_code == 2 && T.budget >= 9) {   // STATUS_CODE_ERROR
          auto& s = T.kvs["error"];
          if (s.insert("true").second) T.budget -= 9;
        }
        for (auto& kv : span_kvs) kv_add(T, kv.first, kv.second);

        if (want_spans) {
          // span-section capture (parallel to SO->spans): raw ids for
          // the parent resolve, attrs MOVED (kv_add above was their
          // last reader) so capture adds only the short name/id copies
          // per span — the legacy path (flags=0) stores nothing. The
          // max_spans cap applies at emit, in REGROUPED order: parse
          // order can differ from the regrouped walk order when one
          // trace's spans interleave across scopes, so an early
          // capture cap would truncate a different row set than the
          // Python walk.
          SpanSum sum;
          sum.start_ns = start_ns;
          sum.end_ns = end_ns;
          sum.kind = (uint32_t)kind;
          sum.status = status_code;
          sum.name = name;
          sum.span_id.assign((const char*)p + span_id_r.off, span_id_r.len);
          if (have_parent)
            sum.parent_id.assign((const char*)p + parent_r.off,
                                 parent_r.len);
          sum.attrs = std::move(span_kvs);
          SO->sums.push_back(std::move(sum));
        }

        if (!have_parent) {
          if (!T.have_root || start_ns < T.root_start) {
            T.have_root = true;
            T.root_start = start_ns;
            T.root_svc = svc;
            T.root_name = name;
          }
        } else if (!T.have_first || start_ns < T.first_start) {
          T.have_first = true;
          T.first_start = start_ns;
          T.first_svc = svc;
          T.first_name = name;
        }

        RowTmp row{};
        row.trace_idx = (uint32_t)ti;
        row.svc_idx = intern(svc);
        row.name_idx = intern(name);
        row.kind = (uint32_t)kind;
        row.status = status_code;
        row.flags = have_parent ? 1u : 0u;
        row.start_ns = start_ns;
        row.end_ns = end_ns;
        if (span_id_r.len && span_id_r.len <= 8)   // right-align, zero-pad
          memcpy(row.span_id + (8 - span_id_r.len), p + span_id_r.off,
                 span_id_r.len);
        if (parent_r.len && parent_r.len <= 8)
          memcpy(row.parent_id + (8 - parent_r.len), p + parent_r.off,
                 parent_r.len);
        rows.push_back(row);
      }
    }
    off = bend;
  }

  // ---- emit ----
  std::string out;
  out.reserve(src_len + (traces.size() * 256) + 64);
  put_u32(out, (uint32_t)traces.size());
  put_u32(out, (uint32_t)n_spans);
  for (auto& T : traces) {
    uint64_t start_ns = T.max_end ? T.min_start : 0;
    uint64_t end_ns = T.max_end;
    uint32_t start_s = (uint32_t)((start_ns / 1000000000ull) & 0xFFFFFFFF);
    uint32_t end_s = (uint32_t)((end_ns / 1000000000ull) & 0xFFFFFFFF);
    // max(0, end - start): clock-skewed end < start must clamp to 0 (the
    // unsigned underflow previously saturated to 0xFFFFFFFF, diverging
    // from the Python walks, which now clamp to 0 too)
    uint64_t dur_ms =
        (end_ns > start_ns) ? (end_ns - start_ns) / 1000000ull : 0;
    if (dur_ms > 0xFFFFFFFFull) dur_ms = 0xFFFFFFFFull;

    out.append((const char*)T.tid.data(), 16);
    put_u32(out, start_s);
    put_u32(out, end_s);

    // segment: 8B header + Trace{repeated ResourceSpans batches = 1}
    size_t seg_size = 8;
    for (auto& B : T.batches) {
      size_t body = 0;
      for (auto& r : B.passthrough) body += r.len;
      for (auto& S : B.scopes) {
        size_t sbody = 0;
        for (auto& r : S.passthrough) sbody += r.len;
        for (auto& r : S.spans) sbody += 1 + varint_size(r.len) + r.len;
        S.body_size = sbody;
        body += 1 + varint_size(sbody) + sbody;
      }
      B.body_size = body;
      seg_size += 1 + varint_size(body) + body;
    }
    put_u32(out, (uint32_t)seg_size);
    char hdr[8];
    memcpy(hdr, &start_s, 4);
    memcpy(hdr + 4, &end_s, 4);
    out.append(hdr, 8);
    auto emit_varint = [&out](uint64_t v) {
      while (v >= 0x80) { out.push_back((char)(v | 0x80)); v >>= 7; }
      out.push_back((char)v);
    };
    for (auto& B : T.batches) {
      out.push_back((char)0x0A);               // Trace.batches (field 1 LEN)
      emit_varint(B.body_size);
      for (auto& r : B.passthrough)
        out.append((const char*)p + r.off, r.len);
      for (auto& S : B.scopes) {
        out.push_back((char)0x12);             // ResourceSpans.scope_spans
        emit_varint(S.body_size);
        for (auto& r : S.passthrough)
          out.append((const char*)p + r.off, r.len);
        for (auto& r : S.spans) {
          out.push_back((char)0x12);           // ScopeSpans.spans
          emit_varint(r.len);
          out.append((const char*)p + r.off, r.len);
        }
      }
    }

    // search data (data.py encode_search_data wire format)
    std::string sd;
    put_u32(sd, start_s);
    put_u32(sd, end_s);
    put_u32(sd, (uint32_t)dur_ms);
    const std::string& rsvc = T.have_root ? T.root_svc
                              : (T.have_first ? T.first_svc : std::string());
    const std::string& rname = T.have_root ? T.root_name
                               : (T.have_first ? T.first_name : std::string());
    put_u16s(sd, rsvc);
    put_u16s(sd, rname);
    uint16_t nk = (uint16_t)std::min(T.kvs.size(), (size_t)0xFFFF);
    sd.append((const char*)&nk, 2);
    size_t ki = 0;
    for (auto& kv : T.kvs) {                   // std::map: sorted keys
      if (ki++ >= nk) break;
      put_u16s(sd, kv.first);
      uint16_t nv = (uint16_t)std::min(kv.second.size(), (size_t)0xFFFF);
      sd.append((const char*)&nv, 2);
      size_t vi = 0;
      for (auto& v : kv.second) {              // std::set: sorted values
        if (vi++ >= nv) break;
        put_u16s(sd, v);
      }
    }

    if (want_spans) {
      // ---- optional trailing SPAN SECTION (data.py collect_span_rows
      // + encode_search_data parity): rows in REGROUPED walk order
      // (batches → scopes → spans — the exact order the Python walk
      // sees on the regrouped trace), parents resolved by raw span id
      // within this trace's captured rows (first id occurrence wins,
      // never self), caps applied like the Python walk. A trace with
      // zero captured rows emits NO section — byte-identical to the
      // legacy wire form.
      struct SpanRow {
        int parent = -1;
        uint32_t dur_ms = 0, kind = 0;
        std::map<std::string, std::set<std::string>> kvs;
      };
      std::vector<SpanRow> srows;
      std::unordered_map<std::string, int> idx_of;  // raw span id → row
      std::vector<std::string> parent_ids;
      for (auto& B : T.batches) {
        const std::string& ssvc = B.svc_str;
        for (auto& S : B.scopes) {
          for (auto& sum : S.sums) {
            if ((long long)srows.size() >= max_spans) break;
            SpanRow r;
            uint64_t d = (sum.end_ns > sum.start_ns)
                             ? (sum.end_ns - sum.start_ns) / 1000000ull
                             : 0;
            if (d > 0xFFFFFFFFull) d = 0xFFFFFFFFull;
            r.dur_ms = sum.end_ns ? (uint32_t)d : 0;
            r.kind = sum.kind;
            long long n_kv = 0;
            if (!ssvc.empty()) {
              r.kvs["service.name"].insert(ssvc);
              n_kv++;
            }
            if (!sum.name.empty() && n_kv < max_span_kvs) {
              r.kvs["name"].insert(sum.name);
              n_kv++;
            }
            if (sum.status == 2 && n_kv < max_span_kvs) {
              r.kvs["error"].insert("true");
              n_kv++;
            }
            for (auto& kv : sum.attrs) {
              if (n_kv >= max_span_kvs) break;
              if (kv.second.empty()) continue;  // unindexed value type
              r.kvs[kv.first].insert(kv.second);
              n_kv++;  // counts per attribute, dupes included (py parity)
            }
            if (!sum.span_id.empty())
              idx_of.emplace(sum.span_id, (int)srows.size());
            parent_ids.push_back(sum.parent_id);
            srows.push_back(std::move(r));
          }
        }
      }
      for (size_t i = 0; i < srows.size(); i++) {
        const std::string& pid = parent_ids[i];
        if (pid.empty()) continue;
        auto it = idx_of.find(pid);
        if (it != idx_of.end() && it->second != (int)i)
          srows[i].parent = it->second;  // self-parent stays -1
      }
      if (!srows.empty()) {
        uint16_t ns = (uint16_t)std::min(srows.size(), (size_t)0xFFFF);
        sd.append((const char*)&ns, 2);
        size_t ri = 0;
        for (auto& r : srows) {
          if (ri++ >= ns) break;
          uint16_t par = (r.parent >= 0 && r.parent < 0xFFFF)
                             ? (uint16_t)r.parent
                             : 0xFFFF;
          sd.append((const char*)&par, 2);
          put_u32(sd, r.dur_ms);
          sd.push_back((char)(r.kind & 0xFF));
          uint16_t nsk = (uint16_t)std::min(r.kvs.size(), (size_t)0xFFFF);
          sd.append((const char*)&nsk, 2);
          size_t ski = 0;
          for (auto& kv : r.kvs) {             // std::map: sorted keys
            if (ski++ >= nsk) break;
            put_u16s(sd, kv.first);
            uint16_t nsv =
                (uint16_t)std::min(kv.second.size(), (size_t)0xFFFF);
            sd.append((const char*)&nsv, 2);
            size_t svi = 0;
            for (auto& v : kv.second) {        // std::set: sorted values
              if (svi++ >= nsv) break;
              put_u16s(sd, v);
            }
          }
        }
      }
    }

    put_u32(out, (uint32_t)sd.size());
    out += sd;
  }

  // ---- span summaries (generator feed): string table + 56B rows ----
  put_u32(out, (uint32_t)strtab.size());
  for (auto& s : strtab) put_u16s(out, s);
  put_u32(out, (uint32_t)rows.size());
  static_assert(sizeof(RowTmp) == 56, "summary row layout is the ABI");
  for (auto& r : rows) out.append((const char*)&r, sizeof(RowTmp));

  if (out.size() > dst_cap) return -3;
  memcpy(dst, out.data(), out.size());
  return (long long)out.size();
}

extern "C" {

long long tt_ingest_regroup(const char* src_c, size_t src_len,
                            long long max_search_bytes,
                            char* dst, size_t dst_cap) {
  // legacy entry point: no span section — byte-identical to the
  // pre-span builds (stale-binding safety: callers probe for the new
  // symbol and fall back to the Python walk when it is absent)
  return ingest_regroup_impl(src_c, src_len, max_search_bytes, 0, 0, 0,
                             dst, dst_cap);
}

long long tt_ingest_regroup2(const char* src_c, size_t src_len,
                             long long max_search_bytes, long long flags,
                             long long max_spans, long long max_span_kvs,
                             char* dst, size_t dst_cap) {
  return ingest_regroup_impl(src_c, src_len, max_search_bytes, flags,
                             max_spans, max_span_kvs, dst, dst_cap);
}

}  // extern "C"
