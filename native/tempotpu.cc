// Native host runtime for tempo-tpu: block codecs + hashing.
//
// Wraps the system libzstd / liblz4 / libsnappy — the role the reference
// fills with vendored Go asm codec libraries (SURVEY.md §7 native mapping).
// Exposed as a C ABI consumed via ctypes (tempo_tpu/ops/native.py).
// All functions return the produced byte count, or a negative error code.

#ifndef _GNU_SOURCE
#define _GNU_SOURCE  // memmem
#endif
#include <cstddef>
#include <cstdint>
#include <cstring>

#include <zstd.h>
#include <zstd_errors.h>

// liblz4 / libsnappy ship no dev headers in this image; declare the stable
// C ABIs directly and link against the versioned runtime libraries.
extern "C" {
int LZ4_compress_default(const char* src, char* dst, int srcSize, int dstCapacity);
int LZ4_decompress_safe(const char* src, char* dst, int compressedSize, int dstCapacity);

typedef enum {
  SNAPPY_OK = 0,
  SNAPPY_INVALID_INPUT = 1,
  SNAPPY_BUFFER_TOO_SMALL = 2,
} snappy_status;
snappy_status snappy_compress(const char* input, size_t input_length,
                              char* compressed, size_t* compressed_length);
snappy_status snappy_uncompress(const char* compressed, size_t compressed_length,
                                char* uncompressed, size_t* uncompressed_length);
}

extern "C" {

long long tt_zstd_compress(const char* src, size_t src_len,
                           char* dst, size_t dst_cap, int level) {
  size_t n = ZSTD_compress(dst, dst_cap, src, src_len, level);
  if (ZSTD_isError(n)) return -1;
  return (long long)n;
}

long long tt_zstd_content_size(const char* src, size_t src_len) {
  // exact decompressed size from the frame header, so the caller can
  // allocate once instead of a 32x guess (a 1 MB page was paying a
  // 32 MB zeroed-buffer alloc per decompress). -2 = frame does not
  // declare a size (streamed writer); -1 = not a zstd frame.
  unsigned long long c = ZSTD_getFrameContentSize(src, src_len);
  if (c == ZSTD_CONTENTSIZE_ERROR) return -1;
  if (c == ZSTD_CONTENTSIZE_UNKNOWN) return -2;
  return (long long)c;
}

long long tt_zstd_decompress(const char* src, size_t src_len,
                             char* dst, size_t dst_cap) {
  unsigned long long content = ZSTD_getFrameContentSize(src, src_len);
  if (content != ZSTD_CONTENTSIZE_UNKNOWN &&
      content != ZSTD_CONTENTSIZE_ERROR && content > dst_cap) {
    return -2;  // caller must grow dst
  }
  size_t n = ZSTD_decompress(dst, dst_cap, src, src_len);
  if (ZSTD_isError(n)) {
    // streaming encoders omit the frame content size; a too-small dst then
    // surfaces here rather than in the precheck — keep it retryable
    return ZSTD_getErrorCode(n) == ZSTD_error_dstSize_tooSmall ? -2 : -1;
  }
  return (long long)n;
}

long long tt_lz4_compress(const char* src, size_t src_len,
                          char* dst, size_t dst_cap) {
  int n = LZ4_compress_default(src, dst, (int)src_len, (int)dst_cap);
  if (n <= 0) return -1;
  return (long long)n;
}

long long tt_lz4_decompress(const char* src, size_t src_len,
                            char* dst, size_t dst_cap) {
  int n = LZ4_decompress_safe(src, dst, (int)src_len, (int)dst_cap);
  if (n < 0) return -1;
  return (long long)n;
}

long long tt_snappy_compress(const char* src, size_t src_len,
                             char* dst, size_t dst_cap) {
  size_t out_len = dst_cap;
  if (snappy_compress(src, src_len, dst, &out_len) != SNAPPY_OK) return -1;
  return (long long)out_len;
}

long long tt_snappy_decompress(const char* src, size_t src_len,
                               char* dst, size_t dst_cap) {
  size_t out_len = dst_cap;
  if (snappy_uncompress(src, src_len, dst, &out_len) != SNAPPY_OK) return -1;
  return (long long)out_len;
}

// Dictionary substring scan: find all strings in a packed dictionary
// containing `needle`. Packed layout: concatenated utf-8 bytes + an
// (n+1)-entry offset table. This is the 10M-distinct-values answer for
// substring (bytes.Contains) semantics — the host-side prefilter of the
// TPU search engine — where python-level scanning is too slow.
long long tt_substr_scan(const char* buf, const long long* offsets,
                         long long n_strs, const char* needle,
                         long long needle_len, int* out_ids,
                         long long out_cap) {
  long long found = 0;
  if (needle_len == 0) {
    if (n_strs > out_cap) return -2;  // grow, never truncate silently
    for (long long i = 0; i < n_strs; i++)
      out_ids[found++] = (int)i;
    return found;
  }
  // ONE memmem pass over the whole packed buffer instead of one call
  // per string: at 10M short values the per-call overhead dominates
  // (~500ms vs ~100ms measured). Strings are concatenated WITHOUT
  // separators, so a raw hit can straddle a boundary — validate that
  // the match lies inside a single string before accepting, else resume
  // one byte past the false hit.
  const char* end = buf + offsets[n_strs];
  const char* p = buf;
  long long cur = 0;       // monotone string cursor (offsets ascend)
  while (p < end) {
    const char* hit =
        (const char*)memmem(p, (size_t)(end - p), needle, (size_t)needle_len);
    if (hit == nullptr) break;
    long long pos = hit - buf;
    while (offsets[cur + 1] <= pos) cur++;
    if (pos + needle_len <= offsets[cur + 1]) {
      if (found >= out_cap) return -2;  // caller must grow out buffer
      out_ids[found++] = (int)cur;
      p = buf + offsets[cur + 1];  // further hits in this string are dupes
      cur++;
    } else {
      p = hit + 1;  // boundary-straddling false hit
    }
  }
  return found;
}

// xxhash64 (XXH64) — self-contained implementation so we do not depend on
// a system libxxhash being present.
static const uint64_t P1 = 11400714785074694791ULL;
static const uint64_t P2 = 14029467366897019727ULL;
static const uint64_t P3 = 1609587929392839161ULL;
static const uint64_t P4 = 9650029242287828579ULL;
static const uint64_t P5 = 2870177450012600261ULL;

static inline uint64_t rotl64(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}
static inline uint64_t read64(const char* p) {
  uint64_t v;
  memcpy(&v, p, 8);
  return v;
}
static inline uint32_t read32(const char* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}
static inline uint64_t round1(uint64_t acc, uint64_t input) {
  acc += input * P2;
  acc = rotl64(acc, 31);
  acc *= P1;
  return acc;
}
static inline uint64_t merge_round(uint64_t acc, uint64_t val) {
  val = round1(0, val);
  acc ^= val;
  acc = acc * P1 + P4;
  return acc;
}

unsigned long long tt_xxhash64(const char* data, size_t len,
                               unsigned long long seed) {
  const char* p = data;
  const char* end = data + len;
  uint64_t h;
  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2, v2 = seed + P2, v3 = seed, v4 = seed - P1;
    const char* limit = end - 32;
    do {
      v1 = round1(v1, read64(p)); p += 8;
      v2 = round1(v2, read64(p)); p += 8;
      v3 = round1(v3, read64(p)); p += 8;
      v4 = round1(v4, read64(p)); p += 8;
    } while (p <= limit);
    h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
    h = merge_round(h, v1);
    h = merge_round(h, v2);
    h = merge_round(h, v3);
    h = merge_round(h, v4);
  } else {
    h = seed + P5;
  }
  h += (uint64_t)len;
  while (p + 8 <= end) {
    h ^= round1(0, read64(p));
    h = rotl64(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    h ^= (uint64_t)read32(p) * P1;
    h = rotl64(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= (uint64_t)(uint8_t)(*p) * P5;
    h = rotl64(h, 11) * P1;
    p++;
  }
  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

// CRC32C (Castagnoli), slice-by-8 — RecordBatch v2 integrity on the
// kafka ingest path (pure-python table CRC is ~5 MB/s; this is ~1 GB/s).
static uint32_t crc32c_tbl[8][256];

// built at library load (single-threaded) — ctypes callers drop the GIL,
// so lazy init here would be a data race
static bool crc32c_tables_built = [] {
  for (uint32_t n = 0; n < 256; n++) {
    uint32_t c = n;
    for (int k = 0; k < 8; k++) c = c & 1 ? 0x82f63b78u ^ (c >> 1) : c >> 1;
    crc32c_tbl[0][n] = c;
  }
  for (uint32_t n = 0; n < 256; n++) {
    uint32_t c = crc32c_tbl[0][n];
    for (int s = 1; s < 8; s++) {
      c = crc32c_tbl[0][c & 0xff] ^ (c >> 8);
      crc32c_tbl[s][n] = c;
    }
  }
  return true;
}();

unsigned int tt_crc32c(const char* data, size_t len, unsigned int crc) {
  (void)crc32c_tables_built;
  const unsigned char* p = (const unsigned char*)data;
  uint32_t c = crc ^ 0xffffffffu;
  while (len && ((uintptr_t)p & 7)) {
    c = crc32c_tbl[0][(c ^ *p++) & 0xff] ^ (c >> 8);
    len--;
  }
  while (len >= 8) {
    uint64_t x;
    memcpy(&x, p, 8);
    x ^= c;
    c = crc32c_tbl[7][x & 0xff] ^ crc32c_tbl[6][(x >> 8) & 0xff] ^
        crc32c_tbl[5][(x >> 16) & 0xff] ^ crc32c_tbl[4][(x >> 24) & 0xff] ^
        crc32c_tbl[3][(x >> 32) & 0xff] ^ crc32c_tbl[2][(x >> 40) & 0xff] ^
        crc32c_tbl[1][(x >> 48) & 0xff] ^ crc32c_tbl[0][(x >> 56) & 0xff];
    p += 8;
    len -= 8;
  }
  while (len--) c = crc32c_tbl[0][(c ^ *p++) & 0xff] ^ (c >> 8);
  return c ^ 0xffffffffu;
}

}  // extern "C"
