"""Elastic search workers: one SearchBlockRequest in, one SearchResponse out.

Role-equivalent to cmd/tempo-serverless (handler.go:50-112): a stateless
process that executes exactly one frontend-sharded search job against
object storage — the scale-out burst tier queriers proxy to (reference
querier.searchExternalEndpoint with hedging + prefer-self). Here the
worker owns a TPU-backed TempoDB reader over the shared backend; deploy N
of them behind any HTTP balancer for elastic read capacity.

Protocol: POST /search-block, body = serialized tempopb.SearchBlockRequest,
response = serialized tempopb.SearchResponse (content-type
application/protobuf).
"""

from __future__ import annotations

from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tempo_tpu import tempopb
from tempo_tpu.backend.raw import RawBackend
from tempo_tpu.db import TempoDB, TempoDBConfig


class SearchWorker:
    def __init__(self, backend: RawBackend, cfg: TempoDBConfig | None = None,
                 wal_dir: str = "/tmp/tempo-tpu-worker-wal"):
        self.db = TempoDB(backend, wal_dir, cfg)

    def handle(self, req: tempopb.SearchBlockRequest) -> tempopb.SearchResponse:
        return self.db.search_block(req).response()


def serve_worker(worker: SearchWorker, host: str = "0.0.0.0", port: int = 0):
    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):  # noqa: N802 — stdlib API
            if self.path != "/search-block":
                self.send_error(404)
                return
            length = int(self.headers.get("Content-Length", 0))
            req = tempopb.SearchBlockRequest()
            try:
                req.ParseFromString(self.rfile.read(length))
            except Exception as e:  # noqa: BLE001 — malformed body
                # 400, not 500: the hedging caller retries 5xx, and a
                # body that never parsed will never parse
                self.send_error(400, str(e))
                return
            try:
                resp = worker.handle(req)
            except Exception as e:  # noqa: BLE001 — one job, one error
                self.send_error(500, str(e))
                return
            body = resp.SerializeToString()
            self.send_response(200)
            self.send_header("Content-Type", "application/protobuf")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    return ThreadingHTTPServer((host, port), Handler)
