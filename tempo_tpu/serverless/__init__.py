from .handler import SearchWorker, serve_worker

__all__ = ["SearchWorker", "serve_worker"]
