"""WAL-side search block: appended live, linearly scanned, crash-replayed.

Role-equivalent to the reference's StreamingSearchBlock
(tempodb/search/streaming_search_block.go:22-237) and RescanBlocks
(rescan_blocks.go:20-107): search data for traces in the head block is
appended to a sidecar WAL file (`<wal name>.search`); searches over live /
not-yet-completed data scan it on the host; on block completion the
entries feed the columnar backend search block build.
"""

from __future__ import annotations

import os

from tempo_tpu import tempopb
from tempo_tpu.encoding.v2.objects import marshal_object, unmarshal_objects
from .data import (
    SearchData,
    clone_search_data,
    decode_search_data,
    encode_search_data,
)
from .pipeline import UINT32_MAX
from tempo_tpu.utils.ids import pad_trace_id


class StreamingSearchBlock:
    def __init__(self, path: str, _replay: bool = False):
        self.path = path
        self._entries: dict[bytes, SearchData] = {}
        # epoch versions the entry set for the hot-tier scan cache; the
        # stage itself builds lazily (first gate-on search) so gate-off
        # and write-only processes never import the kernel machinery
        self._epoch = 0
        self._stage = None
        if _replay:
            self._replay()
            self._fh = open(path, "ab")
        else:
            self._fh = open(path, "wb")

    def append(self, trace_id: bytes, sd: SearchData) -> None:
        tid = pad_trace_id(trace_id)
        self._fh.write(marshal_object(tid, encode_search_data(sd)))
        self._fh.flush()
        self._merge(tid, sd)

    def _merge(self, tid: bytes, sd: SearchData) -> None:
        cur = self._entries.get(tid)
        if cur is None:
            sd.trace_id = tid
            self._entries[tid] = sd
        else:
            # copy-on-write: published entries stay immutable so the
            # hot-tier scan can build pages from a snapshot of
            # references without holding the instance lock
            merged = clone_search_data(cur)
            merged.merge(sd)
            self._entries[tid] = merged
        self._epoch += 1

    def __len__(self) -> int:
        return len(self._entries)

    def entries(self) -> list[SearchData]:
        """Merged entries in ascending trace-id order (feeds the columnar
        build at completion)."""
        return [self._entries[t] for t in sorted(self._entries)]

    # ---- host linear scan (live/WAL data volume is small) ----

    # entries checked between request-deadline reads on the legacy walk:
    # cheap enough to bound overrun, coarse enough that the contextvar
    # read never shows up against per-entry match cost
    _DEADLINE_STRIDE = 256

    def search(self, req: tempopb.SearchRequest, results) -> None:
        from tempo_tpu.robustness import deadline as rdeadline

        from .data import search_data_matches
        from .live_tier import LIVE_TIER

        if rdeadline.expired():
            # the budget is already gone: book partial instead of
            # walking a potentially huge live set (PR 9 contract — the
            # batcher's legs already respect this)
            self._book_deadline(results)
            return
        if LIVE_TIER.enabled:
            from .live_tier import _HotStage, scan_search_data

            if self._stage is None:
                self._stage = _HotStage()
            if scan_search_data(self.entries(), req, results,
                                self._stage, self._epoch):
                return
        for i, sd in enumerate(self._entries.values()):
            if i % self._DEADLINE_STRIDE == 0 and i and rdeadline.expired():
                self._book_deadline(results)
                return
            results.metrics.inspected_traces += 1
            if search_data_matches(sd, req):
                results.add(_meta_from_sd(sd))
                if results.complete:
                    return

    @staticmethod
    def _book_deadline(results) -> None:
        from tempo_tpu.observability import metrics as obs

        results.metrics.partial = True
        obs.partial_results.inc(reason="deadline")

    # ---- lifecycle ----

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None

    def clear(self) -> None:
        self.close()
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass

    def _replay(self) -> None:
        with open(self.path, "rb") as f:
            buf = f.read()
        off = 0
        for tid, payload in unmarshal_objects(buf, tolerate_truncation=True):
            off += 8 + len(tid) + len(payload)
            try:
                sd = decode_search_data(payload, tid)
            except Exception:
                continue  # skip a corrupt entry, keep scanning
            self._merge(tid, sd)
        if off < len(buf):
            with open(self.path, "ab") as f:
                f.truncate(off)

    @classmethod
    def rescan(cls, path: str) -> "StreamingSearchBlock":
        return cls(path, _replay=True)


def _meta_from_sd(sd: SearchData) -> "tempopb.TraceSearchMetadata":
    m = tempopb.TraceSearchMetadata()
    m.trace_id = sd.trace_id.hex()
    m.start_time_unix_nano = sd.start_ns
    m.duration_ms = min(sd.dur_ms, UINT32_MAX)
    m.root_service_name = sd.root_service
    m.root_trace_name = sd.root_name
    return m
