"""Structural query IR: a small typed tree compiled onto the scan kernels.

The reference era's search language is ``tag = value AND duration
range`` — one conjunctive predicate per request, interpreted per entry.
This module is the front half of the structural query engine
(docs/search-structural-queries.md): a typed IR with

  - **span-scope leaves**: tag term (substring, the engine-wide
    semantics), duration range, span kind;
  - **combinators**: AND / OR / NOT at both span and trace scope;
  - **structural relations**: ``child`` (parent-child) and ``desc``
    (ancestor-descendant) joining two span-level sub-predicates;
  - **scopes**: span-level expressions select spans, trace-level
    expressions select traces;
  - **aggregates**: ``count(matching spans) CMP n`` and duration
    quantiles over matched spans, lowered to exact integer-count
    predicates (nearest-rank; see ``Quantile``).

Parsed from a compact JSON form on the HTTP search API (``?q=``).
Parse failures raise :class:`IRSyntaxError` carrying the JSON path of
the offending node (``$.and[1].count.op``) — the HTTP layer maps it to
a 400 with that diagnostic, never a 500 from deep in compile
(docs/api.md#structural-queries documents the query form and the error
shape).

The back half — lowering onto the fused device kernels — lives in
search/structural.py (TiLT's idiom, arxiv 2301.12030: compile the
query into an imperative kernel instead of interpreting a tree per
row).
"""

from __future__ import annotations

import json
import urllib.parse
from dataclasses import dataclass
from typing import Union

__all__ = [
    "IRSyntaxError",
    "SpanExpr", "SpanTag", "SpanDur", "SpanKind",
    "SpanAnd", "SpanOr", "SpanNot", "ChildOf", "DescOf",
    "TraceExpr", "TraceTag", "TraceDur",
    "Exists", "Count", "Quantile",
    "TraceAnd", "TraceOr", "TraceNot",
    "parse", "parse_quoted", "to_json", "quote", "node_count",
    "CMP_OPS", "SPAN_KINDS", "MAX_NODES", "MAX_Q_DEN",
]

# comparison operators shared by count/quantile aggregates; the device
# lowering and the host evaluator consume the same table
CMP_OPS = (">", ">=", "<", "<=", "==", "!=")

# OTLP span kinds (trace.proto SpanKind) by wire value; the JSON form
# accepts either the symbolic name or the integer
SPAN_KINDS = {
    "unspecified": 0,
    "internal": 1,
    "server": 2,
    "client": 3,
    "producer": 4,
    "consumer": 5,
}

# defensive caps — a parse-time bound so a hostile query can neither
# explode the compiled plan nor the integer math the quantile lowering
# depends on (q_den * span_count must stay within int32 on device)
MAX_NODES = 64
MAX_Q_DEN = 1000
UINT32_MAX = 0xFFFFFFFF


class IRSyntaxError(ValueError):
    """Malformed structural query: client data, mapped to HTTP 400.

    ``path`` is the JSON path of the offending node (``$.count.op``) so
    the client can locate the mistake without reading server code."""

    def __init__(self, path: str, message: str) -> None:
        self.path = path
        super().__init__(f"{message} (at {path})")


# ---------------------------------------------------------------------------
# node types — frozen, hashable, order-stable


@dataclass(frozen=True)
class SpanTag:
    """Span-scope tag term: some kv of THIS span has key ``key`` and a
    value containing ``value`` (the engine-wide substring semantics;
    empty ``value`` matches any value under the key)."""

    key: str
    value: str


@dataclass(frozen=True)
class SpanDur:
    """Span duration within [lo_ms, hi_ms] inclusive."""

    lo_ms: int
    hi_ms: int


@dataclass(frozen=True)
class SpanKind:
    """Span kind equals ``kind`` (OTLP wire value)."""

    kind: int


@dataclass(frozen=True)
class SpanAnd:
    args: tuple["SpanExpr", ...]


@dataclass(frozen=True)
class SpanOr:
    args: tuple["SpanExpr", ...]


@dataclass(frozen=True)
class SpanNot:
    arg: "SpanExpr"


@dataclass(frozen=True)
class ChildOf:
    """Spans matching ``child`` whose DIRECT parent matches ``parent``."""

    parent: "SpanExpr"
    child: "SpanExpr"


@dataclass(frozen=True)
class DescOf:
    """Spans matching ``span`` with SOME proper ancestor matching
    ``anc``."""

    anc: "SpanExpr"
    span: "SpanExpr"


SpanExpr = Union[SpanTag, SpanDur, SpanKind, SpanAnd, SpanOr, SpanNot,
                 ChildOf, DescOf]


@dataclass(frozen=True)
class TraceTag:
    """Trace-scope tag term over the per-trace rolled-up kv set (the
    legacy request's ``tags`` semantics as an IR leaf)."""

    key: str
    value: str


@dataclass(frozen=True)
class TraceDur:
    """Whole-trace duration within [lo_ms, hi_ms] inclusive."""

    lo_ms: int
    hi_ms: int


@dataclass(frozen=True)
class Exists:
    """Trace has at least one span matching ``of``."""

    of: SpanExpr


@dataclass(frozen=True)
class Count:
    """count(spans matching ``of``) CMP ``n``."""

    of: SpanExpr
    op: str
    n: int


@dataclass(frozen=True)
class Quantile:
    """Nearest-rank duration quantile over matched spans, compared to a
    millisecond threshold: with ``m`` matched spans the rank is
    ``r = max(1, ceil(q * m))`` and the quantile value is the r-th
    smallest duration. ``q`` is the exact rational ``q_num/q_den`` so
    host and device use identical integer math (no float divergence);
    zero matched spans make the predicate False."""

    of: SpanExpr
    q_num: int
    q_den: int
    op: str
    x_ms: int


@dataclass(frozen=True)
class TraceAnd:
    args: tuple["TraceExpr", ...]


@dataclass(frozen=True)
class TraceOr:
    args: tuple["TraceExpr", ...]


@dataclass(frozen=True)
class TraceNot:
    arg: "TraceExpr"


TraceExpr = Union[TraceTag, TraceDur, Exists, Count, Quantile,
                  TraceAnd, TraceOr, TraceNot]


def node_count(node: object) -> int:
    """Total nodes in the tree (the MAX_NODES budget unit)."""
    if isinstance(node, (SpanAnd, SpanOr, TraceAnd, TraceOr)):
        return 1 + sum(node_count(a) for a in node.args)
    if isinstance(node, (SpanNot, TraceNot)):
        return 1 + node_count(node.arg)
    if isinstance(node, ChildOf):
        return 1 + node_count(node.parent) + node_count(node.child)
    if isinstance(node, DescOf):
        return 1 + node_count(node.anc) + node_count(node.span)
    if isinstance(node, (Exists, Count, Quantile)):
        return 1 + node_count(node.of)
    return 1


# ---------------------------------------------------------------------------
# JSON form


def _err(path: str, msg: str) -> IRSyntaxError:
    return IRSyntaxError(path, msg)


def _one_key(doc: object, path: str) -> tuple[str, object]:
    if not isinstance(doc, dict):
        raise _err(path, f"expected an object, got {type(doc).__name__}")
    if len(doc) != 1:
        raise _err(path, "expected exactly one operator key, got "
                         f"{sorted(str(k) for k in doc)!r}")
    k, v = next(iter(doc.items()))
    if not isinstance(k, str):
        raise _err(path, "operator key must be a string")
    return k, v


def _parse_int(v: object, path: str, lo: int = 0,
               hi: int = UINT32_MAX) -> int:
    if isinstance(v, bool) or not isinstance(v, int):
        raise _err(path, f"expected an integer, got {type(v).__name__}")
    if not lo <= v <= hi:
        raise _err(path, f"value {v} out of range [{lo}, {hi}]")
    return v


def _parse_str(v: object, path: str) -> str:
    if not isinstance(v, str):
        raise _err(path, f"expected a string, got {type(v).__name__}")
    return v


def _parse_tag(v: object, path: str) -> tuple[str, str]:
    if not isinstance(v, dict):
        raise _err(path, "tag expects {\"k\": key, \"v\": substring}")
    extra = set(v) - {"k", "v"}
    if extra:
        raise _err(path, f"unknown tag field(s) {sorted(extra)!r}")
    if "k" not in v:
        raise _err(path + ".k", "tag key \"k\" is required")
    key = _parse_str(v["k"], path + ".k")
    if not key:
        raise _err(path + ".k", "tag key must be non-empty")
    val = _parse_str(v.get("v", ""), path + ".v")
    return key, val


def _parse_dur(v: object, path: str) -> tuple[int, int]:
    if not isinstance(v, dict):
        raise _err(path, "dur expects {\"min_ms\": int, \"max_ms\": int}")
    extra = set(v) - {"min_ms", "max_ms"}
    if extra:
        raise _err(path, f"unknown dur field(s) {sorted(extra)!r}")
    lo = _parse_int(v.get("min_ms", 0), path + ".min_ms")
    hi = _parse_int(v.get("max_ms", UINT32_MAX), path + ".max_ms")
    if lo > hi:
        raise _err(path, f"empty duration range [{lo}, {hi}]")
    return lo, hi


def _parse_op(v: object, path: str) -> str:
    op = _parse_str(v, path)
    if op not in CMP_OPS:
        raise _err(path, f"unknown comparison {op!r}; one of {CMP_OPS}")
    return op


def _parse_q(v: object, path: str) -> tuple[int, int]:
    """Quantile as an exact rational: accepts a decimal string
    ("0.9", "0.99") or a number. Strings are preferred — they carry the
    author's exact precision; floats round-trip through their shortest
    repr. Denominator capped at MAX_Q_DEN so the device-side integer
    rank math stays within int32."""
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        v = repr(float(v))
    s = _parse_str(v, path).strip()
    try:
        if "." in s:
            whole, frac = s.split(".", 1)
            if not (whole + frac).isdigit() or len(frac) == 0:
                raise ValueError
            den = 10 ** len(frac)
            num = int(whole) * den + int(frac)
        else:
            if not s.isdigit():
                raise ValueError
            num, den = int(s), 1
    except ValueError:
        raise _err(path, f"quantile {s!r} is not a decimal in (0, 1]") \
            from None
    if den > MAX_Q_DEN:
        raise _err(path, f"quantile precision beyond 1/{MAX_Q_DEN} "
                         "is not supported")
    if not 0 < num <= den:
        raise _err(path, f"quantile {s!r} must be in (0, 1]")
    return num, den


def _parse_kind(v: object, path: str) -> int:
    if isinstance(v, str):
        k = SPAN_KINDS.get(v.lower())
        if k is None:
            raise _err(path, f"unknown span kind {v!r}; one of "
                             f"{sorted(SPAN_KINDS)} or 0-5")
        return k
    return _parse_int(v, path, lo=0, hi=5)


def _parse_span(doc: object, path: str) -> SpanExpr:
    op, v = _one_key(doc, path)
    if op == "tag":
        return SpanTag(*_parse_tag(v, path + ".tag"))
    if op == "dur":
        return SpanDur(*_parse_dur(v, path + ".dur"))
    if op == "kind":
        return SpanKind(_parse_kind(v, path + ".kind"))
    if op in ("and", "or"):
        if not isinstance(v, list) or not v:
            raise _err(path + f".{op}", f"{op} expects a non-empty array")
        args = tuple(_parse_span(a, f"{path}.{op}[{i}]")
                     for i, a in enumerate(v))
        return SpanAnd(args) if op == "and" else SpanOr(args)
    if op == "not":
        return SpanNot(_parse_span(v, path + ".not"))
    if op == "child":
        if not isinstance(v, dict) or set(v) != {"parent", "child"}:
            raise _err(path + ".child",
                       "child expects {\"parent\": span, \"child\": span}")
        return ChildOf(_parse_span(v["parent"], path + ".child.parent"),
                       _parse_span(v["child"], path + ".child.child"))
    if op == "desc":
        if not isinstance(v, dict) or set(v) != {"anc", "span"}:
            raise _err(path + ".desc",
                       "desc expects {\"anc\": span, \"span\": span}")
        return DescOf(_parse_span(v["anc"], path + ".desc.anc"),
                      _parse_span(v["span"], path + ".desc.span"))
    raise _err(path, f"unknown span operator {op!r}")


def _parse_trace(doc: object, path: str) -> TraceExpr:
    op, v = _one_key(doc, path)
    if op == "tag":
        return TraceTag(*_parse_tag(v, path + ".tag"))
    if op == "dur":
        return TraceDur(*_parse_dur(v, path + ".dur"))
    if op == "exists":
        return Exists(_parse_span(v, path + ".exists"))
    if op == "count":
        if not isinstance(v, dict):
            raise _err(path + ".count", "count expects "
                       "{\"of\": span, \"op\": cmp, \"n\": int}")
        extra = set(v) - {"of", "op", "n"}
        if extra:
            raise _err(path + ".count",
                       f"unknown count field(s) {sorted(extra)!r}")
        if "of" not in v:
            raise _err(path + ".count.of", "count \"of\" is required")
        return Count(
            of=_parse_span(v["of"], path + ".count.of"),
            op=_parse_op(v.get("op", ">"), path + ".count.op"),
            n=_parse_int(v.get("n", 0), path + ".count.n",
                         hi=2**31 - 1),
        )
    if op == "quantile":
        if not isinstance(v, dict):
            raise _err(path + ".quantile", "quantile expects {\"of\": "
                       "span, \"q\": \"0.9\", \"op\": cmp, \"ms\": int}")
        extra = set(v) - {"of", "q", "op", "ms"}
        if extra:
            raise _err(path + ".quantile",
                       f"unknown quantile field(s) {sorted(extra)!r}")
        for req_field in ("of", "q", "ms"):
            if req_field not in v:
                raise _err(f"{path}.quantile.{req_field}",
                           f"quantile \"{req_field}\" is required")
        q_num, q_den = _parse_q(v["q"], path + ".quantile.q")
        return Quantile(
            of=_parse_span(v["of"], path + ".quantile.of"),
            q_num=q_num, q_den=q_den,
            op=_parse_op(v.get("op", ">="), path + ".quantile.op"),
            x_ms=_parse_int(v["ms"], path + ".quantile.ms"),
        )
    if op in ("and", "or"):
        if not isinstance(v, list) or not v:
            raise _err(path + f".{op}", f"{op} expects a non-empty array")
        args = tuple(_parse_trace(a, f"{path}.{op}[{i}]")
                     for i, a in enumerate(v))
        return TraceAnd(args) if op == "and" else TraceOr(args)
    if op == "not":
        return TraceNot(_parse_trace(v, path + ".not"))
    # a bare span operator at trace scope is sugar for exists
    if op in ("child", "desc"):
        return Exists(_parse_span(doc, path))
    raise _err(path, f"unknown trace operator {op!r}")


def parse(text: str) -> TraceExpr:
    """Parse the compact JSON form into a trace-level IR tree. Raises
    :class:`IRSyntaxError` (a ValueError subtype the API layer maps to
    400) with a JSON-path diagnostic on any malformed input."""
    try:
        doc = json.loads(text)
    except json.JSONDecodeError as e:
        raise IRSyntaxError("$", f"invalid JSON: {e.msg} "
                                 f"(line {e.lineno} col {e.colno})") \
            from None
    expr = _parse_trace(doc, "$")
    n = node_count(expr)
    if n > MAX_NODES:
        raise _err("$", f"query has {n} nodes; the limit is {MAX_NODES}")
    return expr


# ---------------------------------------------------------------------------
# serialization — the request-tag transport (search/structural.py stows
# the percent-quoted compact JSON in a reserved tag so the IR survives
# the frontend <-> querier proto round-trip without a schema change)


def to_json(node: object) -> str:
    """Compact canonical JSON of an IR tree (inverse of :func:`parse`)."""
    return json.dumps(_unparse(node), separators=(",", ":"),
                      sort_keys=True)


def _unparse(node: object) -> dict[str, object]:
    if isinstance(node, (SpanTag, TraceTag)):
        return {"tag": {"k": node.key, "v": node.value}}
    if isinstance(node, (SpanDur, TraceDur)):
        return {"dur": {"min_ms": node.lo_ms, "max_ms": node.hi_ms}}
    if isinstance(node, SpanKind):
        return {"kind": node.kind}
    if isinstance(node, (SpanAnd, TraceAnd)):
        return {"and": [_unparse(a) for a in node.args]}
    if isinstance(node, (SpanOr, TraceOr)):
        return {"or": [_unparse(a) for a in node.args]}
    if isinstance(node, (SpanNot, TraceNot)):
        return {"not": _unparse(node.arg)}
    if isinstance(node, ChildOf):
        return {"child": {"parent": _unparse(node.parent),
                          "child": _unparse(node.child)}}
    if isinstance(node, DescOf):
        return {"desc": {"anc": _unparse(node.anc),
                         "span": _unparse(node.span)}}
    if isinstance(node, Exists):
        return {"exists": _unparse(node.of)}
    if isinstance(node, Count):
        return {"count": {"of": _unparse(node.of), "op": node.op,
                          "n": node.n}}
    if isinstance(node, Quantile):
        return {"quantile": {"of": _unparse(node.of),
                             "q": _q_decimal(node.q_num, node.q_den),
                             "op": node.op, "ms": node.x_ms}}
    raise TypeError(f"not an IR node: {type(node).__name__}")


def _q_decimal(num: int, den: int) -> str:
    """Exact decimal form of a quantile rational, guaranteed to
    re-parse: ``q=1`` must emit "1", never "1." (float-format rstrip
    produced exactly that unparseable form). Integer math throughout;
    a denominator with no short decimal expansion (only reachable from
    hand-built trees — the parser produces powers of ten) rounds to the
    parser's maximum precision."""
    if num == den:
        return "1"
    if den == 1:
        return str(num)
    for k in range(1, 10):
        scaled = num * 10 ** k
        if scaled % den == 0:
            return f"0.{scaled // den:0{k}d}"
    return f"{num / den:.3f}"


def quote(text: str) -> str:
    """Percent-encode the JSON for the reserved request tag: the tag
    wire form (api/params logfmt encoding) splits on spaces and '=' —
    quoting with no safe characters removes both."""
    return urllib.parse.quote(text, safe="")


def parse_quoted(quoted: str) -> TraceExpr:
    """Parse the percent-encoded transport form out of a request tag."""
    return parse(urllib.parse.unquote(quoted))
