"""Hot-tier live search: rolling device batches over in-flight traces.

The reference era only sees a trace after flush + poll (PAPER.md:
FlatBuffer-search era) — measured push→searchable is flush+poll bound
at p50 2.6s / p99 3.4s. This module closes the gap: the ingesters'
LIVE (not-yet-cut) traces absorb into a per-tenant rolling columnar
stage scanned by the SAME fused scan kernel as backend blocks, and the
WAL head/completing generations scan through the identical machinery
via :func:`scan_search_data` (the `StreamingSearchBlock` gate-on path).
The per-entry Python `search_data_matches` walk becomes the gate-off
fallback route.

Staging is epoch-versioned micro-batching: every absorb/evict bumps the
tenant epoch; a search rebuilds the columnar container only when the
epoch moved, and the container's page axis pads to a fixed pow2 `tier`
capacity so the jit key stays SHAPE-ONLY — absorbing entries within a
tier re-enters the same compiled kernel with a new traced live count;
only a tier overflow (capacity doubling) pays a fresh XLA trace.

Eviction follows the ingester lifecycle: a cut trace leaves the live
stage for the WAL head (scanned there), a completed block leaves the
WAL for the ingester's recently-flushed list, and the recently-flushed
leg retires EARLY once the backend block is poll-visible
(`mark_poll_visible`, fed by TempoDB.poll) so the reader leg and the
ingester leg never double-answer; the 300s recently-flushed window
remains the cross-process bound.

On top of the tier rides the tail-subscription API ("push me spans
matching P as they arrive"): standing queries registered per tenant,
evaluated against each push micro-batch, bounded queues with drop-oldest
overflow, per-tenant subscription caps.

`search_live_tier_enabled` false (default) is a TRUE noop: every hook
reads one attribute and returns; search takes the existing per-entry
walk byte-identically (asserted by tests/test_live_tier.py and the
analysis noop contracts).
"""

from __future__ import annotations

import functools
import threading
from collections import deque

import jax
import jax.numpy as jnp

from .data import (
    SearchData,
    clone_search_data,
    decode_search_data,
    search_data_matches,
)
from .engine import (
    StagedPages,
    _bucket,
    cpu_pinned,
    fetch_scan_out,
    pad_page_axis,
    scan_kernel,
)


def _tier_valid(entry_valid, n_pages, tier):
    """Mask capacity pages beyond the tenant's live page count.

    `tier` is the hot stage's static pow2 page-capacity descriptor —
    part of the jit key (static_argnames), so absorbing entries within
    a tier re-runs the SAME compiled kernel with only the traced
    `n_pages` changing; a tier overflow recompiles once for the doubled
    capacity. None = container staged without capacity semantics
    (passthrough, the legacy full-page layout).
    """
    if tier is None:
        return entry_valid
    page_live = (jnp.arange(entry_valid.shape[0], dtype=jnp.int32)[:, None]
                 < n_pages)
    return jnp.logical_and(entry_valid, page_live)


@functools.partial(jax.jit,
                   static_argnames=("n_terms", "top_k", "plan", "tier"))
def hot_scan_kernel(kv_key, kv_val, entry_start, entry_end, entry_dur,
                    entry_valid, n_pages, term_keys, val_ranges, dur_lo,
                    dur_hi, win_start, win_end, span_cols=None,
                    s_tables=None, *, n_terms, top_k, plan=None, tier=None):
    """The hot-tier dispatch: scan_kernel over a capacity-padded rolling
    stage. Delegation keeps it byte-identical to the backend-block scan
    — same match mask, same masked top-k — with one prelude: the static
    `tier` capacity descriptor masks pages beyond the traced live count
    so a stage scanned mid-absorb never reads a stale capacity page."""
    entry_valid = _tier_valid(entry_valid, n_pages, tier)
    return scan_kernel(kv_key, kv_val, entry_start, entry_end, entry_dur,
                       entry_valid, term_keys, val_ranges, dur_lo, dur_hi,
                       win_start, win_end, None, None, span_cols, s_tables,
                       n_terms=n_terms, top_k=top_k, widths=None, plan=plan)


class _HotStage:
    """Epoch-cached columnar build over one entry set. Rebuilds only
    when the epoch moved; the page axis pads to the pow2 `tier` so the
    kernel's jit key is shape-only (see module docstring)."""

    def __init__(self):
        self.epoch = -1
        self.pages = None
        self.tier = 0
        self.host = None       # capacity-padded DEVICE_ARRAYS dict
        self.span_host = None  # staged span columns (structural), or None
        self.span_stale = True

    def ensure(self, entries: list[SearchData], epoch: int):
        if self.epoch == epoch and self.pages is not None:
            return self.pages
        from .columnar import ColumnarPages

        pages = ColumnarPages.build(entries)
        self.pages = pages
        self.tier = _bucket(pages.n_pages)
        self.host = pad_page_axis(pages, self.tier)
        self.span_host = None
        self.span_stale = True
        self.epoch = epoch
        from tempo_tpu.observability import metrics as obs

        obs.live_tier_rebuilds.inc()
        return pages

    def span_columns(self):
        """Lazily staged structural span columns (only a structural
        request pays the staging)."""
        if self.span_stale:
            from .structural import STRUCTURAL

            self.span_host = None
            if STRUCTURAL.enabled:
                self.span_host = STRUCTURAL.stage_single(self.pages,
                                                         self.tier)
            self.span_stale = False
        return self.span_host


def scan_search_data(entries: list[SearchData], req, results,
                     stage: _HotStage, epoch: int) -> bool:
    """Kernel-scan a SearchData set — the replacement for the per-entry
    Python `search_data_matches` walk. Byte-identical to the
    backend-block host scan: same dictionary compile (may prune), same
    compiled structural plan (eval_host stays the gate-off route), same
    masked top-k and render path. Returns True when the scan handled
    the request (results updated; a dictionary prune counts — nothing
    could match), False when the caller must run the legacy walk."""
    from .backend_search_block import default_engine
    from .pipeline import compile_query
    from . import structural as _structural

    if not entries:
        return True
    engine = default_engine()
    pages = stage.ensure(entries, epoch)
    cq = compile_query(pages.key_dict, pages.val_dict, req,
                       cache_on=pages, host_only=True)
    expr = _structural.structural_query(req)
    if cq is not None and expr is not None:
        cq.structural = _structural.compile_structural(
            expr, [pages], cache_on=pages, host_only=True,
            entry_kv_slots=pages.geometry.kv_per_entry)
    if cq is None:  # dictionary prefilter pruned: no entry can match
        return True
    top_k = engine._resolve_top_k(cq)
    st = getattr(cq, "structural", None)
    with cpu_pinned():
        dev = {k: jnp.asarray(v) for k, v in stage.host.items()}
        plan = s_tables = span_dev = None
        if st is not None:
            plan = st.plan
            s_tables = tuple(jnp.asarray(t) if t is not None else None
                             for t in st.tables())
            span_host = stage.span_columns()
            if span_host is not None:
                span_dev = {k: jnp.asarray(v) for k, v in span_host.items()}
        out = hot_scan_kernel(
            dev["kv_key"], dev["kv_val"], dev["entry_start"],
            dev["entry_end"], dev["entry_dur"], dev["entry_valid"],
            jnp.int32(pages.n_pages),
            jnp.asarray(cq.term_keys), jnp.asarray(cq.val_ranges),
            jnp.uint32(cq.dur_lo), jnp.uint32(min(cq.dur_hi, 0xFFFFFFFF)),
            jnp.uint32(cq.win_start),
            jnp.uint32(min(cq.win_end, 0xFFFFFFFF)),
            span_dev, s_tables,
            n_terms=cq.n_terms, top_k=top_k, plan=plan, tier=stage.tier)
        _, inspected, scores, idx = fetch_scan_out(out)
    results.metrics.inspected_traces += inspected
    holder = StagedPages(device={}, n_pages=pages.n_pages, pages=pages)
    for m in engine.results(holder, cq, scores, idx):
        results.add(m)
    return True


class TailSubscription:
    """One standing query: a bounded notification queue with drop-oldest
    overflow (a slow consumer loses the OLDEST notifications and sees
    its `dropped` count rise, it never blocks the push path)."""

    def __init__(self, tenant: str, req, max_queue: int = 256):
        self.tenant = tenant
        self.req = req
        self.dropped = 0
        self.closed = False
        self._q: deque = deque()
        self._max_queue = max_queue
        self._cond = threading.Condition()

    def offer(self, meta) -> None:
        with self._cond:
            if self.closed:
                return
            if len(self._q) >= self._max_queue:
                self._q.popleft()
                self.dropped += 1
                from tempo_tpu.observability import metrics as obs

                obs.live_tail_dropped.inc(reason="queue",
                                          tenant=self.tenant)
            self._q.append(meta)
            self._cond.notify_all()

    def poll(self, timeout_s: float | None = None) -> list:
        """Drain pending notifications, blocking up to timeout_s for the
        first one. Returns [] on timeout or once closed."""
        with self._cond:
            if not self._q and not self.closed:
                self._cond.wait(timeout_s)
            out = list(self._q)
            self._q.clear()
            return out

    def close(self) -> None:
        with self._cond:
            self.closed = True
            self._cond.notify_all()


class _TenantHot:
    def __init__(self):
        self.entries: dict[bytes, SearchData] = {}  # live (uncut) traces
        self.epoch = 0
        self.stage = _HotStage()
        self.visible: set[str] = set()  # poll-visible backend block ids
        self.subs: list[TailSubscription] = []


class LiveTier:
    """Process-wide hot-tier gate + per-tenant rolling stages (the
    PACKING/STRUCTURAL/OWNERSHIP singleton idiom: the most recent
    TempoDB's config wins; `enabled=False` is a true noop — one
    attribute read per hook)."""

    def __init__(self):
        self.enabled = False
        self.max_entries = 4096
        self.max_subscriptions = 16
        self._lock = threading.Lock()
        self._tenants: dict[str, _TenantHot] = {}

    def configure(self, enabled: bool = False, max_entries: int = 4096,
                  max_subscriptions: int = 16) -> None:
        with self._lock:
            self.max_entries = int(max_entries)
            self.max_subscriptions = int(max_subscriptions)
            self._tenants = {}
            self.enabled = bool(enabled)

    def _tenant(self, tenant: str) -> _TenantHot:
        t = self._tenants.get(tenant)
        if t is None:
            t = self._tenants[tenant] = _TenantHot()
        return t

    # ---- ingest-side hooks (called with the instance lock held, so
    # tier state mirrors the ingester's live set deterministically; the
    # lock order instance.lock → tier lock is acyclic — LiveTier never
    # calls back into the ingester) ----

    def absorb(self, tenant: str, trace_id: bytes, raw: bytes) -> None:
        """Absorb one push micro-batch member into the live stage.
        Corrupt SearchData drops silently — exactly the lazy-decode
        behavior of `_LiveTrace.search_data`."""
        if not self.enabled:
            return
        if not raw:
            return
        try:
            sd = decode_search_data(raw, trace_id)
        except Exception:  # noqa: BLE001 — mirror the lazy-decode drop
            return
        with self._lock:
            t = self._tenant(tenant)
            prev = t.entries.get(trace_id)
            if prev is not None:
                merged = clone_search_data(prev)
                merged.merge(sd)
                t.entries[trace_id] = merged
            else:
                t.entries[trace_id] = sd
            t.epoch += 1
            n = len(t.entries)
        from tempo_tpu.observability import metrics as obs

        obs.live_tier_entries.set(n, tenant=tenant)

    def mark_cut(self, tenant: str, trace_ids) -> None:
        """Cut traces leave the live stage — they are now WAL-head
        entries, scanned there (StreamingSearchBlock's gate-on path)."""
        if not self.enabled:
            return
        with self._lock:
            t = self._tenants.get(tenant)
            if t is None:
                return
            evicted = 0
            for tid in trace_ids:
                if t.entries.pop(tid, None) is not None:
                    evicted += 1
            if evicted:
                t.epoch += 1
            n = len(t.entries)
        if evicted:
            from tempo_tpu.observability import metrics as obs

            obs.live_tier_evictions.inc(evicted, reason="cut")
            obs.live_tier_entries.set(n, tenant=tenant)

    def drop_tenant(self, tenant: str) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._tenants.pop(tenant, None)

    # ---- poll-visibility (fed by TempoDB.poll on the reader) ----

    def mark_poll_visible(self, metas_by_tenant: dict) -> None:
        """Record the backend blocks the reader's poll made visible.
        The ingester's recently-flushed leg consults this set to retire
        a flushed block EARLY (the reader leg now answers for it) —
        without it, both legs scan the block for the full 300s
        recently-flushed window and dedupe eats the duplicates."""
        if not self.enabled:
            return
        with self._lock:
            for tenant, ms in metas_by_tenant.items():
                self._tenant(tenant).visible = {
                    m.block_id for m in ms}

    def poll_visible(self, tenant: str, block_id: str) -> bool:
        if not self.enabled:
            return False
        with self._lock:
            t = self._tenants.get(tenant)
            return t is not None and block_id in t.visible

    # ---- search ----

    def search(self, tenant: str, req, results) -> bool:
        """Kernel-scan the tenant's live stage. Returns True when the
        hot tier answered (the caller must NOT run the legacy per-entry
        walk), False on gate-off or overflow (stage past max_entries —
        the caller falls back to the walk and the fallback is counted)."""
        if not self.enabled:
            return False
        with self._lock:
            t = self._tenants.get(tenant)
            if t is None:
                return True  # no live traces: nothing to scan
            if len(t.entries) > self.max_entries:
                from tempo_tpu.observability import metrics as obs

                obs.live_tier_scans.inc(result="fallback_overflow")
                return False
            entries = [t.entries[tid] for tid in sorted(t.entries)]
            epoch = t.epoch
            stage = t.stage
        if not entries:
            return True
        from tempo_tpu.observability import metrics as obs

        handled = scan_search_data(entries, req, results, stage, epoch)
        obs.live_tier_scans.inc(result="scan" if handled else "fallback")
        return handled

    # ---- tail subscriptions ----

    def subscribe(self, tenant: str, req,
                  max_queue: int = 256) -> TailSubscription | None:
        """Register a standing query. None = per-tenant cap reached
        (the caller surfaces 429-style rejection)."""
        if not self.enabled:
            return None
        with self._lock:
            t = self._tenant(tenant)
            t.subs = [s for s in t.subs if not s.closed]
            if len(t.subs) >= self.max_subscriptions:
                from tempo_tpu.observability import metrics as obs

                obs.live_tail_dropped.inc(reason="cap", tenant=tenant)
                return None
            sub = TailSubscription(tenant, req, max_queue=max_queue)
            t.subs.append(sub)
            n = len(t.subs)
        from tempo_tpu.observability import metrics as obs

        obs.live_tail_subscriptions.set(n, tenant=tenant)
        return sub

    def unsubscribe(self, sub: TailSubscription) -> None:
        if not self.enabled:
            return
        sub.close()
        with self._lock:
            t = self._tenants.get(sub.tenant)
            if t is None:
                return
            t.subs = [s for s in t.subs if s is not sub and not s.closed]
            n = len(t.subs)
        from tempo_tpu.observability import metrics as obs

        obs.live_tail_subscriptions.set(n, tenant=sub.tenant)

    def has_subscribers(self, tenant: str) -> bool:
        if not self.enabled:
            return False
        with self._lock:
            t = self._tenants.get(tenant)
            return bool(t and t.subs)

    def notify_push(self, tenant: str, trace_id: bytes, raw: bytes) -> None:
        """Evaluate standing queries against one push micro-batch
        member. The decode happens at most once per push and ONLY when
        the tenant has live subscriptions; structural predicates
        evaluate via eval_host (search_data_matches), the same route the
        gate-off walk uses."""
        if not self.enabled:
            return
        with self._lock:
            t = self._tenants.get(tenant)
            subs = list(t.subs) if t else []
        if not subs or not raw:
            return
        try:
            sd = decode_search_data(raw, trace_id)
        except Exception:  # noqa: BLE001 — corrupt push: nothing to notify
            return
        meta = None
        from tempo_tpu.observability import metrics as obs

        for sub in subs:
            if sub.closed:
                continue
            if search_data_matches(sd, sub.req):
                if meta is None:
                    from .streaming import _meta_from_sd

                    meta = _meta_from_sd(sd)
                sub.offer(meta)
                obs.live_tail_notifications.inc(tenant=tenant)


LIVE_TIER = LiveTier()
