"""Device-side aggregate analytics (docs/search-analytics.md).

Two faces of one reduction idiom, both gated by
``storage.search_analytics_enabled`` (default off — every hook is one
attribute read, contract-checked like the other gates):

**Ingest side.** The metrics generator's native summary feed (fixed
56-byte rows, modules/generator.py) is a per-span Python walk on the
push-ack path: per row, a tuple build, a dict probe, a bisect, a float
divide, two lock round-trips. With the gate on, the whole micro-batch
decodes in one numpy structured view and the (series, latency-bucket)
tallies compute as ONE dense count kernel — sort the composite keys,
``searchsorted`` the key-space edges, diff — the scatter-free counting
idiom the scan kernels already use (no scatter on the VPU hot path).
The host then drains per-SERIES deltas into the exact same
``ManagedRegistry`` handles the walk would have fed: integer bucket/call
counts arrive as bulk adds, and the float latency sums fold sequentially
per series in row order, so the registry state is byte-identical to the
per-span walk (differential-fuzzed in tests/test_analytics.py).

Latency binning runs on-device WITHOUT int64 (JAX x32): the nanosecond
duration splits into two int31 limbs and each static bucket edge becomes
an integer threshold pair ``T = min{n : float64(n/1e9) > edge}`` — the
unrolled two-limb compare reproduces ``bisect_left(LATENCY_BUCKETS_S,
dur_ns/1e9)`` exactly. The threshold tuple is a static descriptor in the
jit key, like ``widths``/``plan``; rows pad to pow2 tiers (the live-tier
``_HotStage`` pattern) so successive micro-batches re-enter one compiled
kernel.

**Query side.** ``?agg=red`` rides the search request as a reserved
in-band tag (the structural-query idiom) and compiles onto the fused
scan kernels as one more static plan stage: the final verdict mask (term
predicates AND the structural plan, when present) gates which traces
contribute, and the same dense-count reduction produces group-by-service
calls/errors/latency-histogram answers in the SAME dispatch — single,
coalesced, mesh/dist, and the breaker's host route all return
byte-identical integer counts by construction. The per-entry composite
key ``(service, ms-bucket, error)`` stages once per batch from columns
the host already holds (``entry_root_svc``, ``entry_dur``, the
``error=true`` kv pair every container records for error-status spans).
"""

from __future__ import annotations

import bisect
import functools
import json
import threading
import time

import numpy as np

from tempo_tpu.observability import metrics as obs

# reserved in-band tag carrying the ?agg= spec across the frontend <->
# querier round-trip (the STRUCTURAL_QUERY_TAG / EXHAUSTIVE_SEARCH_TAG
# idiom: excluded from term compilation, probe signatures, and trace
# matching)
AGG_QUERY_TAG = "x-agg-q"

# query-side latency bucket edges in INTEGER milliseconds — the ingest
# edges (generator.LATENCY_BUCKETS_S) times 1000, kept integral because
# entry_dur is already ms and 0.002*1000 is 2.0000000000000004 in
# float64; integer edges make the query-side histogram order-free and
# byte-identical across every dispatch path
MS_BUCKETS = (2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096,
              8192, 16384)
_NB1Q = len(MS_BUCKETS) + 1         # query-side bins incl. +Inf


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


# ---------------------------------------------------------------------------
# exact two-limb duration thresholds (ingest side)

@functools.lru_cache(maxsize=4)
def _dur_thresholds(buckets: tuple) -> tuple:
    """Integer-nanosecond bucket thresholds: for each float edge ``b``,
    ``T = min{n : float64(n/1e9) > b}`` — so ``dur_ns >= T`` is exactly
    ``dur_ns/1e9 > b``, and the bin index ``sum_b [dur >= T_b]`` equals
    ``bisect_left(buckets, dur_ns/1e9)``. Returned as (hi, lo) int31
    limb pairs for the x32 device kernel (hi = T >> 31)."""
    out = []
    for b in buckets:
        n = int(b * 1e9)
        while n > 0 and n / 1e9 > b:
            n -= 1
        while n / 1e9 <= b:
            n += 1
        out.append((n >> 31, n & 0x7FFFFFFF))
    return tuple(out)


@functools.lru_cache(maxsize=4)
def _dur_thresholds_full(buckets: tuple) -> tuple:
    """The same thresholds as full integers — the host fallback's int64
    compare needs no limbs."""
    return tuple((hi << 31) | lo
                 for hi, lo in _dur_thresholds(buckets))


# ---------------------------------------------------------------------------
# the dense count kernel (shared by both ingest reductions)

def _jax():
    import jax
    import jax.numpy as jnp

    return jax, jnp


@functools.lru_cache(maxsize=1)
def _count_kernel():
    jax, jnp = _jax()

    @functools.partial(jax.jit,
                       static_argnames=("n_keys", "tier", "buckets"))
    def analytics_count_kernel(cols, *, n_keys: int, tier: int,
                               buckets):
        """Dense (series, latency-bucket) counts in one pass.

        ``cols`` is one staged int32 [3, tier] array — series index,
        duration hi limb, duration lo limb — pow2-padded (``tier`` is
        the static capacity descriptor, the live-tier idiom, so
        micro-batches within a tier re-enter this compiled kernel).
        Pad rows carry the sentinel series index ``n_keys``, which
        lands one past the counted key range. ``buckets`` is the
        static two-limb threshold descriptor; ``n_keys`` the
        pow2-padded series capacity. Counting is sort +
        searchsorted-diff: scatter-free, the layout the VPU wants."""
        nb1 = len(buckets) + 1
        series_idx, dur_hi, dur_lo = cols[0], cols[1], cols[2]
        b = jnp.zeros(series_idx.shape, dtype=jnp.int32)
        for thi, tlo in buckets:
            ge = (dur_hi > thi) | ((dur_hi == thi) & (dur_lo >= tlo))
            b = b + ge.astype(jnp.int32)
        key = jnp.minimum(series_idx * nb1 + b,
                          jnp.int32(n_keys * nb1))
        skey = jax.lax.sort(key)
        edges = jnp.searchsorted(
            skey, jnp.arange(n_keys * nb1 + 1, dtype=jnp.int32))
        return (edges[1:] - edges[:-1]).astype(jnp.int32)

    return analytics_count_kernel


# native summary-row layout (modules/generator.py _ROW, "<6IQQ8s8s");
# sid/pid decode as void8 so .tobytes() preserves trailing zero bytes —
# the pairing-store keys must match struct's full-width "8s" bytes
_ROW_DT = np.dtype([("ti", "<u4"), ("svc", "<u4"), ("name", "<u4"),
                    ("kind", "<u4"), ("status", "<u4"), ("flags", "<u4"),
                    ("start", "<u8"), ("end", "<u8"),
                    ("sid", "V8"), ("pid", "V8")])


# ---------------------------------------------------------------------------
# query-side staging

class AggStage:
    """Per-batch staged aggregation descriptor: the batch-global service
    table and the per-entry composite key column the kernels count.

    ``entry_agg[p, e] = (svc_gid * NB1 + ms_bucket) * 2 + err`` — int32,
    valid range [0, n_keys); the kernel writes the sentinel ``n_keys``
    for entries the verdict mask rejects. The service axis pads to pow2
    so the static ``agg`` jit key takes log-many values per geometry."""

    __slots__ = ("services", "n_keys", "host", "_device", "_lock")

    def __init__(self, services: tuple, host: np.ndarray):
        self.services = services
        self.n_keys = _pow2(max(1, len(services))) * _NB1Q * 2
        self.host = host
        self._device = None
        self._lock = threading.Lock()

    def device(self):
        """Memoized device placement (uncommitted — mesh dispatches
        reshard it through the kernel's in_spec)."""
        with self._lock:
            if self._device is None:
                _, jnp = _jax()

                self._device = jnp.asarray(self.host)
            return self._device

    def cpu(self):
        """Host-route placement, staged under cpu_pinned by the
        caller (host_scan memoizes the result on the HostBatch)."""
        _, jnp = _jax()

        return jnp.asarray(self.host)

    def decode(self, counts: np.ndarray) -> dict:
        """Dense [n_keys] counts -> {service: {calls, errors, hist}}.
        Integer-only, so every dispatch path decodes identically."""
        s_pad = self.n_keys // (_NB1Q * 2)
        c = np.asarray(counts).reshape(s_pad, _NB1Q, 2)
        series = {}
        for i, svc in enumerate(self.services):
            sub = c[i]
            calls = int(sub.sum())
            if not calls:
                continue
            series[svc] = {
                "calls": calls,
                "errors": int(sub[:, 1].sum()),
                "hist": [int(x) for x in sub.sum(axis=1)],
            }
        return series


def agg_response(series: dict) -> dict:
    """The ?agg=red response payload (docs/search-analytics.md)."""
    return {"type": "red", "buckets_ms": list(MS_BUCKETS),
            "series": series}


def merge_agg(into: dict | None, other: dict | None) -> dict | None:
    """Integer merge of two agg payloads (sub-response fan-in)."""
    if other is None:
        return into
    if into is None:
        return other
    dst = into["series"]
    for svc, s in other["series"].items():
        d = dst.get(svc)
        if d is None:
            dst[svc] = s
        else:
            d["calls"] += s["calls"]
            d["errors"] += s["errors"]
            d["hist"] = [a + b for a, b in zip(d["hist"], s["hist"])]
    return into


def attach_agg(req, spec: str) -> None:
    """Validate an ?agg= spec and stow it in the reserved tag. Raises
    ValueError on anything but the supported grammar (params.py maps it
    to a 400)."""
    spec = (spec or "").strip().lower()
    if spec != "red":
        raise ValueError(
            f"unsupported agg spec {spec!r} (supported: 'red')")
    req.tags[AGG_QUERY_TAG] = spec


def agg_requested(req) -> bool:
    return AGG_QUERY_TAG in req.tags


def _block_entry_agg(pages, svc_index: dict) -> np.ndarray:
    """One block's per-entry composite keys (numpy, host side)."""
    lut = np.empty(len(pages.val_dict) + 1, dtype=np.int64)
    unknown = svc_index[""]
    for i, v in enumerate(pages.val_dict):
        lut[i] = svc_index.get(v, unknown)
    lut[-1] = unknown                     # entry_root_svc == -1
    gids = lut[pages.entry_root_svc]
    bins = np.searchsorted(np.asarray(MS_BUCKETS, dtype=np.int64),
                           pages.entry_dur.astype(np.int64), side="left")
    err = np.zeros(pages.entry_dur.shape, dtype=np.int64)
    kid = bisect.bisect_left(pages.key_dict, "error")
    vid = bisect.bisect_left(pages.val_dict, "true")
    if (kid < len(pages.key_dict) and pages.key_dict[kid] == "error"
            and vid < len(pages.val_dict)
            and pages.val_dict[vid] == "true"):
        err = ((pages.kv_key == kid)
               & (pages.kv_val == vid)).any(axis=-1).astype(np.int64)
    return ((gids * _NB1Q + bins) * 2 + err).astype(np.int32)


def build_agg_stage(blocks, pad_pages: int, entries_per_page: int) \
        -> AggStage:
    """Stage the batch-global composite-key column: one sorted service
    table over every member block's root services (plus the "" unknown
    slot), then per-block id remaps — all host numpy, one pass."""
    names = {""}
    for b in blocks:
        ids = np.unique(b.entry_root_svc[b.entry_valid])
        for i in ids.tolist():
            if i >= 0:
                names.add(b.val_dict[i])
    services = tuple(sorted(names))
    svc_index = {s: i for i, s in enumerate(services)}
    arr = np.zeros((pad_pages, entries_per_page), dtype=np.int32)
    po = 0
    for b in blocks:
        arr[po:po + b.n_pages] = _block_entry_agg(b, svc_index)
        po += b.n_pages
    return AggStage(services, arr)


# ---------------------------------------------------------------------------
# the process-wide engine

class AnalyticsEngine:
    """Process-wide singleton (the LiveTier/STRUCTURAL model): the most
    recent TempoDB's configure() wins; every hook gate-checks
    ``enabled`` first, so the default-off deployment pays one attribute
    read per push and per search."""

    def __init__(self):
        self.enabled = False
        self.min_rows = 1
        self._lock = threading.Lock()

    def configure(self, enabled: bool = False, min_rows: int = 1) -> None:
        with self._lock:
            self.min_rows = max(1, int(min_rows))
            # set LAST: a concurrent hook that observes enabled sees the
            # settled knobs
            self.enabled = bool(enabled)

    # ------------------------------------------------------------------
    # ingest side

    def consume_blob(self, procs, strs, blob, off: int, n_rows: int,
                     tids) -> bool:
        """Batched replacement for the generator processors' per-row
        walk over one native summary blob. Returns True when the blob
        was fully consumed (series updated byte-identically to the
        walk); False hands the blob back to the classic path — unknown
        processor types and tiny blobs stay on the walk."""
        if not self.enabled:
            return False
        from tempo_tpu.modules.generator import (ServiceGraphProcessor,
                                                 SpanMetricsProcessor)

        spm = sgp = None
        for p in procs:
            if type(p) is SpanMetricsProcessor and spm is None:
                spm = p
            elif type(p) is ServiceGraphProcessor and sgp is None:
                sgp = p
            else:
                return False
        if n_rows < self.min_rows:
            return False
        t0 = time.perf_counter()
        r = np.frombuffer(blob, dtype=_ROW_DT, count=n_rows, offset=off)
        if spm is not None:
            self._consume_spanmetrics(spm, strs, r)
        if sgp is not None:
            self._consume_servicegraph(sgp, strs, r, tids)
        from . import planner

        planner.PLANNER.observe("analytics", time.perf_counter() - t0,
                                nbytes=n_rows * _ROW_DT.itemsize)
        return True

    # -- spanmetrics ---------------------------------------------------

    def _consume_spanmetrics(self, spm, strs, r) -> None:
        from tempo_tpu.modules.generator import LATENCY_BUCKETS_S

        n = len(r)
        dur = np.maximum(
            r["end"].astype(np.int64) - r["start"].astype(np.int64), 0)
        svc = r["svc"].astype(np.int64)
        name = r["name"].astype(np.int64)
        kind = r["kind"].astype(np.int64)
        status = r["status"].astype(np.int64)
        # one packed int64 composite key beats np.unique(axis=0)'s void
        # view by ~5x at these sizes; the radix widths come from the
        # batch itself (overflow falls back to the 2-D unique)
        ms = [int(c.max()) + 1 if n else 1
              for c in (svc, name, kind, status)]
        if ms[0] * ms[1] * ms[2] * ms[3] < (1 << 62):
            packed = ((svc * ms[1] + name) * ms[2] + kind) * ms[3] + status
            uk, inverse = np.unique(packed, return_inverse=True)
            uniq = np.empty((len(uk), 4), dtype=np.int64)
            q, uniq[:, 3] = np.divmod(uk, ms[3])
            q, uniq[:, 2] = np.divmod(q, ms[2])
            uniq[:, 0], uniq[:, 1] = np.divmod(q, ms[1])
        else:
            cols = np.stack([svc, name, kind, status], axis=1)
            uniq, inverse = np.unique(cols, axis=0, return_inverse=True)
        # the string table may repeat strings: two distinct (svc, name)
        # index pairs can resolve to one logical series — remap to the
        # canonical group or the registry would split it
        canon: dict[tuple, int] = {}
        g_keys: list[tuple] = []
        g_of_uniq = np.empty(len(uniq), dtype=np.int64)
        for gi, u in enumerate(uniq):
            sk = (strs[int(u[0])], strs[int(u[1])], int(u[2]), int(u[3]))
            j = canon.get(sk)
            if j is None:
                j = canon[sk] = len(g_keys)
                g_keys.append(sk)
            g_of_uniq[gi] = j
        gids = g_of_uniq[inverse.reshape(-1)]
        G = len(g_keys)

        counts = self._count(gids, dur, n_keys=_pow2(G),
                             buckets=LATENCY_BUCKETS_S)
        nb1 = len(LATENCY_BUCKETS_S) + 1
        counts2 = counts.reshape(-1, nb1)

        # per-series float latency values, ROW ORDER preserved within
        # each series (stable sort) — the sequential host fold is what
        # keeps the histogram _sums byte-identical to the walk
        order = np.argsort(gids, kind="stable")
        starts = np.searchsorted(gids[order], np.arange(G + 1))
        vals = (dur.astype(np.float64) / 1e9)[order]
        # last-occurrence order reproduces the walk's final LRU order
        last = np.zeros(G, dtype=np.int64)
        np.maximum.at(last, gids, np.arange(n, dtype=np.int64))
        for g in np.argsort(last, kind="stable").tolist():
            c, h = spm._series_touch(g_keys[g])
            c.inc(int(starts[g + 1] - starts[g]))
            h.observe_bulk(counts2[g].tolist(),
                           vals[starts[g]:starts[g + 1]].tolist())

    # -- service graph -------------------------------------------------

    def _consume_servicegraph(self, sgp, strs, r, tids) -> None:
        now = time.monotonic()
        kind = r["kind"]
        cand = np.nonzero((kind == 2) | (kind == 3))[0]
        if cand.size:
            self._servicegraph_rows(sgp, strs, r, tids, cand, now)
        sgp._maybe_expire(now)

    def _servicegraph_rows(self, sgp, strs, r, tids, cand, now) -> None:
        from tempo_tpu import tempopb

        kind_c = r["kind"][cand].astype(np.int64)
        sid_u = np.frombuffer(r["sid"][cand].tobytes(), dtype="<u8")
        pid_u = np.frombuffer(r["pid"][cand].tobytes(), dtype="<u8")
        # the pairing id: a client's own span id, a server's parent id
        id_u = np.where(kind_c == 3, sid_u, pid_u)
        # canonical trace gid — duplicate trace-id BYTES in tids
        # collapse to one pairing key, exactly as the walk's tuples do
        tid_gid_of: dict[bytes, int] = {}
        tid_gids = np.empty(max(1, len(tids)), dtype=np.int64)
        for i, t in enumerate(tids):
            tid_gids[i] = tid_gid_of.setdefault(bytes(t),
                                                len(tid_gid_of))
        ti_c = r["ti"][cand].astype(np.int64)
        tg = tid_gids[ti_c]
        uid, id_inv = np.unique(id_u, return_inverse=True)
        _, ginv, gcount = np.unique(
            tg * len(uid) + id_inv.reshape(-1),
            return_inverse=True, return_counts=True)
        ginv = ginv.reshape(-1)
        nG = len(gcount)
        order = np.argsort(ginv, kind="stable")
        bounds = np.zeros(nG + 1, dtype=np.int64)
        np.cumsum(gcount, out=bounds[1:])
        ksum = np.bincount(ginv, weights=kind_c,
                           minlength=nG).astype(np.int64)
        # clean groups — exactly one client + one server, nothing
        # mid-pairing in the store — pair IN-BATCH with no store
        # round-trip; everything else replays the walk's _pair_collect
        # in row order, so overwrite/capacity semantics stay the walk's
        clean = (gcount == 2) & (ksum == 5)

        status_c = r["status"][cand].astype(np.int64)
        start_c = r["start"][cand].astype(np.int64)
        end_c = r["end"][cand].astype(np.int64)
        svc_c = r["svc"][cand].astype(np.int64)

        g_clean = np.nonzero(clean)[0]
        if g_clean.size and sgp._store:
            keep = np.ones(len(g_clean), dtype=bool)
            with sgp._lock:
                store = sgp._store
                for i, g in enumerate(g_clean.tolist()):
                    j = int(order[bounds[g]])
                    key = (tids[int(ti_c[j])],
                           int(id_u[j]).to_bytes(8, "little"))
                    if key in store:
                        keep[i] = False
            if not keep.all():
                clean[g_clean[~keep]] = False
                g_clean = g_clean[keep]

        # canonical service gid over the batch's string-table ids (the
        # table may repeat strings — same remap as spanmetrics)
        canon: dict[str, int] = {}
        names: list[str] = []
        lut = np.zeros(int(svc_c.max()) + 1 if cand.size else 1,
                       dtype=np.int64)
        for i in np.unique(svc_c).tolist():
            s = strs[i]
            gi = canon.get(s)
            if gi is None:
                gi = canon[s] = len(names)
                names.append(s)
            lut[i] = gi

        n_clean = len(g_clean)
        lo = bounds[g_clean]
        a = order[lo]
        b = order[lo + 1]
        a_cl = kind_c[a] == 3
        jc = np.where(a_cl, a, b)
        js = np.where(a_cl, b, a)

        extra = []   # replayed emissions: (pos, c_svc, s_svc, c_st,
        #              s_st, c_start, c_end)
        if not clean.all():
            for g in np.nonzero(~clean)[0].tolist():
                for j in order[bounds[g]:bounds[g + 1]].tolist():
                    side = "client" if kind_c[j] == 3 else "server"
                    key = (tids[int(ti_c[j])],
                           int(id_u[j]).to_bytes(8, "little"))
                    em = sgp._pair_collect(
                        key, side, strs[int(svc_c[j])],
                        (int(status_c[j]), int(start_c[j]),
                         int(end_c[j])), now)
                    if em is not None:
                        extra.append((j,) + em)
        total = n_clean + len(extra)
        if not total:
            return
        pos = np.empty(total, dtype=np.int64)
        cg = np.empty(total, dtype=np.int64)
        sg = np.empty(total, dtype=np.int64)
        c_st = np.empty(total, dtype=np.int64)
        s_st = np.empty(total, dtype=np.int64)
        dur = np.empty(total, dtype=np.int64)
        if n_clean:
            # a pair emits where its SECOND row lands — positions
            # restore the walk's emission order, which the per-edge
            # float latency fold depends on
            pos[:n_clean] = np.maximum(jc, js)
            cg[:n_clean] = lut[svc_c[jc]]
            sg[:n_clean] = lut[svc_c[js]]
            c_st[:n_clean] = status_c[jc]
            s_st[:n_clean] = status_c[js]
            dur[:n_clean] = np.maximum(end_c[jc] - start_c[jc], 0)
        for k, (j, e_c_svc, e_s_svc, e_c_st, e_s_st, e_cs,
                e_ce) in enumerate(extra):
            t = n_clean + k
            pos[t] = j
            for svc_str, dst in ((e_c_svc, cg), (e_s_svc, sg)):
                gi = canon.get(svc_str)
                if gi is None:
                    gi = canon[svc_str] = len(names)
                    names.append(svc_str)
                dst[t] = gi
            c_st[t] = e_c_st
            s_st[t] = e_s_st
            dur[t] = max(e_ce - e_cs, 0)
        o = np.argsort(pos, kind="stable")
        cg, sg, c_st, s_st, dur = cg[o], sg[o], c_st[o], s_st[o], dur[o]
        ERR = tempopb.Status.STATUS_CODE_ERROR
        failed = ((c_st == ERR) | (s_st == ERR)).astype(np.int64)
        uek, einv = np.unique(cg * len(names) + sg, return_inverse=True)
        einv = einv.reshape(-1)
        from tempo_tpu.modules.generator import LATENCY_BUCKETS_S

        E = len(uek)
        counts = self._count(einv * 2 + failed, dur,
                             n_keys=_pow2(2 * E),
                             buckets=LATENCY_BUCKETS_S)
        nb1 = len(LATENCY_BUCKETS_S) + 1
        counts2 = counts.reshape(-1, nb1)
        req_n = np.bincount(einv, minlength=E)
        fail_n = np.bincount(einv, weights=failed,
                             minlength=E).astype(np.int64)
        order_e = np.argsort(einv, kind="stable")
        starts_e = np.searchsorted(einv[order_e], np.arange(E + 1))
        vals = (dur.astype(np.float64) / 1e9)[order_e]
        for e, ek in enumerate(uek.tolist()):
            labels = dict(client=names[ek // len(names)],
                          server=names[ek % len(names)])
            sgp.requests.inc(int(req_n[e]), **labels)
            if fail_n[e]:
                sgp.failed.inc(int(fail_n[e]), **labels)
            bins = (counts2[2 * e] + counts2[2 * e + 1]).tolist()
            sgp.latency.observe_bulk(
                bins, vals[starts_e[e]:starts_e[e + 1]].tolist(),
                **labels)

    # -- the shared dense count ---------------------------------------

    def _count(self, sidx: np.ndarray, dur: np.ndarray, n_keys: int,
               buckets: tuple) -> np.ndarray:
        """Dense (series, bucket) counts for one micro-batch: the device
        kernel behind the breaker/watchdog, with a byte-identical
        integer numpy fallback (counts are exact either way — the route
        only changes where the sort ran)."""
        from tempo_tpu.robustness import BREAKER, GUARD, DeviceFault

        nb1 = len(buckets) + 1
        K = n_keys * nb1
        thr = _dur_thresholds(tuple(buckets))
        out = None
        route = "host"
        # two-limb keys cover dur < 2^62 ns (~146 years) — beyond that
        # the int64 host path answers (still exact)
        if (BREAKER.allow_device()
                and (dur.size == 0 or int(dur.max()) < (1 << 62))):
            try:
                out = GUARD.run(
                    "analytics",
                    lambda: self._count_device(sidx, dur, n_keys, thr))
                route = "device"
            except DeviceFault:
                out = None
        if out is None:
            full = _dur_thresholds_full(tuple(buckets))
            b = np.zeros(len(dur), dtype=np.int64)
            for t in full:
                b += dur >= t
            key = sidx.astype(np.int64) * nb1 + b
            out = np.bincount(key, minlength=K)[:K]
        obs.search_analytics_dispatches.labels(route=route).inc()
        return out.astype(np.int64)

    def _count_device(self, sidx, dur, n_keys: int, thr: tuple):
        _, jnp = _jax()

        n = len(sidx)
        tier = _pow2(max(1, n))
        cols = np.empty((3, tier), dtype=np.int32)
        cols[0, :n] = sidx
        cols[0, n:] = n_keys       # sentinel: pad rows land past range
        cols[1, :n] = dur >> 31
        cols[2, :n] = dur & 0x7FFFFFFF
        cols[1:, n:] = 0
        obs.search_analytics_staged_bytes.set(cols.nbytes)
        out = _count_kernel()(jnp.asarray(cols), n_keys=n_keys,
                              tier=tier, buckets=thr)
        return np.asarray(out)

    # ------------------------------------------------------------------
    # query side

    def stage_for_batch(self, batch) -> AggStage:
        """Memoized per-batch staging of the composite-key column
        (BlockBatch or HostBatch — both carry .blocks; the page count
        comes from the staged arrays so pads line up)."""
        st = getattr(batch, "_agg_stage", None)
        if st is None:
            d = getattr(batch, "device", None) or getattr(
                batch, "cat", None)
            pad_pages = int(d["entry_valid"].shape[0])
            epp = batch.blocks[0].geometry.entries_per_page
            st = build_agg_stage(batch.blocks, pad_pages, epp)
            batch._agg_stage = st     # benign race: idempotent content
        return st


ANALYTICS = AnalyticsEngine()
