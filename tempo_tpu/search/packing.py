"""Packed HBM residency: bit-width-adaptive columns, unpacked in-register.

The fused scan is a linear pass over staged dictionary-id columns, so
spans/sec/chip is bounded by HBM bytes moved — and the HBM budget caps
how many blocks stay resident (the dominant latency lever: PR 11's
ownership bench measured 42% vs 78% hit ratio). Yet every value-id
column stages at the width of the WIDEST case even when a block's
dictionary has 200 distinct values. This module narrows the RESIDENT
format to what each batch's recorded dictionary cardinality actually
needs — compressed near-data execution in the Taurus sense (arxiv
2506.20010), with the pack-at-stage / unpack-in-kernel split of the
GPU-offloaded OLAP engines' compressed-scan layout (arxiv 2601.19911):

  kv id columns   code = id + 1 (pad -1 → 0) stored uint8/uint16/uint32,
                  or 4-bit two-codes-per-byte for ≤15-value dictionaries
  duration        exact uint16 when the block rollup's max fits; else
                  uint16 buckets ``dur >> s`` plus a small residual —
                  the kernel's range compare is exact on bucket interior
                  and reconstructs the full uint32 ONLY for rows sitting
                  on a boundary bucket
  probe hit masks the dict-probe product ([T, v_pad] bool) bit-packs to
                  uint32 words, 8x fewer HBM bytes pinned per cached
                  compile product (32x fewer bits than the 1-byte bools)

Kernels take a static per-column width descriptor (``widths`` — part of
the jit shape key, so compile-cache keys stay value-independent) and
widen with shifts/masks fused into the existing compares: no separate
decompression pass, no extra HBM round trip. The term tables, compile
cache and all query-side products stay in the id domain, so packed and
unpacked batches share every compiled predicate.

Gate: ``search_packed_residency`` (TempoDBConfig + YAML), default off —
a TRUE noop: call sites read one attribute (``PACKING.enabled``) and
take the byte-identical legacy path. Enabled vs disabled is also
byte-identical (the unpack is exact); only the resident bytes move.
"""

from __future__ import annotations

import functools

import numpy as np

# width descriptors for the kv id columns; "u4" packs two 4-bit codes
# per byte (id+1, pad 0), the rest are plain code arrays of that width
_KV_DTYPES = {"u8": np.uint8, "u16": np.uint16, "u32": np.uint32}


class PackedResidency:
    """Process-wide gate (module singleton ``PACKING``, the OWNERSHIP /
    PLANNER idiom): TempoDBConfig flips ``enabled``; staging sites
    consult ``plan_widths``/``pack_hits``, which are self-gated so the
    disabled path is one attribute read."""

    __slots__ = ("enabled",)

    def __init__(self) -> None:
        self.enabled = False

    def plan_widths(self, n_keys: int, n_vals: int, max_dur_ms: int):
        """The width descriptor for a batch: (key_width, val_width,
        dur_width) chosen from the recorded dictionary cardinalities and
        the header duration rollup, or None (= the unpacked legacy
        layout) when the gate is off. Static per staged batch — it is
        part of every consuming kernel's jit shape key."""
        if not self.enabled:
            return None
        return (width_for_cardinality(n_keys),
                width_for_cardinality(n_vals),
                dur_width(max_dur_ms))

    def pack_hits(self, hits):
        """Bit-pack a device-probe hit mask (bool [..., v_pad] → uint32
        words [..., v_pad/32]) when the gate is on; identity when off."""
        if not self.enabled:
            return hits
        return pack_mask_words(hits)


PACKING = PackedResidency()


def configure(enabled: bool | None = None) -> PackedResidency:
    """Apply config (TempoDBConfig.search_packed_residency) to the
    process gate — most recent TempoDB wins, the PROFILER idiom."""
    if enabled is not None:
        PACKING.enabled = bool(enabled)
    return PACKING


# ---------------------------------------------------------------------------
# width selection (host side)


def width_for_cardinality(n: int) -> str:
    """Narrowest storage for a dictionary of `n` distinct ids. Codes are
    id+1 with 0 reserved for the pad slot, so the boundaries sit at
    15/16, 255/256 and 65535/65536 (n values need n+1 codes)."""
    if n <= 15:
        return "u4"
    if n <= 255:
        return "u8"
    if n <= 65_535:
        return "u16"
    return "u32"


def dur_width(max_dur_ms: int) -> str:
    """Duration storage for a batch whose header rollup caps durations
    at `max_dur_ms`: exact uint16 when it fits; else uint16 buckets
    ``dur >> s`` with the smallest shift that fits, plus a residual
    column holding the shifted-out low bits (uint8 when s <= 8)."""
    m = max(0, int(max_dur_ms))
    if m <= 0xFFFF:
        return "u16"
    return f"q{m.bit_length() - 16}"


def legacy_kv_itemsize(n: int) -> int:
    """Bytes/slot the UNPACKED layout uses for a dictionary of `n` ids
    (multiblock.stack_host's signed narrowing with its -1 sentinel) —
    the logical-bytes baseline the packed accounting reports against."""
    return 1 if n <= 127 else (2 if n <= 32_767 else 4)


# ---------------------------------------------------------------------------
# host-side packing (numpy, at stack/stage time)


def pack_ids_array(arr: np.ndarray, w: str) -> np.ndarray:
    """Pack an int32 id array (-1 = pad) into width `w` codes (id+1,
    pad 0). For "u4" the last axis must be even; two codes share a byte
    (low nibble = even slot)."""
    codes = arr.astype(np.int32, copy=False) + 1
    if w == "u4":
        lo = codes[..., 0::2]
        hi = codes[..., 1::2]
        return (lo | (hi << 4)).astype(np.uint8)
    return codes.astype(_KV_DTYPES[w])


def pack_duration(arr: np.ndarray, dw: str):
    """(quantized, residual-or-None) for a uint32 duration column under
    descriptor `dw`. "u16" is an exact narrowing (the batch rollup
    proved every duration fits); "q<s>" stores ``dur >> s`` uint16
    buckets plus the shifted-out low bits so the kernel can reconstruct
    exactly at bucket boundaries."""
    if dw == "u16":
        return arr.astype(np.uint16), None
    s = int(dw[1:])
    a = arr.astype(np.uint32, copy=False)
    res_dt = np.uint8 if s <= 8 else np.uint16
    return (a >> s).astype(np.uint16), (a & ((1 << s) - 1)).astype(res_dt)


def pack_columns(arrays: dict, widths) -> dict:
    """Pack a staged column dict (engine.DEVICE_ARRAYS layout) in place
    of its kv/duration columns; adds "entry_dur_res" for quantized
    durations. Used by the single-block and distributed staging paths
    (the batched path packs per block inside stack_host)."""
    kw, vw, dw = widths
    out = dict(arrays)
    kv_key, kv_val = arrays["kv_key"], arrays["kv_val"]
    if "u4" in (kw, vw) and kv_key.shape[-1] % 2:
        # nibble packing pairs slots: pad BOTH kv columns to an even
        # capacity so they unpack to the same slot count
        pad = [(0, 0)] * (kv_key.ndim - 1) + [(0, 1)]
        kv_key = np.pad(kv_key, pad, constant_values=-1)
        kv_val = np.pad(kv_val, pad, constant_values=-1)
    out["kv_key"] = pack_ids_array(kv_key, kw)
    out["kv_val"] = pack_ids_array(kv_val, vw)
    q, res = pack_duration(arrays["entry_dur"], dw)
    out["entry_dur"] = q
    if res is not None:
        out["entry_dur_res"] = res
    return out


def logical_nbytes(n_entries_padded: int, kv_slots: int, n_keys: int,
                   n_vals: int) -> int:
    """Bytes the UNPACKED layout would pin for this many (padded)
    entries: narrowed kv columns + uint32 start/end/dur + bool valid.
    The physical/logical split the accounting gauges report — identical
    to physical when the gate is off."""
    kv = n_entries_padded * kv_slots * (legacy_kv_itemsize(n_keys)
                                        + legacy_kv_itemsize(n_vals))
    return int(kv + n_entries_padded * (4 + 4 + 4 + 1))


# ---------------------------------------------------------------------------
# in-kernel unpack (jnp; `w`/`dw`/`widths` are static at every call
# site — the jit-purity checker enforces that no tracer reaches a width
# descriptor parameter)


def unpack_ids(arr, w):
    """int32 id view (-1 = pad) of a packed kv column — the widening
    shifts/masks fuse into the consuming compare (no separate
    decompression pass materializes in HBM)."""
    import jax.numpy as jnp

    if w is None:
        return arr
    if w == "u4":
        lo = arr & jnp.uint8(0x0F)
        hi = arr >> 4
        codes = jnp.stack([lo, hi], axis=-1)
        codes = codes.reshape(arr.shape[:-1] + (arr.shape[-1] * 2,))
        return codes.astype(jnp.int32) - 1
    return arr.astype(jnp.int32) - 1


def duration_ok(entry_dur, entry_dur_res, dur_lo, dur_hi, dw):
    """The duration range predicate under descriptor `dw`. Quantized
    widths compare uint16 buckets against the query bounds' buckets —
    exact on the bucket interior — and reconstruct the full uint32
    (bucket << s | residual) ONLY for rows that hit a boundary bucket,
    where the bucket compare is ambiguous."""
    import jax.numpy as jnp

    lo = dur_lo.astype(jnp.uint32)
    hi = dur_hi.astype(jnp.uint32)
    if dw is None or not dw.startswith("q"):
        dur = entry_dur.astype(jnp.uint32)
        return (dur >= lo) & (dur <= hi)
    s = int(dw[1:])
    q = entry_dur.astype(jnp.uint32)
    lo_q = lo >> s
    hi_q = hi >> s
    inside = (q > lo_q) & (q < hi_q)
    boundary = (q == lo_q) | (q == hi_q)
    full = (q << s) | entry_dur_res.astype(jnp.uint32)
    exact = (full >= lo) & (full <= hi)
    return inside | (boundary & exact)


def mask_select(row, ids):
    """Membership lookup on one term's hit-mask row: `row` is [V] bool
    or [W] uint32 bit-words; `ids` indexes the value axis. The packed
    path gathers one word and selects the bit in-register."""
    import jax.numpy as jnp

    if row.dtype == jnp.uint32:
        word = row[ids >> 5]
        return (word >> (ids & 31).astype(jnp.uint32)) & jnp.uint32(1) != 0
    return row[ids]


def mask_select_grouped(vh, g, t, ids):
    """Grouped variant for the multi-block mask table: `vh` is
    [G, T, V] bool or [G, T, W] uint32 words; `g` broadcasts the
    per-page dictionary group over `ids`."""
    import jax.numpy as jnp

    if vh.dtype == jnp.uint32:
        word = vh[g, t, ids >> 5]
        return (word >> (ids & 31).astype(jnp.uint32)) & jnp.uint32(1) != 0
    return vh[g, t, ids]


def is_packed_mask(x) -> bool:
    """True when a probe product's hit mask is in the bit-packed
    format (compile-cache entries from the other gate state must be
    treated as misses so one assembled batch never mixes formats)."""
    return getattr(x, "dtype", None) is not None \
        and str(x.dtype) == "uint32"


@functools.lru_cache(maxsize=1)
def _pack_mask_jit():
    import jax

    @jax.jit
    def _pack(hits):
        import jax.numpy as jnp

        V = hits.shape[-1]
        W = -(-V // 32)
        if W * 32 != V:
            pad = [(0, 0)] * (hits.ndim - 1) + [(0, W * 32 - V)]
            hits = jnp.pad(hits, pad)
        u = hits.reshape(hits.shape[:-1] + (W, 32)).astype(jnp.uint32)
        return (u << jnp.arange(32, dtype=jnp.uint32)).sum(
            axis=-1).astype(jnp.uint32)

    return _pack


def pack_mask_words(hits):
    """bool [..., V] hit mask → uint32 [..., ceil(V/32)] bit-words on
    device (bit i of word w = value id 32*w + i). Already-packed input
    passes through (idempotent across cache/coalesce boundaries)."""
    if is_packed_mask(hits):
        return hits
    return _pack_mask_jit()(hits)


def unpack_mask_words(words, v_pad: int) -> np.ndarray:
    """Host-side expansion of a packed mask row set back to bool — the
    parity bridge for tests/bench (dict_probe.hits_to_ids)."""
    a = np.asarray(words)
    bits = np.unpackbits(a.view(np.uint8), axis=-1, bitorder="little")
    return bits[..., :v_pad].astype(bool)
