"""Host-side query compilation: dictionaries in, device predicate out.

Role-equivalent to the reference's search pipeline (tempodb/search/
pipeline.go:20-183) and tag probes (pkg/tempofb/searchdata_util.go:47-100),
re-cut for the dictionary-encoded columnar layout: the substring match
(`bytes.Contains`) is evaluated ONCE per (block, query) over the block's
value dictionary on the host — cheap, exact — producing the value-id sets
the device kernel tests membership against. A term whose key or value set
is empty prunes the whole block before any device work (the reference's
MatchesBlock header rollup, backend_search_block.go:202-210).
"""

from __future__ import annotations

import bisect
import hashlib
import threading
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

from tempo_tpu import tempopb

INT32_SENTINEL = np.int32(2**31 - 1)
UINT32_MAX = 0xFFFFFFFF

# Hidden debug flag (reference tempodb/search/pipeline.go:14
# SecretExhaustiveSearchTag): a request carrying this tag forces a FULL
# traversal — block pruning and result-limit early-quit are suppressed so
# every page of every block is scanned. The remaining (non-secret) tag
# predicates still apply, as in the reference where the secret tag adds a
# filter without dropping the others. In-band, undocumented, for
# benchmarking scans.
EXHAUSTIVE_SEARCH_TAG = "x-dbg-exhaustive"


def is_exhaustive(req: tempopb.SearchRequest) -> bool:
    return EXHAUSTIVE_SEARCH_TAG in req.tags


@dataclass
class CompiledQuery:
    term_keys: np.ndarray   # int32 [T]
    term_vals: np.ndarray   # int32 [T, V] sorted, padded with INT32_SENTINEL
    val_ranges: np.ndarray  # int32 [T, R, 2] inclusive [lo,hi] id ranges,
                            # padded with [1,0] (never matches)
    dur_lo: int
    dur_hi: int
    win_start: int
    win_end: int
    limit: int
    # device-probe product (search/dict_probe.py): bool [T, v_pad] value
    # hit mask, resident on device. When set, val_ranges is the
    # never-match padding and the scan kernels test membership with a
    # mask lookup instead of range compares — the probe result never
    # crosses the host boundary.
    val_hits: object = None

    @property
    def n_terms(self) -> int:
        return int(self.term_keys.shape[0])


def ids_to_ranges(ids: np.ndarray) -> np.ndarray:
    """Collapse a sorted id set into inclusive [lo,hi] runs. Sorted
    dictionaries make substring hits clumpy (all values sharing a prefix
    are contiguous), so R is typically far below V — and the device tests
    ranges with pure compares, the TPU-friendly alternative to a
    membership gather (gathers serialize on the VPU; measured 35ms vs
    <5ms per 1M entries)."""
    if ids.size == 0:
        return np.zeros((0, 2), dtype=np.int32)
    breaks = np.nonzero(np.diff(ids) > 1)[0]
    lo = np.concatenate([[0], breaks + 1])
    hi = np.concatenate([breaks, [ids.size - 1]])
    return np.stack([ids[lo], ids[hi]], axis=1).astype(np.int32)


def block_header_skip_reason(header: dict,
                             req: tempopb.SearchRequest) -> str | None:
    """Why the header rollup prunes this block — None when it doesn't.
    The reason string feeds the per-query stats' skipped-blocks
    breakdown (search/query_stats.py): an operator reading an explain
    must be able to tell "out of the time window" from "no span that
    long" without re-deriving it."""
    if is_exhaustive(req):
        return None  # debug flag: never prune
    if req.start and header.get("max_end_s", UINT32_MAX) < req.start:
        return "time_range"
    if req.end and header.get("min_start_s", 0) > req.end:
        return "time_range"
    if req.min_duration_ms and header.get("max_dur_ms", UINT32_MAX) < req.min_duration_ms:
        return "duration"
    if req.max_duration_ms and header.get("min_dur_ms", 0) > req.max_duration_ms:
        return "duration"
    return None


NATIVE_SCAN_THRESHOLD = 50_000


def substring_value_ids(val_dict: list, needle: str,
                        packed: tuple | None = None) -> np.ndarray:
    """Ids of dictionary values containing `needle` — the host-side answer
    to bytes.Contains semantics (SURVEY.md §7 hard parts). Small
    dictionaries scan vectorized in numpy; huge ones (the 10M-distinct-
    values BASELINE config) go through the native C++ memmem scan over a
    packed byte dictionary (`packed` = (bytes, int64 offsets), cacheable
    per block via ColumnarPages.packed_val_dict)."""
    if not needle:
        return np.arange(len(val_dict), dtype=np.int32)
    if not val_dict:
        return np.zeros(0, dtype=np.int32)
    if len(val_dict) >= NATIVE_SCAN_THRESHOLD:
        from tempo_tpu.ops import native

        if native.available():
            if packed is None:
                packed = pack_val_dict(val_dict)
            buf, offsets = packed
            return native.substr_scan(buf, offsets, needle.encode("utf-8"))
    arr = np.array(val_dict, dtype=np.str_)
    hits = np.char.find(arr, needle) >= 0
    return np.nonzero(hits)[0].astype(np.int32)


def pack_val_dict(val_dict: list) -> tuple:
    """(concatenated utf-8 bytes, int64 offsets[n+1]) for the native scan."""
    blobs = [v.encode("utf-8") for v in val_dict]
    offsets = np.zeros(len(blobs) + 1, dtype=np.int64)
    np.cumsum([len(b) for b in blobs], out=offsets[1:])
    return b"".join(blobs), offsets


_PRUNED = "pruned"  # cache sentinel: block provably cannot match these tags
_COMPILE_CACHE_MAX = 128     # distinct tag-sets kept per dictionary
_COMPILE_CACHE_DICTS = 4096  # distinct dictionaries tracked
# entries whose probe product is a DEVICE hit mask pin HBM (~v_pad bytes
# per term — 10 MB/term at 10M values), so they get a much tighter
# per-dictionary bound than the host-only entries. Bit-packed masks
# (search/packing.py, 8x fewer bytes per entry) afford an 8x deeper
# bound at the same HBM charge — more distinct tag-sets stay compiled.
_PROBE_CACHE_MAX = 8
_PROBE_CACHE_MAX_PACKED = 64
_COMPILE_CACHE: OrderedDict = OrderedDict()
_compile_cache_lock = threading.Lock()


def _dict_fingerprint(cache_on, key_dict: list, val_dict: list) -> bytes:
    """Content digest of the dictionaries, computed once per container
    OUTSIDE the cache lock (a 1M-value dictionary hashes for ~100ms — it
    must not serialize every other thread's compiles). sha256, not
    hash(): a 64-bit collision would silently serve another dictionary's
    compiled term ids, an undetectable wrong-results failure.

    Containers decoded from the encoding/v2 search object carry the
    digest of their ENCODED dictionary sections (`_dict_section_sha`,
    columnar.from_bytes — one C-speed pass over contiguous bytes at
    build, zero cost at open), so the first cache touch skips the
    ~100ms-per-1M-values python walk; synthetic/test containers built
    in memory fall back to it."""
    fp = getattr(cache_on, "_dict_fingerprint", None)
    if fp is None:
        fp = getattr(cache_on, "_dict_section_sha", None)
        if fp is None:
            h = hashlib.sha256()
            for part in key_dict:
                h.update(part.encode("utf-8", "surrogatepass"))
                h.update(b"\x00")
            h.update(b"\x01")
            for part in val_dict:
                h.update(part.encode("utf-8", "surrogatepass"))
                h.update(b"\x00")
            fp = h.digest()
        cache_on._dict_fingerprint = fp
    return fp


def _tags_sig(req) -> tuple:
    """Cache key for the dictionary-probe part of query compilation: only
    the tag terms (and the exhaustive flag) touch the dictionaries —
    duration/window/limit are scalar passthroughs. The structural
    reserved tag is excluded like the exhaustive flag (it is not a term;
    its OWN compilation caches separately in search/structural.py), so
    structural variants of one base predicate share the probe product.
    The ?agg= reserved tag is likewise not a term — the aggregate stage
    is batch-scoped, never per-predicate."""
    from .analytics import AGG_QUERY_TAG
    from .structural import STRUCTURAL_QUERY_TAG

    return (tuple(sorted((k, v) for k, v in req.tags.items()
                         if k not in (EXHAUSTIVE_SEARCH_TAG,
                                      STRUCTURAL_QUERY_TAG,
                                      AGG_QUERY_TAG))),
            is_exhaustive(req))


def compile_query(key_dict: list, val_dict: list,
                  req: tempopb.SearchRequest,
                  packed_vals: tuple | None = None,
                  cache_on=None, staged_dict=None,
                  host_only: bool = False) -> CompiledQuery | None:
    """Returns None when the block provably cannot match (key absent from
    the key dictionary, or no dictionary value satisfies a term). Under the
    exhaustive debug flag blocks are never pruned: an unsatisfiable term
    compiles to an empty value-range set (scanned, matches nothing).

    `cache_on`: a host container object (the block's ColumnarPages) to
    memoize the dictionary-probe product on, keyed by (dictionary
    CONTENT, tag terms) — the serving path compiles every query against
    every block's dictionaries (O(blocks) per query, VERDICT r2 #1);
    blocks are immutable, so repeated tag-sets hit, and blocks that
    SHARE dictionaries (the common production shape: the same services/
    status codes tenant-wide) share one probe. Bounded LRU per
    dictionary; the fingerprint is computed once per container.

    `staged_dict`: a dict_probe.DeviceDict for this value dictionary —
    when present the substring probe runs ON DEVICE (staging-time
    routing already applied the `search_device_probe_min_vals`
    threshold) and the compiled query carries the [T, v_pad] hit mask
    instead of host-folded ranges. The cache key is unchanged, so
    repeated tag-sets skip all probe work on either path; a cached
    host-path product is served to a device-capable caller (and vice
    versa) — both are exact, only the kernel's membership test
    differs.

    `host_only`: the breaker's host-fallback path — the probe must not
    touch the device AT ALL: staged dictionaries are ignored, and a
    CACHED product carrying a device hit mask is treated as a miss
    (reading its arrays would hang on the very wedged device the
    fallback is escaping); the fresh host product overwrites it."""
    if host_only:
        staged_dict = None
    sig = None
    fp = None
    if cache_on is not None:
        sig = _tags_sig(req)
        fp = _dict_fingerprint(cache_on, key_dict, val_dict)
        with _compile_cache_lock:
            cache = _COMPILE_CACHE.get(fp)
            if cache is None:
                cache = _COMPILE_CACHE[fp] = OrderedDict()
                _COMPILE_CACHE.move_to_end(fp)
                while len(_COMPILE_CACHE) > _COMPILE_CACHE_DICTS:
                    _COMPILE_CACHE.popitem(last=False)
            hit = cache.get(sig)
            if hit is not None:
                cache.move_to_end(sig)
        if hit is not None and not isinstance(hit, str) \
                and hit[3] is not None:
            # the cached product is a DEVICE hit mask: unusable while
            # the breaker blocks the device (or on the explicit host
            # path) — recompile through host and overwrite it
            from tempo_tpu.robustness import BREAKER

            from . import packing

            if host_only or BREAKER.blocking():
                hit = None
            elif packing.is_packed_mask(hit[3]) != packing.PACKING.enabled:
                # minted under the other packed-residency gate state:
                # treat as a miss so one assembled batch never mixes
                # mask formats (the fresh product overwrites it)
                hit = None
        if hit is not None:
            # _PRUNED can only come from a non-exhaustive probe (the
            # exhaustive flag is part of the signature)
            return None if isinstance(hit, str) else _from_probe(hit, req)

    out = _probe_tags(key_dict, val_dict, req, packed_vals,
                      staged_dict=staged_dict, fp=fp)
    if sig is not None:
        from . import packing

        with _compile_cache_lock:
            cache = _COMPILE_CACHE.get(fp)
            if cache is not None:
                cache[sig] = _PRUNED if out is None else out
                while len(cache) > _COMPILE_CACHE_MAX:
                    cache.popitem(last=False)
                # device hit masks pin HBM: keep only the newest few.
                # Bit-packed masks are 8x smaller, so they get an 8x
                # deeper bound at the same HBM charge.
                probed = [s for s, o in cache.items()
                          if not isinstance(o, str) and o[3] is not None
                          and not packing.is_packed_mask(o[3])]
                while len(probed) > _PROBE_CACHE_MAX:
                    cache.pop(probed.pop(0), None)
                packed = [s for s, o in cache.items()
                          if not isinstance(o, str) and o[3] is not None
                          and packing.is_packed_mask(o[3])]
                while len(packed) > _PROBE_CACHE_MAX_PACKED:
                    cache.pop(packed.pop(0), None)
    return None if out is None else _from_probe(out, req)


def _from_probe(probe, req) -> CompiledQuery:
    term_keys, term_vals, val_ranges, val_hits = probe
    return CompiledQuery(
        term_keys=term_keys,
        term_vals=term_vals,
        val_ranges=val_ranges,
        val_hits=val_hits,
        dur_lo=req.min_duration_ms or 0,
        dur_hi=req.max_duration_ms or UINT32_MAX,
        win_start=req.start or 0,
        win_end=req.end or UINT32_MAX,
        limit=req.limit or 20,
    )


def _device_probe_tags(terms, key_dict, staged_dict, exhaustive):
    """Device-path value probe: ONE vmapped kernel call for all terms;
    the only host sync is the [T]-bool any_hits fetch that prune
    decisions need. Returns the probe product or None (pruned).
    Raises ValueError when a needle exceeds the kernel's unroll bound —
    the caller falls back to the exact host scan."""
    from . import dict_probe

    term_key_ids = []
    needles = []
    for k, v in terms:
        i = bisect.bisect_left(key_dict, k)
        if i >= len(key_dict) or key_dict[i] != k:
            if not exhaustive:
                return None
            i = -1
        term_key_ids.append(i)
        nb = v.encode("utf-8")
        if len(nb) > dict_probe.MAX_NEEDLE_BYTES:
            raise ValueError("needle too long for device probe")
        needles.append(nb)
    hits, any_hits = dict_probe.probe_value_hits(staged_dict, needles)
    if not exhaustive:
        any_host = np.asarray(any_hits)
        for t, ki in enumerate(term_key_ids):
            if ki >= 0 and not any_host[t]:
                return None  # no dictionary value satisfies this term
    # missing keys (exhaustive only) must contribute an all-false row
    # regardless of what the probe said for their needle
    key_ok = np.asarray(term_key_ids, dtype=np.int32) >= 0
    if not key_ok.all():
        import jax.numpy as jnp

        hits = hits & jnp.asarray(key_ok)[:, None]
    from . import packing

    if packing.PACKING.enabled:
        # packed residency: the compile-cache product (and everything
        # assembled from it) carries uint32 bit-words instead of 1-byte
        # bools — 8x fewer HBM bytes pinned per cached tag-set; the
        # scan kernels select the bit in-register (packing.mask_select)
        hits = packing.PACKING.pack_hits(hits)
    T = len(term_key_ids)
    term_keys = np.asarray(term_key_ids, dtype=np.int32)
    term_vals = np.full((T, 1), INT32_SENTINEL, dtype=np.int32)
    val_ranges = np.tile(np.array([1, 0], dtype=np.int32), (T, 1, 1))
    return term_keys, term_vals, val_ranges, hits


def _use_device_probe(staged_dict, terms, fp) -> bool:
    """Placement for a staged dictionary's substring probe. Static path
    (planner disabled): staged == device, exactly the pre-planner
    behavior. Planner enabled: the cost model chooses — its "host"
    verdict falls through to the exact host scan even though the packed
    bytes sit in HBM (both paths are exact; only the time moves). The
    decision memoizes through the compile cache: one verdict per
    (dictionary, tag-set), shared by every block of the group and every
    member of a coalesced dispatch."""
    from tempo_tpu.robustness import BREAKER

    from . import dict_probe, planner

    if BREAKER.blocking():
        # device circuit breaker open/half-open: the probe stays on the
        # exact host path even though the packed bytes sit in HBM —
        # results are identical, only the time moves (and the host walk
        # finishes, which a wedged device dispatch would not)
        return False
    p = planner.PLANNER
    if not p.enabled:
        return True
    lmax = max(len(v.encode("utf-8")) for _, v in terms)
    if lmax > dict_probe.MAX_NEEDLE_BYTES:
        return False  # host fallback regardless — no decision burned
    packed = staged_dict.packed
    T = len(terms)
    Lp = dict_probe._pow2(max(1, lmax))
    # the probe kernel's jit signature (dict_probe.probe_value_hits) —
    # lets the planner predict whether a device choice pays a compile
    shape_key = ("probe", staged_dict.mesh is not None,
                 tuple(packed.buf.shape), tuple(packed.off.shape), T, Lp)
    d = p.decide_probe(
        n_vals=packed.n_vals, dict_bytes=packed.real_bytes, n_terms=T,
        resident=True, packed=True, staged_bytes=staged_dict.nbytes,
        n_shards=(packed.n_shards if staged_dict.mesh is not None else 1),
        shape_key=shape_key, fp=packed.fingerprint or fp, site="compile")
    return d.target == "device"


def _probe_tags(key_dict: list, val_dict: list, req,
                packed_vals: tuple | None, staged_dict=None, fp=None):
    """The expensive, tags-only part of compilation: binary-search keys,
    then either the host substring scan folded to range sets, or the
    device probe (staged_dict present, and — when the offload planner is
    enabled — the cost model picks device) yielding a device hit mask.
    Returns (term_keys, term_vals, val_ranges, val_hits) or None
    (pruned)."""
    from .analytics import AGG_QUERY_TAG
    from .structural import STRUCTURAL_QUERY_TAG

    exhaustive = is_exhaustive(req)
    terms = sorted((k, v) for k, v in req.tags.items()
                   if k not in (EXHAUSTIVE_SEARCH_TAG,
                                STRUCTURAL_QUERY_TAG,
                                AGG_QUERY_TAG))
    if staged_dict is not None and terms \
            and _use_device_probe(staged_dict, terms, fp):
        from tempo_tpu.robustness import GUARD, DeviceFault

        try:
            # watchdog-bounded like every other device dispatch: a probe
            # kernel that hangs or errors books a breaker fault and the
            # EXACT host scan below answers instead (byte-identical)
            return GUARD.run(
                "dict_probe",
                lambda: _device_probe_tags(terms, key_dict, staged_dict,
                                           exhaustive))
        except ValueError:
            pass  # oversized needle: exact host path below
        except DeviceFault:
            pass  # wedged/erroring probe: fault booked, host path below
    if terms:
        # the host memmem walk is PR4's motivating cost (312ms at 10M
        # distinct values) — record it under its own mode so the stage
        # histogram shows host-vs-device probe cost side by side, and
        # feed the offload planner's host-side rate (with the dictionary
        # fingerprint, so predicted-vs-actual error resolves)
        import time as _time

        from tempo_tpu.observability import profile
        from . import planner

        # bytes are estimated unconditionally (O(256) sample): a
        # planner-DISABLED deployment's /debug/profile dump must still
        # carry the host-probe byte totals, or the offline calibration
        # replay (scripts/calibrate_offload.py) — whose whole point is
        # deciding if the planner is worth enabling — falls back to the
        # hardcoded default host rate instead of this host's measured one
        nb = len(terms) * planner.dict_bytes_est(val_dict)
        t0 = _time.perf_counter()
        try:
            return _host_probe_tags(terms, key_dict, val_dict,
                                    packed_vals, exhaustive)
        finally:
            dt = _time.perf_counter() - t0
            profile.observe_stage("build", "host_probe", dt, nbytes=nb)
            planner.PLANNER.observe("host_probe", dt, nbytes=nb, fp=fp)
            from . import query_stats

            qs = query_stats.current()
            if qs is not None:
                # the host memmem walk is HOST work this query paid for
                # — the per-query bytes-by-placement split counts it
                qs.add_host_probe(dt, nb)
                qs.add_inspected(nbytes=nb, placement="host")
    return _host_probe_tags(terms, key_dict, val_dict, packed_vals,
                            exhaustive)


def _host_probe_tags(terms, key_dict, val_dict, packed_vals, exhaustive):
    term_key_ids = []
    term_val_sets = []
    for k, v in terms:
        i = bisect.bisect_left(key_dict, k)
        if i >= len(key_dict) or key_dict[i] != k:
            if not exhaustive:
                return None
            term_key_ids.append(-1)
            term_val_sets.append(np.zeros(0, dtype=np.int32))
            continue
        ids = substring_value_ids(val_dict, v, packed=packed_vals)
        if ids.size == 0 and not exhaustive:
            return None
        term_key_ids.append(i)
        term_val_sets.append(np.sort(ids))

    T = len(term_key_ids)
    if T:
        vmax = max(s.size for s in term_val_sets)
        V = 1
        while V < vmax:
            V *= 2
        term_vals = np.full((T, V), INT32_SENTINEL, dtype=np.int32)
        range_sets = [ids_to_ranges(s) for s in term_val_sets]
        rmax = max(r.shape[0] for r in range_sets)
        R = 1
        while R < rmax:
            R *= 2
        # pad with [1,0] — an empty range no value id satisfies
        val_ranges = np.tile(np.array([1, 0], dtype=np.int32), (T, R, 1))
        for t, (s, r) in enumerate(zip(term_val_sets, range_sets)):
            term_vals[t, :s.size] = s
            val_ranges[t, :r.shape[0]] = r
        term_keys = np.asarray(term_key_ids, dtype=np.int32)
    else:
        term_keys = np.zeros(0, dtype=np.int32)
        term_vals = np.zeros((0, 1), dtype=np.int32)
        val_ranges = np.zeros((0, 1, 2), dtype=np.int32)

    # host path: no device hit mask (val_hits slot keeps the probe
    # product a uniform 4-tuple across both paths)
    return term_keys, term_vals, val_ranges, None
