"""Multi-block batched scanning.

The reference searches blocks one job at a time (10 MiB page ranges per
job, searchsharding.go); on TPU the economics invert — kernel dispatch
has fixed cost and HBM is huge, so MANY blocks batch into ONE kernel
call: block page-arrays concatenate along the page axis (geometry is
uniform per (E, C) bucket), a per-page block-id column maps results back,
and the query compiles once against a MERGED dictionary space.

Dictionary merging: each block has private key/val dictionaries. Rather
than re-encoding blocks to a global dictionary (expensive write-side),
the query compiles per block — per-page TERM COLUMNS: for block b and
term t, the key id and value ranges differ; we build [P_total] per-term
key-id arrays and range tables indexed by each page's block, so the
kernel's compares stay uniform. This is the context-parallel analog of
SURVEY.md §5 long-context: the corpus axis (blocks × pages) is the
sequence axis, sharded over the mesh.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from tempo_tpu import tempopb
from tempo_tpu.observability import profile

from .columnar import ColumnarPages
from .dict_probe import _pow2
from .engine import DEFAULT_TOP_K, masked_topk
from . import packing
from .packing import duration_ok, mask_select_grouped, unpack_ids
from .pipeline import (
    CompiledQuery,
    compile_query,
    ids_to_ranges,
    INT32_SENTINEL,
    UINT32_MAX,
)

import functools


@dataclass
class BlockBatch:
    """Several blocks' pages stacked along the page axis on device."""
    device: dict                    # arrays [P_total, ...]
    page_block: np.ndarray          # int32 [P_total] block index per page
    blocks: list                    # list[ColumnarPages]
    page_offset: list               # start page of each block in the stack
    # dict fingerprint -> dict_probe.DeviceDict for every DISTINCT value
    # dictionary that cleared the device-probe threshold at staging time:
    # query compilation then runs the substring probe on device against
    # these instead of the host memmem walk. Staged with the batch,
    # accounted in `nbytes`, re-uploaded with it after an HBM eviction.
    staged_dicts: dict = field(default_factory=dict)
    # packed-residency width descriptor (search/packing.py): static per
    # batch, part of every consuming kernel's jit shape key; None = the
    # unpacked legacy layout
    widths: tuple | None = None
    # what the unpacked layout would pin for these page arrays — the
    # logical side of the physical/logical accounting split (equal to
    # device_nbytes when widths is None)
    logical_device_nbytes: int = 0
    # structural-engine span columns on device (search/structural.py):
    # staged with the batch only when search_structural_enabled AND some
    # block carries spans; None keeps the legacy kernel pytree exactly
    span_device: dict | None = None
    # True = span columns are in the segment-aligned SHARDED layout
    # (search_structural_shard_spans): chunk-per-shard span axis with
    # shard-local coordinates, so the dist kernels evaluate the
    # structural mask inside shard_map. Static at every consuming call
    # site — part of the jit shape key like `widths`
    span_sharded: bool = False

    @property
    def n_pages(self) -> int:
        return int(self.page_block.shape[0])

    @property
    def device_nbytes(self) -> int:
        """Physical HBM pinned by the stacked page arrays alone (packed
        bytes when widths is set; span columns included — they are
        resident with the batch)."""
        hit = getattr(self, "_device_nbytes", None)
        if hit is None:
            hit = self._device_nbytes = int(
                sum(int(a.nbytes) for a in self.device.values())
                + sum(int(a.nbytes)
                      for a in (self.span_device or {}).values()))
        return hit

    @property
    def nbytes(self) -> int:
        """HBM pinned by this batch: the stacked page arrays PLUS the
        staged dictionary arrays — the cache budget must see both or a
        high-cardinality tenant's dictionaries become unaccounted
        residents. Physical (packed) bytes: that is what the budget
        buys, and why packing fits ~2x more blocks per budget."""
        return (self.device_nbytes
                + int(sum(d.nbytes for d in self.staged_dicts.values())))

    @property
    def logical_nbytes(self) -> int:
        """The unpacked-layout equivalent of `nbytes` (dictionaries are
        already byte buffers — same on both sides of the split)."""
        return (int(self.logical_device_nbytes or self.device_nbytes)
                + int(sum(d.nbytes for d in self.staged_dicts.values())))


@dataclass
class HostBatch:
    """The host-RAM half of a staged batch: stacked (padded) numpy arrays
    ready for a device put. This is the overflow tier between the object
    store and HBM — an HBM-evicted batch re-stages from here with ONE
    H2D copy, skipping IO + decompress + restack (VERDICT r3 #2). Under
    owner-routed HBM it is also the NON-owner serving tier (host_scan
    runs over these arrays), which is why an ownership rebalance drops
    only the HBM half: the host copy keeps serving routed-away queries."""
    cat: dict                       # stacked host arrays incl. page_block
    page_block: np.ndarray
    blocks: list                    # list[ColumnarPages]
    page_offset: list
    # dict fingerprint -> dict_probe.PackedDeviceDict: the host half of
    # the device-probe staging, packed once per distinct dictionary and
    # kept with the batch so an HBM-evicted batch re-uploads with one
    # H2D copy, not a re-pack of 10M strings
    packed_dicts: dict = field(default_factory=dict)
    # packed-residency descriptor + logical bytes of the stacked copies
    # (see BlockBatch) — the host tier stages the SAME packed format, so
    # an HBM re-stage is one H2D put of the packed arrays and the
    # host-fallback scan runs the packed kernel directly
    widths: tuple | None = None
    cat_logical_nbytes: int = 0
    # structural span columns, host tier (see BlockBatch.span_device):
    # the host-fallback scan runs the same structural kernel over these
    span_cat: dict | None = None

    @property
    def cat_nbytes(self) -> int:
        """Physical bytes of the stacked copies alone (the H2D unit)."""
        return int(sum(a.nbytes for a in self.cat.values())
                   + sum(a.nbytes
                         for a in (self.span_cat or {}).values()))

    @property
    def logical_nbytes(self) -> int:
        """`nbytes` with the stacked copies at the unpacked layout —
        the logical side of the host-tier accounting split."""
        return int((self.cat_logical_nbytes or self.cat_nbytes)
                   + sum(b.nbytes for b in self.blocks)
                   + sum(d.nbytes for d in self.packed_dicts.values()))

    @property
    def nbytes(self) -> int:
        # the entry pins BOTH the stacked copies and each block's source
        # ColumnarPages (needed for result rendering + query compile) —
        # budget against real RAM, not just the cat arrays, or a 32 GB
        # budget pins ~64 GB (code-review r4)
        return int(self.cat_nbytes
                   + sum(b.nbytes for b in self.blocks)
                   + sum(d.nbytes for d in self.packed_dicts.values()))

    @property
    def n_pages(self) -> int:
        # duck-types with BlockBatch so the host-fallback scan renders
        # results through the same MultiBlockEngine.results
        return int(self.page_block.shape[0])


def _pack_batch_dicts(blocks: list[ColumnarPages],
                      probe_min_vals: int | None,
                      n_shards: int = 1) -> dict:
    """fp -> PackedDeviceDict for every DISTINCT value dictionary above
    the device-probe threshold (None = dict_probe default; <= 0
    disables). Packing memoizes on the immutable block container, so an
    evicted batch restacked from the same blocks packs nothing.

    With the offload planner enabled, dictionaries above the floor get a
    per-GROUP stage-time decision (once per distinct dictionary per
    staged batch — a fused multi-query dispatch over this batch then
    inherits one verdict, never re-plans per member): a "host" verdict
    skips the pack+stage entirely, so the HBM and H2D investment is only
    made where the cost model says the device probe pays it back. The
    verdict is frozen into the staged batch until it re-stages (HBM
    eviction, blocklist churn) — the same lifetime every other staging
    property has."""
    from . import dict_probe, planner
    from .pipeline import _dict_fingerprint

    mv = (dict_probe.DEVICE_PROBE_MIN_VALS if probe_min_vals is None
          else probe_min_vals)
    out: dict = {}
    if mv <= 0:
        return out
    S = max(1, int(n_shards))
    vetoed: set = set()  # host verdicts memoize like device ones: ONE
    # decision per distinct dictionary per staged batch, even when many
    # blocks share a vetoed dictionary (no per-block ring/metric spam)
    for b in blocks:
        if len(b.val_dict) < mv:
            continue
        fp = _dict_fingerprint(b, b.key_dict, b.val_dict)
        if fp in out or fp in vetoed:
            continue
        if planner.stage_veto(b, fp, n_shards=S):
            vetoed.add(fp)
            continue
        hit = getattr(b, "_device_dict_packed", None)
        packed_ok = hit is not None and hit.n_shards == S
        if packed_ok:
            out[fp] = hit
        else:
            out[fp] = b._device_dict_packed = dict_probe.pack_device_dict(
                b.val_dict, n_shards=S, fingerprint=fp)
    return out


def stack_host(blocks: list[ColumnarPages],
               pad_to: int | None = None,
               probe_min_vals: int | None = 0,
               n_shards: int = 1) -> HostBatch:
    """Concatenate uniform-geometry blocks along the page axis on host.

    `probe_min_vals` routes value dictionaries at/above that size into
    the packed device-probe staging (`HostBatch.packed_dicts`); the
    default 0 keeps direct/test callers dictionary-free — the serving
    path (MultiBlockEngine.stage_host) passes its configured
    threshold."""
    E = blocks[0].geometry.entries_per_page
    C = C0 = max(b.geometry.kv_per_entry for b in blocks)
    n_keys = max(len(b.key_dict) for b in blocks)
    n_vals = max(len(b.val_dict) for b in blocks)
    # packed residency (search/packing.py): choose per-column storage
    # widths from the recorded dictionary cardinalities + the duration
    # rollup. Gate off = widths None = the legacy layout below,
    # byte-identical, one attribute read.
    widths = None
    if packing.PACKING.enabled:
        widths = packing.PACKING.plan_widths(
            n_keys, n_vals, max(b.max_dur_ms() for b in blocks))
        if widths is not None and "u4" in widths[:2] and C % 2:
            C += 1  # nibble packing pairs slots; both kv columns must
            # unpack to one slot count (extra slot is pad, never matches)
    # narrow the kv columns to the smallest dtype the dictionaries allow:
    # the kernel compares against int32 term tables with XLA promoting
    # inline (no widened copy materializes), so the RESIDENT format can
    # be this narrow — the kv pair is ~70% of a batch's bytes, and both
    # HBM footprint and an evicted group's re-stage time (H2D-bound
    # through the axon relay at ~50 MB/s) shrink proportionally
    # (VERDICT r4 #2). Dtype chosen BEFORE stacking so concatenate
    # produces the narrow array directly (no full-width transient);
    # packed widths likewise transform per block before stacking.
    def _narrow(n):
        return (np.int8 if n <= 127          # -1 sentinel stays in range
                else np.int16 if n <= 32_767 else np.int32)
    kv_dtype = {"kv_key": _narrow(n_keys), "kv_val": _narrow(n_vals)}
    kv_width = None if widths is None else {"kv_key": widths[0],
                                            "kv_val": widths[1]}
    arrays = {name: [] for name in ("kv_key", "kv_val", "entry_start",
                                    "entry_end", "entry_dur", "entry_valid")}
    page_block = []
    page_offset = []
    total = 0
    for bi, b in enumerate(blocks):
        if b.geometry.entries_per_page != E:
            raise ValueError("blocks must share entries_per_page to batch")
        page_offset.append(total)
        P = b.n_pages
        for name in arrays:
            arr = getattr(b, name)
            if name in ("kv_key", "kv_val"):
                if kv_width is None:
                    arr = arr.astype(kv_dtype[name], copy=False)
                    if arr.shape[2] < C:
                        pad = np.full((P, E, C - arr.shape[2]), -1,
                                      dtype=kv_dtype[name])
                        arr = np.concatenate([arr, pad], axis=2)
                else:
                    if arr.shape[2] < C:
                        pad = np.full((P, E, C - arr.shape[2]), -1,
                                      dtype=arr.dtype)
                        arr = np.concatenate([arr, pad], axis=2)
                    arr = packing.pack_ids_array(arr, kv_width[name])
            arrays[name].append(arr)
        page_block.extend([bi] * P)
        total += P
    if len(blocks) == 1 and not (pad_to and pad_to > total):
        # single-block fast path: the block already matches the bucket
        # shape, so the concatenate below would be a pure copy of every
        # column — serve views of the (possibly just-transformed)
        # arrays instead
        cat = {k: v[0] for k, v in arrays.items()}
    else:
        cat = {k: np.concatenate(v, axis=0) for k, v in arrays.items()}
    page_block = np.asarray(page_block, dtype=np.int32)

    if widths is not None:
        # duration column: exact uint16, or uint16 buckets + residual
        # (packing.pack_duration) — packed BEFORE page padding so the
        # pad rows below are valid zero buckets
        q, res = packing.pack_duration(cat["entry_dur"], widths[2])
        cat["entry_dur"] = q
        if res is not None:
            cat["entry_dur_res"] = res

    if pad_to and pad_to > total:
        extra = pad_to - total
        for name, arr in cat.items():
            pad = np.zeros((extra,) + arr.shape[1:], dtype=arr.dtype)
            if name in ("kv_key", "kv_val") and widths is None:
                pad -= 1  # packed layouts pad with code 0 (= id -1)
            cat[name] = np.concatenate([arr, pad], axis=0)
        page_block = np.concatenate([
            page_block, np.full(extra, -1, dtype=np.int32)
        ])

    cat["page_block"] = page_block
    from .structural import STRUCTURAL

    span_cat = None
    if STRUCTURAL.enabled:
        # structural span segments stack alongside the page columns —
        # gate off is one attribute read and the identical layout
        span_cat = STRUCTURAL.stack_spans(blocks, E,
                                          int(page_block.shape[0]))
    entries_padded = int(page_block.shape[0]) * E
    return HostBatch(cat=cat, page_block=page_block, blocks=blocks,
                     page_offset=page_offset,
                     packed_dicts=_pack_batch_dicts(blocks, probe_min_vals,
                                                    n_shards=n_shards),
                     widths=widths, span_cat=span_cat,
                     cat_logical_nbytes=(
                         packing.logical_nbytes(entries_padded, C0,
                                                n_keys, n_vals)
                         + int(page_block.nbytes)))


def place_batch(host: HostBatch, sharding=None, mesh=None) -> BlockBatch:
    """H2D: put a host-stacked batch on device(s). `mesh` shards staged
    probe dictionaries along the value axis when they were packed for
    that mesh size (engine.stage_host packs with the engine's shard
    count); any mismatch places them unsharded — still correct, the
    probe just runs on one device."""
    import time

    from . import dict_probe

    from tempo_tpu.robustness import FAULTS

    if FAULTS.active:
        FAULTS.hit("h2d_delay")  # slow/wedged relay during staging puts
    mode = "mesh" if sharding is not None else "batched"
    t0 = time.perf_counter()
    cat = host.cat
    if sharding is not None:
        if jax.process_count() > 1:
            # multi-host: each process transfers ONLY its devices' page
            # slices (the callback runs per addressable shard) — the
            # per-host staging of the local shard; device_put of a global
            # array would require every device to be addressable
            dev = {
                k: jax.make_array_from_callback(
                    v.shape, sharding, lambda idx, v=v: v[idx])
                for k, v in cat.items()
            }
        else:
            dev = {k: jax.device_put(v, sharding) for k, v in cat.items()}
    else:
        dev = {k: jnp.asarray(v) for k, v in cat.items()}
    # page-array H2D only; the dictionary placement below times itself
    # (mode=dict_probe) inside place_device_dict
    profile.observe_stage("h2d", mode, time.perf_counter() - t0,
                          nbytes=sum(int(v.nbytes) for v in cat.values()))
    span_dev = None
    span_sharded = False
    if host.span_cat is not None:
        from .structural import STRUCTURAL

        span_host = host.span_cat
        if sharding is not None and STRUCTURAL.shard_spans:
            # segment-aligned span sharding: each trace's contiguous
            # span run lands whole on its page's shard, coordinates
            # rebased shard-local — the host tier KEEPS the replicated
            # layout (host_scan's byte-identical fallback), only the
            # device placement reshards
            E = host.blocks[0].geometry.entries_per_page
            n_sh = int(sharding.mesh.devices.size)
            sh = STRUCTURAL.shard_span_segment(
                span_host, n_sh, int(host.page_block.shape[0]), E)
            if sh is not None:
                span_host = sh
                span_sharded = True
        if sharding is not None and span_sharded:
            from jax.sharding import NamedSharding, PartitionSpec as P
            from tempo_tpu.parallel.mesh import SCAN_AXIS

            # every sharded span array (span axis AND the [P, E] entry
            # range columns) splits on its leading axis, aligned with
            # the page sharding — per-shard span HBM ~1/P of replicated
            sh_spec = NamedSharding(sharding.mesh, P(SCAN_AXIS))
            if jax.process_count() > 1:
                span_dev = {
                    k: jax.make_array_from_callback(
                        v.shape, sh_spec, lambda idx, v=v: v[idx])
                    for k, v in span_host.items()
                }
            else:
                span_dev = {k: jax.device_put(v, sh_spec)
                            for k, v in span_host.items()}
        elif sharding is not None and jax.process_count() > 1:
            # span columns REPLICATE (the legacy layout): parent
            # pointers and segment ranges index the GLOBAL span axis,
            # and the dist kernels evaluate the structural mask outside
            # shard_map then hand the [P,E] verdicts to the sharded scan
            from jax.sharding import NamedSharding, PartitionSpec as P

            rep = NamedSharding(sharding.mesh, P())
            span_dev = {
                k: jax.make_array_from_callback(
                    v.shape, rep, lambda idx, v=v: v[idx])
                for k, v in span_host.items()
            }
        else:
            span_dev = {k: jnp.asarray(v)
                        for k, v in span_host.items()}
    staged = {}
    for fp, pd in host.packed_dicts.items():
        dict_mesh = (mesh if mesh is not None and pd.n_shards > 1
                     and pd.n_shards == int(mesh.devices.size) else None)
        staged[fp] = dict_probe.place_device_dict(pd, mesh=dict_mesh)
    return BlockBatch(device=dev, page_block=host.page_block,
                      blocks=host.blocks, page_offset=host.page_offset,
                      staged_dicts=staged, widths=host.widths,
                      logical_device_nbytes=host.cat_logical_nbytes,
                      span_device=span_dev, span_sharded=span_sharded)


def stack_blocks(blocks: list[ColumnarPages], pad_to: int | None = None,
                 sharding=None, probe_min_vals: int | None = 0,
                 mesh=None, n_shards: int = 1) -> BlockBatch:
    """Concatenate uniform-geometry blocks along the page axis and place
    on device. With `sharding` (a NamedSharding over the page axis) the
    stacked arrays shard across the mesh instead of the default device."""
    return place_batch(stack_host(blocks, pad_to=pad_to,
                                  probe_min_vals=probe_min_vals,
                                  n_shards=n_shards),
                       sharding=sharding, mesh=mesh)


@dataclass
class MultiQuery:
    """Per-block compiled query folded into block-indexed tables."""
    term_keys: np.ndarray    # int32 [B, T] key id per (block, term); -1 = prune
    val_ranges: np.ndarray   # int32 [B, T, R, 2]
    dur_lo: int
    dur_hi: int
    win_start: int
    win_end: int
    limit: int
    n_terms: int
    # device-probe product (search/dict_probe.py): bool [G, T, Vmax]
    # per-dictionary-GROUP value hit masks on device, and the int32 [B]
    # block -> group row map (-1 = this block compiled through the host
    # range path; its val_ranges row applies). The probe output feeds
    # the kernel directly — no id-set ever crossed the host boundary.
    val_hits: object = None
    block_group: np.ndarray | None = None
    # compiled structural predicate (structural.CompiledStructural):
    # static plan + dynamic tables ANDed into the entry mask by the
    # kernels; None = the legacy pytree and executables exactly
    structural: object = None
    # staged ?agg= stage (analytics.AggStage) — batch-scoped composite
    # keys + service table; None = no aggregate stage compiled in
    agg_stage: object = None


def _dict_groups(blocks: list[ColumnarPages], cache_on=None):
    """(fp_of, rep_idx, rows_of): which blocks share which dictionary.
    Query-INDEPENDENT, so it memoizes on `cache_on` (the immutable
    stacked batch): a novel tag-set at 10K blocks then costs
    distinct-dict probes + numpy assembly, not a 10K python loop —
    the dominant share of the r4 cold-tags host cost (VERDICT r4 #3)."""
    from .pipeline import _dict_fingerprint

    if cache_on is not None:
        hit = getattr(cache_on, "_dict_groups", None)
        if hit is not None:
            return hit
    fp_of: list[bytes] = []
    rep_idx: dict[bytes, int] = {}
    rows_of: dict[bytes, list[int]] = {}  # fp → block rows, same pass —
    # a per-group flatnonzero rescan would be O(dicts × B), quadratic
    # exactly when every block has its own dictionary
    for i, b in enumerate(blocks):
        fp = _dict_fingerprint(b, b.key_dict, b.val_dict)
        fp_of.append(fp)
        rep_idx.setdefault(fp, i)
        rows_of.setdefault(fp, []).append(i)
    out = (fp_of, rep_idx, rows_of)
    if cache_on is not None:
        cache_on._dict_groups = out
    return out


def compile_multi(blocks: list[ColumnarPages], req: tempopb.SearchRequest,
                  skip: list[bool] | None = None,
                  cache_on=None, host_only: bool = False) -> MultiQuery | None:
    """Compile the request against every block's dictionaries; blocks that
    prune get key id -1 (no page of theirs can match). `skip[i]` marks
    blocks already pruned by their header rollup — they stay in the batch
    (staging is query-independent) and are masked back to the -1 sentinel
    after assembly. `cache_on`: immutable object (the stacked batch) that
    memoizes the per-block dictionary grouping across queries.
    `host_only`: the breaker's host-fallback compile — no staged
    dictionary is consulted and cached device-resident probe products
    are bypassed (see compile_query)."""
    from tempo_tpu.ops import native
    from .pipeline import NATIVE_SCAN_THRESHOLD

    use_packed = bool(req.tags) and native.available()
    # one probe per DISTINCT dictionary, not per block: a 10K-block
    # tenant usually cycles a handful of dictionary contents (same
    # services/status codes everywhere)
    fp_of, rep_idx, rows_of = _dict_groups(blocks, cache_on=cache_on)
    # dictionaries the batch staged for the on-device probe (BlockBatch
    # .staged_dicts, keyed by the same fingerprints): their substring
    # scan runs on device and yields a hit mask instead of host ranges
    staged_dicts = getattr(cache_on, "staged_dicts", None) or {}
    compiled: dict[bytes, CompiledQuery | None] = {}
    for fp, i in rep_idx.items():
        b = blocks[i]
        compiled[fp] = compile_query(
            b.key_dict, b.val_dict, req,
            packed_vals=(b.packed_val_dict()
                         if use_packed and len(b.val_dict) >= NATIVE_SCAN_THRESHOLD
                         else None),
            cache_on=b,  # blocks are immutable: repeated tag-sets skip
                         # the O(dict) probe (VERDICT r2 #1 host cost)
            staged_dict=None if host_only else staged_dicts.get(fp),
            host_only=host_only,
        )
    per_block: list[CompiledQuery | None] = [
        None if (skip is not None and skip[i]) else compiled[fp_of[i]]
        for i in range(len(blocks))
    ]
    if all(cq is None for cq in per_block):
        return None
    # term count comes from the compiled queries, not len(req.tags): the
    # exhaustive debug tag is not itself a predicate, so raw-tag counting
    # would leave an unmatchable extra -1 key per block
    T = max((cq.n_terms for cq in per_block if cq is not None), default=0)
    B = len(blocks)
    rmax = 1
    for cq in per_block:
        if cq is not None and cq.n_terms:
            rmax = max(rmax, cq.val_ranges.shape[1])
    R = 1
    while R < rmax:
        R *= 2
    term_keys = np.full((B, max(1, T)), -1, dtype=np.int32)
    val_ranges = np.tile(np.array([1, 0], dtype=np.int32), (B, max(1, T), R, 1))
    # assemble per distinct dictionary: one row-broadcast per group
    # instead of a python loop over every (block, term)
    for fp, cq in compiled.items():
        if cq is None or not cq.n_terms:
            continue
        rows = np.asarray(rows_of[fp], dtype=np.int64)
        # clamp to the assembled width: T/R are sized over the UNSKIPPED
        # blocks' queries; a dictionary whose every row is header-skipped
        # may compile wider, and its rows get masked below anyway
        t_n = min(cq.n_terms, term_keys.shape[1])
        r_n = min(cq.val_ranges.shape[1], val_ranges.shape[2])
        term_keys[rows[:, None], np.arange(t_n)] = cq.term_keys[:t_n]
        val_ranges[rows[:, None, None],
                   np.arange(t_n)[:, None],
                   np.arange(r_n)] = cq.val_ranges[:t_n, :r_n]
    # device-probed dictionary groups: stack their [T, v_pad] hit masks
    # along a GROUP axis (pad T and V to the assembled/maximum widths —
    # device ops, nothing syncs to host) and map each block row to its
    # group; -1 rows keep the host range path, so a batch can mix
    # device-probed high-cardinality blocks with host-compiled small ones
    probe_fps = [fp for fp, cq in compiled.items()
                 if cq is not None and cq.n_terms
                 and cq.val_hits is not None]
    val_hits = block_group = None
    if probe_fps:
        Tp = max(1, T)
        # one assembled mask table must be format-uniform: a compile-
        # cache product minted before a packed-residency gate flip can
        # still be bool while its peers are bit-packed words — pack the
        # stragglers (cheap device op) rather than stacking mixed dtypes
        hs = {fp: compiled[fp].val_hits for fp in probe_fps}
        if any(packing.is_packed_mask(h) for h in hs.values()):
            hs = {fp: packing.pack_mask_words(h) for fp, h in hs.items()}
        Vm = max(int(h.shape[1]) for h in hs.values())
        padded = []
        for fp in probe_fps:
            h = hs[fp]
            h = jnp.pad(h, ((0, Tp - h.shape[0]), (0, Vm - h.shape[1])))
            padded.append(h)
        val_hits = jnp.stack(padded)                       # [G, Tp, Vm]
        block_group = np.full(B, -1, dtype=np.int32)
        for g, fp in enumerate(probe_fps):
            block_group[np.asarray(rows_of[fp], dtype=np.int64)] = g

    if skip is not None and any(skip):
        # header-pruned rows back to the unmatchable sentinel (their
        # dict group was assembled wholesale above)
        sk = np.asarray(skip, dtype=bool)
        term_keys[sk] = -1
        val_ranges[sk] = np.array([1, 0], dtype=np.int32)
        if block_group is not None:
            block_group[sk] = -1  # term_keys -1 + range path: can't match

    any_cq = next(cq for cq in per_block if cq is not None)
    return MultiQuery(
        term_keys=term_keys, val_ranges=val_ranges,
        dur_lo=any_cq.dur_lo, dur_hi=any_cq.dur_hi,
        win_start=any_cq.win_start, win_end=any_cq.win_end,
        limit=any_cq.limit, n_terms=T,
        val_hits=val_hits, block_group=block_group,
    )


@dataclass
class CoalescedQuery:
    """Several requests' MultiQueries stacked along a new QUERY axis for
    one fused dispatch over a shared staged batch — the continuous-
    batching shape: predicate tables become [Q, B, ...] and the kernel
    computes per-query masks + per-query top-k in a single launch."""
    term_keys: np.ndarray    # int32 [Q, B, T]
    val_ranges: np.ndarray   # int32 [Q, B, T, R, 2]
    term_active: np.ndarray  # bool [Q, T] — False = padding term (no-op)
    dur_lo: np.ndarray       # uint32 [Q]
    dur_hi: np.ndarray       # uint32 [Q]
    win_start: np.ndarray    # uint32 [Q]
    win_end: np.ndarray      # uint32 [Q]
    n_terms: int             # padded (static) term count
    n_queries: int           # REAL queries; padding rows match nothing
    # device-probe product stacked along the query axis: bool
    # [Q, G, T, Vmax] hit masks + int32 [Q, B] block->group rows (a
    # member query that compiled through the host path gets an all -1
    # row — its range tables apply). None when no member probed.
    val_hits: object = None
    block_group: np.ndarray | None = None
    # plan-shape stacking (structural.StackedStructural): ONE shared
    # static plan + [Q, ...]-stacked structural parameter tables. None
    # = the legacy pytree and executables exactly.
    structural: object = None
    # batch-scoped ?agg= stage (analytics.AggStage), shared across the
    # query axis — set when any member requested aggregation
    agg_stage: object = None


def stack_queries(mqs: list[MultiQuery]) -> CoalescedQuery:
    """Stack compiled queries over the SAME block batch along the query
    axis. Every shape axis (Q, T, R) pads to a power of two so the jit
    cache keys on predicate SHAPE buckets, never predicate values —
    different tag-sets share one compiled executable.

    Structural queries stack too, when EVERY member carries one and all
    plans are the identical static descriptor (the coalescer's
    stack_group_key guarantees this grouping): their parameter tables
    stack along the same query axis (structural.stack_structural) and
    the shared plan stays one jit key. A mixed group — some structural,
    some not, or differing plans — is a caller bug and raises rather
    than silently dropping a predicate.

    Pad semantics: extra terms of a real query are inactive (neutral-TRUE
    in the AND); whole pad QUERIES get an empty duration window
    (dur_lo=1 > dur_hi=0) so their mask is all-false and their top-k is
    all sentinel — dead lanes, not wrong results (structural pad lanes
    replay member 0's tables behind that same all-false legacy mask)."""
    Qn = len(mqs)
    sts = [getattr(mq, "structural", None) for mq in mqs]
    stacked_st = None
    if any(st is not None for st in sts):
        from .structural import (STRUCTURAL, canonical_bucket,
                                 stack_bucketed, stack_structural)

        if any(st is None for st in sts):
            # plan-shape grouping happens UPSTREAM (stack_group_key);
            # a mixed stack here would silently drop a predicate
            raise ValueError(
                "coalesced structural queries must all share one plan")
        if all(st.plan == sts[0].plan for st in sts[1:]):
            # same exact plan: the exact-descriptor stack (bucketing
            # adds nothing when the plans already share one jit key)
            stacked_st = stack_structural(sts, _pow2(Qn))
        else:
            # mixed plans fuse ONLY through the bucket canonicalization
            # (the bucket_group_key grouping contract): every member
            # must land in the same bucket descriptor
            buckets = {canonical_bucket(st.plan,
                                        STRUCTURAL.bucket_max_nodes)
                       for st in sts}
            if len(buckets) != 1 or None in buckets:
                raise ValueError(
                    "coalesced structural queries must share one plan "
                    "or canonicalize into one bucket shape")
            stacked_st = stack_bucketed(sts, _pow2(Qn), buckets.pop())
    B = mqs[0].term_keys.shape[0]
    Q = _pow2(Qn)
    T = _pow2(max(1, max(mq.n_terms for mq in mqs)))
    R = _pow2(max(mq.val_ranges.shape[2] for mq in mqs))
    term_keys = np.full((Q, B, T), -1, dtype=np.int32)
    val_ranges = np.tile(np.array([1, 0], dtype=np.int32), (Q, B, T, R, 1))
    term_active = np.zeros((Q, T), dtype=bool)
    dur_lo = np.ones(Q, dtype=np.uint32)      # pad: empty dur range
    dur_hi = np.zeros(Q, dtype=np.uint32)
    win_start = np.zeros(Q, dtype=np.uint32)
    win_end = np.zeros(Q, dtype=np.uint32)
    for qi, mq in enumerate(mqs):
        if mq.term_keys.shape[0] != B:
            raise ValueError("coalesced queries must share one batch")
        t_n = mq.term_keys.shape[1]
        r_n = mq.val_ranges.shape[2]
        term_keys[qi, :, :t_n] = mq.term_keys
        val_ranges[qi, :, :t_n, :r_n] = mq.val_ranges
        term_active[qi, :mq.n_terms] = True
        dur_lo[qi] = mq.dur_lo
        dur_hi[qi] = min(mq.dur_hi, 0xFFFFFFFF)
        win_start[qi] = mq.win_start
        win_end[qi] = min(mq.win_end, 0xFFFFFFFF)
    # device-probe members: stack their [G, T, V] group masks along the
    # query axis (device pads/stack — the probe product stays on chip
    # through the fused dispatch); host-path and pad queries carry all-
    # false masks behind an all -1 block_group row, so they never read it
    val_hits = block_group = None
    if any(mq.val_hits is not None for mq in mqs):
        probed = [mq for mq in mqs if mq.val_hits is not None]
        # format-uniform like compile_multi: members compiled across a
        # packed-residency gate flip pack up before stacking
        hits = {id(mq): mq.val_hits for mq in probed}
        if any(packing.is_packed_mask(h) for h in hits.values()):
            hits = {k: packing.pack_mask_words(h) for k, h in hits.items()}
        Gm = max(int(h.shape[0]) for h in hits.values())
        Vm = max(int(h.shape[2]) for h in hits.values())
        dt = next(iter(hits.values())).dtype
        zero = jnp.zeros((Gm, T, Vm), dtype=dt)
        block_group = np.full((Q, B), -1, dtype=np.int32)
        rows = []
        for qi in range(Q):
            mq = mqs[qi] if qi < Qn else None
            if mq is None or mq.val_hits is None:
                rows.append(zero)
                continue
            h = hits[id(mq)]
            rows.append(jnp.pad(h, ((0, Gm - h.shape[0]),
                                    (0, T - h.shape[1]),
                                    (0, Vm - h.shape[2]))))
            block_group[qi] = mq.block_group
        val_hits = jnp.stack(rows)                  # [Q, Gm, T, Vm]
    aggs = [mq for mq in mqs if getattr(mq, "agg_stage", None) is not None]
    return CoalescedQuery(
        term_keys=term_keys, val_ranges=val_ranges, term_active=term_active,
        dur_lo=dur_lo, dur_hi=dur_hi, win_start=win_start, win_end=win_end,
        n_terms=T, n_queries=Qn, val_hits=val_hits, block_group=block_group,
        structural=stacked_st,
        # members share one batch, so their AggStage is the same
        # memoized object — any requester turns the stage on
        agg_stage=aggs[0].agg_stage if aggs else None)


def multi_entry_mask(kv_key, kv_val, entry_start, entry_end, entry_dur,
                     entry_valid, page_block, term_keys, val_ranges,
                     dur_lo, dur_hi, win_start, win_end, *, n_terms: int,
                     term_active=None, val_hits=None, block_group=None,
                     entry_dur_res=None, widths=None):
    """The multi-block predicate: [P,E] bool mask of matching entries.
    Like engine.entry_match_mask but term columns are selected per page
    through the page_block index: key id and ranges become [P]-indexed
    gathers over the SMALL [B,...] tables (cheap — B entries, not 8M).
    Shared by the single-device kernel and the shard_map distributed
    kernel (each shard evaluates it over its local page slice).

    `term_active` ([T] bool, optional): the query-coalescing pad axis —
    queries stacked along a query axis share one static n_terms, so a
    query with fewer real terms marks the excess inactive and they drop
    out of the AND (neutral-TRUE). This is distinct from the -1 key
    sentinel, which means 'term exists but this block can never match
    it' (neutral-FALSE for the block).

    `val_hits` (bool [G, T, Vmax]) + `block_group` (int32 [P-indexable
    [B]]): the device-probe product — pages of a block mapped to group
    g >= 0 test value membership with a hit-mask lookup on that group's
    row (one gather per term); group -1 pages keep the range compares,
    so device-probed and host-compiled blocks mix in one batch.

    `widths` (STATIC at every call site — part of the jit shape key) +
    `entry_dur_res`: the packed-residency descriptor (search/packing.py).
    The kv unpack runs INSIDE the term body so the widening shifts/masks
    fuse into the compares of each pass over the columns — no unpacked
    copy materializes in HBM; packed (uint32-word) hit masks select
    their bit in-register the same way."""
    kw, vw, dw = widths if widths is not None else (None, None, None)
    safe_block = jnp.maximum(page_block, 0)
    mask = entry_valid & (page_block >= 0)[:, None]
    if n_terms:
        if val_hits is not None:
            bg_page = block_group[safe_block]              # [P]
            probe_page = (bg_page >= 0)[:, None, None]     # [P,1,1]
            safe_g = jnp.maximum(bg_page, 0)

        def term_body(t, acc):
            kk = unpack_ids(kv_key, kw)                    # fused widen
            vv = unpack_ids(kv_val, vw)
            k_per_page = term_keys[safe_block, t]          # [P]
            keym = kk == k_per_page[:, None, None]         # [P,E,C]
            lo = val_ranges[safe_block, t, :, 0]           # [P,R]
            hi = val_ranges[safe_block, t, :, 1]
            v = vv[..., None]                              # [P,E,C,1]
            valm = ((v >= lo[:, None, None, :]) &
                    (v <= hi[:, None, None, :])).any(-1)   # [P,E,C]
            if val_hits is not None:
                safe_v = jnp.maximum(vv, 0).astype(jnp.int32)
                mh = (mask_select_grouped(val_hits, safe_g[:, None, None],
                                          t, safe_v)
                      & (vv >= 0))                         # [P,E,C]
                valm = jnp.where(probe_page, mh, valm)
            hit = jnp.any(keym & valm, axis=-1)            # [P,E]
            if term_active is not None:
                hit = hit | ~term_active[t]
            return acc & hit

        mask = jax.lax.fori_loop(0, n_terms, term_body, mask)

    mask = mask & duration_ok(entry_dur, entry_dur_res, dur_lo, dur_hi, dw)
    mask = mask & (entry_end.astype(jnp.uint32) >= win_start.astype(jnp.uint32))
    mask = mask & (entry_start.astype(jnp.uint32) <= win_end.astype(jnp.uint32))
    return mask


def agg_entry_counts(mask, entry_agg, n_keys: int):
    """Dense aggregate counts over the verdict mask: entries the final
    mask accepts contribute their staged composite key (see
    search/analytics.py — (service, latency-bucket, error) for
    ?agg=red), rejected entries take the sentinel ``n_keys`` one past
    the counted range, and sort + searchsorted-diff produces the [K]
    histogram — the scatter-free dense-count idiom, fused into the
    same dispatch as the scan's mask."""
    key = jnp.where(mask, entry_agg, jnp.int32(n_keys)).reshape(-1)
    skey = jax.lax.sort(key)
    edges = jnp.searchsorted(skey,
                             jnp.arange(n_keys + 1, dtype=jnp.int32))
    return (edges[1:] - edges[:-1]).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("n_terms", "top_k", "widths",
                                             "plan", "agg"))
def multi_scan_kernel(kv_key, kv_val, entry_start, entry_end, entry_dur,
                      entry_valid, page_block, term_keys, val_ranges,
                      dur_lo, dur_hi, win_start, win_end,
                      val_hits=None, block_group=None, entry_dur_res=None,
                      span_cols=None, s_tables=None, entry_agg=None,
                      *, n_terms: int, top_k: int, widths=None,
                      plan=None, agg=None):
    mask = multi_entry_mask(
        kv_key, kv_val, entry_start, entry_end, entry_dur, entry_valid,
        page_block, term_keys, val_ranges, dur_lo, dur_hi, win_start,
        win_end, n_terms=n_terms, val_hits=val_hits,
        block_group=block_group, entry_dur_res=entry_dur_res,
        widths=widths,
    )
    if plan is not None:
        # structural predicate (search/structural.py): verdicts fuse
        # into the same dispatch — compiled from the static plan, never
        # interpreted
        from .structural import structural_entry_mask

        mask = mask & structural_entry_mask(
            kv_key, kv_val, entry_dur, entry_valid, page_block,
            entry_dur_res, span_cols, s_tables, plan=plan, widths=widths)
    count = jnp.sum(mask, dtype=jnp.int32)
    inspected = jnp.sum(entry_valid & (page_block >= 0)[:, None], dtype=jnp.int32)
    scores, idx = masked_topk(mask, entry_start, top_k)
    if agg is not None:
        # `agg` (static, the dense key-space size K — part of the jit
        # key like `plan`) adds the ?agg= reduction as one more stage
        # gated by the SAME verdict mask
        return (count, inspected, scores, idx,
                agg_entry_counts(mask, entry_agg, agg))
    return count, inspected, scores, idx


@functools.partial(jax.jit,
                   static_argnames=("mesh", "n_terms", "top_k", "widths",
                                    "plan", "span_sharded", "shard_tail",
                                    "agg"))
def dist_multi_scan_kernel(mesh, kv_key, kv_val, entry_start, entry_end,
                           entry_dur, entry_valid, page_block, term_keys,
                           val_ranges, dur_lo, dur_hi, win_start, win_end,
                           val_hits=None, block_group=None,
                           entry_dur_res=None,
                           span_cols=None, s_tables=None, entry_agg=None,
                           *, n_terms: int, top_k: int, widths=None,
                           plan=None, span_sharded=False,
                           shard_tail: int = 0, agg=None):
    """Multi-block scan sharded over the mesh's scan axis: the stacked
    page axis (blocks × pages — the corpus 'sequence' axis, SURVEY.md §5)
    splits across devices; the [B,...] term tables replicate; counts
    reduce with psum and per-shard top-k candidates all_gather into a
    global top-k — one jit call, collectives riding ICI (the TPU-native
    Results funnel, reference results.go:38-141).

    The structural predicate (plan + span_cols/s_tables) has two
    placements, selected by the STATIC `span_sharded` flag (part of the
    jit key, like `widths`):

      - replicated span columns (legacy): the mask evaluates OUTSIDE
        the shard_map — parent pointers index the global span axis,
        which a page shard cannot see — and its [P, E] verdicts enter
        the sharded region as one more page-sharded operand;
      - segment-aligned sharded span columns
        (search_structural_shard_spans): each trace's span run lives
        whole on its page's shard in shard-local coordinates, so the
        `child` gather and `desc` pointer-doubling evaluate INSIDE
        shard_fn over the local chunk — parent joins scale with the
        mesh, per-shard span HBM ~1/P, and only the per-trace verdict
        feeds the existing collectives."""
    from jax.sharding import PartitionSpec as P
    from tempo_tpu.parallel.mesh import SCAN_AXIS

    n_shards = mesh.devices.size
    E = entry_valid.shape[1]
    local_flat = kv_key.shape[0] // n_shards * E

    struct_mask = None
    sh_span_cols = sh_s_tables = None
    if plan is not None and not span_sharded:
        from .structural import structural_entry_mask

        struct_mask = structural_entry_mask(
            kv_key, kv_val, entry_dur, entry_valid, page_block,
            entry_dur_res, span_cols, s_tables, plan=plan, widths=widths)
    elif plan is not None:
        sh_span_cols, sh_s_tables = span_cols, s_tables

    pages_total = int(kv_key.shape[0])

    def shard_fn(kv_key, kv_val, entry_start, entry_end, entry_dur,
                 entry_valid, page_block, term_keys, val_ranges,
                 dur_lo, dur_hi, win_start, win_end, val_hits,
                 block_group, entry_dur_res, struct_mask,
                 sh_span_cols, sh_s_tables, entry_agg):
        if shard_tail:
            # remainder-shard layout descriptor (static, part of the
            # jit key like `widths`): the trailing `shard_tail` pad
            # pages live on the last shard(s); their entries are
            # already invalid, so this mask is byte-identical — it
            # RECORDS the ragged tail in the compiled layout
            pp = page_block.shape[0]
            gpage = (jax.lax.axis_index(SCAN_AXIS).astype(jnp.int32)
                     * pp + jnp.arange(pp, dtype=jnp.int32))
            entry_valid = entry_valid & (
                gpage < jnp.int32(pages_total - shard_tail))[:, None]
        mask = multi_entry_mask(
            kv_key, kv_val, entry_start, entry_end, entry_dur, entry_valid,
            page_block, term_keys, val_ranges, dur_lo, dur_hi, win_start,
            win_end, n_terms=n_terms, val_hits=val_hits,
            block_group=block_group, entry_dur_res=entry_dur_res,
            widths=widths,
        )
        if struct_mask is not None:
            mask = mask & struct_mask
        if plan is not None and span_sharded:
            from .structural import structural_entry_mask

            # shard-local evaluation: the local span chunk's
            # parent/begin columns are already in local coordinates
            # (shard_span_segment rebased them), so the joins and
            # segment reductions never leave the shard
            mask = mask & structural_entry_mask(
                kv_key, kv_val, entry_dur, entry_valid, page_block,
                entry_dur_res, sh_span_cols, sh_s_tables, plan=plan,
                widths=widths)
        local_count = jnp.sum(mask, dtype=jnp.int32)
        local_inspected = jnp.sum(
            entry_valid & (page_block >= 0)[:, None], dtype=jnp.int32)
        scores, idx = masked_topk(mask, entry_start, top_k)
        shard = jax.lax.axis_index(SCAN_AXIS).astype(jnp.int32)
        gidx = idx + shard * local_flat
        count = jax.lax.psum(local_count, SCAN_AXIS)
        inspected = jax.lax.psum(local_inspected, SCAN_AXIS)
        all_scores = jax.lax.all_gather(scores, SCAN_AXIS).reshape(-1)
        all_idx = jax.lax.all_gather(gidx, SCAN_AXIS).reshape(-1)
        k = min(top_k, all_scores.shape[0])
        top_scores, pos = jax.lax.top_k(all_scores, k)
        if agg is not None:
            # per-shard dense counts over the local page slice psum to
            # the global histogram — integer adds, so the distributed
            # answer is bit-equal to the single-device one
            agg_counts = jax.lax.psum(
                agg_entry_counts(mask, entry_agg, agg), SCAN_AXIS)
            return count, inspected, top_scores, all_idx[pos], agg_counts
        return count, inspected, top_scores, all_idx[pos]

    from tempo_tpu.parallel.mesh import shard_map_compat

    return shard_map_compat(
        shard_fn, mesh=mesh,
        # the probe hit mask + block->group map replicate like the other
        # predicate tables (a None leaf makes its spec a no-op); the
        # duration residual and the structural verdicts shard with the
        # page axis. Sharded span columns split on their leading axis
        # (the chunk-per-shard span axis / the page axis of the entry
        # range columns); the structural parameter tables replicate.
        # The staged ?agg= composite keys shard with their pages.
        in_specs=(P(SCAN_AXIS),) * 7 + (P(),) * 8
        + (P(SCAN_AXIS), P(SCAN_AXIS), P(SCAN_AXIS), P(), P(SCAN_AXIS)),
        out_specs=(P(), P(), P(), P())
        + ((P(),) if agg is not None else ()),
        # all_gather+top_k yields identical values on every shard, but the
        # replication checker can't infer it through the gather
        check=False,
    )(kv_key, kv_val, entry_start, entry_end, entry_dur, entry_valid,
      page_block, term_keys, val_ranges, dur_lo, dur_hi, win_start,
      win_end, val_hits, block_group, entry_dur_res, struct_mask,
      sh_span_cols, sh_s_tables, entry_agg)


@functools.partial(jax.jit, static_argnames=("n_terms", "top_k", "widths",
                                             "plan", "agg"))
def coalesced_scan_kernel(kv_key, kv_val, entry_start, entry_end, entry_dur,
                          entry_valid, page_block, term_keys, val_ranges,
                          term_active, dur_lo, dur_hi, win_start, win_end,
                          val_hits=None, block_group=None,
                          entry_dur_res=None, span_cols=None,
                          s_tables=None, entry_agg=None,
                          *, n_terms: int, top_k: int, widths=None,
                          plan=None, agg=None):
    """The query-axis variant of multi_scan_kernel: predicate tables are
    [Q, ...]-stacked and vmap lifts the per-query mask + top-k over the
    query axis — ONE dispatch serves Q concurrent requests over the same
    staged pages. The page arrays are read once per term loop regardless
    of Q (the scan is bandwidth-bound; queries amortize the read).
    Returns (counts i32 [Q], inspected i32, scores i32 [Q,k],
    flat idx i32 [Q,k]). `inspected` is query-independent (every query
    sees the same staged pages), so it stays scalar.

    `plan` (static) + `s_tables` ([Q, ...]-stacked structural parameter
    tables) + `span_cols` (the batch's staged span columns, SHARED
    across the query axis): plan-shape stacking — every member lowered
    to the same plan descriptor, so vmap lifts one compiled structural
    predicate over per-query tables, same as the legacy tables."""
    inspected = jnp.sum(entry_valid & (page_block >= 0)[:, None],
                        dtype=jnp.int32)

    def one_query(tk, vr, ta, dlo, dhi, ws, we, vh, bg, st_t):
        mask = multi_entry_mask(
            kv_key, kv_val, entry_start, entry_end, entry_dur, entry_valid,
            page_block, tk, vr, dlo, dhi, ws, we,
            n_terms=n_terms, term_active=ta, val_hits=vh, block_group=bg,
            entry_dur_res=entry_dur_res, widths=widths)
        if plan is not None:
            from .structural import structural_entry_mask

            # span_cols close over (query-invariant — vmap broadcasts);
            # only the parameter tables map along the query axis
            mask = mask & structural_entry_mask(
                kv_key, kv_val, entry_dur, entry_valid, page_block,
                entry_dur_res, span_cols, st_t, plan=plan, widths=widths)
        count = jnp.sum(mask, dtype=jnp.int32)
        scores, idx = masked_topk(mask, entry_start, top_k)
        if agg is not None:
            # the staged composite keys are batch-global (entry_agg
            # closes over, query-invariant like span_cols) — each
            # query's verdict mask gates its own [K] dense counts
            return (count, scores, idx,
                    agg_entry_counts(mask, entry_agg, agg))
        return count, scores, idx

    # val_hits/block_group/s_tables are [Q,...]-stacked like the other
    # predicate tables (None vmaps as an empty pytree — no leaves)
    if agg is not None:
        counts, scores, idx, aggs = jax.vmap(one_query)(
            term_keys, val_ranges, term_active, dur_lo, dur_hi,
            win_start, win_end, val_hits, block_group, s_tables)
        return counts, inspected, scores, idx, aggs
    counts, scores, idx = jax.vmap(one_query)(
        term_keys, val_ranges, term_active, dur_lo, dur_hi,
        win_start, win_end, val_hits, block_group, s_tables)
    return counts, inspected, scores, idx


@functools.partial(jax.jit,
                   static_argnames=("mesh", "n_terms", "top_k", "widths",
                                    "plan", "span_sharded", "shard_tail",
                                    "agg"))
def dist_coalesced_scan_kernel(mesh, kv_key, kv_val, entry_start, entry_end,
                               entry_dur, entry_valid, page_block, term_keys,
                               val_ranges, term_active, dur_lo, dur_hi,
                               win_start, win_end, val_hits=None,
                               block_group=None, entry_dur_res=None,
                               span_cols=None, s_tables=None,
                               entry_agg=None,
                               *, n_terms: int, top_k: int, widths=None,
                               plan=None, span_sharded=False,
                               shard_tail: int = 0, agg=None):
    """Coalesced scan sharded over the mesh's scan axis: the page axis
    splits across devices, the [Q,...] query tables replicate, and the
    per-shard per-query top-k candidates all_gather into a per-query
    global top-k (lax.top_k batches over the leading query axis).

    Plan-shape stacking composes with both span layouts (the static
    `span_sharded` flag, see dist_multi_scan_kernel): with replicated
    spans the [Q, P, E] structural verdicts vmap OUTSIDE the shard_map
    and enter page-sharded on their second axis; with segment-aligned
    sharded spans the vmapped evaluation runs INSIDE shard_fn over the
    local span chunk."""
    from jax.sharding import PartitionSpec as P
    from tempo_tpu.parallel.mesh import SCAN_AXIS

    n_shards = mesh.devices.size
    E = entry_valid.shape[1]
    local_flat = kv_key.shape[0] // n_shards * E

    struct_masks = None
    sh_span_cols = sh_s_tables = None
    if plan is not None and not span_sharded:
        from .structural import structural_entry_mask

        struct_masks = jax.vmap(
            lambda st_t: structural_entry_mask(
                kv_key, kv_val, entry_dur, entry_valid, page_block,
                entry_dur_res, span_cols, st_t, plan=plan,
                widths=widths))(s_tables)                # [Q, P, E]
    elif plan is not None:
        sh_span_cols, sh_s_tables = span_cols, s_tables

    pages_total = int(kv_key.shape[0])

    def shard_fn(kv_key, kv_val, entry_start, entry_end, entry_dur,
                 entry_valid, page_block, term_keys, val_ranges,
                 term_active, dur_lo, dur_hi, win_start, win_end,
                 val_hits, block_group, entry_dur_res, struct_masks,
                 sh_span_cols, sh_s_tables, entry_agg):
        if shard_tail:
            # remainder-shard ragged tail (see dist_multi_scan_kernel)
            pp = page_block.shape[0]
            gpage = (jax.lax.axis_index(SCAN_AXIS).astype(jnp.int32)
                     * pp + jnp.arange(pp, dtype=jnp.int32))
            entry_valid = entry_valid & (
                gpage < jnp.int32(pages_total - shard_tail))[:, None]
        local_inspected = jnp.sum(
            entry_valid & (page_block >= 0)[:, None], dtype=jnp.int32)

        def one_query(tk, vr, ta, dlo, dhi, ws, we, vh, bg, sm, st_t):
            mask = multi_entry_mask(
                kv_key, kv_val, entry_start, entry_end, entry_dur,
                entry_valid, page_block, tk, vr, dlo, dhi, ws, we,
                n_terms=n_terms, term_active=ta, val_hits=vh,
                block_group=bg, entry_dur_res=entry_dur_res,
                widths=widths)
            if sm is not None:
                mask = mask & sm
            if plan is not None and span_sharded:
                from .structural import structural_entry_mask

                mask = mask & structural_entry_mask(
                    kv_key, kv_val, entry_dur, entry_valid, page_block,
                    entry_dur_res, sh_span_cols, st_t, plan=plan,
                    widths=widths)
            count = jnp.sum(mask, dtype=jnp.int32)
            scores, idx = masked_topk(mask, entry_start, top_k)
            if agg is not None:
                return (count, scores, idx,
                        agg_entry_counts(mask, entry_agg, agg))
            return count, scores, idx

        if agg is not None:
            counts, scores, idx, agg_local = jax.vmap(one_query)(
                term_keys, val_ranges, term_active, dur_lo, dur_hi,
                win_start, win_end, val_hits, block_group, struct_masks,
                sh_s_tables)
            agg_counts = jax.lax.psum(agg_local, SCAN_AXIS)  # [Q, K]
        else:
            counts, scores, idx = jax.vmap(one_query)(
                term_keys, val_ranges, term_active, dur_lo, dur_hi,
                win_start, win_end, val_hits, block_group, struct_masks,
                sh_s_tables)
        shard = jax.lax.axis_index(SCAN_AXIS).astype(jnp.int32)
        gidx = idx + shard * local_flat
        counts = jax.lax.psum(counts, SCAN_AXIS)
        inspected = jax.lax.psum(local_inspected, SCAN_AXIS)
        all_scores = jax.lax.all_gather(scores, SCAN_AXIS)   # [S, Q, k]
        all_idx = jax.lax.all_gather(gidx, SCAN_AXIS)
        Qn = all_scores.shape[1]
        flat_scores = jnp.swapaxes(all_scores, 0, 1).reshape(Qn, -1)
        flat_idx = jnp.swapaxes(all_idx, 0, 1).reshape(Qn, -1)
        k = min(top_k, flat_scores.shape[-1])
        top_scores, pos = jax.lax.top_k(flat_scores, k)      # batched [Q,k]
        top_idx = jnp.take_along_axis(flat_idx, pos, axis=-1)
        if agg is not None:
            return counts, inspected, top_scores, top_idx, agg_counts
        return counts, inspected, top_scores, top_idx

    from tempo_tpu.parallel.mesh import shard_map_compat

    return shard_map_compat(
        shard_fn, mesh=mesh,
        # stacked structural verdicts [Q, P, E] shard on the PAGE axis
        # (second); sharded span columns on their leading axis; the
        # stacked parameter tables replicate like the query tables; the
        # staged ?agg= composite keys shard with their pages
        in_specs=(P(SCAN_AXIS),) * 7 + (P(),) * 9
        + (P(SCAN_AXIS), P(None, SCAN_AXIS), P(SCAN_AXIS), P(),
           P(SCAN_AXIS)),
        out_specs=(P(), P(), P(), P())
        + ((P(),) if agg is not None else ()),
        # same stance as dist_multi_scan_kernel: the gather+top_k output
        # is replicated but the replication checker can't infer it
        check=False,
    )(kv_key, kv_val, entry_start, entry_end, entry_dur, entry_valid,
      page_block, term_keys, val_ranges, term_active, dur_lo, dur_hi,
      win_start, win_end, val_hits, block_group, entry_dur_res,
      struct_masks, sh_span_cols, sh_s_tables, entry_agg)


class MultiBlockEngine:
    """Batched scan over many blocks in one kernel dispatch; with a mesh,
    the batch shards across devices (the serving-path union of the
    reference's job fan-out and the Results merge)."""

    def __init__(self, top_k: int = DEFAULT_TOP_K, mesh=None,
                 device_probe_min_vals: int | None = None):
        from tempo_tpu.parallel import mesh as mesh_mod

        self.top_k = top_k
        self.mesh = mesh
        self.n_shards = int(mesh.devices.size) if mesh is not None else 1
        # value-dictionary size at which staging also packs+uploads the
        # dictionary bytes for the on-device substring probe (None =
        # dict_probe.DEVICE_PROBE_MIN_VALS; <= 0 keeps every probe on
        # the exact host path). Config: search_device_probe_min_vals.
        self.device_probe_min_vals = device_probe_min_vals
        # the PROCESS-WIDE collective-ordering lock (parallel.mesh
        # .dispatch_lock — see its comment): shared with every other
        # collective dispatch site, including the dictionary probe that
        # fires during query compilation on another thread.
        # Single-device dispatches need no ordering and skip the lock.
        self._dispatch_lock = mesh_mod.dispatch_lock

    def stage_host(self, blocks: list[ColumnarPages]) -> HostBatch:
        """Stack a batch on host, padded for this engine's device layout.

        The padded page count buckets to a power of two (shard-aligned):
        group sizes vary freely with the blocklist, and each distinct
        page count is a separate XLA compile (~20-40s on TPU) — pow2
        bucketing caps the shape count at log2 for <2x masked waste.

        Under the remainder-shard layout
        (search_structural_remainder_pages) the page axis pads only to
        the minimal multiple of the shard count instead: the last shard
        owns the ragged tail (the trailing pad pages), described to the
        dist kernels by the static `shard_tail` jit key — a 9-page
        batch on 8 shards stages 9 pages, not 16."""
        from .structural import STRUCTURAL

        total = sum(b.n_pages for b in blocks)
        pad_to = None
        if STRUCTURAL.remainder_pages:
            pad_to = STRUCTURAL.remainder_pad(total, self.n_shards)
        if pad_to is None:
            pad_to = max(1, self.n_shards)
            while pad_to < total:
                pad_to *= 2
        return stack_host(blocks, pad_to=pad_to,
                          probe_min_vals=self.device_probe_min_vals,
                          n_shards=self.n_shards)

    def _shard_tail(self, batch: BlockBatch, d: dict) -> int:
        """Static ragged-tail descriptor for the dist kernels: the
        count of trailing pad pages, nonzero ONLY under the
        remainder-shard gate. The pow2 layout keeps shard_tail=0 even
        though it pads too — keying the jit cache on every distinct
        tail would reintroduce exactly the per-page-count compiles the
        pow2 bucketing exists to cap."""
        from .structural import STRUCTURAL

        if self.mesh is None or not STRUCTURAL.remainder_pages:
            return 0
        return int(d["kv_key"].shape[0]) - int(batch.n_pages)

    def place(self, host: HostBatch) -> BlockBatch:
        """H2D of a host-stacked batch (sharded over the mesh if any)."""
        if self.mesh is None:
            return place_batch(host)
        from jax.sharding import NamedSharding, PartitionSpec as P
        from tempo_tpu.parallel.mesh import SCAN_AXIS

        spec = NamedSharding(self.mesh, P(SCAN_AXIS))
        return place_batch(host, sharding=spec, mesh=self.mesh)

    def stage(self, blocks: list[ColumnarPages]) -> BlockBatch:
        """Stack + place a batch on device(s)."""
        return self.place(self.stage_host(blocks))

    def scan_async(self, batch: BlockBatch, mq: MultiQuery):
        """Dispatch without device→host sync; returns device arrays.

        Watchdog-bounded (robustness.GUARD): a hung or erroring dispatch
        surfaces as DeviceFault (breaker fault booked) instead of
        wedging the submitter; the batcher's drain answers through the
        byte-identical host path. Guard inactive = direct call."""
        from tempo_tpu.robustness import GUARD

        return GUARD.run("mesh" if self.mesh is not None else "batched",
                         lambda: self._scan_async_impl(batch, mq))

    def _scan_async_impl(self, batch: BlockBatch, mq: MultiQuery):
        from .engine import resolve_top_k

        with profile.dispatch(
                "mesh" if self.mesh is not None else "batched") as rec:
            k = resolve_top_k(self.top_k, mq.limit)
            d = batch.device
            with rec.stage("build"):
                # params uploaded once per MultiQuery (duck-typed:
                # MultiQuery has the same param attributes CompiledQuery
                # has)
                from .engine import ScanEngine

                tk, vr, dlo, dhi, ws, we = ScanEngine.query_device_params(mq)
                vh = getattr(mq, "val_hits", None)
                bg = None if vh is None else jnp.asarray(mq.block_group)
                # structural plan (search/structural.py): static plan in
                # the jit key, dynamic tables uploaded once per query
                st = getattr(mq, "structural", None)
                plan = None if st is None else st.plan
                s_tables = None if st is None else st.device_tables()
                span_cols = (batch.span_device if st is not None
                             else None)
                # ?agg= reduction (search/analytics.py): the staged
                # per-entry composite keys ride the dispatch; the dense
                # key-space size is the static plan-stage descriptor
                agg_stage = getattr(mq, "agg_stage", None)
                agg = None if agg_stage is None else agg_stage.n_keys
                entry_agg = (None if agg_stage is None
                             else agg_stage.device())
            widths = batch.widths
            args = (d["kv_key"], d["kv_val"], d["entry_start"],
                    d["entry_end"], d["entry_dur"], d["entry_valid"],
                    d["page_block"], tk, vr, dlo, dhi, ws, we, vh, bg,
                    d.get("entry_dur_res"), span_cols, s_tables,
                    entry_agg)
            span_sharded = bool(st is not None and batch.span_sharded)
            shard_tail = self._shard_tail(batch, d)
            miss = rec.compile_check(
                ("multi", self.mesh is not None, d["kv_key"].shape,
                 str(d["kv_key"].dtype), str(d["kv_val"].dtype), vr.shape,
                 None if vh is None else (tuple(vh.shape), str(vh.dtype)),
                 widths, mq.n_terms, k,
                 None if st is None else st.shape_sig(), span_sharded,
                 shard_tail, agg,
                 None if span_cols is None else
                 tuple(sorted((n, tuple(a.shape))
                              for n, a in span_cols.items()))))
            stage = "compile" if miss else "execute"
            rec.set(kernel="multi", blocks=len(batch.blocks),
                    scan_bytes=batch.device_nbytes)
            if self.mesh is not None:
                from tempo_tpu.parallel import mesh as mesh_mod

                # see __init__: collective ordering; time queued behind
                # other dispatches lands in the lock_wait stage
                with mesh_mod.locked_collective(rec):
                    with rec.stage(stage):
                        out = dist_multi_scan_kernel(
                            self.mesh, *args, n_terms=mq.n_terms, top_k=k,
                            widths=widths, plan=plan,
                            span_sharded=span_sharded,
                            shard_tail=shard_tail, agg=agg)
                # fence AFTER releasing the collective lock: a fenced
                # wait under dispatch_lock would serialize every other
                # mesh dispatch behind this kernel's completion (the
                # blocking-under-lock class the analysis suite flags).
                # Stage timers accumulate, so the fenced wait still
                # books into the same compile/execute stage.
                with rec.stage(stage):
                    rec.fence(out)
                return out
            with rec.stage(stage):
                out = multi_scan_kernel(*args, n_terms=mq.n_terms, top_k=k,
                                        widths=widths, plan=plan, agg=agg)
                rec.fence(out)
            return out

    def scan(self, batch: BlockBatch, mq: MultiQuery):
        from .engine import fetch_scan_out

        return fetch_scan_out(self.scan_async(batch, mq))

    def coalesced_scan_async(self, batch: BlockBatch, cq: CoalescedQuery,
                             top_k: int):
        """Fused multi-query dispatch without device→host sync; returns
        device arrays (counts [Q], inspected, scores [Q,k], idx [Q,k]).
        `top_k` is the GROUP k — max over the coalesced requests'
        resolved k, so every member's limit is covered.

        Watchdog-bounded like scan_async: a fused dispatch that faults
        delivers DeviceFault to every member's future, and each member's
        drain resubmits its own query on the host path."""
        from tempo_tpu.robustness import GUARD

        return GUARD.run(
            "mesh" if self.mesh is not None else "coalesced",
            lambda: self._coalesced_scan_async_impl(batch, cq, top_k))

    def _coalesced_scan_async_impl(self, batch: BlockBatch,
                                   cq: CoalescedQuery, top_k: int):
        with profile.dispatch(
                "mesh" if self.mesh is not None else "coalesced") as rec:
            d = batch.device
            with rec.stage("build"):
                vh = getattr(cq, "val_hits", None)
                bg = None if vh is None else jnp.asarray(cq.block_group)
                tables = (
                    jnp.asarray(cq.term_keys), jnp.asarray(cq.val_ranges),
                    jnp.asarray(cq.term_active),
                    jnp.asarray(cq.dur_lo), jnp.asarray(cq.dur_hi),
                    jnp.asarray(cq.win_start), jnp.asarray(cq.win_end))
                # plan-shape stacking (structural.StackedStructural):
                # one shared static plan, [Q,...]-stacked parameter
                # tables uploaded once per fused dispatch
                st = getattr(cq, "structural", None)
                plan = None if st is None else st.plan
                s_tables = None if st is None else st.device_tables()
                span_cols = batch.span_device if st is not None else None
                # ?agg= stage: batch-global staged keys shared across
                # the fused query axis (any member requesting agg turns
                # it on for the dispatch; non-requesters ignore their
                # row of the [Q, K] output)
                agg_stage = getattr(cq, "agg_stage", None)
                agg = None if agg_stage is None else agg_stage.n_keys
                entry_agg = (None if agg_stage is None
                             else agg_stage.device())
            st_bytes = 0 if st is None else sum(
                int(getattr(t, "nbytes", 0)) for t in st.tables
                if t is not None)
            rec.add_bytes(h2d=cq.term_keys.nbytes + cq.val_ranges.nbytes
                          + cq.term_active.nbytes + 16 * len(cq.dur_lo)
                          + st_bytes)
            widths = batch.widths
            span_sharded = bool(st is not None and batch.span_sharded)
            shard_tail = self._shard_tail(batch, d)
            args = (d["kv_key"], d["kv_val"], d["entry_start"],
                    d["entry_end"], d["entry_dur"], d["entry_valid"],
                    d["page_block"], *tables, vh, bg,
                    d.get("entry_dur_res"), span_cols, s_tables,
                    entry_agg)
            miss = rec.compile_check(
                ("coalesced", self.mesh is not None, d["kv_key"].shape,
                 str(d["kv_key"].dtype), str(d["kv_val"].dtype),
                 cq.term_keys.shape, cq.val_ranges.shape,
                 None if vh is None else (tuple(vh.shape), str(vh.dtype)),
                 widths, cq.n_terms, top_k,
                 None if st is None else st.shape_sig(), span_sharded,
                 shard_tail, agg,
                 None if span_cols is None else
                 tuple(sorted((n, tuple(a.shape))
                              for n, a in span_cols.items()))))
            stage = "compile" if miss else "execute"
            rec.set(kernel="coalesced", queries=cq.n_queries,
                    scan_bytes=batch.device_nbytes)
            if self.mesh is not None:
                from tempo_tpu.parallel import mesh as mesh_mod

                with mesh_mod.locked_collective(rec):
                    with rec.stage(stage):
                        out = dist_coalesced_scan_kernel(
                            self.mesh, *args, n_terms=cq.n_terms,
                            top_k=top_k, widths=widths, plan=plan,
                            span_sharded=span_sharded,
                            shard_tail=shard_tail, agg=agg)
                # fence outside the collective lock (see
                # _scan_async_impl — same lock-order stance)
                with rec.stage(stage):
                    rec.fence(out)
                return out
            with rec.stage(stage):
                out = coalesced_scan_kernel(*args, n_terms=cq.n_terms,
                                            top_k=top_k, widths=widths,
                                            plan=plan, agg=agg)
                rec.fence(out)
            return out

    def results(self, batch: BlockBatch, mq: MultiQuery,
                scores: np.ndarray, idx: np.ndarray) -> list:
        E = batch.blocks[0].geometry.entries_per_page
        out = []
        for s, i in zip(scores.tolist(), idx.tolist()):
            if s < 0 or len(out) >= mq.limit:
                break
            p, e = divmod(i, E)
            if p >= batch.n_pages:
                continue
            bi = int(batch.page_block[p])
            if bi < 0:
                continue
            pages = batch.blocks[bi]
            lp = p - batch.page_offset[bi]
            m = tempopb.TraceSearchMetadata()
            m.trace_id = bytes(pages.trace_ids[lp, e]).hex()
            m.start_time_unix_nano = int(pages.entry_start[lp, e]) * 1_000_000_000
            m.duration_ms = int(pages.entry_dur[lp, e])
            svc = int(pages.entry_root_svc[lp, e])
            name = int(pages.entry_root_name[lp, e])
            if svc >= 0:
                m.root_service_name = pages.val_dict[svc]
            if name >= 0:
                m.root_trace_name = pages.val_dict[name]
            out.append(m)
        return out
