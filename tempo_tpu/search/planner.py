"""Adaptive host/device offload planner: the profiler turned into policy.

PR 4 proved the host/device crossover is real — at 10M distinct values
the dictionary probe wins on chip but loses 2x on CPU — yet the only
policy was the static ``search_device_probe_min_vals`` threshold. PR 5
built the per-stage dispatch profiler as the measurement substrate. This
module closes the loop: a per-decision cost model over the LIVE profiler
observations chooses, per block group at plan time, whether the
dictionary substring prefilter runs on host (memmem / numpy scan folded
to id ranges) or on device (packed-dictionary rolling-window kernel) —
the central question of "To GPU or Not to GPU" (arxiv 2605.15957) and
the offloading OLAP engine (arxiv 2601.19911): pick placement from a
learned model, not a hand-tuned constant.

Cost model (all inputs observable, nothing guessed twice):

  host(T, B)   = T · rate(host_probe, T·B) · B
  device(...)  = T · rate(device_probe, T·S) · S        probe kernel
               + [pack(B) + h2d(S)]  if not HBM-resident  staging
               + fixed(dispatch)                          launch overhead
               + fixed(compile)      if the jit shape signature is
                                     UNSEEN in the profiler's set
               + fixed(collective)   if mesh-sharded (the all_gather +
                                     dispatch-lock cost of the mesh probe)

where B = real dictionary bytes, S = staged (padded buf+pos+off) bytes,
T = term count. Rates are EWMAs over recent observations, bucketed by
log-size so the model tracks the measured non-linearity (the CPU probe
is ~linear at 1M values and super-linear at 10M — BENCH_r05); fixed
costs are plain EWMAs. Observations arrive two ways:

  - the planner registers as a dispatch-profiler listener
    (observability/profile.py): every finished ``dict_probe`` dispatch
    record feeds the device-probe rate / compile / collective costs,
    and every ``dict_probe`` h2d staging observation feeds the h2d rate;
  - the host prefilter (pipeline._probe_tags) reports its wall time +
    scanned bytes directly (it needs to attach the dictionary
    fingerprint for predicted-vs-actual tracking).

Cold processes don't guess: the first decision runs a one-shot
microbenchmark (a ~100 KB synthetic dictionary through both paths) so
the seed rates are THIS host's, not a constant — a CPU-only process
seeds a slow device-probe rate and correctly keeps 720 MB dictionaries
on host instead of staging them blindly.

Override semantics (the static threshold remains the floor):

  - planner disabled (``search_offload_planner_enabled: false``, the
    default): behavior-identical to the static-threshold path — call
    sites never consult the planner;
  - ``search_device_probe_min_vals <= 0``: host-only, planner or not
    (call sites never reach the planner below the floor);
  - dictionaries >= the threshold: the planner chooses; its "host"
    verdict vetoes staging/probing that the static path would have done.

Both paths are exact (the probe is a prefilter, the scan kernels accept
either product), so planner decisions can never change results — only
where the time goes. Decisions + predicted-vs-actual error are exported
at /debug/planner, ``tempo_search_offload_decisions_total`` /
``tempo_search_offload_predict_error_ratio``, and replayable offline
from a /debug/profile dump via scripts/calibrate_offload.py.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from dataclasses import dataclass, field

from tempo_tpu.observability import metrics as obs

# per-byte cost kinds (seconds per byte; probe kinds are per TERM-byte —
# observations pass nbytes = n_terms * bytes so predictions and
# observations stay in one unit). "scan" is the fused scan kernel's
# execute rate over PHYSICAL staged bytes: with packed residency the
# same pages land in smaller buckets, so the rate table is effectively
# bucketed by the columns' packed width — the /debug/planner view an
# operator reads to see what a byte of residency buys.
PER_BYTE_KINDS = ("host_probe", "device_probe", "pack", "h2d", "scan",
                  # ingest-side analytics reduction (search/analytics
                  # .py): seconds per summary-row byte — observational
                  # like "scan" (fills from live consume_blob calls)
                  "analytics")
# kinds the one-shot microbenchmark seeds: everything the probe
# DECISION consumes. "scan" is observational (it needs a real staged
# batch, which the seed deliberately never creates) and fills from the
# first live dispatches instead.
SEEDED_KINDS = ("host_probe", "device_probe", "pack", "h2d")
# fixed per-event costs (seconds)
FIXED_KINDS = ("dispatch", "compile", "collective")

# conservative cold-start rates used only when the microbenchmark seed is
# disabled or failed — roughly a shared-CPU host, which biases toward
# host (the safe side: never stage hundreds of MB on a guess)
_DEFAULT_RATES = {
    "host_probe": 4e-9,      # ~250 MB/s substring scan
    "device_probe": 8e-9,    # ~125 MB/s (CPU-backend probe kernel)
    "pack": 6e-9,
    "h2d": 1e-9,             # ~1 GB/s put
    "scan": 1e-10,           # ~10 GB/s linear pass (HBM-bound on chip)
    "analytics": 2e-9,       # ~500 MB/s batched summary-row reduction
}
_DEFAULT_FIXED = {"dispatch": 1e-3, "compile": 0.5, "collective": 2e-3}

# staged-bytes estimate when the packed layout doesn't exist yet: buf u8
# (1x) + pos i32 (4x) over pow2-padded byte axis (~1.5x average waste);
# off/n_real are noise at probe scale
_STAGED_FACTOR = 7.5

_SEED_VALS = 2048  # microbenchmark dictionary size (small: the seed must
# cost one small compile + a few ms, not a real staging)


def dict_bytes_est(val_dict, cache_on=None) -> int:
    """Estimated utf-8 byte length of a value dictionary, from an evenly
    spaced 256-value sample — O(1)-ish where an exact sum is O(dict),
    memoized on the immutable container when one is given."""
    if cache_on is not None:
        hit = getattr(cache_on, "_dict_nbytes_est", None)
        if hit is not None:
            return hit
    n = len(val_dict)
    if n == 0:
        est = 0
    elif n <= 256:
        est = sum(len(v.encode("utf-8")) for v in val_dict)
    else:
        step = n // 256
        sample = val_dict[::step][:256]
        est = int(sum(len(v.encode("utf-8")) for v in sample)
                  / len(sample) * n)
    if cache_on is not None:
        cache_on._dict_nbytes_est = est
    return est


@dataclass
class Decision:
    """One planner verdict, kept in the decision ring until its actual
    cost arrives (predicted-vs-actual is the calibration signal)."""
    seq: int
    site: str                 # "stage" | "compile" | "offline"
    target: str               # "host" | "device"
    fp: str | None            # dictionary fingerprint (hex prefix)
    inputs: dict
    predicted_host_s: float
    predicted_device_s: float
    # the chosen side's PROBE-ONLY component (what the later observation
    # actually measures — staging/fixed costs are observed separately)
    predicted_probe_s: float
    # compile cost charged into predicted_device_s when the model
    # predicted a jit miss — a compile-stage dispatch record measures
    # trace+compile+run in one wall time, so resolution against such a
    # record must compare predicted_probe_s + this, not probe alone
    predicted_compile_s: float = 0.0
    actual_s: float | None = None
    error: float | None = None

    def as_dict(self) -> dict:
        d = {
            "seq": self.seq, "site": self.site, "target": self.target,
            "inputs": self.inputs,
            "predicted_host_ms": round(self.predicted_host_s * 1e3, 3),
            "predicted_device_ms": round(self.predicted_device_s * 1e3, 3),
            "predicted_probe_ms": round(self.predicted_probe_s * 1e3, 3),
        }
        if self.fp:
            d["fp"] = self.fp
        if self.actual_s is not None:
            d["actual_probe_ms"] = round(self.actual_s * 1e3, 3)
            d["abs_rel_error"] = round(self.error, 3)
        return d


class _Ewma:
    __slots__ = ("value", "n")

    def __init__(self):
        self.value = None
        self.n = 0

    def update(self, x: float, alpha: float) -> None:
        self.value = x if self.value is None else \
            alpha * x + (1 - alpha) * self.value
        self.n += 1


def _bucket(nbytes: int) -> int:
    """4x-wide log-size buckets: rates are size-dependent (pow2 padding,
    cache effects, the measured super-linear CPU probe at 10M values)."""
    return max(0, int(nbytes).bit_length() // 2)


class OffloadPlanner:
    """Process-wide planner (module singleton ``PLANNER``, the REGISTRY /
    PROFILER idiom): TempoDBConfig flips ``enabled``; the staging and
    query-compile sites consult ``decide_probe`` only when the static
    threshold would have chosen the device path."""

    def __init__(self, enabled: bool = False, alpha: float = 0.25,
                 ring_size: int = 256, seed: bool = True):
        self.enabled = enabled
        self.alpha = alpha
        self.seed_on_first_use = seed
        self._lock = threading.Lock()
        self._rates: dict[tuple, _Ewma] = {}       # (kind, bucket)
        self._rates_global: dict[str, _Ewma] = {k: _Ewma()
                                                for k in PER_BYTE_KINDS}
        self._fixed: dict[str, _Ewma] = {k: _Ewma() for k in FIXED_KINDS}
        self._ring: deque = deque(maxlen=ring_size)
        self._seq = 0
        self._seeded = False
        self._seeding = False        # gates the profiler listeners so the
        # seed microbenchmark's own dispatch doesn't double-feed the model
        self._seed_ms = None
        # True once a REAL device probe has been observed (observe(), not
        # the seed's direct _update) — stage-time decisions, which have no
        # exact jit signature, predict a compile until then
        self._probe_observed = False
        self._decisions = {"host": 0, "device": 0}
        self._mispredict = _Ewma()   # EWMA of |pred-actual|/actual

    # ------------------------------------------------------------------
    # cost model

    def rate(self, kind: str, nbytes: int) -> float:
        """Seconds per byte for `kind` at this size: exact bucket →
        nearest observed bucket → global EWMA → seed default."""
        b = _bucket(nbytes)
        with self._lock:
            e = self._rates.get((kind, b))
            if e is not None and e.value is not None:
                return e.value
            near = None
            for (k, kb), ev in self._rates.items():
                if k != kind or ev.value is None:
                    continue
                if near is None or abs(kb - b) < abs(near[0] - b):
                    near = (kb, ev.value)
            if near is not None:
                return near[1]
            g = self._rates_global[kind]
            if g.value is not None:
                return g.value
        return _DEFAULT_RATES[kind]

    def fixed(self, kind: str) -> float:
        with self._lock:
            e = self._fixed[kind]
            if e.value is not None:
                return e.value
        return _DEFAULT_FIXED[kind]

    def observe(self, kind: str, seconds: float, nbytes: int = 0,
                fp: bytes | str | None = None) -> None:
        """Feed one measurement. Per-byte kinds need nbytes (probe kinds:
        n_terms * bytes); fixed kinds ignore it. `fp` (dictionary
        fingerprint) resolves the pending decision's predicted-vs-actual
        error. Noop when the planner is disabled — call sites on hot
        paths must stay free when the feature is off — and while the
        seed microbenchmark runs: its pack/probe go through the real
        dict_probe code whose instrumentation (pack_device_dict's pack
        observation, the profiler listeners) would double-feed the EWMAs
        on top of the seed's own direct updates."""
        if not self.enabled or self._seeding:
            return
        if kind == "device_probe":
            self._probe_observed = True
        self._update(kind, seconds, nbytes)
        if kind in ("host_probe", "device_probe"):
            self._resolve(kind, seconds, fp)

    def _update(self, kind: str, seconds: float, nbytes: int) -> None:
        with self._lock:
            if kind in FIXED_KINDS:
                self._fixed[kind].update(seconds, self.alpha)
                return
            if nbytes <= 0:
                return
            r = seconds / nbytes
            key = (kind, _bucket(nbytes))
            e = self._rates.get(key)
            if e is None:
                e = self._rates[key] = _Ewma()
            e.update(r, self.alpha)
            self._rates_global[kind].update(r, self.alpha)

    def _resolve(self, kind: str, seconds: float,
                 fp: bytes | str | None,
                 include_compile: bool = False) -> None:
        """Match an observed probe run to the newest unresolved decision
        for the same dictionary+side; record the relative error.
        `include_compile`: the observation came from a compile-stage
        dispatch record (trace+compile+run in one wall time), so compare
        against the decision's predicted compile cost too — otherwise a
        correctly predicted cold-shape compile books as ~100% error."""
        target = "host" if kind == "host_probe" else "device"
        fph = self._fp_hex(fp)
        err = None
        with self._lock:
            for d in reversed(self._ring):
                if d.actual_s is not None or d.target != target:
                    continue
                if fph is not None and d.fp is not None and d.fp != fph:
                    continue
                d.actual_s = seconds
                predicted = d.predicted_probe_s
                if include_compile:
                    predicted += d.predicted_compile_s
                base = max(seconds, 1e-9)
                err = d.error = abs(predicted - seconds) / base
                self._mispredict.update(err, self.alpha)
                break
        if err is not None:
            obs.offload_predict_error.observe(err)

    @staticmethod
    def _fp_hex(fp) -> str | None:
        if not fp:
            return None
        return fp[:16] if isinstance(fp, str) else fp.hex()[:16]

    # ------------------------------------------------------------------
    # decisions

    def decide_probe(self, *, n_vals: int, dict_bytes: int,
                     n_terms: int = 1, resident: bool = False,
                     packed: bool = False, staged_bytes: int | None = None,
                     n_shards: int = 1, shape_key=None,
                     fp: bytes | str | None = None,
                     site: str = "compile") -> Decision:
        """Host or device for one dictionary's substring prefilter. Call
        sites consult this ONLY above the static threshold floor (and
        never when ``search_device_probe_min_vals <= 0`` — host is forced
        there before the planner is reached).

        `resident`: staged device arrays already in HBM (compile-time
        decisions over a staged batch); `packed`: the host-side packing
        exists (an evicted batch re-stages without re-packing);
        `shape_key`: the probe kernel's jit signature, checked against
        the profiler's shape-signature set to predict a compile;
        `n_shards` > 1 adds the mesh collective cost (the all_gather in
        dist_probe_kernel + the process-wide dispatch lock)."""
        self._ensure_seeded()
        T = max(1, int(n_terms))
        B = max(1, int(dict_bytes))
        S = int(staged_bytes) if staged_bytes else int(B * _STAGED_FACTOR)

        host_s = self.rate("host_probe", T * B) * T * B

        dev_probe_s = self.rate("device_probe", T * S) * T * S
        dev_s = dev_probe_s + self.fixed("dispatch")
        if not resident:
            dev_s += self.rate("h2d", S) * S
            if not packed:
                dev_s += self.rate("pack", B) * B
        if n_shards > 1:
            dev_s += self.fixed("collective")
        if shape_key is not None:
            from tempo_tpu.observability.profile import PROFILER

            jit_miss = not PROFILER.seen(shape_key)
        else:
            # stage-time decisions have no exact signature yet: assume a
            # compile until a real device probe has run in this process
            # (the seed feeds rates via _update, deliberately NOT this
            # flag — a cold process's first big dictionary WILL pay the
            # first-shape XLA compile and the prediction must charge it)
            jit_miss = not self._probe_observed
        compile_s = self.fixed("compile") if jit_miss else 0.0
        dev_s += compile_s

        target = "device" if dev_s < host_s else "host"
        with self._lock:
            self._seq += 1
            d = Decision(
                seq=self._seq, site=site, target=target,
                fp=self._fp_hex(fp),
                inputs={"n_vals": int(n_vals), "dict_bytes": B,
                        "n_terms": T, "resident": bool(resident),
                        "staged_bytes": S, "n_shards": int(n_shards),
                        "jit_miss": bool(jit_miss)},
                predicted_host_s=host_s, predicted_device_s=dev_s,
                predicted_probe_s=(dev_probe_s if target == "device"
                                   else host_s),
                predicted_compile_s=(compile_s if target == "device"
                                     else 0.0),
            )
            self._ring.append(d)
            self._decisions[target] += 1
        obs.offload_decisions.inc(target=target, site=site)
        from . import query_stats

        qs = query_stats.current()
        if qs is not None:
            # the query this decision was made FOR sees it in its own
            # explain: target + the chosen side's predicted cost
            qs.add_planner(target, d.predicted_device_s
                           if target == "device" else d.predicted_host_s)
        return d

    # ------------------------------------------------------------------
    # seeding

    def _ensure_seeded(self) -> None:
        if self._seeded or not self.seed_on_first_use:
            return
        with self._lock:
            if self._seeded:
                return
            self._seeded = True   # set FIRST so the seed can't recurse
            self._seeding = True  # gate the profiler listeners: the
            # seed's own probe dispatch emits a dict_probe record +
            # h2d staging observation, and booking those ON TOP of the
            # seed's direct _update calls would double-feed the EWMAs
            # with contradictory samples (full compile wall vs warm/2)
        import time

        t0 = time.perf_counter()
        try:
            self._seed()
        except Exception:  # noqa: BLE001 — seeding is best-effort; the
            pass           # default rates keep decisions sane
        finally:
            self._seeding = False
        self._seed_ms = round((time.perf_counter() - t0) * 1e3, 1)

    def _seed(self) -> None:
        """One-shot microbenchmark: run a small synthetic dictionary
        through both probe paths so a cold process decides from THIS
        host's measured rates (a CPU-only backend seeds a slow device
        rate; a real accelerator seeds a fast one) instead of constants.
        Costs a few ms plus one small XLA compile."""
        import time

        import numpy as np

        vals = [f"seed-value-{i:07d}" for i in range(_SEED_VALS)]
        nb = sum(len(v) for v in vals)
        arr = np.array(vals, dtype=np.str_)
        t0 = time.perf_counter()
        np.char.find(arr, "seed-value-0000512")
        self._update("host_probe", time.perf_counter() - t0, nb)

        from . import dict_probe

        t0 = time.perf_counter()
        pd = dict_probe.pack_device_dict(vals)
        self._update("pack", time.perf_counter() - t0, nb)
        t0 = time.perf_counter()
        dd = dict_probe.place_device_dict(pd)
        for a in dd.device.values():
            a.block_until_ready()
        self._update("h2d", time.perf_counter() - t0, pd.nbytes)

        t0 = time.perf_counter()
        hits, any_hits = dict_probe.probe_value_hits(
            dd, [b"seed-value-0000512"])
        np.asarray(any_hits)
        self._update("compile", time.perf_counter() - t0, 0)
        t0 = time.perf_counter()
        hits, any_hits = dict_probe.probe_value_hits(
            dd, [b"seed-value-0000512"])
        np.asarray(any_hits)
        warm = time.perf_counter() - t0
        # a 2k-value probe is nearly all launch overhead; split it evenly
        # between the fixed dispatch cost and the per-byte rate so both
        # terms start on this host's scale
        self._update("dispatch", warm / 2, 0)
        self._update("device_probe", warm / 2, pd.nbytes)

    # ------------------------------------------------------------------
    # profiler feed (observability/profile.py listeners)

    def ingest_record(self, rec: dict) -> int:
        """One finished dispatch record (Dispatch.as_dict shape).
        dict_probe dispatches carry the probe-placement signal; scan
        dispatches feed the per-byte scan rate over their PHYSICAL
        staged bytes (packed residency moves the same pages into
        smaller size buckets, so the rate table splits by effective
        column width). Returns the number of model updates (the
        offline replay counts them)."""
        if not self.enabled or self._seeding:
            return 0
        mode = rec.get("mode")
        if mode in ("batched", "mesh", "coalesced", "single"):
            stages = rec.get("stages_ms") or {}
            sb = int((rec.get("attrs") or {}).get("scan_bytes") or 0)
            ex = stages.get("execute")
            if ex and sb:
                self._update("scan", ex / 1e3, sb)
                return 1
            return 0
        if mode != "dict_probe":
            return 0
        stages = rec.get("stages_ms") or {}
        attrs = rec.get("attrs") or {}
        n = 0
        nb = int(attrs.get("probe_bytes") or 0)
        fp = attrs.get("fp")
        ex = stages.get("execute")
        if ex and nb:
            self.observe("device_probe", ex / 1e3, nb, fp=fp)
            n += 1
        comp = stages.get("compile")
        if comp:
            # the compile-stage dispatch call = trace+XLA compile + the
            # first run; book it whole as the compile cost (that IS what
            # an unseen shape pays)
            self._update("compile", comp / 1e3, 0)
            if nb:  # a compile record still resolves the decision
                self._resolve("device_probe", comp / 1e3, fp,
                              include_compile=True)
            n += 1
        lw = stages.get("lock_wait")
        if lw:
            self._update("collective", lw / 1e3, 0)
            n += 1
        return n

    def ingest_stage(self, stage: str, mode: str, seconds: float,
                     nbytes: int) -> int:
        """One out-of-record stage observation (profile.observe_stage
        listener): dictionary AND page-batch staging H2D — the batch
        observations carry PHYSICAL (packed) byte counts, so the
        staging-cost side of every decision scales with what actually
        crosses the relay, not the unpacked layout. The host prefilter
        is NOT harvested here — pipeline._probe_tags feeds it directly
        with the dictionary fingerprint attached (and also reports it
        to the profiler, where only the aggregate lands)."""
        if not self.enabled or self._seeding:
            return 0
        if stage == "h2d" and nbytes \
                and mode in ("dict_probe", "batched", "mesh", "single"):
            self._update("h2d", seconds, nbytes)
            return 1
        return 0

    def ingest_profile_snapshot(self, snap: dict) -> int:
        """Offline calibration from a /debug/profile dump
        (scripts/calibrate_offload.py): replay the recent-dispatch ring
        through ingest_record, then seed the per-byte rates from the
        byte-carrying aggregates (mean seconds over mean bytes per
        observation). Returns observations ingested."""
        n = 0
        for rec in snap.get("recent") or []:
            n += self.ingest_record(rec)
        for mode, stages in (snap.get("aggregates") or {}).items():
            for stage, a in stages.items():
                cnt = int(a.get("count") or 0)
                nbytes = int(a.get("bytes") or 0)
                total_s = float(a.get("total_ms") or 0.0) / 1e3
                if not cnt or not nbytes:
                    continue
                kind = None
                if stage == "h2d" and mode == "dict_probe":
                    kind = "h2d"
                elif stage == "build" and mode == "host_probe":
                    kind = "host_probe"
                if kind is not None:
                    self._update(kind, total_s / cnt, nbytes // cnt)
                    n += 1
        return n

    # ------------------------------------------------------------------
    # operator surface

    def snapshot(self, recent: int = 32) -> dict:
        """/debug/planner payload: decisions, calibration state, and the
        cost-model table an operator can sanity-check crossovers from."""
        with self._lock:
            rates = {}
            for kind in PER_BYTE_KINDS:
                buckets = {
                    f"2^{2 * b}B": ev.value
                    for (k, b), ev in sorted(self._rates.items())
                    if k == kind and ev.value is not None
                }
                g = self._rates_global[kind]
                rates[kind] = {
                    "seconds_per_byte": g.value,
                    "observations": g.n,
                    "buckets": buckets,
                }
            fixed = {k: {"seconds": e.value, "observations": e.n}
                     for k, e in self._fixed.items()}
            ring = [d.as_dict() for d in list(self._ring)[-recent:]] \
                if recent > 0 else []
            return {
                "enabled": self.enabled,
                "seeded": self._seeded,
                "seed_ms": self._seed_ms,
                "decisions": dict(self._decisions),
                "mispredict": {
                    "observations": self._mispredict.n,
                    "ewma_abs_rel_error": self._mispredict.value,
                },
                "cost_model": {"rates": rates, "fixed": fixed},
                "recent": ring,
            }

    def reset(self) -> None:
        with self._lock:
            self._rates.clear()
            self._rates_global = {k: _Ewma() for k in PER_BYTE_KINDS}
            self._fixed = {k: _Ewma() for k in FIXED_KINDS}
            self._ring.clear()
            self._seq = 0
            self._seeded = False
            self._seeding = False
            self._seed_ms = None
            self._probe_observed = False
            self._decisions = {"host": 0, "device": 0}
            self._mispredict = _Ewma()


PLANNER = OffloadPlanner()
_listener_registered = False


def structural_node_seconds(node_bytes: dict) -> dict:
    """Structural plan nodes registered with the cost model: each node's
    byte estimate (structural.plan_node_bytes — leaf scans, pointer
    joins with their doubling log-factor, segment reductions) through
    the live per-byte scan rate, the SAME EWMA the fused scan kernels
    calibrate via the dispatch-profiler feed. Consumed by the explain
    tree's est_ms column and the per-node device-seconds apportionment
    (one fused kernel has no per-node timer; the conserved split follows
    this model)."""
    return {nid: nb * PLANNER.rate("scan", nb)
            for nid, nb in node_bytes.items()}


def stage_veto(block, fp, n_shards: int = 1) -> bool:
    """True when the enabled planner places this dictionary's prefilter
    on HOST at staging time — call sites then skip packing/staging
    entirely. The single shared stage-site decision: used by both
    engine.stage_block_dict and multiblock._pack_batch_dicts so the
    cost-model inputs cannot diverge between the single-block and
    batched paths. Always False when the planner is disabled (the
    static-threshold behavior) — EXCEPT while the device circuit
    breaker blocks the device: then every staging is vetoed regardless
    of planner state, so a wedged tunnel is never handed a dictionary
    upload (robustness.breaker; one attribute read when closed)."""
    from tempo_tpu.robustness import BREAKER

    if BREAKER.blocking():
        return True
    if not PLANNER.enabled:
        return False
    S = max(1, int(n_shards))
    packed = getattr(block, "_device_dict_packed", None)
    packed_ok = packed is not None and packed.n_shards == S
    d = PLANNER.decide_probe(
        n_vals=len(block.val_dict),
        dict_bytes=dict_bytes_est(block.val_dict, cache_on=block),
        resident=False, packed=packed_ok,
        staged_bytes=(packed.nbytes if packed_ok else None),
        n_shards=S, fp=fp, site="stage")
    return d.target == "host"


def configure(enabled: bool | None = None, alpha: float | None = None,
              ring_size: int | None = None, seed: bool | None = None,
              reset: bool = False) -> OffloadPlanner:
    """Apply config (TempoDBConfig.search_offload_planner_*) to the
    process planner — the most recent TempoDB wins, matching how the
    profiler/metrics configure. Enabling registers the planner as a
    dispatch-profiler listener (its observation feed)."""
    global _listener_registered
    if reset:
        PLANNER.reset()
    if alpha is not None:
        PLANNER.alpha = float(alpha)
    if ring_size is not None:
        with PLANNER._lock:
            PLANNER._ring = deque(PLANNER._ring, maxlen=int(ring_size))
    if seed is not None:
        PLANNER.seed_on_first_use = bool(seed)
    if enabled is not None:
        PLANNER.enabled = bool(enabled)
        if enabled and not _listener_registered:
            from tempo_tpu.observability.profile import PROFILER

            PROFILER.add_listener(PLANNER.ingest_record)
            PROFILER.add_stage_listener(PLANNER.ingest_stage)
            _listener_registered = True
    return PLANNER
