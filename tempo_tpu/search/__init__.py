"""Search subsystem: columnar tag-search blocks + the JAX scan engine.

This is the north-star path (SURVEY.md §3.4, BASELINE.json): the
reference's FlatBuffer search pages (pkg/tempofb) and hot scan loops
(tempodb/search/backend_search_block.go:184-298, pipeline.go) are
re-designed TPU-first — per-block dictionary-encoded tag columns staged as
device int32 arrays, predicate evaluation as vectorized compares + segment
reductions under jit, sharded over a device mesh with psum/all_gather for
result merge.

  data.py        per-trace search data extraction + wire codec
  streaming.py   WAL-side search block (linear host scan, crash replay)
  columnar.py    the device-ready columnar page format + container codec
  pipeline.py    host-side query compilation (dictionary prefilter,
                 substring semantics) + block-level pruning
  engine.py      the jit scan kernels (single device)
  ir.py          the structural query IR + JSON parser (?q=)
  structural.py  structural compiler: IR -> static plan + tables fused
                 into the scan kernels (parent joins, segment reduces)
  backend_search_block.py  block build/open/search orchestration
"""

from .data import SearchData, SpanData, extract_search_data, \
    encode_search_data, decode_search_data
from .streaming import StreamingSearchBlock
from .columnar import ColumnarPages, PageGeometry
from .backend_search_block import BackendSearchBlock, write_search_block
from .results import SearchResults

__all__ = [
    "SearchData", "SpanData", "extract_search_data",
    "encode_search_data", "decode_search_data", "StreamingSearchBlock",
    "ColumnarPages", "PageGeometry", "BackendSearchBlock",
    "write_search_block", "SearchResults",
]
