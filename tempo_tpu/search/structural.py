"""Structural query engine: compile the IR onto the fused scan kernels.

The front half (tempo_tpu/search/ir.py) parses a typed query tree —
span-scope leaves, AND/OR/NOT, parent-child / descendant relations,
count and duration-quantile aggregates. This module is the back half,
the TiLT idiom (arxiv 2301.12030): the tree is COMPILED, not
interpreted — lowering walks the static plan descriptor at jax trace
time and emits one fused XLA computation that evaluates the whole
query as vectorized array ops over the staged columns, where the data
already lives (the Taurus near-data argument, arxiv 2506.20010):

  - **leaf predicates** reuse the scan engines' membership machinery:
    tag terms probe the block dictionaries through the SAME host
    (memmem → id ranges) and device (packed-dictionary kernel → hit
    mask) paths query compilation uses, and the kernel-side membership
    test is the same range-compare / ``mask_select_grouped`` lookup —
    bit-packed masks and packed-width entry columns (``unpack_ids`` /
    ``duration_ok``) included;
  - **structural relations** lower to vectorized parent-pointer joins
    over the per-trace span segments: ``child`` is one gather through
    the parent-pointer column; ``desc`` is pointer-doubling (a log-many
    static unroll over the padded span axis — jit cache keys stay
    shape-only);
  - **aggregates** lower to segment reductions (one cumsum + two
    gathers per count, via the per-entry span-range columns) whose
    [P, E] verdicts AND into the legacy entry mask feeding the existing
    masked top-k;
  - **quantiles** lower to exact integer COUNT predicates
    (nearest-rank: ``p_q >= X  <=>  #(dur >= X) >= n - ceil(q*n) + 1``)
    so host and device agree bit-for-bit with no sorting and no floats.

``eval_host`` is the reference evaluator — plain python over
``SearchData.spans`` — used by the live/WAL scan path, the proto
fallback scan, and the differential fuzzer that pins compiled == host
byte-for-byte across every engine path.

Noop contract: ``search_structural_enabled`` off means
``structural_query()`` reads one attribute and returns None; legacy
requests take the existing byte-identical path (the noop-contract
checker registers both the gate function and the staging call sites).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, field

import numpy as np

from . import ir

# reserved in-band request tag carrying the percent-quoted compact JSON
# IR (the EXHAUSTIVE_SEARCH_TAG idiom): the structural query survives
# the frontend <-> querier SearchRequest proto round-trip and the URL
# tags encoding without a schema change. Never itself a tag predicate —
# every term-probing site excludes it alongside the exhaustive flag.
STRUCTURAL_QUERY_TAG = "x-structural-q"

_PARSE_CACHE_MAX = 256


class StructuralGate:
    """Process-wide gate + knobs (the PACKING/OWNERSHIP singleton
    idiom). ``enabled`` is read ONCE per request by structural_query;
    everything else in this module only runs behind it."""

    def __init__(self) -> None:
        self.enabled = False
        self.max_spans = 512      # span rows captured per trace at ingest
        self.max_span_kvs = 16    # kv pairs captured per span at ingest
        self.max_nodes = ir.MAX_NODES  # parse-time IR size cap
        # plan-shape query stacking (search_structural_stack_enabled):
        # concurrent structural queries sharing one PLAN descriptor
        # stack along the coalescer's query axis into one fused
        # dispatch. Off (default) keeps the solo-flush behavior exactly
        self.stack_enabled = False
        # segment-aligned span sharding (search_structural_shard_spans):
        # mesh staging reshards the span segment so each trace's span
        # run lands whole on its page's shard — parent joins go
        # shard-local and span HBM per shard drops ~1/P. Off (default)
        # keeps the replicated layout exactly
        self.shard_spans = False
        # shape-bucketed cross-plan stacking
        # (search_structural_bucket_enabled): concurrent structural
        # queries whose plans canonicalize into the SAME bucket shape
        # (canonical_bucket) fuse into one dispatch even when their
        # exact plan descriptors differ — the slot-program tables carry
        # each member's active nodes, padded slots evaluate as masked
        # no-ops. Off (default) keeps exact-plan grouping only
        self.bucket_enabled = False
        # bucket tier cap: a plan whose flattened slot count (span +
        # trace slots, incl. the root-copy slot) exceeds this goes back
        # to exact-plan grouping ("still goes solo" in the docs)
        self.bucket_max_nodes = 16
        # remainder-shard mesh layout
        # (search_structural_remainder_pages): mesh staging pads the
        # page axis to the MINIMAL multiple of the shard count instead
        # of the pow2 bucket — the last shard owns the ragged tail
        # behind the static shard_tail jit descriptor. Off (default)
        # keeps the pow2 bucketing exactly
        self.remainder_pages = False
        self._parse_cache: OrderedDict = OrderedDict()
        self._parse_lock = threading.Lock()

    # ---- staging (called behind `if STRUCTURAL.enabled` guards — the
    # noop-contract GuardedCall rule pins the call-site shape) ----

    def stack_spans(self, blocks: list, E: int, pad_pages: int) -> dict | None:
        """Stack the blocks' span segments for a batched staging:
        flat span arrays concatenate with per-block index remaps (trace
        index += page offset * E, parent/begin += span base), the span
        axis pads to a power of two (shape-only jit keys), and a
        per-span block-row column is precomputed so leaf tables gather
        per block exactly like the page kernels do. Returns the host
        numpy dict, or None when no block carries spans."""
        if not any(getattr(b, "has_spans", False) for b in blocks):
            return None
        total = sum(b.n_spans for b in blocks)
        S = _pow2(max(1, total))
        Cs = max(b.span_kv_key.shape[1] for b in blocks if b.has_spans)
        cols = {
            "span_trace": np.full(S, -1, dtype=np.int32),
            "span_parent": np.full(S, -1, dtype=np.int32),
            "span_block": np.zeros(S, dtype=np.int32),
            "span_dur": np.zeros(S, dtype=np.uint32),
            "span_kind": np.zeros(S, dtype=np.int8),
            "span_kv_key": np.full((S, Cs), -1, dtype=np.int32),
            "span_kv_val": np.full((S, Cs), -1, dtype=np.int32),
            "entry_span_begin": np.zeros((pad_pages, E), dtype=np.int32),
            "entry_span_count": np.zeros((pad_pages, E), dtype=np.int32),
        }
        base = 0
        page_off = 0
        for bi, b in enumerate(blocks):
            P = b.n_pages
            if getattr(b, "has_spans", False):
                n = b.n_spans
                cols["span_trace"][base:base + n] = \
                    b.span_trace + page_off * E
                par = b.span_parent.astype(np.int32, copy=True)
                par[par >= 0] += base
                cols["span_parent"][base:base + n] = par
                cols["span_block"][base:base + n] = bi
                cols["span_dur"][base:base + n] = b.span_dur
                cols["span_kind"][base:base + n] = b.span_kind
                cols["span_kv_key"][base:base + n, :b.span_kv_key.shape[1]] \
                    = b.span_kv_key
                cols["span_kv_val"][base:base + n, :b.span_kv_val.shape[1]] \
                    = b.span_kv_val
                cnt = b.entry_span_count
                cols["entry_span_begin"][page_off:page_off + P] = \
                    np.where(cnt > 0, b.entry_span_begin + base, 0)
                cols["entry_span_count"][page_off:page_off + P] = cnt
                base += n
            page_off += P
        return cols

    def stage_single(self, pages, pad_pages: int) -> dict | None:
        """Single-block variant of stack_spans (engine.stage)."""
        return self.stack_spans([pages], pages.geometry.entries_per_page,
                                pad_pages)

    def stack_group_key(self, batch, st) -> tuple | None:
        """THE plan-shape stacking gate: the coalescer's pending-group
        key for a structural query, or None — one attribute read when
        search_structural_stack_enabled is off (the caller's solo-flush
        path). Two structural queries share a key iff they target the
        same staged batch AND lowered to the identical static plan
        descriptor: the plan is the jit key, so same-plan members share
        one compiled executable and only their parameter tables differ
        — exactly the continuous-batching shape the legacy coalescer
        exploits."""
        if not self.stack_enabled:
            return None
        if self.bucket_enabled:
            bk = self.bucket_group_key(batch, st)
            if bk is not None:
                return bk
        return (id(batch), st.plan)

    def bucket_group_key(self, batch, st) -> tuple | None:
        """THE shape-bucket gate: the coalescer's pending-group key for
        a structural query under cross-plan bucketing, or None — one
        attribute read when search_structural_bucket_enabled is off
        (the caller falls back to exact-plan grouping), and None when
        the plan exceeds the bucket tier cap (it still goes solo /
        exact-plan, never a silently truncated program). Two queries
        share a bucket key iff they target the same staged batch AND
        their plans canonicalize to the identical bucket descriptor
        (canonical_bucket): the DESCRIPTOR is the jit key, member plans
        ride as dynamic per-query slot programs."""
        if not self.bucket_enabled:
            return None
        bk = canonical_bucket(st.plan, self.bucket_max_nodes)
        if bk is None:
            return None
        return (id(batch), bk)

    def remainder_pad(self, total: int, n_shards: int) -> int | None:
        """THE remainder-shard gate: the MINIMAL multiple-of-n_shards
        padded page count for a mesh staging, or None — one attribute
        read when search_structural_remainder_pages is off (the caller
        keeps the pow2 page bucketing exactly). The last shard owns the
        short chunk: the trailing pad pages all land there, described
        by the static per-shard valid length (`shard_tail`) the dist
        kernels carry in their jit key."""
        if not self.remainder_pages:
            return None
        n = max(1, int(n_shards))
        return max(n, -(-int(total) // n) * n)

    def shard_span_segment(self, span_cat: dict, n_shards: int,
                           pad_pages: int, E: int) -> dict | None:
        """THE span-sharding gate: reshard a replicated-layout span
        segment (stack_spans output) into the segment-aligned sharded
        layout, or None — one attribute read when
        search_structural_shard_spans is off, and None whenever the
        page axis does not divide evenly over the mesh (the caller
        keeps the replicated layout; still correct, just not sharded).

        Layout: the span axis becomes ``n_shards`` consecutive chunks
        of one uniform pow2 ``per_shard`` length, chunk ``s`` holding
        exactly the spans of traces whose page lands on shard ``s``
        (per-trace runs are contiguous and a trace lives on one page,
        so segments never straddle chunks). Coordinates REBASE to the
        shard-local frame shard_map hands each device: ``span_trace``
        to the local entry flat index, ``span_parent`` and
        ``entry_span_begin`` to chunk-local span positions — the
        ``child`` gather and ``desc`` pointer-doubling then read only
        local rows, and per-shard span HBM is ~1/P of the replicated
        layout."""
        if not self.shard_spans:
            return None
        if n_shards <= 1 or pad_pages % n_shards:
            return None
        S_old = int(span_cat["span_trace"].shape[0])
        pp = pad_pages // n_shards          # pages per shard
        trace = span_cat["span_trace"]
        live = trace >= 0
        shard_of = np.where(live, trace // (pp * E), -1)
        per_shard = _pow2(max(
            1, int(np.bincount(shard_of[live], minlength=n_shards).max()
                   if live.any() else 1)))
        S_new = n_shards * per_shard
        Cs = span_cat["span_kv_key"].shape[1]
        out = {
            "span_trace": np.full(S_new, -1, dtype=np.int32),
            "span_parent": np.full(S_new, -1, dtype=np.int32),
            "span_block": np.zeros(S_new, dtype=np.int32),
            "span_dur": np.zeros(S_new, dtype=np.uint32),
            "span_kind": np.zeros(S_new, dtype=np.int8),
            "span_kv_key": np.full((S_new, Cs), -1, dtype=np.int32),
            "span_kv_val": np.full((S_new, Cs), -1, dtype=np.int32),
        }
        # old global span index -> chunk-LOCAL position (for the parent
        # and entry_span_begin rebase); -1 = dropped padding row
        local_of = np.full(S_old, -1, dtype=np.int64)
        for s in range(n_shards):
            idx = np.flatnonzero(shard_of == s)
            n = len(idx)
            if not n:
                continue
            local_of[idx] = np.arange(n)
            dst = slice(s * per_shard, s * per_shard + n)
            out["span_trace"][dst] = trace[idx] - s * pp * E
            par = span_cat["span_parent"][idx]
            safe = np.clip(par, 0, S_old - 1)
            # a parent is always the same trace (collect_span_rows
            # resolves within one trace), hence the same shard; a
            # malformed cross-shard pointer maps to -1 (no parent) —
            # the explicit shard check matters because local_of is one
            # global map, so an already-processed OTHER shard's local
            # index would otherwise rebase to a wrong in-chunk row
            out["span_parent"][dst] = np.where(
                (par >= 0) & (shard_of[safe] == s)
                & (local_of[safe] >= 0),
                local_of[safe], -1).astype(np.int32)
            for name in ("span_block", "span_dur", "span_kind"):
                out[name][dst] = span_cat[name][idx]
            out["span_kv_key"][dst] = span_cat["span_kv_key"][idx]
            out["span_kv_val"][dst] = span_cat["span_kv_val"][idx]
        begin = span_cat["entry_span_begin"]
        count = span_cat["entry_span_count"]
        safe_b = np.clip(begin, 0, S_old - 1)
        out["entry_span_begin"] = np.where(
            count > 0, local_of[safe_b], 0).astype(np.int32)
        out["entry_span_count"] = count
        return out


STRUCTURAL = StructuralGate()


def configure(enabled: bool | None = None, max_spans: int | None = None,
              max_span_kvs: int | None = None,
              stack_enabled: bool | None = None,
              shard_spans: bool | None = None,
              bucket_enabled: bool | None = None,
              bucket_max_nodes: int | None = None,
              remainder_pages: bool | None = None) -> StructuralGate:
    """Apply TempoDBConfig.search_structural_* to the process gate (most
    recent TempoDB wins — the PACKING/OWNERSHIP idiom)."""
    if enabled is not None:
        STRUCTURAL.enabled = bool(enabled)
    if max_spans is not None:
        STRUCTURAL.max_spans = max(1, int(max_spans))
    if max_span_kvs is not None:
        STRUCTURAL.max_span_kvs = max(1, int(max_span_kvs))
    if stack_enabled is not None:
        STRUCTURAL.stack_enabled = bool(stack_enabled)
    if shard_spans is not None:
        STRUCTURAL.shard_spans = bool(shard_spans)
    if bucket_enabled is not None:
        STRUCTURAL.bucket_enabled = bool(bucket_enabled)
    if bucket_max_nodes is not None:
        STRUCTURAL.bucket_max_nodes = max(2, int(bucket_max_nodes))
    if remainder_pages is not None:
        STRUCTURAL.remainder_pages = bool(remainder_pages)
    return STRUCTURAL


def structural_query(req) -> "ir.TraceExpr | None":
    """THE gate: the request's parsed structural IR, or None — one
    attribute read (plus one tag-membership test) when
    search_structural_enabled is off, one dict get when the request
    carries no structural tag. A request CARRYING the tag against a
    disabled gate is refused as a client error at this shared altitude
    — every transport (HTTP, gRPC search_recent/search_block/
    search_blocks, live/WAL scans) must answer 400/INVALID_ARGUMENT,
    never a silent legacy-scan superset. Parse results memoize by the
    raw quoted form (dashboards repeat their queries verbatim); a
    malformed value that bypassed API validation surfaces as
    InvalidArgument too, never a 500 from deep in compile."""
    if not STRUCTURAL.enabled:
        if STRUCTURAL_QUERY_TAG in req.tags:
            from tempo_tpu.api.params import InvalidArgument

            raise InvalidArgument(
                "structural queries disabled "
                "(storage.search_structural_enabled: true enables)")
        return None
    raw = req.tags.get(STRUCTURAL_QUERY_TAG, "")
    if not raw:
        return None
    with STRUCTURAL._parse_lock:
        hit = STRUCTURAL._parse_cache.get(raw)
        if hit is not None:
            STRUCTURAL._parse_cache.move_to_end(raw)
            return hit
    try:
        expr = ir.parse_quoted(raw)
    except ir.IRSyntaxError as e:
        from tempo_tpu.api.params import InvalidArgument

        raise InvalidArgument(f"bad structural query: {e}") from None
    with STRUCTURAL._parse_lock:
        STRUCTURAL._parse_cache[raw] = expr
        while len(STRUCTURAL._parse_cache) > _PARSE_CACHE_MAX:
            STRUCTURAL._parse_cache.popitem(last=False)
    return expr


def attach_query(req, expr: "ir.TraceExpr") -> None:
    """Stow an IR tree on a request (the API layer's parse product):
    canonical compact JSON, percent-quoted, in the reserved tag."""
    req.tags[STRUCTURAL_QUERY_TAG] = ir.quote(ir.to_json(expr))


def _pow2(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


# ---------------------------------------------------------------------------
# compilation: IR -> (static plan descriptor, dynamic parameter tables)


@dataclass
class CompiledStructural:
    """One query's compiled structural predicate against one staged
    batch: ``plan`` is the STATIC descriptor (nested tuples of ops and
    leaf indices — part of every consuming kernel's jit key, exactly
    like the packed-residency ``widths``), the tables are dynamic
    arrays (thresholds, per-block term ids, value ranges / probe
    masks), so two queries with the same SHAPE of plan share one
    compiled executable and only the parameters change."""

    plan: tuple
    term_keys: np.ndarray | None      # int32 [B, T]
    val_ranges: np.ndarray | None     # int32 [B, T, R, 2]
    val_hits: object = None           # device [G, T, Vm] (bool/packed)
    block_group: np.ndarray | None = None   # int32 [B]
    dur_params: np.ndarray | None = None    # uint32 [D, 2]
    kind_params: np.ndarray | None = None   # int32 [K]
    agg_params: np.ndarray | None = None    # uint32 [A, 3]
    # cost-model registration: node id (preorder) -> estimated bytes
    # touched on device; the planner's live scan rate turns these into
    # predicted seconds, and measured kernel time apportions across
    # them for the explain tree (docs/search-structural-queries.md)
    node_bytes: dict = field(default_factory=dict)
    node_info: list = field(default_factory=list)  # (nid, op, detail)

    def tables(self) -> tuple:
        """The dynamic-argument pytree every kernel receives."""
        return (self.term_keys, self.val_ranges, self.val_hits,
                self.block_group, self.dur_params, self.kind_params,
                self.agg_params)

    def device_tables(self):
        """Tables as device arrays, uploaded once per compiled query
        (the query_device_params idiom — re-putting per dispatch costs
        ~ms each through a relay)."""
        return _device_tables_cached(self, self.tables())

    def shape_sig(self) -> tuple:
        """Jit-cache contribution: the plan IS shape (static), plus the
        dynamic tables' shapes/dtypes."""
        def sig(t):
            return None if t is None else (tuple(t.shape), str(t.dtype))
        return (self.plan,) + tuple(sig(t) for t in self.tables())

    def weight(self) -> int:
        """Apportionment weight of this predicate's dynamic tables —
        added to the legacy table rows when a fused dispatch's measured
        stage times split across members (query_stats.apportion)."""
        return int(sum(int(t.size) for t in self.tables()
                       if t is not None))

    def explain(self, measured_device_s: float | None = None,
                rate_s_per_byte: float | None = None) -> dict:
        """The compiled plan tree for ?explain=1: per node the op, its
        parameters, estimated cost, and — when a measured kernel total
        is given — its apportioned share of the real device-seconds
        (cost-model-weighted: one fused kernel cannot be timed
        per-node, so the conserved split follows the same per-byte
        model the planner calibrates)."""
        from . import planner

        total_bytes = max(1, sum(self.node_bytes.values()))
        out: dict = {"nodes": []}
        for nid, op, detail in self.node_info:
            nb = self.node_bytes.get(nid, 0)
            rate = (rate_s_per_byte if rate_s_per_byte is not None
                    else planner.PLANNER.rate("scan", nb))
            node = {"id": nid, "op": op, "est_bytes": int(nb),
                    "est_ms": round(nb * rate * 1e3, 6)}
            if detail:
                node["detail"] = detail
            if measured_device_s is not None:
                node["device_ms"] = round(
                    measured_device_s * (nb / total_bytes) * 1e3, 6)
            out["nodes"].append(node)
        return out


@dataclass
class StackedStructural:
    """Q same-plan compiled predicates stacked along the coalescer's
    query axis: ONE static plan (shared jit key — plan equality is what
    the stacking group is keyed on) and 7 dynamic tables with a leading
    [Q] axis, padded to the group max where members may legitimately
    differ (value-range width R; probe-mask G/Vm). Pad query lanes copy
    member 0's tables — always-valid values on lanes the legacy pad
    predicate (empty duration window) already forces all-false."""

    plan: tuple
    tables: tuple            # 7 leaves, each [Q, ...] or None
    n_queries: int

    def device_tables(self):
        return _device_tables_cached(self, self.tables)

    def shape_sig(self) -> tuple:
        def sig(t):
            return None if t is None else (tuple(t.shape), str(t.dtype))
        return (self.plan,) + tuple(sig(t) for t in self.tables)


def stack_structural(sts: list, pad_q: int) -> StackedStructural:
    """Stack same-plan compiled predicates along a new leading query
    axis (pad_q = the coalescer's pow2 query count). All members MUST
    share one plan descriptor (the stack_group_key contract); value
    ranges pad to the pow2 group max with the empty [1, 0] range, and
    probe masks pad to the group (G, Vm) max with all-false rows behind
    an all -1 block_group — members that compiled through the host
    range path never read them, exactly like stack_queries' legacy
    probe stacking."""
    import jax.numpy as jnp

    from . import packing

    plan = sts[0].plan
    for st in sts[1:]:
        if st.plan != plan:
            raise StructuralCompileError(
                "stacked structural members must share one plan")
    Qn = len(sts)

    def lane(i: int):
        # pad lanes replay member 0: valid parameters on dead lanes
        return sts[i] if i < Qn else sts[0]

    def stack_plain(name: str):
        rows = [getattr(lane(i), name) for i in range(pad_q)]
        if rows[0] is None:
            return None
        return np.stack(rows)

    # val_ranges: same (B, T) under one plan, R pads to the pow2 max
    vr0 = sts[0].val_ranges
    val_ranges = None
    if vr0 is not None:
        R = _pow2(max(st.val_ranges.shape[2] for st in sts))
        B, T = vr0.shape[0], vr0.shape[1]
        val_ranges = np.tile(np.array([1, 0], dtype=np.int32),
                             (pad_q, B, T, R, 1))
        for qi in range(pad_q):
            vr = lane(qi).val_ranges
            val_ranges[qi, :, :, :vr.shape[2]] = vr
    # probe product: mixed device/host members stack like stack_queries
    # — zero masks + all -1 group rows for host-path lanes
    val_hits = block_group = None
    if any(st.val_hits is not None for st in sts):
        hits = {id(st): st.val_hits for st in sts
                if st.val_hits is not None}
        if any(packing.is_packed_mask(h) for h in hits.values()):
            hits = {k: packing.pack_mask_words(h)
                    for k, h in hits.items()}
        Gm = max(int(h.shape[0]) for h in hits.values())
        Tm = max(int(h.shape[1]) for h in hits.values())
        Vm = max(int(h.shape[2]) for h in hits.values())
        dt = next(iter(hits.values())).dtype
        zero = jnp.zeros((Gm, Tm, Vm), dtype=dt)
        B = sts[0].term_keys.shape[0]
        block_group = np.full((pad_q, B), -1, dtype=np.int32)
        rows = []
        for qi in range(pad_q):
            st = lane(qi)
            if st.val_hits is None or qi >= Qn:
                rows.append(zero)
                continue
            h = hits[id(st)]
            rows.append(jnp.pad(h, ((0, Gm - h.shape[0]),
                                    (0, Tm - h.shape[1]),
                                    (0, Vm - h.shape[2]))))
            block_group[qi] = st.block_group
        val_hits = jnp.stack(rows)                 # [Q, Gm, Tm, Vm]
    return StackedStructural(
        plan=plan,
        tables=(stack_plain("term_keys"), val_ranges, val_hits,
                block_group, stack_plain("dur_params"),
                stack_plain("kind_params"), stack_plain("agg_params")),
        n_queries=Qn)


# ---------------------------------------------------------------------------
# shape-bucketed cross-plan stacking: canonicalize heterogeneous plans
# into a small static family of bucket shapes so the coalescer fuses
# mixed-plan concurrent queries into ONE dispatch. The bucket
# descriptor ("bucket", NS, NT, has_rel) replaces the exact plan in the
# jit key; each member's exact plan lowers to a per-query int32 slot
# PROGRAM carried in the dynamic tables (the "active-node mask" — pad
# slots are opcode 0 and unreachable from the result slot, so fused
# results stay byte-identical to solo execution).

# span-program opcodes (row = [opcode, a, b, 0]; a/b are table indices
# for leaves, 1-based register indices for combinators — register 0 is
# the dummy all-false register)
_SOP = {"tag": 1, "dur": 2, "kind": 3, "and": 4, "or": 5, "not": 6,
        "child": 7, "desc": 8}
# trace-program opcodes (row = [opcode, a, b, c]; for aggregates a is
# the 1-based SPAN register, b the agg_params row, c the compare code)
_TOP = {"ttag": 1, "tdur": 2, "exists": 3, "count": 4, "q": 5,
        "and": 6, "or": 7, "not": 8}
_CMPC = {">": 0, ">=": 1, "<": 2, "<=": 3, "==": 4, "!=": 5}


def _flatten_span(plan: tuple, rows: list) -> int:
    """Postorder-flatten a span plan into program rows; returns the
    node's 1-based result register. N-ary and/or binarize into chains
    (bit-identical for booleans)."""
    op = plan[0]
    if op in ("tag", "dur", "kind"):
        rows.append([_SOP[op], plan[2], 0, 0])
        return len(rows)
    if op in ("and", "or"):
        r = _flatten_span(plan[2][0], rows)
        for sub in plan[2][1:]:
            r2 = _flatten_span(sub, rows)
            rows.append([_SOP[op], r, r2, 0])
            r = len(rows)
        return r
    if op == "not":
        r = _flatten_span(plan[2], rows)
        rows.append([_SOP["not"], r, 0, 0])
        return len(rows)
    if op in ("child", "desc"):
        ra = _flatten_span(plan[2], rows)
        rb = _flatten_span(plan[3], rows)
        rows.append([_SOP[op], ra, rb, 0])
        return len(rows)
    raise StructuralCompileError(f"bad span plan op {op!r}")


def _flatten_trace(plan: tuple, trows: list, srows: list) -> int:
    op = plan[0]
    if op in ("ttag", "tdur"):
        trows.append([_TOP[op], plan[2], 0, 0])
        return len(trows)
    if op == "exists":
        sr = _flatten_span(plan[2], srows)
        trows.append([_TOP["exists"], sr, 0, 0])
        return len(trows)
    if op in ("count", "q"):
        sr = _flatten_span(plan[4], srows)
        trows.append([_TOP[op], sr, plan[3], _CMPC[plan[2]]])
        return len(trows)
    if op in ("and", "or"):
        r = _flatten_trace(plan[2][0], trows, srows)
        for sub in plan[2][1:]:
            r2 = _flatten_trace(sub, trows, srows)
            trows.append([_TOP[op], r, r2, 0])
            r = len(trows)
        return r
    if op == "not":
        r = _flatten_trace(plan[2], trows, srows)
        trows.append([_TOP["not"], r, 0, 0])
        return len(trows)
    raise StructuralCompileError(f"bad trace plan op {op!r}")


def _flatten_plan(plan: tuple) -> tuple[list, list]:
    """Flatten an exact plan into (span_rows, trace_rows). The final
    trace row is always a root copy — OR(root, root), the boolean
    identity — so the result register is STATICALLY the last trace
    slot whatever the member's real shape (stack_bucketed keeps it at
    slot NT-1 with pad rows in between)."""
    srows: list = []
    trows: list = []
    root = _flatten_trace(plan, trows, srows)
    trows.append([_TOP["or"], root, root, 0])
    return srows, trows


def canonical_bucket(plan: tuple, max_nodes: int) -> tuple | None:
    """Canonicalize an exact plan into its bucket-shape descriptor
    ``("bucket", NS, NT, has_rel)``: NS/NT are the pow2 slot tiers of
    the flattened span/trace programs (NT includes the root-copy
    slot), has_rel marks the child/desc machinery (relation plans
    bucket separately from relation-free ones — fusing them would make
    every member pay the pointer-doubling arms). Returns None when the
    flattened slot count exceeds ``max_nodes``: the plan "still goes
    solo", i.e. falls back to exact-plan grouping."""
    try:
        srows, trows = _flatten_plan(plan)
    except (StructuralCompileError, IndexError, KeyError, TypeError):
        return None
    if len(srows) + len(trows) > max(2, int(max_nodes)):
        return None
    NS = _pow2(len(srows)) if srows else 0
    NT = _pow2(len(trows))
    has_rel = any(r[0] in (_SOP["child"], _SOP["desc"]) for r in srows)
    return ("bucket", NS, NT, bool(has_rel))


@dataclass
class BucketedStructural:
    """Q mixed-plan compiled predicates fused under ONE bucket
    descriptor: ``plan`` is the ("bucket", NS, NT, has_rel) jit key and
    ``tables`` carries NINE dynamic leaves — the 7 standard parameter
    tables with a leading [Q] axis (padded to the group max exactly
    like StackedStructural) plus the per-query span/trace slot programs
    ([Q, NS, 4] / [Q, NT, 4] int32). Member programs index only their
    OWN padded tables, so member-local indices are always in range."""

    plan: tuple
    tables: tuple            # 9 leaves, each [Q, ...] or None
    n_queries: int
    active_nodes: int = 0    # sum of members' real (unpadded) slots
    slot_nodes: int = 0      # n_queries * (NS + NT) bucket slots

    def device_tables(self):
        return _device_tables_cached(self, self.tables)

    def shape_sig(self) -> tuple:
        def sig(t):
            return None if t is None else (tuple(t.shape), str(t.dtype))
        return (self.plan,) + tuple(sig(t) for t in self.tables)


def stack_bucketed(sts: list, pad_q: int,
                   desc: tuple) -> BucketedStructural:
    """Stack mixed-plan compiled predicates under one bucket descriptor
    (every member's canonical_bucket MUST equal ``desc`` — the
    bucket_group_key contract). Parameter tables pad to the group max
    with inert rows a member's program never references (term_keys -1,
    val_ranges [1, 0], agg_params (0, 1, 0) so the computed-but-
    unselected quantile arm never divides by zero); the probe product
    mirrors stack_structural. Pad query lanes replay member 0."""
    import jax.numpy as jnp

    from . import packing

    _op, NS, NT, _rel = desc
    Qn = len(sts)
    active = 0
    sprogs = []
    tprogs = []
    for st in sts:
        srows, trows = _flatten_plan(st.plan)
        active += len(srows) + len(trows)
        sp = np.zeros((max(1, NS), 4), dtype=np.int32)
        if srows:
            sp[:len(srows)] = np.asarray(srows, dtype=np.int32)
        tp = np.zeros((NT, 4), dtype=np.int32)
        body = trows[:-1]
        if body:
            tp[:len(body)] = np.asarray(body, dtype=np.int32)
        tp[NT - 1] = trows[-1]       # root copy -> the result slot
        sprogs.append(sp)
        tprogs.append(tp)

    def lane(i: int):
        return sts[i] if i < Qn else sts[0]

    def lane_prog(progs, i: int):
        return progs[i] if i < Qn else progs[0]

    # term_keys [B, T] -> [Q, B, Tm] (-1 = no term); members that
    # compiled without tag leaves get all -1 rows
    term_keys = val_ranges = None
    if any(st.term_keys is not None for st in sts):
        B = next(st.term_keys.shape[0] for st in sts
                 if st.term_keys is not None)
        Tm = _pow2(max(st.term_keys.shape[1] for st in sts
                       if st.term_keys is not None))
        Rm = _pow2(max(st.val_ranges.shape[2] for st in sts
                       if st.val_ranges is not None))
        term_keys = np.full((pad_q, B, Tm), -1, dtype=np.int32)
        val_ranges = np.tile(np.array([1, 0], dtype=np.int32),
                             (pad_q, B, Tm, Rm, 1))
        for qi in range(pad_q):
            st = lane(qi)
            if st.term_keys is None:
                continue
            term_keys[qi, :, :st.term_keys.shape[1]] = st.term_keys
            vr = st.val_ranges
            val_ranges[qi, :, :vr.shape[1], :vr.shape[2]] = vr

    def stack_padded(name: str, width: tuple, fill) -> np.ndarray | None:
        rows = [getattr(lane(i), name) for i in range(pad_q)]
        if all(r is None for r in rows):
            return None
        Nm = _pow2(max(r.shape[0] for r in rows if r is not None))
        dt = next(r for r in rows if r is not None).dtype
        out = np.empty((pad_q, Nm) + width, dtype=dt)
        out[...] = fill
        for qi, r in enumerate(rows):
            if r is not None:
                out[qi, :r.shape[0]] = r
        return out

    dur_params = stack_padded("dur_params", (2,), 0)
    kind_params = stack_padded("kind_params", (), 0)
    agg_params = stack_padded("agg_params", (3,),
                              np.array([0, 1, 0], dtype=np.uint32))
    # probe product: same zero-mask + all -1 group-row padding as
    # stack_structural for host-path / probe-less members
    val_hits = block_group = None
    if any(st.val_hits is not None for st in sts):
        hits = {id(st): st.val_hits for st in sts
                if st.val_hits is not None}
        if any(packing.is_packed_mask(h) for h in hits.values()):
            hits = {k: packing.pack_mask_words(h)
                    for k, h in hits.items()}
        Gm = max(int(h.shape[0]) for h in hits.values())
        Tm2 = max(int(h.shape[1]) for h in hits.values())
        Vm = max(int(h.shape[2]) for h in hits.values())
        dt = next(iter(hits.values())).dtype
        zero = jnp.zeros((Gm, Tm2, Vm), dtype=dt)
        B = next(st.term_keys.shape[0] for st in sts
                 if st.term_keys is not None)
        block_group = np.full((pad_q, B), -1, dtype=np.int32)
        rows = []
        for qi in range(pad_q):
            st = lane(qi)
            if st.val_hits is None or qi >= Qn:
                rows.append(zero)
                continue
            h = hits[id(st)]
            rows.append(jnp.pad(h, ((0, Gm - h.shape[0]),
                                    (0, Tm2 - h.shape[1]),
                                    (0, Vm - h.shape[2]))))
            block_group[qi] = st.block_group
        val_hits = jnp.stack(rows)                 # [Q, Gm, Tm, Vm]
    span_prog = np.stack([lane_prog(sprogs, i) for i in range(pad_q)])
    trace_prog = np.stack([lane_prog(tprogs, i) for i in range(pad_q)])
    return BucketedStructural(
        plan=desc,
        tables=(term_keys, val_ranges, val_hits, block_group,
                dur_params, kind_params, agg_params,
                span_prog, trace_prog),
        n_queries=Qn, active_nodes=active,
        slot_nodes=Qn * (NS + NT))


def _device_tables_cached(owner, tables: tuple) -> tuple:
    """One upload per compiled/stacked predicate, memoized on the owner
    (shared by CompiledStructural and StackedStructural so the upload
    path has exactly one implementation)."""
    import jax.numpy as jnp

    cached = getattr(owner, "_device_tables", None)
    if cached is None:
        cached = owner._device_tables = tuple(
            (jnp.asarray(t) if isinstance(t, np.ndarray) else t)
            for t in tables)
    return cached


class StructuralCompileError(ValueError):
    """Internal compile failure — the API layer maps it to 400 like a
    parse error (it is always rooted in the query, never the corpus)."""


def compile_structural(expr: "ir.TraceExpr", blocks: list,
                       cache_on=None, staged_dicts: dict | None = None,
                       host_only: bool = False,
                       entry_kv_slots: int = 1) -> CompiledStructural:
    """Lower an IR tree against a batch's blocks: collect leaves, probe
    every distinct dictionary ONCE per leaf set (reusing the host memmem
    / device packed-probe paths with the exhaustive contract — leaves
    must never block-prune, an unmatched leaf is simply False for that
    block), and assemble block-indexed tables exactly like
    compile_multi does for the legacy terms. ``host_only`` is the
    breaker/host-route contract: no staged dictionary is consulted and
    the product carries host range tables only."""
    leaves = _LeafCollector()
    plan = leaves.lower_trace(expr)
    B = max(1, len(blocks))

    term_keys = val_ranges = val_hits = block_group = None
    if leaves.terms:
        term_keys, val_ranges, val_hits, block_group = _assemble_terms(
            leaves.terms, blocks, cache_on=cache_on,
            staged_dicts=staged_dicts, host_only=host_only)
    dur_params = (np.asarray(leaves.durs, dtype=np.uint32)
                  if leaves.durs else None)
    kind_params = (np.asarray(leaves.kinds, dtype=np.int32)
                   if leaves.kinds else None)
    agg_params = (np.asarray(leaves.aggs, dtype=np.uint32)
                  if leaves.aggs else None)

    cs = CompiledStructural(
        plan=plan, term_keys=term_keys, val_ranges=val_ranges,
        val_hits=val_hits, block_group=block_group,
        dur_params=dur_params, kind_params=kind_params,
        agg_params=agg_params, node_info=leaves.node_info)
    # cost-model registration happens against batch-independent proxies
    # here; the engines refresh with real staged sizes at dispatch
    cs.node_bytes = plan_node_bytes(
        plan, n_spans=sum(getattr(b, "n_spans", 0) for b in blocks),
        n_entries=sum(
            getattr(b, "n_pages", 1)
            * getattr(getattr(b, "geometry", None), "entries_per_page",
                      1024)
            for b in blocks),
        span_kv_slots=max(
            [b.span_kv_key.shape[1] for b in blocks
             if getattr(b, "has_spans", False)] or [1]),
        entry_kv_slots=entry_kv_slots)
    _ = B
    return cs


class _LeafCollector:
    """IR walk: dedupe leaves into parameter tables and emit the static
    plan descriptor. Node ids are preorder positions (stable across
    host and device, and across sub-requests of one query — the
    frontend merges explain nodes by id)."""

    def __init__(self) -> None:
        self.terms: list[tuple[str, str]] = []
        self._term_idx: dict[tuple[str, str], int] = {}
        self.durs: list[tuple[int, int]] = []
        self._dur_idx: dict[tuple[int, int], int] = {}
        self.kinds: list[int] = []
        self._kind_idx: dict[int, int] = {}
        self.aggs: list[tuple[int, int, int]] = []
        self.node_info: list[tuple[int, str, str]] = []
        self._next_id = 0

    def _nid(self, op: str, detail: str = "") -> int:
        nid = self._next_id
        self._next_id += 1
        self.node_info.append((nid, op, detail))
        return nid

    def _term(self, key: str, value: str) -> int:
        t = (key, value)
        i = self._term_idx.get(t)
        if i is None:
            i = self._term_idx[t] = len(self.terms)
            self.terms.append(t)
        return i

    def _dur(self, lo: int, hi: int) -> int:
        d = (lo, hi)
        i = self._dur_idx.get(d)
        if i is None:
            i = self._dur_idx[d] = len(self.durs)
            self.durs.append(d)
        return i

    def _kind(self, k: int) -> int:
        i = self._kind_idx.get(k)
        if i is None:
            i = self._kind_idx[k] = len(self.kinds)
            self.kinds.append(k)
        return i

    def lower_span(self, e: "ir.SpanExpr") -> tuple:
        if isinstance(e, ir.SpanTag):
            nid = self._nid("span.tag", f"{e.key}~{e.value}")
            return ("tag", nid, self._term(e.key, e.value))
        if isinstance(e, ir.SpanDur):
            nid = self._nid("span.dur", f"[{e.lo_ms},{e.hi_ms}]ms")
            return ("dur", nid, self._dur(e.lo_ms, e.hi_ms))
        if isinstance(e, ir.SpanKind):
            nid = self._nid("span.kind", str(e.kind))
            return ("kind", nid, self._kind(e.kind))
        if isinstance(e, ir.SpanAnd):
            nid = self._nid("span.and")
            return ("and", nid, tuple(self.lower_span(a) for a in e.args))
        if isinstance(e, ir.SpanOr):
            nid = self._nid("span.or")
            return ("or", nid, tuple(self.lower_span(a) for a in e.args))
        if isinstance(e, ir.SpanNot):
            nid = self._nid("span.not")
            return ("not", nid, self.lower_span(e.arg))
        if isinstance(e, ir.ChildOf):
            nid = self._nid("child", "parent-pointer join")
            return ("child", nid, self.lower_span(e.parent),
                    self.lower_span(e.child))
        if isinstance(e, ir.DescOf):
            nid = self._nid("desc", "pointer-doubling ancestor join")
            return ("desc", nid, self.lower_span(e.anc),
                    self.lower_span(e.span))
        raise StructuralCompileError(
            f"unknown span node {type(e).__name__}")

    def lower_trace(self, e: "ir.TraceExpr") -> tuple:
        if isinstance(e, ir.TraceTag):
            nid = self._nid("trace.tag", f"{e.key}~{e.value}")
            return ("ttag", nid, self._term(e.key, e.value))
        if isinstance(e, ir.TraceDur):
            nid = self._nid("trace.dur", f"[{e.lo_ms},{e.hi_ms}]ms")
            return ("tdur", nid, self._dur(e.lo_ms, e.hi_ms))
        if isinstance(e, ir.Exists):
            nid = self._nid("exists", "segment reduce")
            return ("exists", nid, self.lower_span(e.of))
        if isinstance(e, ir.Count):
            nid = self._nid("count", f"{e.op} {e.n}")
            ai = len(self.aggs)
            self.aggs.append((e.n, 0, 0))
            return ("count", nid, e.op, ai, self.lower_span(e.of))
        if isinstance(e, ir.Quantile):
            nid = self._nid(
                "quantile",
                f"p{e.q_num}/{e.q_den} {e.op} {e.x_ms}ms (rank counts)")
            ai = len(self.aggs)
            self.aggs.append((e.q_num, e.q_den, e.x_ms))
            return ("q", nid, e.op, ai, self.lower_span(e.of))
        if isinstance(e, ir.TraceAnd):
            nid = self._nid("and")
            return ("and", nid, tuple(self.lower_trace(a) for a in e.args))
        if isinstance(e, ir.TraceOr):
            nid = self._nid("or")
            return ("or", nid, tuple(self.lower_trace(a) for a in e.args))
        if isinstance(e, ir.TraceNot):
            nid = self._nid("not")
            return ("not", nid, self.lower_trace(e.arg))
        raise StructuralCompileError(
            f"unknown trace node {type(e).__name__}")


def _assemble_terms(terms: list, blocks: list, cache_on=None,
                    staged_dicts: dict | None = None,
                    host_only: bool = False):
    """Per-block leaf term tables, one dictionary probe per DISTINCT
    dictionary (the compile_multi economics): [B, T] key ids,
    [B, T, R, 2] ranges, and — when a staged dictionary's device probe
    answered — [G, T, Vm] hit masks with the block -> group map.
    Reuses pipeline's probe internals so the host memmem path, the
    device packed-probe kernel, bit-packed masks, breaker fallback and
    watchdog bounds are all the SAME code the legacy terms run."""
    from . import packing
    from .multiblock import _dict_groups
    from .pipeline import _host_probe_tags

    import jax.numpy as jnp

    staged_dicts = staged_dicts or {}
    fp_of, rep_idx, rows_of = _dict_groups(blocks, cache_on=cache_on)
    T = len(terms)
    compiled: dict[bytes, tuple] = {}
    for fp, i in rep_idx.items():
        b = blocks[i]
        compiled[fp] = _probe_leaf_terms(
            b, terms, None if host_only else staged_dicts.get(fp),
            host_only=host_only)

    B = len(blocks)
    rmax = 1
    for tk, tv, vr, vh in compiled.values():
        if vr is not None:
            rmax = max(rmax, vr.shape[1])
    R = _pow2(rmax)
    term_keys = np.full((B, T), -1, dtype=np.int32)
    val_ranges = np.tile(np.array([1, 0], dtype=np.int32), (B, T, R, 1))
    for fp, (tk, _tv, vr, _vh) in compiled.items():
        rows = np.asarray(rows_of[fp], dtype=np.int64)
        term_keys[rows[:, None], np.arange(T)] = tk
        r_n = vr.shape[1]
        val_ranges[rows[:, None, None], np.arange(T)[:, None],
                   np.arange(r_n)] = vr[:, :r_n]

    probe_fps = [fp for fp, c in compiled.items() if c[3] is not None]
    val_hits = block_group = None
    if probe_fps:
        hs = {fp: compiled[fp][3] for fp in probe_fps}
        if any(packing.is_packed_mask(h) for h in hs.values()):
            hs = {fp: packing.pack_mask_words(h) for fp, h in hs.items()}
        Vm = max(int(h.shape[1]) for h in hs.values())
        padded = [jnp.pad(hs[fp], ((0, 0), (0, Vm - hs[fp].shape[1])))
                  for fp in probe_fps]
        val_hits = jnp.stack(padded)                     # [G, T, Vm]
        block_group = np.full(B, -1, dtype=np.int32)
        for g, fp in enumerate(probe_fps):
            block_group[np.asarray(rows_of[fp], dtype=np.int64)] = g
    _ = _host_probe_tags  # referenced via _probe_leaf_terms
    return term_keys, val_ranges, val_hits, block_group


_LEAF_CACHE_MAX = 8
# one lock for every block's leaf-probe LRU (the _compile_cache_lock
# idiom): concurrent structural searches over one block must not race
# the OrderedDict get/move/evict protocol
_leaf_cache_lock = threading.Lock()


def _probe_leaf_terms(block, terms: list, staged_dict, host_only: bool):
    """One dictionary's leaf-term probe, memoized on the immutable
    container: (term_keys [T], term_vals, val_ranges [T,R,2], val_hits)
    — the exhaustive contract (missing key -> -1 row, empty value set
    -> empty ranges) because a structural leaf must evaluate False, not
    prune the block. Device products cache separately from host ones so
    the host route never touches a wedged device's arrays."""
    from .pipeline import (_device_probe_tags, _host_probe_tags,
                           NATIVE_SCAN_THRESHOLD)

    sig = (tuple(terms), bool(staged_dict is not None and not host_only))
    with _leaf_cache_lock:
        cache = getattr(block, "_structural_leaf_cache", None)
        if cache is None:
            cache = block._structural_leaf_cache = OrderedDict()
        hit = cache.get(sig)
        if hit is not None:
            cache.move_to_end(sig)
            return hit
    out = None
    if staged_dict is not None and not host_only:
        from tempo_tpu.robustness import BREAKER, GUARD, DeviceFault

        if not BREAKER.blocking():
            try:
                out = GUARD.run(
                    "dict_probe",
                    lambda: _device_probe_tags(
                        terms, block.key_dict, staged_dict,
                        exhaustive=True))
            except (ValueError, DeviceFault):
                out = None  # oversized needle / wedged probe: host path
    if out is None:
        from tempo_tpu.ops import native

        packed = (block.packed_val_dict()
                  if native.available()
                  and len(block.val_dict) >= NATIVE_SCAN_THRESHOLD
                  else None)
        out = _host_probe_tags(terms, block.key_dict, block.val_dict,
                               packed, True)
    with _leaf_cache_lock:
        cache[sig] = out
        while len(cache) > _LEAF_CACHE_MAX:
            cache.popitem(last=False)
    return out


# ---------------------------------------------------------------------------
# device lowering: the kernel-side mask (called INSIDE the jitted scan
# kernels; `plan` is static at every call site — the jit-purity lint's
# descriptor rule pins that, like the packed-residency `widths`)


def structural_entry_mask(kv_key, kv_val, entry_dur, entry_valid,
                          page_block, entry_dur_res, span_cols, tables,
                          *, plan, widths):
    """[P, E] bool trace verdicts for a compiled structural plan.
    Recursion over the STATIC plan runs at trace time and emits one
    fused computation — compiled, never interpreted per row. Span-level
    sub-plans evaluate to [S] masks over the padded span axis;
    aggregates reduce them to [P, E] through the per-entry span-range
    columns; trace-level leaves evaluate on the entry columns with the
    same unpack/membership code paths the legacy kernel uses. ``plan``
    (like the packed-residency ``widths``) is a static descriptor at
    every call site — the jit-purity lint's descriptor rule pins it."""
    import jax.numpy as jnp

    safe_pb = jnp.maximum(page_block, 0)
    valid = entry_valid & (page_block >= 0)[:, None]
    bucketed = plan[0] == "bucket"
    (term_keys, val_ranges, val_hits, block_group,
     dur_params, kind_params, agg_params) = tables[:7]
    bg_page = None
    if val_hits is not None and block_group is not None:
        bg_page = block_group[safe_pb]                   # [P]
    sctx = None
    if span_cols is not None:
        s_block = jnp.maximum(span_cols["span_block"], 0)
        bg_span = None
        if val_hits is not None and block_group is not None:
            bg_span = block_group[s_block]               # [S]
        sctx = (span_cols["span_trace"] >= 0,            # s_valid
                s_block,
                span_cols["span_parent"],
                span_cols["span_dur"],
                span_cols["span_kind"],
                span_cols["span_kv_key"],
                span_cols["span_kv_val"],
                span_cols["entry_span_begin"],
                span_cols["entry_span_count"],
                bg_span)
    ectx = (kv_key, kv_val, entry_dur, entry_dur_res, valid, safe_pb,
            bg_page)
    if bucketed:
        return _bucket_trace_mask(ectx, sctx, tables, widths,
                                  bucket=plan) & valid
    return _trace_mask(plan, ectx, sctx, tables, widths) & valid


def _seg_count(m, seg_b, seg_n):
    """Matched spans per entry: exclusive cumsum + two gathers — a
    segment reduction with no scatter (the VPU lesson)."""
    import jax.numpy as jnp

    c = jnp.cumsum(m.astype(jnp.int32))
    exc = jnp.concatenate([jnp.zeros(1, jnp.int32), c])
    return exc[seg_b + seg_n] - exc[seg_b]


def _cmp_dev(a, b, op):
    if op == ">":
        return a > b
    if op == ">=":
        return a >= b
    if op == "<":
        return a < b
    if op == "<=":
        return a <= b
    if op == "==":
        return a == b
    return a != b


def _span_mask(plan, sctx, tables, widths):
    """[S] bool mask for a span-level plan node. This is a DESCRIPTOR
    DISPATCHER over `plan` (branch structure decided at trace time):
    callers must pass the static plan, never traced data — the
    jit-purity lint's descriptor rule pins that contract."""
    import jax.numpy as jnp

    from .packing import mask_select_grouped

    if plan is None:
        raise StructuralCompileError("span plan must not be None")
    (s_valid, s_block, s_par, s_dur, s_kind, s_kk, s_vv,
     _seg_b, _seg_n, bg_span) = sctx
    (term_keys, val_ranges, val_hits, _bg, dur_params, kind_params,
     _agg) = tables
    op = plan[0]
    if op == "tag":
        i = plan[2]
        k_per = term_keys[s_block, i]                    # [S]
        keym = s_kk == k_per[:, None]                    # [S,Cs]
        lo = val_ranges[s_block, i, :, 0]                # [S,R]
        hi = val_ranges[s_block, i, :, 1]
        v = s_vv[..., None]                              # [S,Cs,1]
        valm = ((v >= lo[:, None, :]) &
                (v <= hi[:, None, :])).any(-1)           # [S,Cs]
        if bg_span is not None:
            safe_g = jnp.maximum(bg_span, 0)
            safe_v = jnp.maximum(s_vv, 0).astype(jnp.int32)
            mh = (mask_select_grouped(val_hits, safe_g[:, None], i,
                                      safe_v)
                  & (s_vv >= 0))
            valm = jnp.where((bg_span >= 0)[:, None], mh, valm)
        return jnp.any(keym & valm, axis=-1) & s_valid
    if op == "dur":
        i = plan[2]
        return ((s_dur >= dur_params[i, 0]) &
                (s_dur <= dur_params[i, 1]) & s_valid)
    if op == "kind":
        i = plan[2]
        return (s_kind.astype(jnp.int32) == kind_params[i]) & s_valid
    if op == "and":
        m = _span_mask(plan[2][0], sctx, tables, widths)
        for sub in plan[2][1:]:
            m = m & _span_mask(sub, sctx, tables, widths)
        return m
    if op == "or":
        m = _span_mask(plan[2][0], sctx, tables, widths)
        for sub in plan[2][1:]:
            m = m | _span_mask(sub, sctx, tables, widths)
        return m
    if op == "not":
        return ~_span_mask(plan[2], sctx, tables, widths) & s_valid
    if op == "child":
        pm = _span_mask(plan[2], sctx, tables, widths)
        cm = _span_mask(plan[3], sctx, tables, widths)
        safe_par = jnp.maximum(s_par, 0)
        return cm & (s_par >= 0) & pm[safe_par]
    if op == "desc":
        import jax

        am = _span_mask(plan[2], sctx, tables, widths)
        sm = _span_mask(plan[3], sctx, tables, widths)
        safe_par = jnp.maximum(s_par, 0)
        # pointer doubling: after k steps acc covers the first 2^k
        # proper ancestors; the trip count is log2 of the PADDED span
        # axis — static, so the jit key stays shape-only. fori_loop, not
        # a Python unroll: the unrolled gather chain sends XLA's CPU
        # fusion passes into minutes-long optimization on batch-sized
        # span axes (measured), while the rolled loop compiles once.
        def _dbl(_i, carry):
            acc, jump = carry
            safe_j = jnp.maximum(jump, 0)
            acc2 = acc | ((jump >= 0) & acc[safe_j])
            jump2 = jnp.where(jump >= 0, jump[safe_j], -1)
            return acc2, jump2

        S = int(s_par.shape[0])
        acc, _ = jax.lax.fori_loop(
            0, max(1, (S - 1).bit_length()), _dbl,
            ((s_par >= 0) & am[safe_par], s_par))
        return sm & acc
    raise StructuralCompileError(f"bad span plan op {op!r}")


def _trace_mask(plan, ectx, sctx, tables, widths):
    """[P, E] bool mask for a trace-level plan node (plan/widths
    static; a span-less batch evaluates aggregates over zero counts).
    A descriptor dispatcher over `plan`, like _span_mask."""
    import jax.numpy as jnp

    from .packing import duration_ok, mask_select_grouped, unpack_ids

    if plan is None:
        raise StructuralCompileError("trace plan must not be None")
    (kv_key, kv_val, entry_dur, entry_dur_res, valid, safe_pb,
     bg_page) = ectx
    (term_keys, val_ranges, val_hits, _bg, dur_params, _kind,
     agg_params) = tables
    kw, vw, dw = widths if widths is not None else (None, None, None)
    op = plan[0]
    if op == "ttag":
        i = plan[2]
        kk = unpack_ids(kv_key, kw)
        vv = unpack_ids(kv_val, vw)
        k_per_page = term_keys[safe_pb, i]               # [P]
        keym = kk == k_per_page[:, None, None]           # [P,E,C]
        lo = val_ranges[safe_pb, i, :, 0]                # [P,R]
        hi = val_ranges[safe_pb, i, :, 1]
        v = vv[..., None]
        valm = ((v >= lo[:, None, None, :]) &
                (v <= hi[:, None, None, :])).any(-1)
        if bg_page is not None:
            safe_g = jnp.maximum(bg_page, 0)
            safe_v = jnp.maximum(vv, 0).astype(jnp.int32)
            mh = (mask_select_grouped(
                val_hits, safe_g[:, None, None], i, safe_v)
                & (vv >= 0))
            valm = jnp.where((bg_page >= 0)[:, None, None], mh, valm)
        return jnp.any(keym & valm, axis=-1) & valid
    if op == "tdur":
        i = plan[2]
        return duration_ok(entry_dur, entry_dur_res,
                           dur_params[i, 0], dur_params[i, 1], dw) & valid
    if op == "exists":
        if sctx is None:
            return jnp.zeros_like(valid)
        m = _span_mask(plan[2], sctx, tables, widths)
        return (_seg_count(m, sctx[7], sctx[8]) > 0) & valid
    if op == "count":
        cop, ai, sub = plan[2], plan[3], plan[4]
        if sctx is None:
            n = jnp.zeros(valid.shape, dtype=jnp.uint32)
        else:
            m = _span_mask(sub, sctx, tables, widths)
            n = _seg_count(m, sctx[7], sctx[8]).astype(jnp.uint32)
        return _cmp_dev(n, agg_params[ai, 0], cop) & valid
    if op == "q":
        qop, ai, sub = plan[2], plan[3], plan[4]
        if sctx is None:
            return jnp.zeros_like(valid)
        seg_b, seg_n = sctx[7], sctx[8]
        s_dur = sctx[3]
        m = _span_mask(sub, sctx, tables, widths)
        n = _seg_count(m, seg_b, seg_n).astype(jnp.uint32)
        qn = agg_params[ai, 0]
        qd = agg_params[ai, 1]
        x = agg_params[ai, 2]
        # nearest-rank r = ceil(q*n) in pure uint32 math — identical on
        # host (eval_host) so quantiles are bit-exact: no sort, no float
        r = (qn * n + qd - jnp.uint32(1)) // qd
        if qop in (">", ">="):
            inner = (s_dur > x) if qop == ">" else (s_dur >= x)
            ci = _seg_count(m & inner, seg_b, seg_n).astype(jnp.uint32)
            ok = ci >= n - r + jnp.uint32(1)
        elif qop in ("<", "<="):
            inner = (s_dur < x) if qop == "<" else (s_dur <= x)
            ci = _seg_count(m & inner, seg_b, seg_n).astype(jnp.uint32)
            ok = ci >= r
        else:  # == / != via the two one-sided rank tests
            chi = _seg_count(m & (s_dur >= x), seg_b,
                             seg_n).astype(jnp.uint32)
            clo = _seg_count(m & (s_dur <= x), seg_b,
                             seg_n).astype(jnp.uint32)
            eq = (chi >= n - r + jnp.uint32(1)) & (clo >= r)
            ok = eq if qop == "==" else ~eq
        return ok & (n > 0) & valid
    if op == "and":
        m = _trace_mask(plan[2][0], ectx, sctx, tables, widths)
        for sub in plan[2][1:]:
            m = m & _trace_mask(sub, ectx, sctx, tables, widths)
        return m
    if op == "or":
        m = _trace_mask(plan[2][0], ectx, sctx, tables, widths)
        for sub in plan[2][1:]:
            m = m | _trace_mask(sub, ectx, sctx, tables, widths)
        return m
    if op == "not":
        return ~_trace_mask(plan[2], ectx, sctx, tables, widths) & valid
    raise StructuralCompileError(f"bad trace plan op {op!r}")


def _cmp_dyn(a, b, opc):
    """Dynamic-opcode comparison (the bucket-program twin of _cmp_dev):
    all six verdicts compute, the traced compare code selects one."""
    import jax.numpy as jnp

    out = a != b
    for code, m in ((0, a > b), (1, a >= b), (2, a < b),
                    (3, a <= b), (4, a == b)):
        out = jnp.where(opc == code, m, out)
    return out


def _bucket_span_regs(sctx, core, n_slots, prog, has_rel) -> list:
    """Evaluate a span slot program: returns the register list (index 0
    = the dummy all-false register, register i+1 = slot i's [S] mask).
    Each slot computes every opcode arm from ITS dynamic row and
    selects by the traced opcode — the slot-machine dual of
    _span_mask's static descriptor dispatch. Pad slots (opcode 0)
    evaluate to false and are unreachable from any real slot."""
    import jax
    import jax.numpy as jnp

    from .packing import mask_select_grouped

    (s_valid, s_block, s_par, s_dur, s_kind, s_kk, s_vv,
     _seg_b, _seg_n, bg_span) = sctx
    (term_keys, val_ranges, val_hits, _bg, dur_params, kind_params,
     _agg) = core
    S = int(s_valid.shape[0])
    false = jnp.zeros(S, dtype=bool)
    safe_par = jnp.maximum(s_par, 0)
    regs = [false]
    for i in range(n_slots):
        opc, a, b = prog[i, 0], prog[i, 1], prog[i, 2]
        prev = jnp.stack(regs)                       # [i+1, S]
        ra = prev[jnp.clip(a, 0, i)]
        rb = prev[jnp.clip(b, 0, i)]
        val = false
        if term_keys is not None:
            k_per = term_keys[s_block, a]            # [S]
            keym = s_kk == k_per[:, None]            # [S,Cs]
            lo = val_ranges[s_block, a, :, 0]        # [S,R]
            hi = val_ranges[s_block, a, :, 1]
            v = s_vv[..., None]                      # [S,Cs,1]
            valm = ((v >= lo[:, None, :]) &
                    (v <= hi[:, None, :])).any(-1)   # [S,Cs]
            if bg_span is not None:
                safe_g = jnp.maximum(bg_span, 0)
                safe_v = jnp.maximum(s_vv, 0).astype(jnp.int32)
                mh = (mask_select_grouped(val_hits, safe_g[:, None], a,
                                          safe_v)
                      & (s_vv >= 0))
                valm = jnp.where((bg_span >= 0)[:, None], mh, valm)
            tag_m = jnp.any(keym & valm, axis=-1) & s_valid
            val = jnp.where(opc == 1, tag_m, val)
        if dur_params is not None:
            dur_m = ((s_dur >= dur_params[a, 0]) &
                     (s_dur <= dur_params[a, 1]) & s_valid)
            val = jnp.where(opc == 2, dur_m, val)
        if kind_params is not None:
            kind_m = ((s_kind.astype(jnp.int32) == kind_params[a])
                      & s_valid)
            val = jnp.where(opc == 3, kind_m, val)
        val = jnp.where(opc == 4, ra & rb, val)
        val = jnp.where(opc == 5, ra | rb, val)
        val = jnp.where(opc == 6, ~ra & s_valid, val)
        if has_rel:
            val = jnp.where(opc == 7,
                            rb & (s_par >= 0) & ra[safe_par], val)

            # the same rolled pointer doubling as _span_mask's desc
            def _dbl(_i, carry):
                acc, jump = carry
                safe_j = jnp.maximum(jump, 0)
                acc2 = acc | ((jump >= 0) & acc[safe_j])
                jump2 = jnp.where(jump >= 0, jump[safe_j], -1)
                return acc2, jump2

            acc, _ = jax.lax.fori_loop(
                0, max(1, (S - 1).bit_length()), _dbl,
                ((s_par >= 0) & ra[safe_par], s_par))
            val = jnp.where(opc == 8, rb & acc, val)
        regs.append(val)
    return regs


def _bucket_trace_mask(ectx, sctx, tables, widths, *, bucket):
    """[P, E] bool verdicts for ONE query lane of a bucket-stacked
    group. ``bucket`` = ("bucket", NS, NT, has_rel) is the static
    descriptor (part of every consuming kernel's jit key, like
    ``plan``); tables[7]/tables[8] are this lane's span/trace slot
    programs. The result register is statically the last trace slot
    (the flattener's root-copy contract), so no dynamic final gather
    is needed."""
    import jax.numpy as jnp

    from .packing import duration_ok, mask_select_grouped, unpack_ids

    core = tables[:7]
    span_prog, trace_prog = tables[7], tables[8]
    (kv_key, kv_val, entry_dur, entry_dur_res, valid, safe_pb,
     bg_page) = ectx
    (term_keys, val_ranges, val_hits, _bg, dur_params, _kind,
     agg_params) = core
    kw, vw, dw = widths if widths is not None else (None, None, None)
    NS, NT = bucket[1], bucket[2]
    sprev = seg_b = seg_n = s_dur = None
    if bucket[1]:
        if sctx is not None:
            sregs = _bucket_span_regs(sctx, core, NS, span_prog,
                                      bucket[3])
            sprev = jnp.stack(sregs)                 # [NS+1, S]
            seg_b, seg_n, s_dur = sctx[7], sctx[8], sctx[3]
    kk = vv = None
    if term_keys is not None:
        kk = unpack_ids(kv_key, kw)
        vv = unpack_ids(kv_val, vw)
    false = jnp.zeros(valid.shape, dtype=bool)
    tregs = [false]
    for i in range(NT):
        opc, a, b, c = (trace_prog[i, 0], trace_prog[i, 1],
                        trace_prog[i, 2], trace_prog[i, 3])
        prev = jnp.stack(tregs)
        ra = prev[jnp.clip(a, 0, i)]
        rb = prev[jnp.clip(b, 0, i)]
        val = false
        if term_keys is not None:
            k_per_page = term_keys[safe_pb, a]       # [P]
            keym = kk == k_per_page[:, None, None]   # [P,E,C]
            lo = val_ranges[safe_pb, a, :, 0]        # [P,R]
            hi = val_ranges[safe_pb, a, :, 1]
            v = vv[..., None]
            valm = ((v >= lo[:, None, None, :]) &
                    (v <= hi[:, None, None, :])).any(-1)
            if bg_page is not None:
                safe_g = jnp.maximum(bg_page, 0)
                safe_v = jnp.maximum(vv, 0).astype(jnp.int32)
                mh = (mask_select_grouped(
                    val_hits, safe_g[:, None, None], a, safe_v)
                    & (vv >= 0))
                valm = jnp.where((bg_page >= 0)[:, None, None], mh,
                                 valm)
            ttag_m = jnp.any(keym & valm, axis=-1) & valid
            val = jnp.where(opc == 1, ttag_m, val)
        if dur_params is not None:
            tdur_m = duration_ok(entry_dur, entry_dur_res,
                                 dur_params[a, 0], dur_params[a, 1],
                                 dw) & valid
            val = jnp.where(opc == 2, tdur_m, val)
        if sprev is not None:
            sm = sprev[jnp.clip(a, 0, NS)]
            cnt = _seg_count(sm, seg_b, seg_n).astype(jnp.uint32)
            val = jnp.where(opc == 3, (cnt > 0) & valid, val)
            if agg_params is not None:
                count_m = _cmp_dyn(cnt, agg_params[b, 0], c) & valid
                val = jnp.where(opc == 4, count_m, val)
                qn = agg_params[b, 0]
                # pad agg rows are (0, 1, 0): the clamp keeps the
                # computed-but-unselected arm division-safe anyway
                qd = jnp.maximum(agg_params[b, 1], jnp.uint32(1))
                x = agg_params[b, 2]
                r = (qn * cnt + qd - jnp.uint32(1)) // qd
                hi_inner = jnp.where(c == 0, s_dur > x, s_dur >= x)
                lo_inner = jnp.where(c == 2, s_dur < x, s_dur <= x)
                c_hi = _seg_count(sm & hi_inner, seg_b,
                                  seg_n).astype(jnp.uint32)
                c_lo = _seg_count(sm & lo_inner, seg_b,
                                  seg_n).astype(jnp.uint32)
                ok_hi = c_hi >= cnt - r + jnp.uint32(1)
                ok_lo = c_lo >= r
                eq = ok_hi & ok_lo
                q_ok = jnp.where(c <= 1, ok_hi,
                                 jnp.where(c <= 3, ok_lo,
                                           jnp.where(c == 4, eq, ~eq)))
                val = jnp.where(opc == 5,
                                q_ok & (cnt > 0) & valid, val)
        elif agg_params is not None:
            # span-less batch: exists/q are false, count still compares
            # against zero — the _trace_mask sctx-None semantics
            n0 = jnp.zeros(valid.shape, dtype=jnp.uint32)
            count_m = _cmp_dyn(n0, agg_params[b, 0], c) & valid
            val = jnp.where(opc == 4, count_m, val)
        val = jnp.where(opc == 6, ra & rb, val)
        val = jnp.where(opc == 7, ra | rb, val)
        val = jnp.where(opc == 8, ~ra & valid, val)
        tregs.append(val)
    return tregs[-1]


# ---------------------------------------------------------------------------
# host reference evaluator (the differential-fuzz oracle and the
# live/WAL + proto-fallback execution path)


_CMP = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}


def eval_host(expr: "ir.TraceExpr", sd) -> bool:
    """Reference semantics over a SearchData (with its span rows):
    byte-for-byte what the compiled kernels answer — substring tag
    terms, inclusive ranges, pointer joins, and the SAME integer
    rank-count quantile formula (never a sort, never a float)."""
    spans = list(getattr(sd, "spans", ()) or ())
    n_spans = len(spans)

    def sev(e) -> list:
        if isinstance(e, ir.SpanTag):
            out = []
            for sp in spans:
                vs = sp.kvs.get(e.key)
                out.append(bool(vs) and (not e.value or
                                         any(e.value in x for x in vs)))
            return out
        if isinstance(e, ir.SpanDur):
            return [e.lo_ms <= sp.dur_ms <= e.hi_ms for sp in spans]
        if isinstance(e, ir.SpanKind):
            return [sp.kind == e.kind for sp in spans]
        if isinstance(e, ir.SpanAnd):
            ms = [sev(a) for a in e.args]
            return [all(m[i] for m in ms) for i in range(n_spans)]
        if isinstance(e, ir.SpanOr):
            ms = [sev(a) for a in e.args]
            return [any(m[i] for m in ms) for i in range(n_spans)]
        if isinstance(e, ir.SpanNot):
            return [not v for v in sev(e.arg)]
        if isinstance(e, ir.ChildOf):
            pm, cm = sev(e.parent), sev(e.child)
            return [cm[i] and 0 <= spans[i].parent < n_spans
                    and pm[spans[i].parent] for i in range(n_spans)]
        if isinstance(e, ir.DescOf):
            am, sm = sev(e.anc), sev(e.span)
            out = []
            for i in range(n_spans):
                ok = False
                if sm[i]:
                    p = spans[i].parent
                    # bounded walk: malformed parent cycles terminate
                    # after n_spans hops (the device doubling covers the
                    # same reachable set)
                    for _ in range(n_spans):
                        if not 0 <= p < n_spans:
                            break
                        if am[p]:
                            ok = True
                            break
                        p = spans[p].parent
                out.append(ok)
            return out
        raise StructuralCompileError(
            f"unknown span node {type(e).__name__}")

    def tev(e) -> bool:
        if isinstance(e, ir.TraceTag):
            vs = sd.kvs.get(e.key)
            return bool(vs) and (not e.value
                                 or any(e.value in x for x in vs))
        if isinstance(e, ir.TraceDur):
            return e.lo_ms <= sd.dur_ms <= e.hi_ms
        if isinstance(e, ir.Exists):
            return any(sev(e.of))
        if isinstance(e, ir.Count):
            return _CMP[e.op](sum(sev(e.of)), e.n)
        if isinstance(e, ir.Quantile):
            m = sev(e.of)
            n = sum(m)
            if n == 0:
                return False
            r = (e.q_num * n + e.q_den - 1) // e.q_den
            if e.op in (">", ">="):
                ci = sum(1 for i, v in enumerate(m) if v and
                         (spans[i].dur_ms > e.x_ms if e.op == ">"
                          else spans[i].dur_ms >= e.x_ms))
                return ci >= n - r + 1
            if e.op in ("<", "<="):
                ci = sum(1 for i, v in enumerate(m) if v and
                         (spans[i].dur_ms < e.x_ms if e.op == "<"
                          else spans[i].dur_ms <= e.x_ms))
                return ci >= r
            chi = sum(1 for i, v in enumerate(m)
                      if v and spans[i].dur_ms >= e.x_ms)
            clo = sum(1 for i, v in enumerate(m)
                      if v and spans[i].dur_ms <= e.x_ms)
            eq = (chi >= n - r + 1) and (clo >= r)
            return eq if e.op == "==" else not eq
        if isinstance(e, ir.TraceAnd):
            return all(tev(a) for a in e.args)
        if isinstance(e, ir.TraceOr):
            return any(tev(a) for a in e.args)
        if isinstance(e, ir.TraceNot):
            return not tev(e.arg)
        raise StructuralCompileError(
            f"unknown trace node {type(e).__name__}")

    return tev(expr)


# ---------------------------------------------------------------------------
# cost model + explain attribution


def plan_node_bytes(plan: tuple, n_spans: int, n_entries: int,
                    span_kv_slots: int = 1,
                    entry_kv_slots: int = 1) -> dict:
    """Per-node device-byte estimates — the unit the planner's
    calibrated scan rate (seconds/byte) turns into predicted seconds,
    and the conserved weights measured kernel time apportions over for
    the explain tree. Deliberately simple: bytes touched per op,
    including the log-factor of the doubling join."""
    S = max(1, n_spans)
    PE = max(1, n_entries)
    out: dict[int, int] = {}

    def w_span(p) -> None:
        op, nid = p[0], p[1]
        if op == "tag":
            out[nid] = S * span_kv_slots * 8
        elif op == "dur":
            out[nid] = S * 4
        elif op == "kind":
            out[nid] = S
        elif op in ("and", "or"):
            out[nid] = S * len(p[2])
            for sub in p[2]:
                w_span(sub)
        elif op == "not":
            out[nid] = S
            w_span(p[2])
        elif op == "child":
            out[nid] = S * 12
            w_span(p[2])
            w_span(p[3])
        elif op == "desc":
            out[nid] = S * 12 * max(1, (S - 1).bit_length())
            w_span(p[2])
            w_span(p[3])

    def w_trace(p) -> None:
        op, nid = p[0], p[1]
        if op == "ttag":
            out[nid] = PE * entry_kv_slots * 8
        elif op == "tdur":
            out[nid] = PE * 4
        elif op == "exists":
            out[nid] = S * 4 + PE * 8
            w_span(p[2])
        elif op in ("count", "q"):
            out[nid] = (S * 4 + PE * 8) * (2 if op == "q" else 1)
            w_span(p[4])
        elif op in ("and", "or"):
            out[nid] = PE * len(p[2])
            for sub in p[2]:
                w_trace(sub)
        elif op == "not":
            out[nid] = PE
            w_trace(p[2])

    w_trace(plan)
    return out


def span_device_bytes(span_cols) -> int:
    """Physical bytes of a staged span segment (budget accounting)."""
    if not span_cols:
        return 0
    return int(sum(int(getattr(a, "nbytes", 0))
                   for a in span_cols.values()))
