"""Device-ready columnar search pages.

The TPU-first redesign of the reference's FlatBuffer SearchPage
(pkg/tempofb/tempo.fbs, search_page_builder.go): instead of byte-level
FlatBuffer accessors scanned entry-by-entry on CPU, a block's search data
is dictionary-encoded once at build time — tag keys and values become
int32 ids into per-block sorted dictionaries — and laid out DENSELY so the
device predicate is pure compares + lane reductions, no scatter/gather on
the hot path:

  kv_key    int32 [P, E, C]  key id of each kv slot (pad -1)
  kv_val    int32 [P, E, C]  value id of each kv slot (pad -1)
  entry_start u32 [P, E]   trace start, unix seconds
  entry_end   u32 [P, E]   trace end, unix seconds
  entry_dur   u32 [P, E]   trace duration, ms (exact parity with the
                           proto oracle's (end_ns-start_ns)//1e6)
  entry_valid bool[P, E]
  entry_root_svc/name int32 [P, E]  val-dict ids for result rendering
  trace_ids  u8 [P, E, 16]  stays host-side for result construction

P = pages, E = entries/page, C = kv slots per entry. A term match is
``any((kv_key == k) & (kv_val in ranges), axis=-1)`` — a VPU-friendly
reduction (membership = OR of [lo,hi] range compares, see
pipeline.ids_to_ranges). Ragged tag sets are padded/truncated to C (the reference
likewise caps search data per trace, limits.go max_search_bytes_per_trace);
that capacity trade is the price of static shapes on a shape-static
accelerator (SURVEY.md §7 hard parts). An earlier CSR + scatter layout
benchmarked ~20x slower on TPU than numpy on CPU — scatters serialize on
the VPU; dense + reduce is the idiomatic mapping.
"""

from __future__ import annotations

import bisect
import hashlib
import json
import struct
from dataclasses import dataclass, field

import numpy as np

from .data import SearchData
from tempo_tpu.utils.ids import pad_trace_id

_MAGIC = 0x54505553  # "TPUS"
_VERSION = 2
_HDR = struct.Struct("<IIQ")
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")


@dataclass(frozen=True)
class PageGeometry:
    entries_per_page: int = 1024
    # CAP on kv slots per entry; the build sizes the actual capacity C to
    # the corpus (next pow2 of the real max), so this only bounds memory
    # for pathologically tagged traces (cf. reference
    # max_search_bytes_per_trace, limits.go)
    kv_per_entry: int = 64


@dataclass
class ColumnarPages:
    geometry: PageGeometry
    key_dict: list          # sorted list[str]
    val_dict: list          # sorted list[str]
    kv_key: np.ndarray      # int32 [P,E,C]
    kv_val: np.ndarray      # int32 [P,E,C]
    entry_start: np.ndarray  # uint32 [P,E]
    entry_end: np.ndarray    # uint32 [P,E]
    entry_dur: np.ndarray    # uint32 [P,E]
    entry_valid: np.ndarray  # bool [P,E]
    entry_root_svc: np.ndarray   # int32 [P,E]
    entry_root_name: np.ndarray  # int32 [P,E]
    trace_ids: np.ndarray    # uint8 [P,E,16]
    n_entries: int = 0
    header: dict = field(default_factory=dict)
    # ---- optional span segment (structural query engine) ----
    # Flat span axis S in build order, per-trace CONTIGUOUS (the segment
    # property the structural kernels' cumsum reductions and parent
    # joins rely on); absent (None) for legacy containers and whenever
    # search_structural_enabled captured no spans at ingest.
    span_trace: np.ndarray | None = None    # int32 [S] flat entry p*E+e
    span_parent: np.ndarray | None = None   # int32 [S] flat span idx, -1
    span_dur: np.ndarray | None = None      # uint32 [S] ms
    span_kind: np.ndarray | None = None     # int8 [S] OTLP kind
    span_kv_key: np.ndarray | None = None   # int32 [S, Cs] (pad -1)
    span_kv_val: np.ndarray | None = None   # int32 [S, Cs] (pad -1)
    entry_span_begin: np.ndarray | None = None  # int32 [P,E]
    entry_span_count: np.ndarray | None = None  # int32 [P,E]

    @property
    def n_pages(self) -> int:
        return self.kv_key.shape[0]

    @property
    def has_spans(self) -> bool:
        return self.span_trace is not None and self.span_trace.size > 0

    @property
    def n_spans(self) -> int:
        return 0 if self.span_trace is None else int(self.span_trace.shape[0])

    @property
    def nbytes(self) -> int:
        """Host RAM pinned by this container's arrays (page-range views
        over-count toward the parent's full buffers — conservative for a
        byte budget)."""
        return int(sum(getattr(self, name).nbytes
                       for name, _ in self._ARRAYS)
                   + sum(getattr(self, name).nbytes
                         for name, _ in self._SPAN_ARRAYS
                         if getattr(self, name) is not None))

    def slice_pages(self, start: int, count: int) -> "ColumnarPages":
        """A view over pages [start, start+count) — the unit of the
        reference's page-range search jobs (SearchBlockRequest
        startPage/pagesToSearch, searchsharding.go:332-343). Numpy slices
        are views: no copy; dictionaries are shared with the parent."""
        end = min(start + count, self.n_pages)
        start = min(start, end)
        kw = {name: getattr(self, name)[start:end] for name, _ in self._ARRAYS}
        hdr = dict(self.header)
        hdr["n_pages"] = end - start
        hdr["n_entries"] = int(kw["entry_valid"].sum())
        if self.has_spans:
            # spans are per-trace contiguous in build order, so the
            # slice's span rows are one contiguous run; flat entry and
            # span indices remap to the slice's origin (copies, not
            # views — the remap rewrites values)
            E = self.geometry.entries_per_page
            begin = self.entry_span_begin[start:end]
            cnt = self.entry_span_count[start:end]
            live = cnt > 0
            if live.any():
                sb = int(begin[live].min())
                se = int((begin[live] + cnt[live]).max())
            else:
                sb = se = 0
            kw["span_trace"] = self.span_trace[sb:se] - start * E
            par = self.span_parent[sb:se].copy()
            par[par >= 0] -= sb
            kw["span_parent"] = par
            for name in ("span_dur", "span_kind",
                         "span_kv_key", "span_kv_val"):
                kw[name] = getattr(self, name)[sb:se]
            kw["entry_span_begin"] = np.where(live, begin - sb,
                                              0).astype(np.int32)
            kw["entry_span_count"] = cnt
            hdr["n_spans"] = se - sb
        out = ColumnarPages(
            geometry=self.geometry, key_dict=self.key_dict,
            val_dict=self.val_dict, n_entries=hdr["n_entries"],
            header=hdr, **kw,
        )
        # dictionaries are shared with the parent — so are every
        # dictionary-derived product: the native-scan packing, the
        # compile-cache fingerprint, and the device-probe packing
        # (re-deriving any of them per page-range job re-pays an
        # O(dict) walk the parent already did)
        for attr in ("_packed_vals", "_dict_fingerprint",
                     "_dict_section_sha", "_device_dict_packed"):
            cached = getattr(self, attr, None)
            if cached is not None:
                setattr(out, attr, cached)
        return out

    def max_dur_ms(self) -> int:
        """Upper bound on this container's durations — the packed-
        residency width planner's input (search/packing.py). The build
        records the exact max in the header; synthetic containers
        without the rollup fall back to one memoized array scan."""
        v = self.header.get("max_dur_ms")
        if v is None:
            v = getattr(self, "_max_dur_ms", None)
            if v is None:
                v = self._max_dur_ms = (int(self.entry_dur.max())
                                        if self.entry_dur.size else 0)
        return int(v)

    def packed_val_dict(self) -> tuple:
        """Cached (bytes, offsets) packing for the native substring scan
        (huge dictionaries — see pipeline.substring_value_ids)."""
        cached = getattr(self, "_packed_vals", None)
        if cached is None:
            from .pipeline import pack_val_dict

            cached = self._packed_vals = pack_val_dict(self.val_dict)
        return cached

    def values_for_key(self, tag: str):
        """Distinct value strings present under `tag` in this container —
        the tag-values endpoints' columnar extraction (one idiom, used by
        both the querier's blocklist sweep and the ingester's
        recently-completed sweep)."""
        # one binary search on the sorted key dictionary (matching
        # pipeline._probe_tags) — `in` + `.index()` were each a linear
        # walk, paid per tag-values call per block
        kid = bisect.bisect_left(self.key_dict, tag)
        if kid >= len(self.key_dict) or self.key_dict[kid] != tag:
            return
        for v in np.unique(self.kv_val[self.kv_key == kid]).tolist():
            if v >= 0:
                yield self.val_dict[v]

    # ------------------------------------------------------------------
    # build

    @classmethod
    def build(cls, entries: list[SearchData],
              geometry: PageGeometry = PageGeometry()) -> "ColumnarPages":
        E = geometry.entries_per_page

        keys: set[str] = set()
        vals: set[str] = set()
        total_spans = 0
        span_kv_max = 0
        for sd in entries:
            for k, vs in sd.kvs.items():
                keys.add(k)
                vals.update(vs)
            if sd.root_service:
                vals.add(sd.root_service)
            if sd.root_name:
                vals.add(sd.root_name)
            # span rows share the block dictionaries with the trace-level
            # rollup: one sorted id space serves both the legacy term
            # compares and the structural span-leaf compares
            for sp in getattr(sd, "spans", ()):
                total_spans += 1
                width = 0
                for k, vs in sp.kvs.items():
                    keys.add(k)
                    vals.update(vs)
                    width += len(vs)
                span_kv_max = max(span_kv_max, width)
        key_dict = sorted(keys)
        val_dict = sorted(vals)
        kidx = {k: i for i, k in enumerate(key_dict)}
        vidx = {v: i for i, v in enumerate(val_dict)}

        # size the kv capacity to the corpus: next pow2 of the widest
        # entry, capped by geometry (truncation only beyond the cap)
        widest = max(
            (sum(len(vs) for vs in sd.kvs.values()) for sd in entries),
            default=1,
        )
        C = 1
        while C < min(widest, geometry.kv_per_entry):
            C *= 2
        C = min(C, geometry.kv_per_entry)

        P = max(1, -(-len(entries) // E))
        kv_key = np.full((P, E, C), -1, dtype=np.int32)
        kv_val = np.full((P, E, C), -1, dtype=np.int32)
        entry_start = np.zeros((P, E), dtype=np.uint32)
        entry_end = np.zeros((P, E), dtype=np.uint32)
        entry_dur = np.zeros((P, E), dtype=np.uint32)
        entry_valid = np.zeros((P, E), dtype=bool)
        entry_root_svc = np.full((P, E), -1, dtype=np.int32)
        entry_root_name = np.full((P, E), -1, dtype=np.int32)
        trace_ids = np.zeros((P, E, 16), dtype=np.uint8)

        # span segment (structural engine): flat arrays in entry order,
        # per-trace contiguous; Cs sized like C (pow2 of the widest span,
        # capped) — absent entirely when no entry carries spans, keeping
        # gate-off containers byte-identical to the legacy layout
        SPAN_KV_CAP = 64
        span_arrays = None
        if total_spans:
            Cs = 1
            while Cs < min(span_kv_max, SPAN_KV_CAP):
                Cs *= 2
            Cs = min(max(Cs, 1), SPAN_KV_CAP)
            span_arrays = {
                "span_trace": np.full(total_spans, -1, dtype=np.int32),
                "span_parent": np.full(total_spans, -1, dtype=np.int32),
                "span_dur": np.zeros(total_spans, dtype=np.uint32),
                "span_kind": np.zeros(total_spans, dtype=np.int8),
                "span_kv_key": np.full((total_spans, Cs), -1,
                                       dtype=np.int32),
                "span_kv_val": np.full((total_spans, Cs), -1,
                                       dtype=np.int32),
                "entry_span_begin": np.zeros((P, E), dtype=np.int32),
                "entry_span_count": np.zeros((P, E), dtype=np.int32),
            }
        span_cursor = 0

        n_entries = 0
        truncated = 0
        min_start, max_end = 0xFFFFFFFF, 0
        min_dur, max_dur = 0xFFFFFFFF, 0
        for i, sd in enumerate(entries):
            p, e = divmod(i, E)
            sd_spans = getattr(sd, "spans", ())
            if span_arrays is not None and sd_spans:
                sa = span_arrays
                base = span_cursor
                sa["entry_span_begin"][p, e] = base
                sa["entry_span_count"][p, e] = len(sd_spans)
                for si, sp in enumerate(sd_spans):
                    row = base + si
                    sa["span_trace"][row] = i
                    if 0 <= sp.parent < len(sd_spans):
                        sa["span_parent"][row] = base + sp.parent
                    sa["span_dur"][row] = min(sp.dur_ms, 0xFFFFFFFF)
                    sa["span_kind"][row] = sp.kind & 0x7F
                    slot = 0
                    for k in sorted(sp.kvs):
                        if slot >= Cs:
                            break
                        for v in sorted(sp.kvs[k]):
                            if slot >= Cs:
                                break
                            sa["span_kv_key"][row, slot] = kidx[k]
                            sa["span_kv_val"][row, slot] = vidx[v]
                            slot += 1
                span_cursor += len(sd_spans)
            entry_start[p, e] = sd.start_s & 0xFFFFFFFF
            entry_end[p, e] = sd.end_s & 0xFFFFFFFF
            entry_dur[p, e] = min(sd.dur_ms, 0xFFFFFFFF)
            entry_valid[p, e] = True
            if sd.root_service:
                entry_root_svc[p, e] = vidx[sd.root_service]
            if sd.root_name:
                entry_root_name[p, e] = vidx[sd.root_name]
            tid = pad_trace_id(sd.trace_id)
            trace_ids[p, e] = np.frombuffer(tid, dtype=np.uint8)
            if sum(len(vs) for vs in sd.kvs.values()) > C:
                truncated += 1
            slot = 0
            for k in sorted(sd.kvs):
                if slot >= C:
                    break
                for v in sorted(sd.kvs[k]):
                    if slot >= C:
                        break
                    kv_key[p, e, slot] = kidx[k]
                    kv_val[p, e, slot] = vidx[v]
                    slot += 1
            n_entries += 1
            if sd.start_s:
                min_start = min(min_start, sd.start_s)
            max_end = max(max_end, sd.end_s)
            min_dur = min(min_dur, sd.dur_ms)
            max_dur = max(max_dur, sd.dur_ms)

        header = {
            "n_entries": n_entries,
            "n_pages": P,
            "entries_per_page": E,
            "kv_per_entry": C,  # actual capacity, not the geometry cap
            "n_keys": len(key_dict),
            "n_vals": len(val_dict),
            "truncated_entries": truncated,
            "min_start_s": 0 if min_start == 0xFFFFFFFF else min_start,
            "max_end_s": max_end,
            "min_dur_ms": 0 if min_dur == 0xFFFFFFFF else min_dur,
            "max_dur_ms": max_dur,
        }
        if span_arrays is not None:
            header["n_spans"] = total_spans
            header["span_kv_per_entry"] = int(
                span_arrays["span_kv_key"].shape[1])
        return cls(
            geometry=PageGeometry(E, C), key_dict=key_dict, val_dict=val_dict,
            kv_key=kv_key, kv_val=kv_val,
            entry_start=entry_start, entry_end=entry_end, entry_dur=entry_dur,
            entry_valid=entry_valid, entry_root_svc=entry_root_svc,
            entry_root_name=entry_root_name, trace_ids=trace_ids,
            n_entries=n_entries, header=header,
            **(span_arrays or {}),
        )

    # ------------------------------------------------------------------
    # decode back to entries (search-block compaction: the reference never
    # compacts search data — its search blocks just age out, SURVEY.md §3.5;
    # we rebuild the merged block's search data from the inputs instead)

    def to_entries(self) -> list:
        """Vectorized: touch only valid entries and real (non-pad) kv
        slots — interpreter work is O(live data), not O(P*E*C)."""
        E = self.entry_valid.shape[1]
        ps, es = np.nonzero(self.entry_valid)
        starts = self.entry_start[ps, es].tolist()
        ends = self.entry_end[ps, es].tolist()
        durs = self.entry_dur[ps, es].tolist()
        svcs = self.entry_root_svc[ps, es].tolist()
        names = self.entry_root_name[ps, es].tolist()
        tids = self.trace_ids[ps, es]  # [N,16]

        slot_index = {}
        out = []
        for i in range(len(ps)):
            sd = SearchData(
                trace_id=tids[i].tobytes(),
                start_s=starts[i], end_s=ends[i], dur_ms=durs[i],
            )
            if svcs[i] >= 0:
                sd.root_service = self.val_dict[svcs[i]]
            if names[i] >= 0:
                sd.root_name = self.val_dict[names[i]]
            out.append(sd)
            slot_index[(int(ps[i]), int(es[i]))] = sd

        kp, ke, _kc = np.nonzero(self.kv_key >= 0)
        kkeys = self.kv_key[self.kv_key >= 0].tolist()
        kvals = self.kv_val[self.kv_key >= 0].tolist()
        for p, e, k, v in zip(kp.tolist(), ke.tolist(), kkeys, kvals):
            sd = slot_index.get((p, e))
            if sd is not None:
                sd.kvs.setdefault(self.key_dict[k], set()).add(self.val_dict[v])
        if self.has_spans:
            # span segment round-trip (search-block compaction rebuilds
            # merged search data from inputs): flat parent pointers fold
            # back to intra-trace indices
            from .data import SpanData

            begins = self.entry_span_begin[ps, es].tolist()
            counts = self.entry_span_count[ps, es].tolist()
            for i in range(len(ps)):
                sd = out[i]
                b, n = begins[i], counts[i]
                for row in range(b, b + n):
                    par = int(self.span_parent[row])
                    sp = SpanData(
                        parent=(par - b if par >= 0 else -1),
                        dur_ms=int(self.span_dur[row]),
                        kind=int(self.span_kind[row]))
                    kk = self.span_kv_key[row]
                    vv = self.span_kv_val[row]
                    for k, v in zip(kk[kk >= 0].tolist(),
                                    vv[kk >= 0].tolist()):
                        sp.kvs.setdefault(self.key_dict[k],
                                          set()).add(self.val_dict[v])
                    sd.spans.append(sp)
        return out

    # ------------------------------------------------------------------
    # container codec

    _ARRAYS = (
        ("kv_key", np.int32), ("kv_val", np.int32),
        ("entry_start", np.uint32), ("entry_end", np.uint32),
        ("entry_dur", np.uint32), ("entry_valid", np.bool_),
        ("entry_root_svc", np.int32), ("entry_root_name", np.int32),
        ("trace_ids", np.uint8),
    )
    # optional span-segment sections (structural engine): written only
    # when the container carries spans, so legacy/gate-off containers
    # stay byte-identical; readers treat absence as "no spans"
    _SPAN_ARRAYS = (
        ("span_trace", np.int32), ("span_parent", np.int32),
        ("span_dur", np.uint32), ("span_kind", np.int8),
        ("span_kv_key", np.int32), ("span_kv_val", np.int32),
        ("entry_span_begin", np.int32), ("entry_span_count", np.int32),
    )

    def to_bytes(self) -> bytes:
        sections: dict[str, bytes] = {}
        for name, _ in self._ARRAYS:
            sections[name] = np.ascontiguousarray(getattr(self, name)).tobytes()
        if self.has_spans:
            for name, _ in self._SPAN_ARRAYS:
                sections[name] = np.ascontiguousarray(
                    getattr(self, name)).tobytes()
        sections["key_dict"] = _pack_strs(self.key_dict)
        sections["val_dict"] = _pack_strs(self.val_dict)

        offsets = {}
        body = bytearray()
        for name, blob in sections.items():
            offsets[name] = [len(body), len(blob)]
            body += blob
        hdr = dict(self.header)
        hdr["sections"] = offsets
        # content digest of the ENCODED dictionary sections, recorded at
        # build so open-time readers get the query-compile cache
        # fingerprint for free (pipeline._dict_fingerprint — the sha256
        # walk over 1M decoded strings costs ~100ms per first cache
        # touch; this is one C-speed pass over bytes already in hand)
        digest = _dict_sections_sha(sections["key_dict"],
                                    sections["val_dict"])
        hdr["dict_sha"] = digest.hex()
        # the writer's own instance adopts the section digest too, so a
        # built-then-serialized container shares its compile-cache
        # fingerprint with every reader that decodes it
        self._dict_section_sha = digest
        hdr_b = json.dumps(hdr).encode()
        return _HDR.pack(_MAGIC, _VERSION, len(hdr_b)) + hdr_b + bytes(body)

    @classmethod
    def from_bytes(cls, buf: bytes) -> "ColumnarPages":
        magic, version, hdr_len = _HDR.unpack_from(buf)
        if magic != _MAGIC:
            raise ValueError("bad search container magic")
        if version != _VERSION:
            raise ValueError(f"unsupported search container version {version}")
        hdr = json.loads(buf[_HDR.size:_HDR.size + hdr_len])
        base = _HDR.size + hdr_len
        sections = hdr.pop("sections")

        P = hdr["n_pages"]
        E = hdr["entries_per_page"]
        C = hdr["kv_per_entry"]
        shapes = {
            "kv_key": (P, E, C), "kv_val": (P, E, C),
            "entry_start": (P, E), "entry_end": (P, E), "entry_dur": (P, E),
            "entry_valid": (P, E), "entry_root_svc": (P, E),
            "entry_root_name": (P, E), "trace_ids": (P, E, 16),
        }
        kw = {}
        for name, dtype in cls._ARRAYS:
            off, length = sections[name]
            arr = np.frombuffer(buf, dtype=dtype, count=length // np.dtype(dtype).itemsize,
                                offset=base + off)
            kw[name] = arr.reshape(shapes[name])
        S = int(hdr.get("n_spans", 0) or 0)
        if S and "span_trace" in sections:
            Cs = int(hdr.get("span_kv_per_entry", 1))
            span_shapes = {
                "span_trace": (S,), "span_parent": (S,),
                "span_dur": (S,), "span_kind": (S,),
                "span_kv_key": (S, Cs), "span_kv_val": (S, Cs),
                "entry_span_begin": (P, E), "entry_span_count": (P, E),
            }
            for name, dtype in cls._SPAN_ARRAYS:
                off, length = sections[name]
                arr = np.frombuffer(
                    buf, dtype=dtype,
                    count=length // np.dtype(dtype).itemsize,
                    offset=base + off)
                kw[name] = arr.reshape(span_shapes[name])
        off, length = sections["key_dict"]
        key_sec = buf[base + off: base + off + length]
        key_dict = _unpack_strs(key_sec)
        off, length = sections["val_dict"]
        val_sec = buf[base + off: base + off + length]
        val_dict = _unpack_strs(val_sec)
        out = cls(
            geometry=PageGeometry(E, C), key_dict=key_dict, val_dict=val_dict,
            n_entries=hdr["n_entries"], header=hdr, **kw,
        )
        # dictionary fingerprint for the query-compile cache: recorded
        # in the header at build (v2 containers); older containers
        # re-hash the encoded section bytes here — still one C-speed
        # pass over contiguous bytes, never the python string walk
        ds = hdr.get("dict_sha")
        out._dict_section_sha = (bytes.fromhex(ds) if ds
                                 else _dict_sections_sha(key_sec, val_sec))
        return out


def _dict_sections_sha(key_sec: bytes, val_sec: bytes) -> bytes:
    """Content digest of the encoded dictionary sections. The encoding
    (_pack_strs) is injective and the separator keeps (key, val) section
    boundaries unambiguous, so equal digests mean equal dictionaries —
    the same contract pipeline._dict_fingerprint's string walk gives."""
    h = hashlib.sha256()
    h.update(key_sec)
    h.update(b"\x01")
    h.update(val_sec)
    return h.digest()


def _pack_strs(strs: list) -> bytes:
    out = bytearray(_U32.pack(len(strs)))
    for s in strs:
        b = s.encode("utf-8")[:0xFFFF]
        out += _U16.pack(len(b)) + b
    return bytes(out)


def _unpack_strs(buf: bytes) -> list:
    (n,) = _U32.unpack_from(buf)
    off = 4
    out = []
    for _ in range(n):
        (ln,) = _U16.unpack_from(buf, off)
        off += 2
        out.append(buf[off:off + ln].decode("utf-8", errors="replace"))
        off += ln
    return out
