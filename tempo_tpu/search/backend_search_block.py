"""Backend search block: build, open, scan.

Role-equivalent to the reference's BackendSearchBlock
(tempodb/search/backend_search_block.go:28-298): at block completion the
WAL search entries are rewritten into the columnar container (`search`
object, page-compressed) plus a small JSON header (`search-header.json`)
used for block-level pruning without touching the container. Search =
header prune → dictionary query compile (may prune) → device kernel →
top-k rendered to TraceSearchMetadata.
"""

from __future__ import annotations

import json

from tempo_tpu import tempopb
from tempo_tpu.backend.raw import RawBackend
from tempo_tpu.backend.types import BlockMeta, NAME_SEARCH, NAME_SEARCH_HEADER
from tempo_tpu.encoding.v2.compression import compress, decompress

from .columnar import ColumnarPages, PageGeometry
from .data import SearchData
from .engine import ScanEngine, StagedPages, stage
from .pipeline import block_header_skip_reason, compile_query
from .results import SearchResults

_DEFAULT_ENGINE = None


def default_engine() -> ScanEngine:
    global _DEFAULT_ENGINE
    if _DEFAULT_ENGINE is None:
        _DEFAULT_ENGINE = ScanEngine()
    return _DEFAULT_ENGINE


def host_scan_single(pages: ColumnarPages, cq, top_k: int):
    """The single-block host fallback (breaker open, or the device
    dispatch faulted): the SAME scan_kernel pinned to the CPU backend
    over the host container — byte-identical to the device dispatch
    (same padded shapes, host range tables; masked_topk's equal-start
    tie caveat applies). The batched twin is search/batcher.host_scan."""
    import time

    import jax.numpy as jnp

    from tempo_tpu.observability import profile

    from .engine import (
        _bucket,
        cpu_pinned,
        fetch_scan_out,
        pad_page_axis,
        scan_kernel,
    )

    from .structural import STRUCTURAL

    t0 = time.perf_counter()
    with cpu_pinned():
        host = pad_page_axis(pages, _bucket(pages.n_pages))
        dev = {k: jnp.asarray(v) for k, v in host.items()}
        # structural predicate on the single-block host route: the
        # host-only compile attached range tables; span columns stage on
        # the CPU backend — same kernel, same plan, byte-identical
        st = getattr(cq, "structural", None)
        plan = s_tables = span_dev = None
        if st is not None:
            plan = st.plan
            s_tables = tuple(jnp.asarray(t) if t is not None else None
                             for t in st.tables())
            if STRUCTURAL.enabled:
                span_host = STRUCTURAL.stage_single(
                    pages, _bucket(pages.n_pages))
                if span_host is not None:
                    span_dev = {k: jnp.asarray(v)
                                for k, v in span_host.items()}
        out = scan_kernel(
            dev["kv_key"], dev["kv_val"], dev["entry_start"],
            dev["entry_end"], dev["entry_dur"], dev["entry_valid"],
            jnp.asarray(cq.term_keys), jnp.asarray(cq.val_ranges),
            jnp.uint32(cq.dur_lo), jnp.uint32(min(cq.dur_hi, 0xFFFFFFFF)),
            jnp.uint32(cq.win_start),
            jnp.uint32(min(cq.win_end, 0xFFFFFFFF)),
            None, None, span_dev, s_tables,
            n_terms=cq.n_terms, top_k=top_k, plan=plan)
        res = fetch_scan_out(out)
    profile.observe_stage("execute", "host_fallback",
                          time.perf_counter() - t0)
    return res


def write_search_block(backend: RawBackend, meta: BlockMeta,
                       entries: list[SearchData],
                       geometry: PageGeometry = PageGeometry(),
                       encoding: str | None = None) -> dict:
    # None = zstd when the codec exists on this host, else zlib — the
    # header records whichever codec actually wrote the pages, so reads
    # are unaffected. Production callers pass cfg.search_encoding.
    if encoding is None:
        from tempo_tpu.encoding.v2.compression import best_available

        encoding = best_available("zstd")
    pages = ColumnarPages.build(entries, geometry)
    blob = compress(pages.to_bytes(), encoding)
    header = dict(pages.header)
    header["encoding"] = encoding
    header["compressed_size"] = len(blob)
    if header.get("truncated_entries"):
        # surface kv-slot truncation (a silent false-negative class:
        # entries wider than C lose tags) — operators watch this counter
        from tempo_tpu.observability import metrics as obs

        obs.truncated_tag_entries.inc(header["truncated_entries"],
                                      tenant=meta.tenant_id)
    backend.write(meta.tenant_id, meta.block_id, NAME_SEARCH, blob)
    backend.write(meta.tenant_id, meta.block_id, NAME_SEARCH_HEADER,
                  json.dumps(header).encode())
    # record the container geometry on the block meta and re-commit it —
    # meta.json written last stays the commit record, now carrying what
    # the frontend job sharder needs (page count/bytes for range math)
    meta.search_pages = header["n_pages"]
    meta.search_size = len(blob)
    meta.search_entries_per_page = header["entries_per_page"]
    meta.search_kv_per_entry = header["kv_per_entry"]
    backend.write_block_meta(meta)
    return header


class BackendSearchBlock:
    def __init__(self, backend: RawBackend, meta: BlockMeta,
                 header: dict | None = None,
                 probe_min_vals: int | None = None):
        """header: an already-fetched rollup (TempoDB's header cache /
        restart snapshot) — saves one backend GET per container open.

        probe_min_vals: the device-probe staging threshold
        (cfg.search_device_probe_min_vals) — the single-block path must
        honor the same knob as the batcher, including <= 0 = host-only
        probing; None = the dict_probe library default."""
        self.backend = backend
        self.meta = meta
        self.probe_min_vals = probe_min_vals
        self._header: dict | None = header
        self._pages: ColumnarPages | None = None
        self._staged: StagedPages | None = None
        self._lock = __import__("threading").Lock()

    def header(self) -> dict:
        if self._header is None:
            self._header = json.loads(self.backend.read(
                self.meta.tenant_id, self.meta.block_id, NAME_SEARCH_HEADER
            ))
        return self._header

    def pages(self) -> ColumnarPages:
        """Load the host columnar container (cached). Device staging is a
        separate step: the batcher stages groups of blocks together, and
        dictionary-only readers (tag lookups) never need device arrays."""
        with self._lock:
            if self._pages is None:
                hdr = self.header()
                blob = self.backend.read(self.meta.tenant_id,
                                         self.meta.block_id, NAME_SEARCH)
                raw = decompress(blob, hdr.get("encoding", "zstd"))
                self._pages = ColumnarPages.from_bytes(raw)
            return self._pages

    def staged(self) -> StagedPages:
        """Device-stage this block alone (cached — HBM is the cache tier
        for hot blocks, cf. reference shouldCache heuristics). The batched
        serving path uses the batcher's group staging instead. The H2D
        transfer runs outside the lock shared with pages() so
        dictionary-only readers (tag lookups) never wait on it; a racing
        duplicate stage is benign and the first publish wins."""
        with self._lock:
            if self._staged is not None:
                return self._staged
        sp = stage(self.pages(), probe_min_vals=self.probe_min_vals)
        with self._lock:
            if self._staged is None:
                self._staged = sp
            return self._staged

    def search(self, req: tempopb.SearchRequest,
               results: SearchResults | None = None,
               engine: ScanEngine | None = None) -> SearchResults:
        from tempo_tpu.robustness import BREAKER, GUARD, DeviceFault

        from . import query_stats

        engine = engine or default_engine()
        results = results or SearchResults.for_request(req)
        results.metrics.inspected_blocks += 1
        qs = query_stats.current()

        reason = block_header_skip_reason(self.header(), req)
        if reason is not None:
            results.metrics.skipped_blocks += 1
            if qs is not None:
                qs.add_skip(reason)
            return results

        from tempo_tpu.ops import native
        from tempo_tpu.search.pipeline import NATIVE_SCAN_THRESHOLD

        def _packed(pages):
            return (pages.packed_val_dict()
                    if req.tags and native.available()
                    and len(pages.val_dict) >= NATIVE_SCAN_THRESHOLD
                    else None)

        out = render_pages = None
        pruned = False
        from tempo_tpu.observability import metrics as obs
        from tempo_tpu.search.ownership import OWNERSHIP

        # same contract as the batcher: breaker open/half-open without a
        # probe token means the host route — no staging put, no device
        # dispatch; a mid-flight DeviceFault falls through to host too.
        # Owner routing applies here exactly like the batched path: a
        # non-owner answers this block from the byte-identical host scan
        # instead of staging a duplicate device copy.
        allow_device = BREAKER.allow_device()
        if allow_device and OWNERSHIP.enabled:
            if not OWNERSHIP.owns_block(self.meta.block_id):
                allow_device = False
                obs.hbm_owner_routed.inc(route="non_owner_host")
        from tempo_tpu.search import structural as _structural

        expr = _structural.structural_query(req)
        if allow_device:
            try:
                sp = GUARD.run("h2d", self.staged)
                # staged_dict present → the substring probe runs on
                # device (staging already applied the size threshold);
                # the host memmem path stays the exact fallback for
                # oversized needles
                with query_stats.attributed_dispatch(qs,
                                                     fallback_wall=False):
                    # attributed: compilation can fire the device probe
                    cq = compile_query(
                        sp.pages.key_dict, sp.pages.val_dict, req,
                        packed_vals=_packed(sp.pages), cache_on=sp.pages,
                        staged_dict=sp.staged_dict)
                    if cq is not None and expr is not None:
                        from .pipeline import _dict_fingerprint

                        sd_map = None
                        if sp.staged_dict is not None:
                            fp = _dict_fingerprint(
                                sp.pages, sp.pages.key_dict,
                                sp.pages.val_dict)
                            sd_map = {fp: sp.staged_dict}
                        cq.structural = _structural.compile_structural(
                            expr, [sp.pages], cache_on=sp.pages,
                            staged_dicts=sd_map,
                            entry_kv_slots=sp.pages.geometry.kv_per_entry)
                        if qs is not None:
                            qs.add_structural(cq.structural)
                if cq is None:  # dictionary prefilter pruned the block
                    pruned = True
                else:
                    with query_stats.attributed_dispatch(qs):
                        out = engine.scan_staged(sp, cq)
                    obs.scan_dispatches.inc(mode="single")
                    render_pages = sp.pages
                    placement = "device"
            except DeviceFault:
                out = None  # fault booked; byte-identical host path below
                pruned = False
        if out is None and not pruned:
            pages = self.pages()
            cq = compile_query(pages.key_dict, pages.val_dict, req,
                               packed_vals=_packed(pages), cache_on=pages,
                               host_only=True)
            if cq is not None and expr is not None:
                cq.structural = _structural.compile_structural(
                    expr, [pages], cache_on=pages, host_only=True,
                    entry_kv_slots=pages.geometry.kv_per_entry)
                if qs is not None:
                    qs.add_structural(cq.structural)
            if cq is None:
                pruned = True
            else:
                out = host_scan_single(pages, cq,
                                       engine._resolve_top_k(cq))
                obs.scan_dispatches.inc(mode="host_fallback")
                render_pages = pages
                placement = "host"
        if pruned:
            results.metrics.skipped_blocks += 1
            if qs is not None:
                qs.add_skip("dict")
            return results

        count, inspected, scores, idx = out
        results.metrics.inspected_traces += inspected
        nbytes = int(self.header().get("compressed_size", 0))
        results.metrics.inspected_bytes += nbytes
        if qs is not None:
            qs.add_inspected(blocks=1, nbytes=nbytes, placement=placement)
        results.metrics.truncated_entries += int(
            self.header().get("truncated_entries", 0) or 0)
        holder = StagedPages(device={}, n_pages=render_pages.n_pages,
                             pages=render_pages)
        for m in engine.results(holder, cq, scores, idx):
            results.add(m)
        return results
