"""Owner-routed HBM placement: one distributed block cache across the fleet.

Every process used to stage and evict its own BlockBatch HBM cache
independently, so at production blocklist sizes the whole fleet thrashed
the same hot set — an eviction cost a ~720 MB re-stage instead of a
route change. This module gives block PLACEMENT GROUPS consistent-hash
ownership across the fleet: a block id hashes onto one of a fixed set of
placement groups (the shared Lamping-Veach jump hash in
``utils.hashing`` — the same helper ``backend/netcache.py`` selects
memcached servers with), and each group's owner is resolved on the
existing ``modules/ring.py`` consistent-hash ring — one ring
implementation for write placement, compactor job ownership AND HBM
ownership, deliberately not a third hash scheme.

Placement is PRECOMPUTED per membership generation: :meth:`set_members`
builds the full group -> owner table once, so the hot-path lookup is two
hashes plus a tuple index, placement can never drift with ring heartbeat
aging, and a membership change reports exactly which groups moved — the
rebalance is a placement DIFF, not a cache flush.

Routing contract (docs/search-hbm-ownership.md):

  - the frontend sends a block group's sub-queries to the owner first
    (retries fall back to the round-robin querier pool);
  - the owner serves the group device-resident (HBM staged + pinned),
    and cross-request coalescing fuses N tenants' dashboards over a hot
    group on that one host;
  - a NON-owner receiving the query serves it through the byte-identical
    host route (the breaker's fallback path) instead of staging a
    duplicate HBM copy;
  - owner death / a wedged owner degrade through the retry + breaker +
    host route (chaos-tested in tests/test_faults.py), never hang;
  - eviction-by-rebalance is a placement change: the old owner drops
    (or, while a search pins the batch, defers) residency and the new
    owner pre-stages (``TempoDB.rebalance_ownership``).

Noop contract: ``search_hbm_ownership_enabled: false`` (the default)
costs ONE attribute read (``OWNERSHIP.enabled``) at every call site and
routing is byte-identical — the same contract the planner and
query-stats knobs carry, pinned by the static noop-contract checker
(analysis/contracts.py registers both the gate and the guarded calls).
"""

from __future__ import annotations

import threading
from typing import Iterable

from tempo_tpu.observability import metrics as obs
from tempo_tpu.utils.hashing import fnv1a_64, jump_hash, mix64

DEFAULT_PLACEMENT_GROUPS = 64
# tokens per member on the ownership ring: enough for an even split at
# small fleets without making the table rebuild (n_groups ring walks)
# noticeable on a membership change
_RING_TOKENS = 64


def _group_token(group: int) -> int:
    """Ring token (32-bit, the ring's token space) for a placement
    group id — mix64-finalized so consecutive group ids spread across
    the whole token space instead of clustering in one ring segment."""
    return mix64(fnv1a_64(b"hbm-group-%d" % group)) & 0xFFFFFFFF


class OwnershipMap:
    """Process-wide block-group -> owner placement map.

    Lookup methods read two immutable tuples swapped atomically under
    ``_lock`` by :meth:`set_members` — the hot path takes no lock. All
    lookups answer "this member owns it" while the layer is DISABLED or
    no membership is installed: single-process deployments behave
    exactly as before the layer existed.
    """

    def __init__(self, n_groups: int = DEFAULT_PLACEMENT_GROUPS) -> None:
        self.enabled = False
        self.self_id = "self"
        self.generation = 0
        self.n_groups = int(n_groups)
        self._lock = threading.Lock()
        self._members: tuple[str, ...] = ()
        self._owners: tuple[str, ...] = ()      # group id -> member id
        self._owner_idx: tuple[int, ...] = ()   # group id -> member index
        # the hot-path snapshot: (n_groups, owners, owner_idx) swapped
        # as ONE tuple so a lookup never pairs a fresh group count with
        # a stale table (configure() can resize n_groups while another
        # thread is mid-lookup — indexing a 64-entry table with a
        # 128-group hash would IndexError a live query)
        self._table: tuple[int, tuple[str, ...], tuple[int, ...]] = \
            (self.n_groups, (), ())

    # ---- membership (the rebalance surface) ----

    @property
    def members(self) -> tuple[str, ...]:
        return self._members

    def set_members(self, members: Iterable[str],
                    self_id: str | None = None) -> int:
        """Install a fleet membership and precompute the placement table;
        returns how many placement groups MOVED owner (0 on the first
        install — nothing was placed before). Idempotent for an unchanged
        member set (no generation bump), so repeated ``configure()``
        calls from TempoDB construction never churn placement."""
        new = tuple(dict.fromkeys(m for m in members if m))
        if not new:
            raise ValueError("ownership members must be non-empty")
        with self._lock:
            if self_id is not None:
                self.self_id = self_id
            if new == self._members:
                self._publish_locked()
                return 0
            # lazy: modules.ring (via the modules package) must not load
            # at search-package import time
            from tempo_tpu.modules.ring import Ring

            ring = Ring(replication_factor=1)
            for m in new:
                # Ring.register seeds its token RNG from the member id,
                # so every process derives the IDENTICAL table from the
                # same member list — no coordination needed
                ring.register(m, n_tokens=_RING_TOKENS)
            idx = {m: i for i, m in enumerate(new)}
            owners: list[str] = []
            for g in range(self.n_groups):
                got = ring.get(_group_token(g), rf=1)
                owners.append(got[0])
            moved = sum(1 for old, cur in zip(self._owners, owners)
                        if old != cur)
            self._members = new
            self._owners = tuple(owners)
            self._owner_idx = tuple(idx[o] for o in owners)
            self._table = (self.n_groups, self._owners, self._owner_idx)
            self.generation += 1
            if moved:
                obs.hbm_owner_rebalance_moves.inc(moved)
            self._publish_locked()
            return moved

    def _publish_locked(self) -> None:
        obs.hbm_owner_generation.set(float(self.generation))
        obs.hbm_owner_groups.set(float(
            sum(1 for o in self._owners if o == self.self_id)))

    # ---- placement lookups (hot path: no lock, no clock) ----

    def group_of(self, block_id: str) -> int:
        """Placement group of a block id: shared jump hash over the
        shared fnv1a — deterministic on every member."""
        return jump_hash(fnv1a_64(block_id.encode()), self.n_groups)

    def owner_of(self, block_id: str) -> str | None:
        """Owning member id, or None while no membership is installed."""
        n, owners, _ = self._table
        if not owners:
            return None
        return owners[jump_hash(fnv1a_64(block_id.encode()), n)]

    def owner_index(self, block_id: str) -> int | None:
        """Owner's index in the member list — the frontend's
        member -> querier mapping (index mod pool size). None = no
        routing preference (layer off or no membership)."""
        if not self.enabled:
            return None
        n, _, idx = self._table
        if not idx:
            return None
        return idx[jump_hash(fnv1a_64(block_id.encode()), n)]

    def owns_block(self, block_id: str) -> bool:
        if not self.enabled:
            return True
        n, owners, _ = self._table
        if not owners:
            return True
        return owners[jump_hash(fnv1a_64(block_id.encode()), n)] \
            == self.self_id

    def owns_group(self, gkey: tuple) -> bool:
        """Does this member own staged batch group ``gkey`` (a tuple of
        batcher job keys ``(block_id, start_page, n_pages)``)? The
        group's ANCHOR block (first job) decides: under frontend
        owner-routing every block in a received group is owned anyway,
        and any deterministic representative keeps routing
        byte-identical — a non-owner's host route returns the same
        answer either way."""
        if not self.enabled:
            return True
        n, owners, _ = self._table
        if not owners:
            return True
        anchor = str(gkey[0][0])
        return owners[jump_hash(fnv1a_64(anchor.encode()), n)] \
            == self.self_id

    # ---- operator surface ----

    def snapshot(self) -> dict[str, object]:
        """/debug/ownership payload: the map, generation, member split."""
        with self._lock:
            owners = self._owners
            members = self._members
            gen = self.generation
            self_id = self.self_id
        counts: dict[str, int] = {}
        for o in owners:
            counts[o] = counts.get(o, 0) + 1
        return {
            "enabled": self.enabled,
            "generation": gen,
            "self": self_id,
            "members": list(members),
            "n_groups": self.n_groups,
            "owners": {str(g): o for g, o in enumerate(owners)},
            "groups_per_member": counts,
        }

    def reset(self) -> None:
        """Back to the factory state (tests)."""
        with self._lock:
            self.enabled = False
            self.self_id = "self"
            self.generation = 0
            self.n_groups = DEFAULT_PLACEMENT_GROUPS
            self._members = ()
            self._owners = ()
            self._owner_idx = ()
            self._table = (self.n_groups, (), ())
            self._publish_locked()


OWNERSHIP = OwnershipMap()


def configure(enabled: bool | None = None,
              members: str | Iterable[str] | None = None,
              self_id: str | None = None,
              groups: int | None = None) -> OwnershipMap:
    """Apply config (TempoDBConfig.search_hbm_ownership_*) to the
    process-wide map — the most recent TempoDB wins, the REGISTRY idiom.
    ``members`` accepts the comma-separated config string or an
    iterable; empty/None with the layer enabled auto-derives the fleet
    from the multihost env contract
    (parallel.multihost.ownership_members) so a mesh fleet needs zero
    extra config."""
    if groups is not None and int(groups) > 0 \
            and int(groups) != OWNERSHIP.n_groups:
        with OWNERSHIP._lock:
            OWNERSHIP.n_groups = int(groups)
            # the placement table is per group count: drop it so the
            # member install below (or the next one) re-derives. The
            # hot-path snapshot swaps as one tuple, so a concurrent
            # lookup keeps pairing the OLD count with the OLD table
            OWNERSHIP._members = ()
            OWNERSHIP._owners = ()
            OWNERSHIP._owner_idx = ()
            OWNERSHIP._table = (int(groups), (), ())
    mlist: list[str] | None
    if isinstance(members, str):
        parsed = [m.strip() for m in members.split(",") if m.strip()]
        mlist = parsed or None
    elif members is not None:
        mlist = [str(m) for m in members]
    else:
        mlist = None
    if enabled is not None:
        OWNERSHIP.enabled = bool(enabled)
    if mlist is None and OWNERSHIP.enabled and not OWNERSHIP.members:
        from tempo_tpu.parallel.multihost import ownership_members

        auto_members, auto_self = ownership_members()
        mlist = auto_members
        if self_id is None:
            self_id = auto_self
    if mlist is not None:
        OWNERSHIP.set_members(mlist, self_id=self_id)
    elif self_id:
        OWNERSHIP.self_id = self_id
    return OWNERSHIP
