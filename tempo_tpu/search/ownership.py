"""Owner-routed HBM placement: one distributed block cache across the fleet.

Every process used to stage and evict its own BlockBatch HBM cache
independently, so at production blocklist sizes the whole fleet thrashed
the same hot set — an eviction cost a ~720 MB re-stage instead of a
route change. This module gives block PLACEMENT GROUPS consistent-hash
ownership across the fleet: a block id hashes onto one of a fixed set of
placement groups (the shared Lamping-Veach jump hash in
``utils.hashing`` — the same helper ``backend/netcache.py`` selects
memcached servers with), and each group's owner is resolved on the
existing ``modules/ring.py`` consistent-hash ring — one ring
implementation for write placement, compactor job ownership AND HBM
ownership, deliberately not a third hash scheme.

Placement is PRECOMPUTED per membership generation: :meth:`set_members`
builds the full group -> owner table once, so the hot-path lookup is two
hashes plus a tuple index, placement can never drift with ring heartbeat
aging, and a membership change reports exactly which groups moved — the
rebalance is a placement DIFF, not a cache flush.

Routing contract (docs/search-hbm-ownership.md):

  - the frontend sends a block group's sub-queries to the owner first
    (retries fall back to the round-robin querier pool);
  - the owner serves the group device-resident (HBM staged + pinned),
    and cross-request coalescing fuses N tenants' dashboards over a hot
    group on that one host;
  - a NON-owner receiving the query serves it through the byte-identical
    host route (the breaker's fallback path) instead of staging a
    duplicate HBM copy;
  - owner death / a wedged owner degrade through the retry + breaker +
    host route (chaos-tested in tests/test_faults.py), never hang;
  - eviction-by-rebalance is a placement change: the old owner drops
    (or, while a search pins the batch, defers) residency and the new
    owner pre-stages (``TempoDB.rebalance_ownership``).

Heat-adaptive replication (``search_hbm_ownership_rf`` > 1): rf=1 makes
the single owner of the hour's hot placement group a tail bottleneck —
it saturates while every other chip idles, and its death forces a cold
re-stage of exactly the hottest data. With replication on, every served
group feeds a per-group EWMA heat table (:meth:`record_access`, the
decayed-counter form ``r <- r*exp(-dt/tau) + 1/tau`` that converges on
the true access rate); a group crossing
``search_hbm_ownership_hot_rate`` PROMOTES to a replica set — the first
``rf`` distinct members the ownership ring yields for its token
(``Ring.get(token, rf)``), primary first, precomputed per generation
like the owner table. A promoted group's replicas serve device-resident
too (:meth:`owns_group` answers true for them), the frontend hedges
their dispatches (:class:`HedgeTimer`), and a promotion/demotion fires
the change hook so TempoDB can pre-stage the new replica / release the
demoted residency in the background. Demotion is hysteretic (half the
promotion rate) so a group oscillating around the threshold doesn't
flap its replica residency.

Noop contract: ``search_hbm_ownership_enabled: false`` (the default)
costs ONE attribute read (``OWNERSHIP.enabled``) at every call site and
routing is byte-identical — the same contract the planner and
query-stats knobs carry, pinned by the static noop-contract checker
(analysis/contracts.py registers both the gate and the guarded calls).
Replication carries the same contract one level up: with
``search_hbm_ownership_rf`` <= 1 (the default), :meth:`record_access`,
:meth:`replica_indices` and the hedge timer are each ONE attribute read
(``replicated`` / ``armed``) — no clock read, no lock, no thread spawn
— and routing stays exactly the rf=1 behavior.
"""

from __future__ import annotations

import contextlib
import contextvars
import math
import threading
import time as _time
from typing import Iterable, Iterator

from tempo_tpu.observability import metrics as obs
from tempo_tpu.utils.hashing import fnv1a_64, jump_hash, mix64

DEFAULT_PLACEMENT_GROUPS = 64
# tokens per member on the ownership ring: enough for an even split at
# small fleets without making the table rebuild (n_groups ring walks)
# noticeable on a membership change
_RING_TOKENS = 64
# per-group access-rate EWMA time constant: the decayed-counter update
# converges on the true rate (in 1/s) within a few tau for any access
# pattern, so "accesses per second" is what hot_rate compares against
_HEAT_TAU_S = 30.0
# demotion hysteresis: a promoted group demotes only after its rate
# decays below this fraction of the promotion threshold — a group
# oscillating around hot_rate must not flap replica residency (every
# flap is a replica drop + a future cold re-stage)
_DEMOTE_FRACTION = 0.5
# hedge-delay derivation: before _HEDGE_MIN_SAMPLES direct dispatch
# observations, fall back to the profiler-stage seed, then the default
_HEDGE_MIN_SAMPLES = 8
_HEDGE_DEFAULT_S = 0.05
_HEDGE_FLOOR_S = 0.002

# context-scoped member-identity override (see self_as): None = use
# OWNERSHIP.self_id, the production single-identity path
_SELF_OVERRIDE: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "tempo_ownership_self", default=None)


def _group_token(group: int) -> int:
    """Ring token (32-bit, the ring's token space) for a placement
    group id — mix64-finalized so consecutive group ids spread across
    the whole token space instead of clustering in one ring segment."""
    return mix64(fnv1a_64(b"hbm-group-%d" % group)) & 0xFFFFFFFF


@contextlib.contextmanager
def self_as(member_id: str) -> Iterator[None]:
    """Serve the body AS another fleet member (tests/bench): a single
    process simulating several hosts must answer hedged dispatches
    CONCURRENTLY under different identities, and mutating
    ``OWNERSHIP.self_id`` would race one attempt's routing against
    another's. The contextvar scopes the identity to this thread's
    context instead; production deployments never set it."""
    token = _SELF_OVERRIDE.set(str(member_id))
    try:
        yield
    finally:
        _SELF_OVERRIDE.reset(token)


class HedgeTimer:
    """The hedge delay for replicated dispatch: how long the frontend
    waits on a promoted group's primary before firing the same batch at
    the next replica.

    ``search_hedge_delay_ms`` > 0 pins it; the default (0 = auto)
    derives a p99-ish bound from a Jacobson/Karels EWMA over completed
    dispatch walls (``mean + 3*dev`` — the TCP RTO estimator, cheap and
    robust without a histogram). Until enough direct observations
    exist, the dispatch profiler's stage EWMAs seed the estimate
    (``execute``/``d2h`` stage listener — what a healthy primary answer
    costs), then the default. Noop contract: disarmed (rf <= 1) is ONE
    attribute read — no clock, no lock, no thread."""

    def __init__(self) -> None:
        self.armed = False
        self.fixed_ms = 0.0
        self._lock = threading.Lock()
        self._mean = 0.0
        self._dev = 0.0
        self._n = 0
        self._seed_mean = 0.0
        self._seed_n = 0
        self._listening = False

    def configure(self, armed: bool, fixed_ms: float | None = None) -> None:
        if fixed_ms is not None:
            self.fixed_ms = max(0.0, float(fixed_ms))
        self.armed = bool(armed)
        if self.armed and not self._listening:
            # profiler-stage seed: registered once per process, and the
            # listener itself is gated on `armed` so a later disarm
            # costs one attribute read per stage observation
            from tempo_tpu.observability.profile import PROFILER

            PROFILER.add_stage_listener(self._on_stage)
            self._listening = True

    def _on_stage(self, stage: str, mode: str, seconds: float,
                  nbytes: int) -> None:
        if not self.armed:
            return
        if stage not in ("execute", "d2h"):
            return
        with self._lock:
            if self._seed_n == 0:
                self._seed_mean = seconds
            else:
                self._seed_mean += 0.125 * (seconds - self._seed_mean)
            self._seed_n += 1

    def observe(self, seconds: float) -> None:
        """Fold one completed (un-hedged or winning) dispatch wall into
        the delay estimate."""
        if not self.armed:
            return
        with self._lock:
            if self._n == 0:
                self._mean = seconds
                self._dev = seconds / 2.0
            else:
                err = seconds - self._mean
                self._mean += 0.125 * err
                self._dev += 0.25 * (abs(err) - self._dev)
            self._n += 1

    def delay_s(self) -> float:
        """Current hedge delay in seconds."""
        if not self.armed:
            return _HEDGE_DEFAULT_S
        if self.fixed_ms > 0:
            return self.fixed_ms / 1000.0
        with self._lock:
            if self._n >= _HEDGE_MIN_SAMPLES:
                return max(_HEDGE_FLOOR_S, self._mean + 3.0 * self._dev)
            if self._seed_n:
                return max(_HEDGE_FLOOR_S, 3.0 * self._seed_mean)
            return _HEDGE_DEFAULT_S

    def snapshot(self) -> dict:
        delay = self.delay_s()
        with self._lock:
            return {
                "armed": self.armed,
                "fixed_ms": self.fixed_ms,
                "delay_ms": round(delay * 1e3, 3),
                "observed": self._n,
                "mean_ms": round(self._mean * 1e3, 3),
                "dev_ms": round(self._dev * 1e3, 3),
            }

    def reset(self) -> None:
        with self._lock:
            self.armed = False
            self.fixed_ms = 0.0
            self._mean = 0.0
            self._dev = 0.0
            self._n = 0
            self._seed_mean = 0.0
            self._seed_n = 0


class OwnershipMap:
    """Process-wide block-group -> owner placement map.

    Lookup methods read two immutable tuples swapped atomically under
    ``_lock`` by :meth:`set_members` — the hot path takes no lock. All
    lookups answer "this member owns it" while the layer is DISABLED or
    no membership is installed: single-process deployments behave
    exactly as before the layer existed.

    Replication state is split the same way: the per-generation replica
    table (group -> first-rf ring members) is immutable and swapped
    with the owner table; the PROMOTED set is a frozenset swapped under
    ``_heat_lock`` — a hot-path replica lookup is one attribute read
    plus a set membership test, never a lock."""

    def __init__(self, n_groups: int = DEFAULT_PLACEMENT_GROUPS) -> None:
        self.enabled = False
        self.self_id = "self"
        self.generation = 0
        self.n_groups = int(n_groups)
        self._lock = threading.Lock()
        self._members: tuple[str, ...] = ()
        self._owners: tuple[str, ...] = ()      # group id -> member id
        self._owner_idx: tuple[int, ...] = ()   # group id -> member index
        # the hot-path snapshot: (n_groups, owners, owner_idx) swapped
        # as ONE tuple so a lookup never pairs a fresh group count with
        # a stale table (configure() can resize n_groups while another
        # thread is mid-lookup — indexing a 64-entry table with a
        # 128-group hash would IndexError a live query)
        self._table: tuple[int, tuple[str, ...], tuple[int, ...]] = \
            (self.n_groups, (), ())
        # ---- heat-adaptive replication (rf > 1) ----
        self.rf = 1
        self.hot_rate = 0.0
        # the replication gate: ONE attribute read decides the whole
        # heat/replica/hedge layer (recomputed by configure())
        self.replicated = False
        self._replica_depth = 0
        self._replicas: tuple[tuple[str, ...], ...] = ()
        self._replica_idx: tuple[tuple[int, ...], ...] = ()
        self._heat_lock = threading.Lock()
        self._heat: dict[int, list] = {}       # group -> [rate, last_t]
        self._promoted: frozenset = frozenset()
        self._events: dict[int, dict] = {}     # group -> change stamps
        self._change_hook = None

    # ---- membership (the rebalance surface) ----

    @property
    def members(self) -> tuple[str, ...]:
        return self._members

    def set_members(self, members: Iterable[str],
                    self_id: str | None = None) -> int:
        """Install a fleet membership and precompute the placement table
        (owners AND the first-rf replica sets — one ring walk yields
        both); returns how many placement groups MOVED owner (0 on the
        first install — nothing was placed before). Idempotent for an
        unchanged member set at an unchanged replica depth (no
        generation bump), so repeated ``configure()`` calls from
        TempoDB construction never churn placement."""
        new = tuple(dict.fromkeys(m for m in members if m))
        if not new:
            raise ValueError("ownership members must be non-empty")
        depth = max(1, min(int(self.rf), len(new)))
        with self._lock:
            if self_id is not None:
                self.self_id = self_id
            if new == self._members and depth == self._replica_depth:
                self._publish_locked()
                return 0
            # lazy: modules.ring (via the modules package) must not load
            # at search-package import time
            from tempo_tpu.modules.ring import Ring

            ring = Ring(replication_factor=1)
            for m in new:
                # Ring.register seeds its token RNG from the member id,
                # so every process derives the IDENTICAL table from the
                # same member list — no coordination needed
                ring.register(m, n_tokens=_RING_TOKENS)
            idx = {m: i for i, m in enumerate(new)}
            owners: list[str] = []
            replicas: list[tuple[str, ...]] = []
            for g in range(self.n_groups):
                got = ring.get(_group_token(g), rf=depth)
                owners.append(got[0])
                replicas.append(tuple(got))
            moved = sum(1 for old, cur in zip(self._owners, owners)
                        if old != cur)
            self._members = new
            self._owners = tuple(owners)
            self._owner_idx = tuple(idx[o] for o in owners)
            self._replica_depth = depth
            self._replicas = tuple(replicas)
            self._replica_idx = tuple(
                tuple(idx[m] for m in reps) for reps in replicas)
            self._table = (self.n_groups, self._owners, self._owner_idx)
            self.generation += 1
            if moved:
                obs.hbm_owner_rebalance_moves.inc(moved)
            self._publish_locked()
            return moved

    def _publish_locked(self) -> None:
        obs.hbm_owner_generation.set(float(self.generation))
        obs.hbm_owner_groups.set(float(
            sum(1 for o in self._owners if o == self.self_id)))

    def set_change_hook(self, hook) -> None:
        """Register the promotion/demotion callback — called as
        ``hook(group, "up"|"down", replica_member_ids)`` on a
        short-lived background thread (never a serving thread: the hook
        pre-stages or releases HBM residency). Most recent TempoDB
        wins, the same REGISTRY idiom :func:`configure` follows."""
        self._change_hook = hook

    # ---- placement lookups (hot path: no lock, no clock) ----

    def _effective_self(self) -> str:
        ov = _SELF_OVERRIDE.get()
        return ov if ov is not None else self.self_id

    def group_of(self, block_id: str) -> int:
        """Placement group of a block id: shared jump hash over the
        shared fnv1a — deterministic on every member."""
        return jump_hash(fnv1a_64(block_id.encode()), self.n_groups)

    def owner_of(self, block_id: str) -> str | None:
        """Owning member id, or None while no membership is installed."""
        n, owners, _ = self._table
        if not owners:
            return None
        return owners[jump_hash(fnv1a_64(block_id.encode()), n)]

    def owner_index(self, block_id: str) -> int | None:
        """Owner's index in the member list — the frontend's
        member -> querier mapping (index mod pool size). None = no
        routing preference (layer off or no membership)."""
        if not self.enabled:
            return None
        n, _, idx = self._table
        if not idx:
            return None
        return idx[jump_hash(fnv1a_64(block_id.encode()), n)]

    def _is_replica_here(self, g: int, me: str) -> bool:
        """Promoted-replica membership for group ``g`` — the replica
        table is per-generation immutable, the promoted set a swapped
        frozenset: no lock on this path."""
        if not (self.replicated and g in self._promoted):
            return False
        reps = self._replicas
        return g < len(reps) and me in reps[g]

    def owns_block(self, block_id: str) -> bool:
        if not self.enabled:
            return True
        n, owners, _ = self._table
        if not owners:
            return True
        g = jump_hash(fnv1a_64(block_id.encode()), n)
        me = self._effective_self()
        if owners[g] == me:
            return True
        return self._is_replica_here(g, me)

    def owns_group(self, gkey: tuple) -> bool:
        """Does this member own staged batch group ``gkey`` (a tuple of
        batcher job keys ``(block_id, start_page, n_pages)``)? The
        group's ANCHOR block (first job) decides: under frontend
        owner-routing every block in a received group is owned anyway,
        and any deterministic representative keeps routing
        byte-identical — a non-owner's host route returns the same
        answer either way. A heat-promoted group's REPLICAS own it too:
        a replica stages and serves device-resident, which is what
        makes the hedged dispatch it receives fast."""
        if not self.enabled:
            return True
        n, owners, _ = self._table
        if not owners:
            return True
        anchor = str(gkey[0][0])
        g = jump_hash(fnv1a_64(anchor.encode()), n)
        me = self._effective_self()
        if owners[g] == me:
            return True
        return self._is_replica_here(g, me)

    def replica_indices(self, block_id: str) -> tuple[int, ...]:
        """Member indices of the block's replica set, PRIMARY FIRST —
        the frontend's hedge targets. Empty unless the block's group is
        heat-promoted: an un-promoted group has exactly its owner, and
        the frontend's plain owner routing already covers that."""
        if not self.replicated:
            return ()
        promoted = self._promoted
        if not promoted:
            return ()
        n, _, _ = self._table
        g = jump_hash(fnv1a_64(block_id.encode()), n)
        if g not in promoted or g >= len(self._replica_idx):
            return ()
        return self._replica_idx[g]

    def replicas_of(self, block_id: str) -> tuple[str, ...]:
        """Replica member ids (primary first) for a heat-promoted
        block's group; empty when not promoted."""
        if not self.replicated:
            return ()
        promoted = self._promoted
        if not promoted:
            return ()
        n, _, _ = self._table
        g = jump_hash(fnv1a_64(block_id.encode()), n)
        if g not in promoted or g >= len(self._replicas):
            return ()
        return self._replicas[g]

    def is_replica(self, block_id: str) -> bool:
        """Does this member hold ``block_id``'s group through the
        heat-promoted replica set (owner included)? Operator surface
        for the residency rows."""
        if not self.enabled:
            return False
        n, _, _ = self._table
        g = jump_hash(fnv1a_64(block_id.encode()), n)
        return self._is_replica_here(g, self._effective_self())

    # ---- heat table (replication gate: one attribute read when off) ----

    def record_access(self, block_id: str) -> None:
        """Feed the per-group EWMA heat table — one call per served
        group scan (the batcher's dispatch site, which observes every
        scan the process serves). Crossing ``hot_rate`` promotes the
        group to its precomputed replica set; decaying below the
        hysteresis floor demotes it. Promotion/demotion fires the
        change hook on a background thread — this method runs on the
        serving hot path and must not stage or evict anything itself."""
        if not self.replicated:
            return
        n, _, _ = self._table
        g = jump_hash(fnv1a_64(block_id.encode()), n)
        now = _time.monotonic()
        fire = None
        with self._heat_lock:
            ent = self._heat.get(g)
            if ent is None:
                ent = self._heat[g] = [0.0, now]
            dt = max(0.0, now - ent[1])
            rate = ent[0] * math.exp(-dt / _HEAT_TAU_S) + 1.0 / _HEAT_TAU_S
            ent[0] = rate
            ent[1] = now
            if g not in self._promoted:
                if rate >= self.hot_rate:
                    fire = self._promote_locked(g)
            elif rate < self.hot_rate * _DEMOTE_FRACTION:
                fire = self._demote_locked(g)
        if fire is not None:
            self._fire_change(*fire)

    def _promote_locked(self, g: int) -> tuple:
        self._promoted = self._promoted | {g}
        self._events.setdefault(g, {})["promoted_t"] = _time.time()
        obs.hbm_replica_promotions.inc(dir="up")
        reps = self._replicas[g] if g < len(self._replicas) else ()
        return (g, "up", reps)

    def _demote_locked(self, g: int) -> tuple:
        self._promoted = self._promoted - {g}
        self._events.setdefault(g, {})["demoted_t"] = _time.time()
        obs.hbm_replica_promotions.inc(dir="down")
        reps = self._replicas[g] if g < len(self._replicas) else ()
        return (g, "down", reps)

    def _fire_change(self, g: int, direction: str, replicas: tuple) -> None:
        hook = self._change_hook
        if hook is None:
            return
        # background thread: the hook pre-stages (promotion) or sweeps
        # residency (demotion) — seconds of H2D/eviction work that must
        # never ride the serving thread that tipped the rate over
        threading.Thread(target=hook, args=(g, direction, replicas),
                         name="ownership-heat", daemon=True).start()

    def sweep(self, now: float | None = None) -> int:
        """Demote promoted groups whose rate has DECAYED below the
        hysteresis floor. Promotion is access-driven, so a group whose
        traffic vanishes entirely can only demote here — called from
        :meth:`snapshot` and the batcher's rebalance walk, which is
        what makes rebalance load-aware: stale replicas demote first,
        then drop through the ordinary owns_group residency walk.
        Returns the number of demotions (hooks fire per demotion)."""
        if not self.replicated:
            return 0
        if now is None:
            now = _time.monotonic()
        fires = []
        with self._heat_lock:
            for g in list(self._promoted):
                ent = self._heat.get(g)
                rate = 0.0
                if ent is not None:
                    dt = max(0.0, now - ent[1])
                    rate = ent[0] * math.exp(-dt / _HEAT_TAU_S)
                    ent[0] = rate
                    ent[1] = now
                if rate < self.hot_rate * _DEMOTE_FRACTION:
                    fires.append(self._demote_locked(g))
        for f in fires:
            self._fire_change(*f)
        return len(fires)

    # ---- operator surface ----

    def snapshot(self) -> dict[str, object]:
        """/debug/ownership payload: the map, generation, member split,
        and the per-group heat table (rate, rf, replica set, last
        promotion/demotion stamps)."""
        if self.replicated:
            self.sweep()
        with self._lock:
            owners = self._owners
            members = self._members
            gen = self.generation
            self_id = self.self_id
        counts: dict[str, int] = {}
        for o in owners:
            counts[o] = counts.get(o, 0) + 1
        out: dict[str, object] = {
            "enabled": self.enabled,
            "generation": gen,
            "self": self_id,
            "members": list(members),
            "n_groups": self.n_groups,
            "owners": {str(g): o for g, o in enumerate(owners)},
            "groups_per_member": counts,
            "rf": self.rf,
            "hot_rate": self.hot_rate,
            "replicated": self.replicated,
        }
        heat: dict[str, dict] = {}
        now = _time.monotonic()
        with self._heat_lock:
            promoted = self._promoted
            for g, ent in self._heat.items():
                rate = ent[0] * math.exp(
                    -max(0.0, now - ent[1]) / _HEAT_TAU_S)
                up = g in promoted and g < len(self._replicas)
                row: dict[str, object] = {
                    "rate": round(rate, 4),
                    "promoted": g in promoted,
                    "rf": len(self._replicas[g]) if up else 1,
                    "replicas": list(self._replicas[g]) if up else [],
                }
                for k, v in self._events.get(g, {}).items():
                    row[k] = round(v, 3)
                heat[str(g)] = row
        out["heat"] = heat
        out["hedge"] = HEDGE.snapshot()
        return out

    def reset(self) -> None:
        """Back to the factory state (tests)."""
        with self._lock:
            self.enabled = False
            self.self_id = "self"
            self.generation = 0
            self.n_groups = DEFAULT_PLACEMENT_GROUPS
            self._members = ()
            self._owners = ()
            self._owner_idx = ()
            self._table = (self.n_groups, (), ())
            self.rf = 1
            self.hot_rate = 0.0
            self.replicated = False
            self._replica_depth = 0
            self._replicas = ()
            self._replica_idx = ()
            self._change_hook = None
            self._publish_locked()
        with self._heat_lock:
            self._heat = {}
            self._promoted = frozenset()
            self._events = {}
        HEDGE.reset()


OWNERSHIP = OwnershipMap()
HEDGE = HedgeTimer()


def configure(enabled: bool | None = None,
              members: str | Iterable[str] | None = None,
              self_id: str | None = None,
              groups: int | None = None,
              rf: int | None = None,
              hot_rate: float | None = None,
              hedge_delay_ms: float | None = None) -> OwnershipMap:
    """Apply config (TempoDBConfig.search_hbm_ownership_* and
    search_hedge_delay_ms) to the process-wide map — the most recent
    TempoDB wins, the REGISTRY idiom. ``members`` accepts the
    comma-separated config string or an iterable; empty/None with the
    layer enabled auto-derives the fleet from the multihost env
    contract (parallel.multihost.ownership_members) so a mesh fleet
    needs zero extra config. ``rf`` > 1 (with a positive ``hot_rate``)
    arms heat-adaptive replication and the hedge timer; the defaults
    keep today's rf=1 behavior bit for bit."""
    if groups is not None and int(groups) > 0 \
            and int(groups) != OWNERSHIP.n_groups:
        with OWNERSHIP._lock:
            OWNERSHIP.n_groups = int(groups)
            # the placement table is per group count: drop it so the
            # member install below (or the next one) re-derives. The
            # hot-path snapshot swaps as one tuple, so a concurrent
            # lookup keeps pairing the OLD count with the OLD table
            OWNERSHIP._members = ()
            OWNERSHIP._owners = ()
            OWNERSHIP._owner_idx = ()
            OWNERSHIP._replica_depth = 0
            OWNERSHIP._replicas = ()
            OWNERSHIP._replica_idx = ()
            OWNERSHIP._table = (int(groups), (), ())
        with OWNERSHIP._heat_lock:
            # group ids re-hash on a resize: the old heat rates and
            # promotions describe groups that no longer exist
            OWNERSHIP._heat = {}
            OWNERSHIP._promoted = frozenset()
            OWNERSHIP._events = {}
    if rf is not None:
        OWNERSHIP.rf = max(1, int(rf))
    if hot_rate is not None:
        OWNERSHIP.hot_rate = max(0.0, float(hot_rate))
    mlist: list[str] | None
    if isinstance(members, str):
        parsed = [m.strip() for m in members.split(",") if m.strip()]
        mlist = parsed or None
    elif members is not None:
        mlist = [str(m) for m in members]
    else:
        mlist = None
    if enabled is not None:
        OWNERSHIP.enabled = bool(enabled)
    if mlist is None and OWNERSHIP.enabled and not OWNERSHIP.members:
        from tempo_tpu.parallel.multihost import ownership_members

        auto_members, auto_self = ownership_members()
        mlist = auto_members
        if self_id is None:
            self_id = auto_self
    if mlist is not None:
        OWNERSHIP.set_members(mlist, self_id=self_id)
    elif self_id:
        OWNERSHIP.self_id = self_id
    # the replication gate is ONE precomputed attribute: enabled, rf>1
    # and a positive promotion threshold — everything the heat/hedge
    # layer tests on its hot paths
    OWNERSHIP.replicated = bool(
        OWNERSHIP.enabled and OWNERSHIP.rf > 1 and OWNERSHIP.hot_rate > 0)
    if OWNERSHIP.members:
        depth = max(1, min(OWNERSHIP.rf, len(OWNERSHIP.members)))
        if depth != OWNERSHIP._replica_depth:
            # rf changed after the members installed: rebuild the
            # replica table at the new depth (generation bumps — the
            # frontend's batch plans re-key, routing potential changed)
            OWNERSHIP.set_members(OWNERSHIP.members)
    HEDGE.configure(armed=OWNERSHIP.replicated, fixed_ms=hedge_delay_ms)
    return OWNERSHIP
