"""Device-resident dictionary probe: the substring prefilter on chip.

The host-side dictionary probe (pipeline.substring_value_ids — numpy
char.find, or the native memmem walk) sits serially in front of every
fresh (block, tag-set) dispatch; at BASELINE high cardinality it is the
dominant cost (312 ms at 10M distinct values, BENCH_r05) while the device
scan itself is single-digit ms. This module moves the probe to where the
columns already live — the near-data-processing move of "Near Data
Processing in Taurus Database" / the predicate-offload pattern of
"GPU-Augmented OLAP Execution Engine" (PAPERS.md): evaluate the filter
on device and stop shipping intermediate id-sets across the host
boundary.

Layout (staged once per block, cached with the batch):

  buf  u8  [S, N]    packed utf-8 dictionary bytes, value-contiguous,
                     zero-padded; S = probe shards (mesh size, else 1)
  pos  i32 [S, N]    position→value-id map: shard-LOCAL value id owning
                     each byte, -1 on padding
  off  i32 [S, V+1]  per-value byte offsets into the shard's buffer
                     (pad values collapse to empty ranges)

The kernel is gather-free on the match side: a needle of length L is a
rolling-window equality, unrolled over needle chars as L shifted compares
of the whole buffer (`buf[j:j+N] == needle[j]`) ANDed together — pure
vector compares at full VPU width. A window must not span a value
boundary, which the same unroll enforces through the position map
(`pos[i+j] == pos[i]`). The per-byte match vector segment-reduces into a
per-value hit mask via cumsum + offset differencing (`hits[v] =
cumsum(match)[off[v+1]] - cumsum(match)[off[v]] > 0`) — a deterministic
segment reduction with one [V]-sized gather over a monotone index,
instead of an [N]-sized scatter (scatters serialize on the VPU,
columnar.py's layout lesson).

Mesh sharding splits the dictionary along the VALUE axis: each device
probes its contiguous value range and the per-shard hit masks all_gather
into the replicated global mask — the same collective shape
parallel/dist_search.py uses for scan results.

The probe output (a [T, V] bool mask) feeds the scan kernel directly on
device: engine.entry_match_mask / multiblock.multi_entry_mask test value
membership with a mask lookup instead of the host-compiled [T,R,2] range
compares, so no id-set ever crosses the host boundary. (bench.py's
high-cardinality phase re-validates the mask-lookup-vs-range-compare
tradeoff rather than assuming the old gather-serialization measurement.)
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from tempo_tpu.observability import profile

# Dictionaries below this many distinct values keep the exact host path
# (numpy / native memmem): the probe there is microseconds-to-low-ms and
# staging dictionary bytes to HBM would cost more than it saves. Mirrors
# pipeline.NATIVE_SCAN_THRESHOLD, which hands the HOST scan to the native
# memmem walk at the same scale. Plumbed as TempoDBConfig
# `search_device_probe_min_vals`; <= 0 disables device probing.
DEVICE_PROBE_MIN_VALS = 50_000

# Needles longer than this fall back to the host scan for the whole
# query: the kernel unrolls one shifted compare per needle byte, so the
# unroll factor is bounded to keep compiles small. Tag needles are
# short in practice (service names, ids); 64 bytes covers them.
MAX_NEEDLE_BYTES = 64


def _pow2(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


@dataclass
class PackedDeviceDict:
    """Host-side staging product for one distinct value dictionary."""
    n_vals: int            # real value count
    n_shards: int          # S — probe shards (mesh size at stage time)
    v_shard: int           # padded values per shard; v_pad = S * v_shard
    buf: np.ndarray        # uint8 [S, N]
    pos: np.ndarray        # int32 [S, N] local value id per byte, -1 pad
    off: np.ndarray        # int32 [S, v_shard + 1]
    n_real: np.ndarray     # int32 [S] real values in each shard
    fingerprint: bytes     # pipeline._dict_fingerprint of the source dict

    @property
    def v_pad(self) -> int:
        return self.n_shards * self.v_shard

    @property
    def nbytes(self) -> int:
        return int(self.buf.nbytes + self.pos.nbytes + self.off.nbytes
                   + self.n_real.nbytes)

    @property
    def real_bytes(self) -> int:
        """Unpadded dictionary byte length (the host scan's work unit;
        the offload planner's host-cost input). Derived from the shard
        offsets — no dictionary walk."""
        hit = getattr(self, "_real_bytes", None)
        if hit is None:
            S = self.n_shards
            hit = int(self.off[np.arange(S), self.n_real].sum())
            self._real_bytes = hit
        return hit


@dataclass
class DeviceDict:
    """A PackedDeviceDict's arrays resident on device(s)."""
    packed: PackedDeviceDict
    device: dict           # name -> jnp array (buf/pos/off/n_real)
    mesh: object = None    # the mesh the arrays were placed for (or None)

    @property
    def v_pad(self) -> int:
        return self.packed.v_pad

    @property
    def n_vals(self) -> int:
        return self.packed.n_vals

    @property
    def nbytes(self) -> int:
        return int(sum(int(a.nbytes) for a in self.device.values()))


def pack_device_dict(val_dict: list, n_shards: int = 1,
                     fingerprint: bytes = b"") -> PackedDeviceDict:
    """Pack a sorted value dictionary for the device probe, split into
    `n_shards` contiguous value ranges (the mesh's value-axis split; 1
    when unsharded). Byte and value axes pad to power-of-two buckets so
    the probe kernel compiles once per (size-bucket, needle-bucket)."""
    import time as _time

    t_pack0 = _time.perf_counter()
    n_vals = len(val_dict)
    S = max(1, int(n_shards))
    v_shard = _pow2(max(1, -(-n_vals // S)))
    blobs = [v.encode("utf-8") for v in val_dict]
    lens = np.fromiter((len(b) for b in blobs), dtype=np.int64,
                       count=n_vals)
    shard_bytes = []
    for s in range(S):
        lo, hi = s * v_shard, min((s + 1) * v_shard, n_vals)
        shard_bytes.append(int(lens[lo:hi].sum()) if lo < hi else 0)
    N = _pow2(max(1, max(shard_bytes)))
    if max(shard_bytes) >= 2**31:
        raise ValueError("dictionary shard exceeds int32 byte addressing")
    buf = np.zeros((S, N), dtype=np.uint8)
    pos = np.full((S, N), -1, dtype=np.int32)
    off = np.zeros((S, v_shard + 1), dtype=np.int32)
    n_real = np.zeros(S, dtype=np.int32)
    for s in range(S):
        lo, hi = s * v_shard, min((s + 1) * v_shard, n_vals)
        if lo >= hi:
            continue
        n_real[s] = hi - lo
        ln = lens[lo:hi]
        ends = np.cumsum(ln)
        nb = int(ends[-1])
        off[s, 1:hi - lo + 1] = ends
        off[s, hi - lo + 1:] = nb  # pad values: empty [nb, nb) ranges
        if nb:
            blob = b"".join(blobs[lo:hi])
            buf[s, :nb] = np.frombuffer(blob, dtype=np.uint8)
            pos[s, :nb] = np.repeat(
                np.arange(hi - lo, dtype=np.int32), ln)
    out = PackedDeviceDict(n_vals=n_vals, n_shards=S, v_shard=v_shard,
                           buf=buf, pos=pos, off=off, n_real=n_real,
                           fingerprint=fingerprint)
    from . import planner

    # pack cost is part of a non-resident device decision: feed the
    # planner's rate (noop when the planner is disabled)
    planner.PLANNER.observe("pack", _time.perf_counter() - t_pack0,
                            nbytes=out.real_bytes)
    return out


def place_device_dict(packed: PackedDeviceDict, mesh=None,
                      sharding=None) -> DeviceDict:
    """H2D for a packed dictionary. With a mesh the shard axis (axis 0)
    splits across devices; `sharding` overrides (multi-host staging uses
    make_array_from_callback upstream)."""
    import time

    t0 = time.perf_counter()
    host = {"buf": packed.buf, "pos": packed.pos, "off": packed.off,
            "n_real": packed.n_real}
    if sharding is not None:
        dev = {k: jax.device_put(v, sharding) for k, v in host.items()}
    elif mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        from tempo_tpu.parallel.mesh import SCAN_AXIS

        spec = NamedSharding(mesh, P(SCAN_AXIS))
        if jax.process_count() > 1:
            dev = {
                k: jax.make_array_from_callback(
                    v.shape, spec, lambda idx, v=v: v[idx])
                for k, v in host.items()
            }
        else:
            dev = {k: jax.device_put(v, spec) for k, v in host.items()}
    else:
        dev = {k: jnp.asarray(v) for k, v in host.items()}
    profile.observe_stage("h2d", "dict_probe", time.perf_counter() - t0,
                          nbytes=packed.nbytes)
    return DeviceDict(packed=packed, device=dev, mesh=mesh)


def stage_val_dict(val_dict: list, n_shards: int = 1, mesh=None,
                   fingerprint: bytes = b"",
                   cache_on=None) -> DeviceDict:
    """pack + place, memoizing the HOST packing on `cache_on` (the
    immutable ColumnarPages) so an HBM-evicted batch re-uploads with one
    H2D copy, not a re-pack of 10M strings."""
    packed = None
    if cache_on is not None:
        hit = getattr(cache_on, "_device_dict_packed", None)
        if hit is not None and hit.n_shards == max(1, int(n_shards)):
            packed = hit
    if packed is None:
        packed = pack_device_dict(val_dict, n_shards=n_shards,
                                  fingerprint=fingerprint)
        if cache_on is not None:
            cache_on._device_dict_packed = packed
    return place_device_dict(packed, mesh=mesh)


# ---------------------------------------------------------------------------
# kernels


def _probe_core(buf, pos, off, n_real, needles, lens, empties,
                *, n_needle_max: int):
    """hits bool [T, V] over ONE shard's byte buffer.

    buf u8 [N], pos i32 [N] (local value id, -1 pad), off i32 [V+1],
    n_real i32 scalar, needles u8 [T, Lp], lens i32 [T], empties bool [T].
    """
    N = buf.shape[0]
    V = off.shape[0] - 1
    # window reads run to i + L - 1: extend with bytes that can never
    # match (pos sentinel -2 differs from both real ids and -1 padding)
    buf_ext = jnp.concatenate(
        [buf, jnp.zeros((n_needle_max,), dtype=buf.dtype)])
    pos_ext = jnp.concatenate(
        [pos, jnp.full((n_needle_max,), -2, dtype=pos.dtype)])

    def one_term(needle, ln, empty):
        acc = pos >= 0  # windows must start on a real dictionary byte
        for j in range(n_needle_max):  # static unroll: shifted compares
            active = jnp.int32(j) < ln
            ok = ((buf_ext[j:j + N] == needle[j])
                  & (pos_ext[j:j + N] == pos))  # same-value boundary check
            acc = acc & (ok | ~active)
        # segment-reduce match positions into per-value hits: cumsum +
        # offset differencing (one monotone [V] gather, no scatter)
        c = jnp.concatenate([
            jnp.zeros((1,), jnp.int32),
            jnp.cumsum(acc.astype(jnp.int32)),
        ])
        hits = (c[off[1:]] - c[off[:-1]]) > 0
        # empty needle: every real value matches (host semantics —
        # including zero-length values, which own no byte positions)
        hits = jnp.where(empty, jnp.arange(V, dtype=jnp.int32) < n_real,
                         hits)
        return hits

    return jax.vmap(one_term)(needles, lens, empties)


@functools.partial(jax.jit, static_argnames=("n_needle_max",))
def probe_kernel(buf, pos, off, n_real, needles, lens, empties,
                 *, n_needle_max: int):
    """Single-device probe over [S, ...] staged arrays — EVERY shard is
    probed (vmapped) and reassembled in shard order, so a dictionary
    packed for an S-way mesh but placed unsharded (place_batch's
    mismatch fallback) still yields the full [T, v_pad] mask, just
    without the parallelism. Returns (hits bool [T, v_pad],
    any_hits bool [T])."""
    local = jax.vmap(
        lambda b, p, o, nr: _probe_core(b, p, o, nr, needles, lens,
                                        empties,
                                        n_needle_max=n_needle_max)
    )(buf, pos, off, n_real)                           # [S, T, v_shard]
    hits = jnp.swapaxes(local, 0, 1).reshape(needles.shape[0], -1)
    return hits, hits.any(axis=1)


@functools.partial(jax.jit, static_argnames=("mesh", "n_needle_max"))
def dist_probe_kernel(mesh, buf, pos, off, n_real, needles, lens, empties,
                      *, n_needle_max: int):
    """Mesh probe: the dictionary's value axis is split across shards
    (axis 0 of the staged arrays); every device probes its value range
    and the local masks all_gather into the replicated global [T, v_pad]
    mask — same collective shape as dist_search's result funnel."""
    from jax.sharding import PartitionSpec as P
    from tempo_tpu.parallel.mesh import SCAN_AXIS, shard_map_compat

    def shard_fn(buf, pos, off, n_real, needles, lens, empties):
        local = _probe_core(buf[0], pos[0], off[0], n_real[0],
                            needles, lens, empties,
                            n_needle_max=n_needle_max)     # [T, v_shard]
        all_h = jax.lax.all_gather(local, SCAN_AXIS)       # [S, T, vs]
        hits = jnp.swapaxes(all_h, 0, 1).reshape(local.shape[0], -1)
        return hits, hits.any(axis=1)

    return shard_map_compat(
        shard_fn, mesh=mesh,
        in_specs=(P(SCAN_AXIS),) * 4 + (P(),) * 3,
        out_specs=(P(), P()),
        # all_gather output is identical on every shard; the replication
        # checker can't infer it through the gather (same stance as
        # dist_search)
        check=False,
    )(buf, pos, off, n_real, needles, lens, empties)


def probe_value_hits(ddev: DeviceDict, needles: list[bytes]):
    """Run the device probe for a list of utf-8 needles against a staged
    dictionary. Returns (hits [T, v_pad] bool, any_hits [T] bool) DEVICE
    arrays — nothing synchronizes to host here; callers fetch any_hits
    (a few bytes) only when they need prune decisions.

    Raises ValueError for needles longer than MAX_NEEDLE_BYTES — callers
    fall back to the exact host scan for that query."""
    T = len(needles)
    if T == 0:
        raise ValueError("probe_value_hits needs at least one needle")
    lmax = max(len(n) for n in needles)
    if lmax > MAX_NEEDLE_BYTES:
        raise ValueError(f"needle exceeds {MAX_NEEDLE_BYTES} bytes")
    with profile.dispatch("dict_probe") as rec:
        with rec.stage("build"):
            Lp = _pow2(max(1, lmax))
            arr = np.zeros((T, Lp), dtype=np.uint8)
            lens = np.zeros(T, dtype=np.int32)
            empties = np.zeros(T, dtype=bool)
            for t, nb in enumerate(needles):
                arr[t, :len(nb)] = np.frombuffer(nb, dtype=np.uint8)
                lens[t] = len(nb)
                empties[t] = len(nb) == 0
        d = ddev.device
        rec.add_bytes(h2d=arr.nbytes + lens.nbytes + empties.nbytes)
        miss = rec.compile_check(
            ("probe", ddev.mesh is not None, d["buf"].shape,
             d["off"].shape, T, Lp))
        stage = "compile" if miss else "execute"
        # probe_bytes/fp: the offload planner's device-rate feed — it
        # listens on finished dispatch records (mode=dict_probe) and
        # needs the work size (terms × staged bytes) plus the dictionary
        # identity to resolve predicted-vs-actual error
        rec.set(n_vals=ddev.n_vals, n_terms=T,
                probe_bytes=T * ddev.nbytes,
                fp=(ddev.packed.fingerprint.hex()[:16]
                    if ddev.packed.fingerprint else None))
        if ddev.mesh is not None:
            from tempo_tpu.parallel.mesh import locked_collective

            # collective dispatch: serialize with every other shard_map
            # enqueue in the process (the probe fires during query
            # compile, concurrent with scan dispatches on the same
            # devices — an interleaved per-device queue deadlocks the
            # collectives)
            with locked_collective(rec):
                with rec.stage(stage):
                    out = dist_probe_kernel(
                        ddev.mesh, d["buf"], d["pos"], d["off"],
                        d["n_real"], jnp.asarray(arr), jnp.asarray(lens),
                        jnp.asarray(empties), n_needle_max=Lp)
            # fence after releasing the collective lock (lock-order
            # suite: no blocking wait under dispatch_lock); the stage
            # timer accumulates so kernel time books to the same stage
            with rec.stage(stage):
                rec.fence(out)
            return out
        with rec.stage(stage):
            out = probe_kernel(d["buf"], d["pos"], d["off"], d["n_real"],
                               jnp.asarray(arr), jnp.asarray(lens),
                               jnp.asarray(empties), n_needle_max=Lp)
            rec.fence(out)
        return out


def hits_to_ids(hits_row) -> np.ndarray:
    """Host-side view of one term's hit mask as a sorted id array — the
    parity bridge to pipeline.substring_value_ids for tests/bench.
    Accepts both mask formats: bool rows and the packed-residency
    uint32 bit-words (search/packing.py)."""
    a = np.asarray(hits_row)
    if a.dtype == np.uint32:
        from .packing import unpack_mask_words

        a = unpack_mask_words(a, a.shape[-1] * 32)
    return np.nonzero(a)[0].astype(np.int32)
