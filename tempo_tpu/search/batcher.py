"""Serving-path batch scanning: many blocks (or page ranges), few kernels.

This is where the TPU economics land in the serving path. The reference's
production search IS its job fan-out — one goroutine per 10 MiB page range
(modules/frontend/searchsharding.go:163-306, tempodb/pool) — because on
CPU the per-job cost is the scan itself. On TPU the per-dispatch overhead
(host sync + kernel launch through a relay, ~ms) dwarfs the scan of a
single block, so the batcher inverts the shape: jobs GROUP into batches
whose pages stack along the device page axis and scan in ONE kernel call
(`multiblock.multi_scan_kernel`; with a mesh, the shard_map variant whose
collectives replace the Results funnel).

Properties the grouping keeps:
- **stable AND churn-local**: jobs sort by (block id, page range) and
  group boundaries are content-defined — a job starts a new group based
  only on a stable hash of its own key (like content-defined chunking in
  dedup stores) — so the same blocklist yields the same groups query
  after query, and a block arriving or leaving the blocklist reshapes
  only its own neighborhood up to the next hash anchor: O(1) cached
  batches invalidate per poll instead of every group downstream of the
  new uuid's sort position.
- **bucketed**: only jobs sharing page geometry (E entries/page, C kv
  slots) stack together — static shapes per bucket mean XLA compiles once
  per (bucket, n_terms, top_k).
- **prune-aware without cache churn**: header- or dictionary-pruned jobs
  stay IN the staged batch (composition never depends on the query); the
  compiled query neutralizes them (key id -1 → no page can match) and
  their entries are subtracted from inspected counts on the host.
- **pipelined with early quit**: group i+1 stages + dispatches while
  group i's results transfer; dispatch stops once the result limit is met
  (reference results.go:38-78 quit channel).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field

import numpy as np

from tempo_tpu import robustness
from tempo_tpu.observability import metrics as obs
from tempo_tpu.observability import profile
from tempo_tpu.observability import tracing

from . import query_stats
from . import structural as _structural
from .analytics import ANALYTICS, agg_requested
from .engine import DEFAULT_TOP_K, fetch_coalesced_out, resolve_top_k, \
    start_fetch
from .ownership import OWNERSHIP
from .multiblock import MultiBlockEngine, compile_multi, stack_queries
from .pipeline import block_header_skip_reason
from .results import SearchResults


def host_scan(host, mq, top_k: int):
    """The host route's execution (breaker fallback AND the ownership
    layer's non-owner serve): run the SAME multi_scan_kernel over the
    host-tier stacked arrays, pinned to the CPU backend — no
    wedged-device array is ever touched, no duplicate HBM copy is ever
    staged on a non-owner. Because it is
    the same kernel over the same padded shapes and the same compiled
    predicate semantics (host range tables; the device hit-mask path
    yields identical matches), the results are byte-identical to the
    device dispatch, with the one documented caveat shared by
    masked_topk's two-stage path: equal-start ties at the top-k
    boundary may resolve to a different (equally valid) entry than the
    MESH kernel's gather ordering would pick.

    The CPU-staged arrays memoize on the HostBatch (`_cpu_staged`), so
    a wedged-device soak re-stages each batch once, not per query; the
    memo dies with the host-tier entry. Returns the drain-format host
    tuple (count, inspected, scores, idx), plus the dense ?agg= counts
    when the query carries an agg_stage — the same integer reduction
    the device kernels run, so the host route's aggregate is
    byte-identical by construction."""
    import jax.numpy as jnp

    from .engine import cpu_pinned
    from .multiblock import multi_scan_kernel

    t0 = time.perf_counter()
    with cpu_pinned():
        dev = getattr(host, "_cpu_staged", None)
        if dev is None:
            dev = {k: jnp.asarray(v) for k, v in host.cat.items()}
            host._cpu_staged = dev
        tk = jnp.asarray(mq.term_keys)
        vr = jnp.asarray(mq.val_ranges)
        # structural predicate on the host route: the host-only compile
        # produced range tables (no device mask is ever touched) and the
        # span columns stage once per batch on the CPU backend — same
        # kernel, same plan, byte-identical verdicts
        st = getattr(mq, "structural", None)
        plan = s_tables = span_dev = None
        if st is not None:
            plan = st.plan
            s_tables = tuple(
                (jnp.asarray(t) if t is not None and not hasattr(
                    t, "devices") else t) for t in st.tables())
            span_host = getattr(host, "span_cat", None)
            if span_host is not None:
                span_dev = getattr(host, "_cpu_span_staged", None)
                if span_dev is None:
                    span_dev = {k: jnp.asarray(v)
                                for k, v in span_host.items()}
                    host._cpu_span_staged = span_dev
        # ?agg= composite keys, CPU-pinned and memoized like the page
        # arrays above (the AggStage itself is shared with the device
        # route via the batch memo — only the placement differs)
        agg_stage = getattr(mq, "agg_stage", None)
        agg = entry_agg = None
        if agg_stage is not None:
            agg = agg_stage.n_keys
            entry_agg = getattr(host, "_cpu_agg_staged", None)
            if entry_agg is None:
                entry_agg = host._cpu_agg_staged = agg_stage.cpu()
        out = multi_scan_kernel(
            dev["kv_key"], dev["kv_val"], dev["entry_start"],
            dev["entry_end"], dev["entry_dur"], dev["entry_valid"],
            dev["page_block"], tk, vr,
            jnp.uint32(mq.dur_lo), jnp.uint32(min(mq.dur_hi, 0xFFFFFFFF)),
            jnp.uint32(mq.win_start),
            jnp.uint32(min(mq.win_end, 0xFFFFFFFF)),
            None, None, dev.get("entry_dur_res"),
            span_dev, s_tables, entry_agg,
            n_terms=mq.n_terms, top_k=top_k,
            # the host tier stages the SAME packed layout (stack_host
            # packs before the tiers fork), so the fallback kernel
            # unpacks with the batch's own width descriptor
            widths=getattr(host, "widths", None), plan=plan, agg=agg)
        count, inspected, scores, idx, *ext = out
        res = (int(count), int(inspected), np.asarray(scores),
               np.asarray(idx))
        if ext:
            res += (np.asarray(ext[0]),)
    profile.observe_stage("execute", "host_fallback",
                          time.perf_counter() - t0)
    return res


@dataclass
class ScanJob:
    """One schedulable scan unit: a page range of one block's search
    container (whole block = range [0, n_pages))."""
    key: tuple              # (block_id, start_page, n_pages) — cache identity
    pages_fn: object        # () -> ColumnarPages for this range (host)
    header: dict            # search-header rollup (pruning + sizes)
    n_pages: int
    n_entries: int
    geometry: tuple         # (entries_per_page, kv_per_entry) bucket key
    meta: object = None     # BlockMeta, for diagnostics

    @property
    def bytes_est(self) -> int:
        """Share of the block's compressed bytes this job covers — the
        inspected_bytes accounting unit (reference results.go metrics)."""
        total = max(1, self.header.get("n_pages", self.n_pages))
        return int(self.header.get("compressed_size", 0) * self.n_pages / total)


@dataclass
class _CachedBatch:
    batch: object           # multiblock.BlockBatch
    nbytes: int
    # unpacked-layout equivalent of nbytes (the logical side of the
    # packed-residency accounting split; == nbytes when packing is off).
    # Fixed at stage time so add/remove stay symmetric.
    logical: int = 0
    jobs: list = field(default_factory=list)
    # per-query memo: everything O(group-size) that depends only on the
    # request's predicate (header prune, per-block compile tables, metric
    # sums) — repeated queries over a 10K-block blocklist must not pay
    # O(blocks) python per query (VERDICT r2 #1). Keyed by the full
    # predicate signature; bounded LRU.
    query_cache: OrderedDict = field(default_factory=OrderedDict)
    # HBM pin count: searches holding this batch (between acquisition and
    # their final drain). Eviction skips pinned entries so budget
    # pressure from one tenant never drops a batch another request is
    # actively scanning — its device arrays would survive via the
    # in-flight references anyway, but the budget would double-pay when
    # the next query immediately re-stages it
    pins: int = 0


_QUERY_CACHE_MAX = 32
_PRUNE_CACHE_MAX = 4096  # (group, predicate) header-prune memos kept


def _predicate_sig(req) -> tuple:
    """Everything about the request that affects pruning/compilation —
    NOT limit (scalar on the MultiQuery, filled per query). The raw
    structural tag rides separately: _tags_sig excludes it (it is not a
    dictionary term), but two requests differing only structurally must
    not share a prepare() memo."""
    from .pipeline import _tags_sig
    from .structural import STRUCTURAL_QUERY_TAG

    return (_tags_sig(req), req.min_duration_ms or 0,
            req.max_duration_ms or 0, req.start or 0, req.end or 0,
            req.tags.get(STRUCTURAL_QUERY_TAG, ""))


class _PendingCoalesce:
    """Queries waiting on one staged batch for the window to close."""

    __slots__ = ("batch", "gen", "items")

    def __init__(self, batch, gen):
        self.batch = batch
        self.gen = gen
        self.items = []     # [(mq, top_k, Future, t_submit, QueryStats|None)]


class _FusedOut:
    """One fused dispatch's device output, demuxed lazily: the blocking
    D2H sync runs once, on the FIRST waiter's drain thread — never on
    the submitter whose submit() happened to trigger a size flush (that
    thread has its own dispatch loop to run; syncing there would
    serialize its next group behind this group's fetch).

    The sync runs OUTSIDE the lock (lock-order suite: a d2h sync under
    a lock turns a wedged device into a pile-up of threads parked on
    the lock, each burning its own watchdog): the first waiter CLAIMS
    the fetch under the lock, fetches unlocked, publishes via the done
    event; later waiters park on the event, not the lock. A faulted
    fetch publishes its exception to every waiter — one watchdog burn
    for the group instead of one per member (each member's drain then
    resubmits its own query on the host path, as before)."""

    __slots__ = ("_out", "_host", "_exc", "_claimed", "_done")

    def __init__(self, out):
        self._out = out
        self._host = None
        self._exc = None
        self._claimed = threading.Lock()
        self._done = threading.Event()

    def host(self):
        if not self._done.is_set() and self._claimed.acquire(blocking=False):
            # first waiter: the one real d2h sync, not under any lock
            try:
                self._host = fetch_coalesced_out(self._out)
                self._out = None
            except Exception as e:  # noqa: BLE001 — published to waiters
                self._exc = e
            finally:
                # set even when a BaseException (KeyboardInterrupt)
                # aborts the claimer: waiters must never park forever.
                # The interrupt itself propagates on the claimer's
                # thread only — republishing it to every member would
                # turn one operator Ctrl-C into N failed queries
                self._done.set()
        else:
            self._done.wait()
        if self._exc is not None:
            raise self._exc
        if self._host is None:
            # claimer died without publishing (interpreter-control
            # exception mid-fetch): RuntimeError is device-fault-shaped,
            # so each member's drain resubmits on the host path
            raise RuntimeError("fused d2h fetch aborted before publishing")
        return self._host


class _FusedSlice:
    """One member query's view of a _FusedOut; unpacks like the direct
    path's (count, inspected, scores, idx) tuple so drain code cannot
    tell a fused dispatch from a solo one."""

    __slots__ = ("_shared", "_qi")

    def __init__(self, shared, qi):
        self._shared = shared
        self._qi = qi

    def __iter__(self):
        counts, inspected, scores, idx, *ext = self._shared.host()
        qi = self._qi
        res = (int(counts[qi]), inspected, scores[qi], idx[qi])
        if ext:
            # fused ?agg= counts demux like scores: row qi of the [Q, K]
            # dense-count matrix belongs to this member
            res += (ext[0][qi],)
        return iter(res)


class QueryCoalescer:
    """Cross-request query coalescing: concurrent searches whose next
    dispatch targets the SAME staged BlockBatch stack their compiled
    queries along a query axis and execute as ONE fused
    coalesced_scan_kernel launch — continuous batching for scans. N
    tenants' dashboards over the same device-resident columns then cost
    ~1 dispatch per coalescing window instead of N.

    Mechanics:
    - submit() parks the query in a per-batch pending group and arms a
      window timer (`window_s`, a few ms). The flush NEVER waits for
      more peers — it fires on the timer or when `max_queries` stack up,
      so a lone query is delayed by at most the window.
    - A dispatch with no potential peer skips the window entirely (the
      `peers` hint on submit, per-BATCH, not merely per-process): serial
      latency is unchanged, and a single request's own sharded
      sub-requests — which target disjoint batches and can never fuse —
      don't tax each other either. The window is only paid when another
      in-flight search could actually share this batch's dispatch.
    - Single-query flushes go through the ordinary multi_scan_kernel so
      they reuse its already-compiled executables.
    - Query tables pad (Q, T, R, top_k) to power-of-two buckets
      (multiblock.stack_queries), so the jit cache keys on predicate
      SHAPE, never predicate values — different tag-sets share one
      compiled executable.
    """

    def __init__(self, engine: MultiBlockEngine, window_s: float = 0.003,
                 max_queries: int = 8, active_fn=None):
        self.engine = engine
        self.window_s = window_s
        self.max_queries = max(2, max_queries)
        # how many searches are in flight right now; <=1 → flush
        # immediately (no peer exists to wait for)
        self._active_fn = active_fn or (lambda: 2)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # pending-group key: (id(batch), None) for legacy queries, the
        # stack_group_key tuple (id(batch), plan) for structural ones —
        # same-plan structural peers share a group, different plans
        # wait out disjoint windows and flush solo
        self._pending: dict[tuple, _PendingCoalesce] = {}
        # window deadlines served by ONE long-lived scheduler thread
        # (lazily started): a threading.Timer per armed window would
        # create an OS thread per batch per window on the serving hot
        # path — pure churn at thousands of windows/sec. Heap entries
        # carry gen SECOND so equal deadlines tie-break on the unique
        # int and group keys (which hold plan tuples) never compare.
        self._deadlines: list[tuple[float, int, tuple]] = []  # (t, gen, key)
        self._sched: threading.Thread | None = None
        self._flush_pool = None  # lazily built with the scheduler
        self._gen = 0
        self.dispatches = 0   # fused + solo kernel launches issued here
        self.fused = 0        # launches that served >1 query
        self.queries = 0      # queries served
        self.structural_queries = 0  # structural queries served here
        self.structural_stacked = 0  # ...that shared a fused dispatch
        self.structural_bucketed = 0  # ...whose fused group mixed plans
        # per-bucket occupancy (/debug/scan): str(bucket descriptor) ->
        # {queries, dispatches, active_nodes, slot_nodes} — over-padded
        # buckets show up as a low active/slot ratio
        self._bucket_stats: dict[str, dict] = {}

    def submit(self, batch, mq, top_k: int, peers: int | None = None):
        """Queue one compiled query against `batch`; returns a Future
        resolving to the engine's (count, inspected, scores, idx) — the
        same host types drain code gets from a direct dispatch. `peers`
        is the caller's count of in-flight searches that could target
        THIS batch (self included); <=1 flushes immediately.

        Structural queries group by PLAN SHAPE (stack_group_key): with
        search_structural_stack_enabled, same-plan concurrent queries
        stack along the fused query axis like any other coalesced
        member; with it off (or for a plan no peer shares) they flush
        solo, and the stack_events counter says which.

        The submitter's active QueryStats is captured WITH the item
        (the contextvar does not survive into the window-timer flush
        thread): at flush time the dispatch's profiled stage times are
        apportioned across the member queries' stats."""
        import concurrent.futures
        import heapq
        import time as _time

        fut = concurrent.futures.Future()
        st = getattr(mq, "structural", None)
        key = (id(batch), None)
        if st is not None:
            skey = None
            if _structural.STRUCTURAL.stack_enabled:
                skey = _structural.STRUCTURAL.stack_group_key(batch, st)
            if skey is None:
                # stacking disabled: dispatch solo NOW (the pre-stacking
                # behavior — the solo flush reuses this plan's compiled
                # executable). gen=-1 marks the metric as already
                # recorded here, so _run won't double-book solo_shape.
                obs.structural_stack_events.inc(result="solo_disabled")
                grp = _PendingCoalesce(batch, -1)
                grp.items.append((mq, top_k, fut, _time.perf_counter(),
                                  query_stats.current()))
                self._run(grp)
                return fut
            key = skey
        if getattr(mq, "agg_stage", None) is not None:
            # ?agg= members group apart from plain peers: the agg static
            # changes the fused kernel's jit key, and a mixed group
            # would make the no-agg hot path's compiled shape depend on
            # whichever member happened to join the window
            key = key + ("agg",)
        flush_now = None
        with self._lock:
            grp = self._pending.get(key)
            if grp is None:
                self._gen += 1
                grp = self._pending[key] = _PendingCoalesce(batch, self._gen)
            grp.items.append((mq, top_k, fut, _time.perf_counter(),
                              query_stats.current()))
            if len(grp.items) >= self.max_queries:
                del self._pending[key]
                flush_now = grp
            elif len(grp.items) == 1:
                hint = peers if peers is not None else self._active_fn()
                if hint <= 1:
                    # no peer can share this batch's dispatch: a window
                    # would be pure added latency
                    del self._pending[key]
                    flush_now = grp
                else:
                    heapq.heappush(
                        self._deadlines,
                        (_time.perf_counter() + self.window_s, grp.gen,
                         key))
                    if self._sched is None:
                        self._flush_pool = \
                            concurrent.futures.ThreadPoolExecutor(
                                max_workers=4,
                                thread_name_prefix="coalesce-flush")
                        self._sched = threading.Thread(
                            target=self._window_loop, daemon=True,
                            name="coalesce-window")
                        self._sched.start()
                    self._cv.notify()
            # queue-depth gauge AFTER the flush-now removal above: only
            # queries actually parked in a window count as pending
            obs.coalesce_pending.set(
                sum(len(g.items) for g in self._pending.values()))
        if flush_now is not None:
            self._run(flush_now)
        return fut

    def _window_loop(self) -> None:
        """Single scheduler thread draining window deadlines. Stale
        entries (groups a size-triggered flush already took) are skipped
        by the gen check — nothing is ever cancelled out of the heap.
        Due flushes are HANDED OFF to a small pool: _run stages, uploads
        and may jit-compile a first-seen kernel shape, and running that
        inline would head-of-line-block every other batch's window
        behind one slow group."""
        import heapq
        import time as _time

        while True:
            grp = None
            with self._cv:
                while not self._deadlines:
                    self._cv.wait()
                deadline, gen, key = self._deadlines[0]
                wait = deadline - _time.perf_counter()
                if wait > 0:
                    self._cv.wait(wait)
                    continue
                heapq.heappop(self._deadlines)
                pend = self._pending.get(key)
                if pend is None or pend.gen != gen:
                    continue  # size-triggered flush beat the window
                del self._pending[key]
                obs.coalesce_pending.set(
                    sum(len(g.items) for g in self._pending.values()))
                grp = pend
            self._flush_pool.submit(self._run, grp)

    @staticmethod
    def _attribute(items, recs, wall_s: float) -> None:
        """Apportion one (possibly fused) dispatch's cost across the
        member queries' stats by their padded predicate-table rows,
        CONSERVING the totals: per stage, the attributed shares sum to
        the dispatch total exactly (query_stats.apportion gives the
        last member the float remainder). With profiling disabled there
        are no records; the measured wall books as "execute" so the
        per-tenant device-seconds bill degrades to wall-clock rather
        than to zero."""
        stats = [it[4] for it in items]
        if all(s is None for s in stats):
            return
        totals: dict[str, float] = {}
        h2d = 0
        for rd in recs:
            for k, v in (rd.get("stages_ms") or {}).items():
                totals[k] = totals.get(k, 0.0) + v / 1e3
            h2d += rd.get("h2d_bytes", 0)
        if not totals:
            totals = {"execute": wall_s}

        def table_rows(mq) -> int:
            # stacked structural members weigh their plan's parameter
            # tables alongside the legacy term tables — a member whose
            # probe masks dominated the fused kernel's reads gets the
            # proportional share (conservation via apportion as before).
            # st is each member's OWN CompiledStructural, so under
            # shape-bucketed stacking the weight counts the member's
            # ACTIVE node tables, never the bucket's pad slots
            w = max(1, int(mq.term_keys.size))
            st = getattr(mq, "structural", None)
            if st is not None:
                w += st.weight()
            return w

        weights = [table_rows(it[0]) for it in items]
        shares = query_stats.apportion(totals, weights)
        byte_shares = query_stats.apportion({"b": float(h2d)}, weights)
        for qs, share, bs in zip(stats, shares, byte_shares):
            if qs is not None:
                qs.add_device_stages(share, h2d_bytes=bs["b"],
                                     fused_q=len(items))

    def _run(self, grp: _PendingCoalesce) -> None:
        import time as _time

        from tempo_tpu.observability import profile

        items = grp.items
        try:
            now = _time.perf_counter()
            for _mq, _k, _fut, t0, _qs in items:
                obs.coalesce_wait_seconds.observe(now - t0)
            structural = bool(
                items and getattr(items[0][0], "structural", None)
                is not None)
            # a fused structural group whose member plans DIFFER fused
            # through the bucket canonicalization (bucket_group_key) —
            # booked separately so mixed-traffic fusion is observable
            bucketed = structural and len(items) > 1 and any(
                getattr(it[0], "structural").plan
                != items[0][0].structural.plan for it in items[1:])
            with self._lock:  # _run races: window thread vs size flush
                self.dispatches += 1
                self.queries += len(items)
                if len(items) > 1:
                    self.fused += 1
                if structural:
                    self.structural_queries += len(items)
                    if len(items) > 1:
                        self.structural_stacked += len(items)
                    if bucketed:
                        self.structural_bucketed += len(items)
            if structural and grp.gen >= 0:
                # gen=-1 groups booked solo_disabled at submit; here a
                # fused flush books every member as stacked (bucketed
                # when plans differ) and a lone member as solo_shape —
                # unstackable (peerless) plan shapes are visible, never
                # a silent solo flush
                if bucketed:
                    obs.structural_stack_events.inc(
                        len(items), result="stacked_bucketed")
                elif len(items) > 1:
                    obs.structural_stack_events.inc(len(items),
                                                    result="stacked")
                else:
                    obs.structural_stack_events.inc(result="solo_shape")
            if len(items) == 1:
                mq, _k, fut, _t0, _qs = items[0]
                t0d = _time.perf_counter()
                with profile.collect_records() as recs:
                    out = self.engine.scan_async(grp.batch, mq)
                self._attribute(items, recs, _time.perf_counter() - t0d)
                start_fetch(out)
                obs.scan_dispatches.inc(mode="batched")
                fut.set_result(out)
                return
            mqs = [mq for mq, _k, _f, _t, _qs in items]
            cq = stack_queries(mqs)
            st = getattr(cq, "structural", None)
            if st is not None and getattr(st, "slot_nodes", 0):
                # bucket occupancy: active (real) vs slot (padded)
                # nodes per bucket descriptor — /debug/scan surfaces
                # over-padded buckets
                bkey = str(st.plan)
                with self._lock:
                    row = self._bucket_stats.setdefault(
                        bkey, {"queries": 0, "dispatches": 0,
                               "active_nodes": 0, "slot_nodes": 0})
                    row["queries"] += st.n_queries
                    row["dispatches"] += 1
                    row["active_nodes"] += st.active_nodes
                    row["slot_nodes"] += st.slot_nodes
            k = max(k for _mq, k, _f, _t, _qs in items)
            t0d = _time.perf_counter()
            with profile.collect_records() as recs:
                out = self.engine.coalesced_scan_async(grp.batch, cq, k)
            self._attribute(items, recs, _time.perf_counter() - t0d)
            obs.scan_dispatches.inc(mode="coalesced")
            obs.coalesced_queries.inc(len(items))
            # D2H starts async NOW; the one blocking sync point happens
            # on the first waiter's drain (lazy demux), not here — a
            # size-triggered flush runs on the last submitter's thread,
            # which still has its own dispatch loop to overlap
            start_fetch(out)
            shared = _FusedOut(out)
            for qi, (_mq, _k, fut, _t0, _qs) in enumerate(items):
                fut.set_result(_FusedSlice(shared, qi))
        except BaseException as e:  # noqa: BLE001 — delivered via futures
            for _mq, _k, fut, _t0, _qs in items:
                if not fut.done():
                    fut.set_exception(e)

    def stats(self) -> dict:
        with self._lock:
            pending = sum(len(g.items) for g in self._pending.values())
            bucket_rows = {bk: dict(row)
                           for bk, row in self._bucket_stats.items()}
        return {
            "dispatches": self.dispatches,
            "fused_dispatches": self.fused,
            "queries": self.queries,
            "ratio": round(self.queries / max(1, self.dispatches), 3),
            "pending": pending,
            "window_ms": self.window_s * 1e3,
            # plan-shape stacking visibility (/debug/scan): how many
            # structural queries came through and what share of them
            # actually shared a fused dispatch
            "structural_queries": self.structural_queries,
            "structural_stacked": self.structural_stacked,
            "structural_stack_ratio": round(
                self.structural_stacked
                / max(1, self.structural_queries), 3),
            # shape-bucketed fusion visibility: mixed-plan queries that
            # shared a dispatch, plus per-bucket stack ratios and node
            # occupancy (active = real slots, the rest is bucket pad)
            "structural_bucketed": self.structural_bucketed,
            "buckets": {
                bk: {
                    "queries": row["queries"],
                    "dispatches": row["dispatches"],
                    "stack_ratio": round(
                        row["queries"] / max(1, row["dispatches"]), 3),
                    "occupancy": round(
                        row["active_nodes"]
                        / max(1, row["slot_nodes"]), 3),
                }
                for bk, row in bucket_rows.items()
            },
        }


class BlockBatcher:
    """Groups ScanJobs into staged device batches and runs searches over
    them. Thread-safe; one instance per TempoDB."""

    def __init__(self, mesh=None, top_k: int = DEFAULT_TOP_K,
                 max_batch_pages: int = 4096,
                 cache_bytes: int = 4 << 30,
                 host_cache_bytes: int | None = None,
                 pipeline_depth: int = 2,
                 io_workers: int = 8,
                 coalesce_window_s: float = 0.003,
                 coalesce_max_queries: int = 8,
                 device_probe_min_vals: int | None = None):
        self.engine = MultiBlockEngine(
            top_k=top_k, mesh=mesh,
            device_probe_min_vals=device_probe_min_vals)
        self.max_batch_pages = max_batch_pages
        self.cache_bytes = cache_bytes
        if host_cache_bytes is None:
            # auto-size: the host tier retains stacked batches (and pins
            # their source pages), so an unconditional 32 GB default
            # OOM-kills small hosts — cap at half of physical RAM
            import os
            try:
                phys = os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
            except (ValueError, OSError, AttributeError):
                phys = 16 << 30
            host_cache_bytes = min(32 << 30, phys // 2)
        self.host_cache_bytes = host_cache_bytes
        self.pipeline_depth = max(1, pipeline_depth)
        self.io_workers = io_workers
        self._cache: OrderedDict[tuple, _CachedBatch] = OrderedDict()
        self._cache_total = 0
        self._probe_dict_total = 0  # staged-dict bytes across _cache
        # logical (unpacked-layout) bytes across both tiers — the other
        # half of the packed-residency accounting split: budgets charge
        # PHYSICAL bytes (that is why packing fits more blocks), the
        # logical gauges say how much unpacked data those bytes carry
        self._cache_logical = 0
        self._host_logical = 0
        # host-RAM tier between the object store and HBM: stacked numpy
        # batches, byte-budgeted separately. An HBM eviction leaves the
        # host copy, so re-staging an evicted batch is one H2D copy, not
        # IO + decompress + restack (VERDICT r3 #2)
        self._host_cache: OrderedDict[tuple, object] = OrderedDict()
        self._host_total = 0
        # host-fallback CPU-pinned array copies (host_scan's per-batch
        # memo), charged to the host budget separately so eviction can
        # release exactly what was charged
        self._cpu_staged_bytes: dict[tuple, int] = {}
        self._staging: dict[tuple, threading.Event] = {}
        # ownership rebalance evictions deferred while a search pins the
        # batch: gkey -> the exact entry to drop at unpin. Keyed by entry
        # IDENTITY at eviction time so a marker gone stale (the LRU got
        # there first, or a re-stage replaced the object) is discarded
        # instead of double-subtracting the budget
        self._evict_deferred: dict[tuple, _CachedBatch] = {}
        self._warmed_shapes: set = set()  # compile-warm dedupe
        self._prune_cache: OrderedDict = OrderedDict()
        self._plan_cache: OrderedDict = OrderedDict()
        self._lock = threading.Lock()
        # staging lookahead: stages group i+1 while group i's kernel
        # runs, overlapping H2D with compute (double-buffering). More
        # than one thread so CONCURRENT searches' lookaheads don't
        # serialize behind each other (each search still submits one at
        # a time; _staged dedupes racing stages)
        import concurrent.futures
        self._prefetcher = concurrent.futures.ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="stage-prefetch")
        # cross-request query coalescing: concurrent searches' dispatches
        # over the same staged batch fuse into one multi-query kernel
        # launch. coalesce_max_queries <= 1 disables (every submit
        # dispatches directly, the pre-coalescer behavior).
        # _interest counts, per batch gkey, how many in-flight searches
        # plan to scan it; _unplanned counts searches that entered but
        # haven't resolved their plan yet (unknown targets — they could
        # hit any batch, so they count as potential peers everywhere).
        # The coalescing window is armed only when interest+unplanned
        # says a same-batch peer can actually arrive: a single request's
        # sharded sub-requests cover DISJOINT batches and must not tax
        # each other a window apiece
        self._interest: dict[tuple, int] = {}
        self._unplanned = 0
        self.coalescer = None
        if coalesce_max_queries > 1:
            self.coalescer = QueryCoalescer(
                self.engine, window_s=coalesce_window_s,
                max_queries=coalesce_max_queries)
        self.last_dispatches = 0  # diagnostics: dispatch SUBMITS in last
        # search — under coalescing several searches can share one kernel
        # launch, so the global launch count lives in the
        # scan_dispatches{mode=batched|coalesced} counters instead
        self.last_scan = None     # /debug/scan: last search's breakdown

    # ------------------------------------------------------------------
    # planning

    def _cuts(self, j: ScanJob) -> bool:
        """Content-defined group boundary: depends ONLY on this job's key
        and size, never on neighbors, so group composition is a local
        property. Cut probability 1/divisor makes the expected group
        ~max_batch_pages/2, leaving headroom so churn rarely propagates
        through the hard page cap to the next anchor. plan() additionally
        guards cuts behind a min group size (max_batch_pages/4, the CDC
        min-chunk-size trick) so groups never fragment below batching
        efficiency."""
        import zlib

        divisor = max(2, self.max_batch_pages // (2 * max(1, j.n_pages)))
        return zlib.crc32(repr(j.key).encode()) % divisor == 0

    def plan(self, jobs: list[ScanJob]) -> list[list[ScanJob]]:
        buckets: dict[tuple, list[ScanJob]] = {}
        for j in sorted(jobs, key=lambda j: j.key):
            buckets.setdefault(j.geometry, []).append(j)
        groups = []
        for _geo, js in sorted(buckets.items()):
            cur: list[ScanJob] = []
            cur_pages = 0
            min_pages = self.max_batch_pages // 4
            for j in js:
                if cur and (cur_pages + j.n_pages > self.max_batch_pages
                            or (cur_pages >= min_pages and self._cuts(j))):
                    groups.append(cur)
                    cur, cur_pages = [], 0
                cur.append(j)
                cur_pages += j.n_pages
            if cur:
                groups.append(cur)
        return groups

    # ------------------------------------------------------------------
    # staging cache

    @staticmethod
    def _dict_bytes(batch) -> int:
        """HBM held by a batch's staged device-probe dictionaries."""
        return sum(int(d.nbytes)
                   for d in getattr(batch, "staged_dicts", {}).values())

    def _publish_gauges_locked(self) -> None:
        """Occupancy gauges for /metrics (caller holds self._lock): HBM
        + host tier bytes, and the HBM share held by staged device-probe
        dictionaries across resident batches. All three are running
        totals (the _cache_total idiom) — this must stay O(1), it runs
        on every stage/evict under the global lock."""
        obs.hbm_cache_bytes.set(self._cache_total)
        obs.host_cache_bytes.set(self._host_total)
        obs.probe_dict_bytes.set(self._probe_dict_total)
        obs.hbm_logical_bytes.set(self._cache_logical)
        obs.host_logical_bytes.set(self._host_logical)

    def _evict_host_locked(self) -> None:
        """LRU-evict host-tier batches until the budget holds — caller
        holds self._lock. An entry's charge is its nbytes plus any
        CPU-pinned fallback copies host_scan memoized on it."""
        while (self._host_total > self.host_cache_bytes
               and len(self._host_cache) > 1):
            k, oldh = self._host_cache.popitem(last=False)
            self._host_total -= oldh.nbytes
            self._host_logical -= oldh.logical_nbytes
            self._host_total -= self._cpu_staged_bytes.pop(k, 0)
            obs.batch_cache_events.inc(result="host_evict")

    def _drop_hbm_locked(self, gkey: tuple) -> None:
        """Remove one staged batch and release its budget charge —
        caller holds self._lock. The single eviction primitive shared by
        the LRU, the ownership rebalance, and the deferred-at-unpin
        sweep, so the accounting subtraction happens in exactly one
        place."""
        old = self._cache.pop(gkey, None)
        if old is None:
            return
        self._cache_total -= old.nbytes
        self._cache_logical -= old.logical
        self._probe_dict_total -= self._dict_bytes(old.batch)
        obs.batch_cache_events.inc(result="evict")

    def _evict_hbm_locked(self) -> None:
        """LRU-evict staged batches until the HBM budget holds — caller
        holds self._lock. Pinned entries (actively scanned by some
        search) are skipped: evicting them reclaims nothing (the
        in-flight dispatch pins the device arrays) and guarantees an
        immediate re-stage."""
        while self._cache_total > self.cache_bytes and len(self._cache) > 1:
            victim = next((k for k, v in self._cache.items()
                           if v.pins <= 0), None)
            if victim is None:
                break  # everything pinned: over budget until a drain
            self._drop_hbm_locked(victim)
        self._publish_gauges_locked()

    def _run_deferred_evictions_locked(self) -> None:
        """Ownership-rebalance evictions deferred while pinned run NOW
        (at unpin) — exactly once: a marker whose cache entry is gone or
        replaced (an LRU eviction or a re-stage beat us here) is
        discarded without touching the budget, so a rebalance and an LRU
        eviction targeting the same batch can never double-subtract its
        bytes. Caller holds self._lock."""
        if not self._evict_deferred:
            return
        for gkey, entry in list(self._evict_deferred.items()):
            if self._cache.get(gkey) is not entry:
                del self._evict_deferred[gkey]  # stale: already gone
                continue
            if entry.pins > 0:
                continue  # another search still holds it
            self._drop_hbm_locked(gkey)
            del self._evict_deferred[gkey]
            obs.hbm_owner_rebalance_evictions.inc(result="dropped")

    def rebalance_ownership(self) -> dict:
        """Treat an ownership rebalance as a PLACEMENT change for the
        HBM cache: every resident batch whose group this member no
        longer owns is dropped now, or — while a search pins it —
        deferred to the unpin sweep. Host-tier entries stay: the
        non-owner route serves from exactly that tier, so dropping them
        would re-pay IO+decompress on the next routed-away query."""
        if not OWNERSHIP.enabled:
            return {"hbm_dropped": 0, "hbm_deferred": 0}
        # load-aware: demote heat-promoted groups whose rate decayed
        # below the hysteresis floor FIRST, so a stale replica's
        # residency falls out through the ordinary owns_group walk below
        # (same dropped/deferred path a placement move takes)
        OWNERSHIP.sweep()
        dropped = deferred = 0
        with self._lock:
            for gkey in list(self._cache):
                if OWNERSHIP.owns_group(gkey):
                    self._evict_deferred.pop(gkey, None)  # owned again:
                    # a pending deferral from an older generation is void
                    continue
                entry = self._cache[gkey]
                if entry.pins > 0:
                    # count a deferral once per BATCH, not once per
                    # rebalance: a batch pinned across several
                    # membership flips re-arrives here each time
                    if self._evict_deferred.get(gkey) is not entry:
                        deferred += 1
                    self._evict_deferred[gkey] = entry
                else:
                    self._evict_deferred.pop(gkey, None)
                    self._drop_hbm_locked(gkey)
                    dropped += 1
            self._publish_gauges_locked()
        if dropped:
            obs.hbm_owner_rebalance_evictions.inc(dropped, result="dropped")
        if deferred:
            obs.hbm_owner_rebalance_evictions.inc(deferred,
                                                  result="deferred")
        return {"hbm_dropped": dropped, "hbm_deferred": deferred}

    def ownership_residency(self) -> list:
        """Per-resident-batch ownership view for /debug/ownership: which
        placement group each staged batch anchors to, who owns it, and
        whether a deferred rebalance eviction is pending on it."""
        with self._lock:
            rows = [(k, v.nbytes, v.pins, k in self._evict_deferred)
                    for k, v in self._cache.items()]
        out = []
        for gkey, nbytes, pins, pending in rows:
            anchor = str(gkey[0][0])
            out.append({
                "anchor_block": anchor,
                "placement_group": OWNERSHIP.group_of(anchor),
                "owner": OWNERSHIP.owner_of(anchor),
                "owned": OWNERSHIP.owns_block(anchor),
                "jobs": len(gkey),
                "bytes": int(nbytes),
                "pins": int(pins),
                "deferred_evict": pending,
                # residency held through a heat-promoted replica set
                # rather than plain ownership (owner included while
                # the group is promoted)
                "replica": OWNERSHIP.is_replica(anchor),
            })
        return out

    def _staged(self, group: list[ScanJob]) -> _CachedBatch:
        key = tuple(j.key for j in group)
        while True:
            with self._lock:
                hit = self._cache.get(key)
                if hit is not None:
                    self._cache.move_to_end(key)
                    obs.batch_cache_events.inc(result="hit")
                    return hit
                ev = self._staging.get(key)
                if ev is None:
                    # we are the stager for this key
                    ev = self._staging[key] = threading.Event()
                    break
            # another thread is staging this exact group: wait for it
            # rather than duplicating the IO+decompress+H2D (and
            # transiently doubling HBM for the batch)
            ev.wait()
        try:
            host = self._load_host(key, group)
            # H2D only on the hot path; watchdog-bounded — a staging put
            # into a wedged tunnel raises DeviceFault (breaker fault
            # booked) and the caller answers through the host route
            batch = robustness.GUARD.run(
                "h2d", lambda: self.engine.place(host))
            # batch.nbytes covers the stacked page arrays AND any staged
            # probe dictionaries — both live in HBM under this budget
            # (physical/packed bytes; the logical twin feeds the gauges)
            nbytes = int(batch.nbytes)
            entry = _CachedBatch(batch=batch, nbytes=nbytes,
                                 logical=int(batch.logical_nbytes),
                                 jobs=list(group))
            with self._lock:
                obs.batch_cache_events.inc(result="miss")
                prev = self._cache.pop(key, None)
                if prev is not None:
                    self._cache_total -= prev.nbytes
                    self._cache_logical -= prev.logical
                    self._probe_dict_total -= self._dict_bytes(prev.batch)
                self._cache[key] = entry
                self._cache_total += nbytes
                self._cache_logical += entry.logical
                self._probe_dict_total += self._dict_bytes(batch)
                self._evict_hbm_locked()
            return entry
        finally:
            with self._lock:
                self._staging.pop(key, None)
            ev.set()

    def _load_host(self, key: tuple, group: list[ScanJob]):
        """Host-tier staging (IO + decompress + stack, NO device put):
        the first half of _staged, and the WHOLE staging for the
        breaker's host-fallback route."""
        with self._lock:
            host = self._host_cache.get(key)
            if host is not None:
                self._host_cache.move_to_end(key)
        if host is None:
            # load host pages outside the lock (IO + decompress
            # dominate)
            import concurrent.futures

            if len(group) > 1:
                with concurrent.futures.ThreadPoolExecutor(
                    max_workers=min(self.io_workers, len(group))
                ) as ex:
                    pages = list(ex.map(lambda j: j.pages_fn(), group))
            else:
                pages = [group[0].pages_fn()]
            host = self.engine.stage_host(pages)
            with self._lock:
                self._host_cache[key] = host
                self._host_total += host.nbytes
                self._host_logical += host.logical_nbytes
                self._evict_host_locked()
                self._publish_gauges_locked()
            obs.batch_cache_events.inc(result="host_miss")
        else:
            obs.batch_cache_events.inc(result="host_hit")
        return host

    def _host_batch(self, group: list[ScanJob]):
        """The host-fallback route's staging: host tier only, deduped
        against concurrent fallers the same way _staged dedupes device
        staging (a distinct event key — a host-route stage must not
        block behind a device stage wedging on the same group)."""
        key = tuple(j.key for j in group)
        ev_key = ("host",) + key
        while True:
            with self._lock:
                if key in self._host_cache:
                    we_stage = False
                    break
                ev = self._staging.get(ev_key)
                if ev is None:
                    ev = self._staging[ev_key] = threading.Event()
                    we_stage = True
                    break
            ev.wait()
        if not we_stage:
            return self._load_host(key, group)  # resident: hit counters
        try:
            return self._load_host(key, group)
        finally:
            with self._lock:
                self._staging.pop(ev_key, None)
            ev.set()

    def invalidate(self, live_block_ids: set[str]) -> None:
        """Drop cached batches containing blocks no longer in the
        blocklist (called from the poll loop) — both HBM and host tiers."""
        with self._lock:
            dead = [k for k in self._cache
                    if any(jk[0] not in live_block_ids for jk in k)]
            for k in dead:
                old = self._cache.pop(k)
                self._cache_total -= old.nbytes
                self._cache_logical -= old.logical
                self._probe_dict_total -= self._dict_bytes(old.batch)
                # a pending rebalance deferral for a dead block's batch
                # is satisfied by this removal — keeping the marker
                # would double-evict whatever re-stages under the key
                self._evict_deferred.pop(k, None)
            dead_h = [k for k in self._host_cache
                      if any(jk[0] not in live_block_ids for jk in k)]
            for k in dead_h:
                oldh = self._host_cache.pop(k)
                self._host_total -= oldh.nbytes
                self._host_logical -= oldh.logical_nbytes
                self._host_total -= self._cpu_staged_bytes.pop(k, 0)
            self._publish_gauges_locked()

    def prewarm(self, groups: list[list[ScanJob]],
                warm_compile: bool = True,
                stop: threading.Event | None = None) -> int:
        """Stage groups ahead of queries (called in the background after
        a poll): fills the host tier + HBM up to their budgets in plan
        order, and optionally warms the XLA compile cache for the
        staged shapes with a throwaway dispatch, so the first real query
        pays neither staging nor compile. Returns groups staged."""
        staged = 0
        budget = self.cache_bytes
        for group in groups:
            if stop is not None and stop.is_set():
                break
            if budget <= 0:
                break
            gkey = tuple(j.key for j in group)
            if OWNERSHIP.enabled:
                if not OWNERSHIP.owns_group(gkey):
                    # non-owned groups serve through the host route —
                    # prewarming them would stage exactly the duplicate
                    # HBM copy ownership exists to avoid
                    continue
            with self._lock:
                resident = gkey in self._cache
            try:
                cached = self._staged(group)
            except Exception:  # noqa: BLE001 — prewarm is best-effort
                continue
            # only actual staging WORK spends the budget: charging
            # resident hits would exhaust it on the warm prefix every
            # poll and never reach newly added groups (code-review r4)
            if not resident:
                budget -= cached.nbytes
                staged += 1
            if stop is not None and stop.is_set():
                break
            if warm_compile:
                try:
                    self._warm_compile(cached)
                except Exception:  # noqa: BLE001 — best-effort
                    pass
        return staged

    def _warm_compile(self, cached: _CachedBatch) -> None:
        """Throwaway dispatches to populate the jit cache for this
        batch's shape at the common term counts (0 = duration/window
        only, 2 = the typical tag AND). The jit cache keys on the PADDED
        shape (pow2-bucketed) — warming is deduped per shape signature,
        or a 100-group tenant would device-scan the whole corpus ~200x
        for ~log2 distinct compiles (code-review r4)."""
        import numpy as np

        from .multiblock import MultiQuery

        # dtypes are part of the jit cache key too: dictionary-size
        # narrowing means two same-shaped batches can carry int8 vs
        # int16 kv columns and compile separately (code-review r5);
        # the packed-residency width descriptor likewise
        shape_sig = (cached.batch.device["entry_valid"].shape,
                     cached.batch.device["kv_key"].shape,
                     str(cached.batch.device["kv_key"].dtype),
                     str(cached.batch.device["kv_val"].dtype),
                     cached.batch.widths,
                     len(cached.batch.blocks))
        with self._lock:
            if shape_sig in self._warmed_shapes:
                return
            self._warmed_shapes.add(shape_sig)
        B = len(cached.batch.blocks)
        for n_terms in (0, 2):
            mq = MultiQuery(
                term_keys=np.full((B, max(1, n_terms)), -1, dtype=np.int32),
                val_ranges=np.tile(np.array([1, 0], dtype=np.int32),
                                   (B, max(1, n_terms), 1, 1)),
                dur_lo=1, dur_hi=0,  # empty range: matches nothing
                win_start=1, win_end=0,
                limit=20, n_terms=n_terms)
            self.engine.scan(cached.batch, mq)

    # ------------------------------------------------------------------
    # search

    def search(self, jobs: list[ScanJob], req,
               results: SearchResults | None = None,
               plan_key=None, groups: list | None = None) -> SearchResults:
        """Run the request over all jobs: group → stage → compile →
        dispatch (pipelined, early-quitting) → merge. `plan_key` (e.g.
        (tenant, blocklist-epoch)) memoizes the grouping — the plan is a
        pure function of the job list, and re-sorting 10K jobs per query
        is measurable host overhead. Callers that already hold the plan
        (tempodb's protocol-path job cache) pass `groups` directly.

        Concurrent calls coalesce: dispatches landing on the same staged
        batch within the coalescing window fuse into one multi-query
        kernel launch (see QueryCoalescer). Batches a search is actively
        scanning are pinned in the HBM cache for its duration."""
        with self._lock:
            self._unplanned += 1
        pinned: list[_CachedBatch] = []
        interest: list[tuple] = []   # gkeys registered once planned
        planned = [False]
        try:
            return self._search_impl(jobs, req, results, plan_key, groups,
                                     pinned, interest, planned)
        finally:
            with self._lock:
                if planned[0]:
                    for k in interest:
                        n = self._interest.get(k, 0) - 1
                        if n <= 0:
                            self._interest.pop(k, None)
                        else:
                            self._interest[k] = n
                else:  # died before the plan resolved
                    self._unplanned -= 1
                for c in pinned:
                    c.pins -= 1
                # evictions deferred by pins run now that they dropped:
                # first the ownership-rebalance deferrals (exactly-once,
                # identity-checked), then ordinary LRU pressure
                self._run_deferred_evictions_locked()
                self._evict_hbm_locked()

    def _search_impl(self, jobs: list[ScanJob], req,
                     results: SearchResults | None,
                     plan_key, groups: list | None,
                     pinned: list, interest: list,
                     planned: list) -> SearchResults:
        from .pipeline import is_exhaustive

        results = results or SearchResults.for_request(req)
        exhaustive = is_exhaustive(req)
        # the active per-query stats (None when the layer is off): this
        # search's skip reasons, cache events, placement bytes and
        # attributed device time all land here. Read ONCE — every
        # recording site below is behind this None check.
        qs = query_stats.current()
        if groups is None and plan_key is not None:
            # one entry per plan_key[0] (tenant): a stale generation is
            # never hittable again (the epoch only moves forward), so
            # keeping it would just pin 10K dead ScanJobs
            tenant_key, gen = plan_key[0], plan_key[1:]
            with self._lock:
                hit = self._plan_cache.get(tenant_key)
                if hit is not None and hit[0] == gen:
                    groups = hit[1]
        if groups is None:
            groups = self.plan(jobs)
            if plan_key is not None:
                with self._lock:
                    self._plan_cache[tenant_key] = (gen, groups)
                    while len(self._plan_cache) > 64:
                        self._plan_cache.popitem(last=False)
        # plan is final: declare which batches this search will scan so
        # the coalescer can tell a real same-batch peer from an unrelated
        # concurrent search (which must not make us wait out a window)
        with self._lock:
            self._unplanned -= 1
            planned[0] = True
            for g in groups:
                k = tuple(j.key for j in g)
                self._interest[k] = self._interest.get(k, 0) + 1
                interest.append(k)
        inflight: deque = deque()
        dispatches = 0
        # per-stage wall time for the LAST search, exposed at /debug/scan
        # (reference pprof/debug role, cmd/tempo/main.go:54-115): the
        # operator's first question about a slow query is which stage ate
        # it — host prune, staging IO+H2D, predicate compile, kernel, or
        # the D2H fetch/merge
        import time as _time
        stages = {"header_prune": 0.0, "staging": 0.0, "prepare": 0.0,
                  "dispatch": 0.0, "drain": 0.0, "host_fallback": 0.0}
        t_search0 = _time.perf_counter()

        def drain_one():
            t0 = _time.perf_counter()
            gkey, cached, mq, pre, fut = inflight.popleft()
            try:
                if hasattr(fut, "result"):  # coalescer Future vs tuple
                    # NOT timed as d2h: a coalescer Future's wait
                    # includes the coalescing window + the group's
                    # stacking/dispatch
                    fut = fut.result()
                # the ACTUAL device→host sync: fused-slice demux happens
                # at unpack, the direct path syncs at the scalar/array
                # fetches — time exactly these so stage=d2h means
                # transfer, not queue. Watchdog-bounded: a wedged
                # device can hang the SYNC even when the enqueue
                # returned, and that hang must become a fault too.
                t0d = _time.perf_counter()

                def _sync(fut=fut):
                    count, inspected, scores, idx, *ext = fut
                    out = (int(count), int(inspected),
                           np.asarray(scores), np.asarray(idx))
                    if ext:
                        # dense ?agg= counts ride the same sync
                        out += (np.asarray(ext[0]),)
                    return out

                count, inspected, scores, idx, *agg_counts = \
                    robustness.GUARD.run("d2h", _sync)
            except robustness.DeadlineExceeded:
                # the request's budget ran out mid-drain: the answer
                # goes out PARTIAL — this group's results are dropped,
                # not waited for
                results.metrics.partial = True
                obs.partial_results.inc(reason="deadline")
                stages["drain"] += _time.perf_counter() - t0
                return
            except robustness.DeviceFault:
                # the dispatch (or its sync) died on the device — the
                # breaker fault is already booked; resubmit THIS query's
                # share of the group on the byte-identical host path.
                # For a fused dispatch every member future fails and
                # each member's drain resubmits its own query here.
                # book_skips=False: the main loop already counted this
                # group's skipped blocks/reasons at prepare time.
                host_route(cached.jobs, gkey,
                           hdr_reasons_for(cached.jobs),
                           book_skips=False)
                stages["drain"] += _time.perf_counter() - t0
                return
            d2h_s = _time.perf_counter() - t0d
            profile.observe_stage(
                "d2h", "batched", d2h_s,
                nbytes=scores.nbytes + idx.nbytes + 8)
            if qs is not None:
                # the wait THIS query paid for its results (for a fused
                # group the first drainer pays the real sync); count=False
                # — the dispatch itself was already attributed at launch
                qs.add_device_stages({"d2h": d2h_s}, count=False)
                qs.add_inspected(blocks=pre["inspected_blocks"],
                                 nbytes=pre["inspected_bytes"],
                                 placement="device")
                # staged bytes this group's scan actually read, both
                # sides of the packed-residency split (physical ==
                # logical when packing is off)
                b = cached.batch
                qs.add_staged(b.device_nbytes,
                              int(b.logical_device_nbytes
                                  or b.device_nbytes))
            # harvest the uploaded per-query tables AFTER the dispatch
            # ran: under coalescing the flush (and its H2D upload) can
            # happen on the window-timer thread, after submit returned —
            # harvesting at submit time saw nothing and repeat predicates
            # re-uploaded their [B,T]/[B,T,R,2] tables every dispatch.
            # A fused dispatch uploads the STACKED tables instead, so
            # per-query params exist only when the single-query kernel
            # ran (solo flush or coalescing disabled)
            new_dp = getattr(mq, "_device_params", None)
            if new_dp is not None:
                # the uploaded query tables live in HBM: account them
                # against the batch so the cache_bytes budget sees
                # per-predicate device memory, not just page arrays
                dpb = int(sum(getattr(a, "nbytes", 0) for a in new_dp))
                with self._lock:
                    if pre.get("device_params") is None:
                        pre["device_params"] = new_dp
                        pre["device_params_bytes"] = dpb
                        cached.nbytes += dpb
                        # residency guard (same as the memo eviction): dp
                        # bytes charged to an already-evicted batch would
                        # inflate the budget with memory the next
                        # eviction can never reclaim
                        if self._cache.get(gkey) is cached:
                            self._cache_total += dpb
                            self._evict_hbm_locked()
            inspected -= pre["entries_skipped"]
            results.metrics.inspected_blocks += pre["inspected_blocks"]
            results.metrics.inspected_bytes += pre["inspected_bytes"]
            results.metrics.truncated_entries += pre["truncated"]
            results.metrics.inspected_traces += max(0, inspected)
            for m in self.engine.results(cached.batch, mq, scores, idx):
                results.add(m)
            if agg_counts:
                results.add_agg(mq.agg_stage.decode(agg_counts[0]))
            stages["drain"] += _time.perf_counter() - t0

        def _skip_reason_counts(skip, reasons) -> dict:
            """reason -> count for the skipped blocks: the header prune
            knows why (time_range/duration); anything skipped beyond it
            was dictionary-pruned (no value can satisfy a term)."""
            out: dict = {}
            for s, r in zip(skip, reasons):
                if s:
                    key = r or "dict"
                    out[key] = out.get(key, 0) + 1
            return out

        def prepare(group, holder, skip, reasons,
                    host_only: bool = False) -> dict:
            """O(group) predicate work, memoized per (batch, predicate):
            per-block compile + metric sums. `skip` is the header-prune
            list (already computed for the pre-staging fast path);
            `reasons` its why-column, carried into the per-query stats'
            skipped-blocks breakdown. `holder` is the staged BlockBatch
            on the device path or the HostBatch on the breaker's
            host-fallback route (both carry .blocks and memoize the
            dictionary grouping); `host_only` keeps the compile off the
            device entirely (see compile_multi)."""
            mq = compile_multi(list(holder.blocks), req,
                               skip=skip, cache_on=holder,
                               host_only=host_only)
            if mq is None:
                return {"all_skip": True, "skipped": len(group),
                        "skip_reasons": _skip_reason_counts(
                            [True] * len(group), reasons)}
            # structural plan (gated: structural_query reads ONE
            # attribute when search_structural_enabled is off). Compiled
            # per (batch, predicate) and memoized with this pre dict; the
            # host route compiles its own host-only twin (range tables,
            # no staged dictionary — byte-identical verdicts).
            st = None
            expr = _structural.structural_query(req)
            if expr is not None:
                blocks = list(holder.blocks)
                st = _structural.compile_structural(
                    expr, blocks, cache_on=holder,
                    staged_dicts=(None if host_only else
                                  getattr(holder, "staged_dicts", None)),
                    host_only=host_only,
                    entry_kv_slots=blocks[0].geometry.kv_per_entry)
            # dictionary-pruned jobs (term key -1 across all terms) count
            # as skipped; under the exhaustive flag nothing is skipped —
            # every page is scanned by definition
            if not exhaustive and mq.n_terms:
                dict_pruned = (mq.term_keys == -1).all(axis=1)
                skip = [s or bool(dict_pruned[i])
                        for i, s in enumerate(skip)]
            pre = {
                "skip_reasons": _skip_reason_counts(skip, reasons),
                "all_skip": False,
                "term_keys": mq.term_keys,
                "val_ranges": mq.val_ranges,
                "val_hits": mq.val_hits,
                "block_group": mq.block_group,
                "structural": st,
                "n_terms": mq.n_terms,
                "dur_lo": mq.dur_lo, "dur_hi": mq.dur_hi,
                "win_start": mq.win_start, "win_end": mq.win_end,
                "skipped": sum(skip),
                "entries_skipped": sum(
                    j.n_entries for j, s in zip(group, skip) if s),
                "inspected_blocks": sum(1 for s in skip if not s),
                "inspected_bytes": sum(
                    j.bytes_est for j, s in zip(group, skip) if not s),
                # write-time kv-slot truncation surfaces on the query it
                # may have falsified; attributed to the page-0 job so a
                # block split across range jobs counts once
                "truncated": sum(
                    int(j.header.get("truncated_entries", 0) or 0)
                    for j, s in zip(group, skip)
                    if not s and j.key[1] == 0),
            }
            return pre

        sig = _predicate_sig(req)
        # ?agg= opt-in (gated: one attribute read + one dict probe while
        # analytics is off). The AggStage itself is staged lazily at
        # dispatch time, memoized per batch — prepare() memos stay
        # shareable with non-agg requests because `pre` carries no agg
        # state
        want_agg = ANALYTICS.enabled and agg_requested(req)

        def host_route(group, gkey, hdr_reasons, book_skips=True):
            """Scan one group ENTIRELY on the host path: this member is
            not the group's owner (owner-routed HBM), the breaker is
            open/half-open without a probe token, or this group's device
            dispatch already faulted (drain resubmit). Host-tier staging
            (no device put), host-only compile (range tables), the same
            kernel pinned to the CPU backend — results byte-identical to
            the device route (see host_scan). Accounting mirrors the
            device drain, with bytes booked placement=host: the answer
            is COMPLETE, not partial — only the placement moved.
            `book_skips=False` on resubmit paths whose main-loop pass
            already counted this group's skipped blocks/reasons —
            re-booking would inflate skipped_blocks and break the
            wedged-vs-healthy identity whenever a block dict-prunes."""
            t0 = _time.perf_counter()
            try:
                host = self._host_batch(group)
                skip = [r is not None for r in hdr_reasons]
                hq = getattr(host, "_host_query_cache", None)
                if hq is None:
                    hq = host._host_query_cache = OrderedDict()
                with self._lock:
                    pre = hq.get(sig)
                    if pre is not None:
                        hq.move_to_end(sig)
                if pre is None:
                    pre = prepare(group, host, skip, hdr_reasons,
                                  host_only=True)
                    with self._lock:
                        hq[sig] = pre
                        while len(hq) > _QUERY_CACHE_MAX:
                            hq.popitem(last=False)
                if qs is not None:
                    qs.add_cache("device_fallback")
                    if book_skips:
                        for r, n in pre.get("skip_reasons", {}).items():
                            qs.add_skip(r, n)
                if book_skips:
                    results.metrics.skipped_blocks += pre.get("skipped", 0)
                if pre["all_skip"]:
                    return
                from .multiblock import MultiQuery

                mq = MultiQuery(
                    term_keys=pre["term_keys"],
                    val_ranges=pre["val_ranges"],
                    dur_lo=pre["dur_lo"], dur_hi=pre["dur_hi"],
                    win_start=pre["win_start"], win_end=pre["win_end"],
                    limit=req.limit or 20, n_terms=pre["n_terms"],
                    structural=pre.get("structural"))
                if want_agg:
                    mq.agg_stage = ANALYTICS.stage_for_batch(host)
                if qs is not None and pre.get("structural") is not None:
                    qs.add_structural(pre["structural"])
                count, inspected, scores, idx, *agg_counts = host_scan(
                    host, mq, resolve_top_k(self.engine.top_k, mq.limit))
                # the CPU-pinned copies host_scan memoized are real RAM:
                # charge them to the host-tier budget (evicting the
                # entry releases both — _load_host subtracts the
                # recorded cpu bytes alongside nbytes). Delta-charged:
                # the span-column memo (_cpu_span_staged) can appear on
                # a LATER structural query after the cat arrays were
                # already charged, and it must not pin unaccounted RAM.
                cpu_b = sum(
                    int(a.nbytes)
                    for memo in (getattr(host, "_cpu_staged", None),
                                 getattr(host, "_cpu_span_staged", None))
                    if memo is not None for a in memo.values())
                if cpu_b:
                    with self._lock:
                        if self._host_cache.get(gkey) is host:
                            prev = self._cpu_staged_bytes.get(gkey, 0)
                            if cpu_b > prev:
                                self._cpu_staged_bytes[gkey] = cpu_b
                                self._host_total += cpu_b - prev
                                self._evict_host_locked()
                                self._publish_gauges_locked()
                obs.scan_dispatches.inc(mode="host_fallback")
                inspected -= pre["entries_skipped"]
                results.metrics.inspected_blocks += pre["inspected_blocks"]
                results.metrics.inspected_bytes += pre["inspected_bytes"]
                results.metrics.truncated_entries += pre["truncated"]
                results.metrics.inspected_traces += max(0, inspected)
                if qs is not None:
                    qs.add_inspected(blocks=pre["inspected_blocks"],
                                     nbytes=pre["inspected_bytes"],
                                     placement="host")
                    qs.add_staged(host.cat_nbytes,
                                  int(host.cat_logical_nbytes
                                      or host.cat_nbytes))
                for m in self.engine.results(host, mq, scores, idx):
                    results.add(m)
                if agg_counts:
                    results.add_agg(mq.agg_stage.decode(agg_counts[0]))
            finally:
                stages["host_fallback"] += _time.perf_counter() - t0

        def hdr_reasons_for(group):
            """Header-only prune BEFORE staging: a decidably-dead group
            (time window, tag rollup) costs no IO and no HBM. Returns
            the per-job skip REASON list (None = scan it) — truthiness
            keeps `all(...)`/`any(...)` semantics of the old bool list
            while the why survives into the query stats. Memoized so
            repeats are O(1)."""
            t0 = _time.perf_counter()
            try:
                return _hdr_reasons_for(group)
            finally:
                stages["header_prune"] += _time.perf_counter() - t0

        def _hdr_reasons_for(group):
            gkey = tuple(j.key for j in group)
            with self._lock:
                reasons = self._prune_cache.get((gkey, sig))
                if reasons is not None:
                    self._prune_cache.move_to_end((gkey, sig))
                    return reasons
            reasons = [block_header_skip_reason(j.header, req)
                       for j in group]
            with self._lock:
                self._prune_cache[(gkey, sig)] = reasons
                while len(self._prune_cache) > _PRUNE_CACHE_MAX:
                    self._prune_cache.popitem(last=False)
            return reasons

        prefetched: dict = {}

        def submit_prefetch(from_idx):
            """One-slot staging lookahead: stage the NEXT live group in a
            background thread while this group's kernel runs — H2D
            overlaps compute (double-buffering; _staged's dedupe makes a
            racing inline stage safe). The cache event is judged NOW:
            by the time the main loop reaches a prefetched group, the
            prefetch has inserted it into the caches and residency
            would misread this query's own cold stage as a hit."""
            if robustness.BREAKER.blocking():
                return  # no lookahead H2D at a blocked device
            for gi in range(from_idx, len(groups)):
                g = groups[gi]
                if all(hdr_reasons_for(g)):
                    continue
                k = tuple(j.key for j in g)
                if OWNERSHIP.enabled:
                    if not OWNERSHIP.owns_group(k):
                        continue  # non-owned: host route, never staged
                with self._lock:
                    resident = k in self._cache
                    host_res = k in self._host_cache
                if not resident and k not in prefetched:
                    prefetched[k] = (
                        self._prefetcher.submit(self._staged, g),
                        "hbm_miss_host_hit" if host_res
                        else "hbm_miss_cold")
                return

        # HBM-resident groups dispatch FIRST: an evicted group's re-stage
        # (H2D-bound, ~seconds through the relay) then overlaps the
        # residents' scans via the lookahead instead of serializing in
        # front of them — and an early-quit on the limit can skip the
        # transfer entirely (VERDICT r4 #2). Deliberate tradeoff: under
        # an early-quit the SCANNED subset (and so the returned set when
        # limit truncates) depends on cache residency — same stance as
        # the reference's goroutine fan-out, where the quit channel
        # freezes whichever jobs happened to finish first
        # (modules/frontend/searchsharding.go + results.go quit).
        with self._lock:
            _res = set(self._cache)
        if 0 < len(_res):
            groups = sorted(
                groups, key=lambda g: tuple(j.key for j in g) not in _res)

        with tracing.start_span("batcher.Search") as span:
            for gi, group in enumerate(groups):
                if results.complete:
                    break
                if robustness.deadline.expired():
                    # the request's budget is gone: stop queueing more
                    # sub-scans behind whatever is slow (a dead device,
                    # a cold cache) — the answer goes out PARTIAL now
                    results.metrics.partial = True
                    obs.partial_results.inc(reason="deadline")
                    break
                gkey = tuple(j.key for j in group)
                hdr_reasons = hdr_reasons_for(group)
                if all(hdr_reasons):
                    results.metrics.skipped_blocks += len(group)
                    if qs is not None:
                        for r in hdr_reasons:
                            qs.add_skip(r)
                    continue
                if OWNERSHIP.enabled:
                    # owner-routed HBM: a group this member doesn't own
                    # serves from the byte-identical host route — a
                    # non-owner never stages a duplicate device copy
                    # (docs/search-hbm-ownership.md); the owner's serve
                    # proceeds below, device-resident. Every served
                    # group feeds the heat table (one attribute read
                    # while replication is off): the batcher's dispatch
                    # loop is the one site that observes every scan,
                    # and a group crossing hot_rate here promotes to
                    # its replica set for hedged dispatch
                    OWNERSHIP.record_access(str(gkey[0][0]))
                    if not OWNERSHIP.owns_group(gkey):
                        obs.hbm_owner_routed.inc(route="non_owner_host")
                        if qs is not None:
                            qs.add_cache("non_owner_route")
                        host_route(group, gkey, hdr_reasons)
                        continue
                if not robustness.BREAKER.allow_device():
                    # breaker open (or half-open with its probe tokens
                    # spent): this group runs the byte-identical host
                    # route — no staging put, no device dispatch
                    host_route(group, gkey, hdr_reasons)
                    continue
                if OWNERSHIP.enabled:
                    # counted AFTER the breaker gate: route=owner means
                    # a device-resident serve, and during a wedged-owner
                    # incident the owned groups above fell into the
                    # breaker's host route instead
                    obs.hbm_owner_routed.inc(route="owner")
                # memo lookup needs the staged batch's identity; the memo
                # itself lives on the cached batch so it dies with it
                t0 = _time.perf_counter()
                pf = prefetched.pop(gkey, None)
                fut_staged, pf_event = pf if pf is not None else (None, None)
                if qs is not None:
                    # cache behavior as THIS query saw it (the global
                    # batch_cache_events counters can't say whose re-stage
                    # it was). A prefetched group carries the event judged
                    # at SUBMIT time — its own lookahead has since
                    # inserted the batch, so reading residency here would
                    # report this query's cold stage as a hit.
                    if pf_event is not None:
                        _event = pf_event
                    else:
                        with self._lock:
                            _event = ("hbm_hit" if gkey in self._cache
                                      else ("hbm_miss_host_hit"
                                            if gkey in self._host_cache
                                            else "hbm_miss_cold"))
                try:
                    cached = (fut_staged.result()
                              if fut_staged is not None
                              else self._staged(group))
                except robustness.DeviceFault:
                    # the staging H2D hit the wedged device (fault
                    # booked): host tier already holds the stacked
                    # arrays, answer from there
                    stages["staging"] += _time.perf_counter() - t0
                    host_route(group, gkey, hdr_reasons)
                    continue
                stages["staging"] += _time.perf_counter() - t0
                if qs is not None:
                    qs.add_cache(_event)
                    if _event != "hbm_hit" and cached.batch.staged_dicts:
                        qs.add_cache("probe_dict_staged",
                                     len(cached.batch.staged_dicts))
                with self._lock:
                    cached.pins += 1
                pinned.append(cached)
                submit_prefetch(gi + 1)
                with self._lock:
                    pre = cached.query_cache.get(sig)
                    if pre is not None:
                        cached.query_cache.move_to_end(sig)
                if pre is None:
                    t0 = _time.perf_counter()
                    # attributed: query compilation can fire the device
                    # dictionary probe (mode=dict_probe) — that dispatch
                    # belongs to this query's bill (no wall fallback:
                    # most of prepare() is host compile work)
                    with query_stats.attributed_dispatch(
                            qs, fallback_wall=False):
                        pre = prepare(group, cached.batch,
                                      [r is not None for r in hdr_reasons],
                                      hdr_reasons)
                    stages["prepare"] += _time.perf_counter() - t0
                    with self._lock:
                        cached.query_cache[sig] = pre
                        while len(cached.query_cache) > _QUERY_CACHE_MAX:
                            _, old = cached.query_cache.popitem(last=False)
                            dpb = old.get("device_params_bytes", 0)
                            cached.nbytes -= dpb
                            # the shared budget only tracks batches still
                            # resident: a concurrent eviction already
                            # removed cached.nbytes (dp bytes included)
                            # wholesale, so adjusting again would
                            # double-subtract and drift the budget
                            if self._cache.get(gkey) is cached:
                                self._cache_total -= dpb
                if qs is not None:
                    for r, n in pre.get("skip_reasons", {}).items():
                        qs.add_skip(r, n)
                if pre["all_skip"]:
                    results.metrics.skipped_blocks += pre["skipped"]
                    continue
                from .multiblock import MultiQuery

                mq = MultiQuery(
                    term_keys=pre["term_keys"], val_ranges=pre["val_ranges"],
                    dur_lo=pre["dur_lo"], dur_hi=pre["dur_hi"],
                    win_start=pre["win_start"], win_end=pre["win_end"],
                    limit=req.limit or 20, n_terms=pre["n_terms"],
                    val_hits=pre.get("val_hits"),
                    block_group=pre.get("block_group"),
                    structural=pre.get("structural"))
                if want_agg:
                    # memoized per batch: repeat ?agg= queries over a
                    # resident batch pay one attribute read, and every
                    # route (direct, coalesced, host resubmit) decodes
                    # against the same service table
                    mq.agg_stage = ANALYTICS.stage_for_batch(cached.batch)
                if qs is not None and pre.get("structural") is not None:
                    # explain plan registration: node cost weights merge
                    # across this query's groups; measured device time
                    # apportions over them at finalize
                    qs.add_structural(pre["structural"])
                dp = pre.get("device_params")
                if dp is not None:
                    # repeated predicates reuse the H2D-uploaded query
                    # tables — a [B,T] table for 10K blocks re-uploaded
                    # per dispatch costs real ms through a relay
                    mq._device_params = dp
                results.metrics.skipped_blocks += pre["skipped"]
                t0 = _time.perf_counter()
                if self.coalescer is not None:
                    # concurrent peers hitting this batch within the
                    # window share ONE fused kernel launch; a dispatch
                    # with no possible same-batch peer (solo search, or
                    # a sibling sub-request over a disjoint batch) flushes
                    # immediately (no added latency). Structural queries
                    # group by PLAN SHAPE inside submit(): same-plan
                    # peers stack along the fused query axis when
                    # search_structural_stack_enabled, anything else
                    # flushes solo (stack_events says which).
                    with self._lock:
                        peers = (self._interest.get(gkey, 1)
                                 + self._unplanned)
                    fut = self.coalescer.submit(
                        cached.batch, mq,
                        resolve_top_k(self.engine.top_k, mq.limit),
                        peers=peers)
                else:
                    try:
                        with query_stats.attributed_dispatch(qs):
                            fut = self.engine.scan_async(cached.batch, mq)
                        start_fetch(fut)  # D2H begins now, overlapping
                    except robustness.DeviceFault:
                        # direct-path dispatch died at submit (fault
                        # booked): answer this group on host NOW — its
                        # skips were already counted above, so the
                        # resubmit must not re-book them. Interest for
                        # this gkey is released by the outer finally.
                        stages["dispatch"] += _time.perf_counter() - t0
                        host_route(group, gkey, hdr_reasons,
                                   book_skips=False)
                        continue
                stages["dispatch"] += _time.perf_counter() - t0
                dispatches += 1
                inflight.append((gkey, cached, mq, pre, fut))
                # this search never returns to this batch: release its
                # interest NOW so later peers don't arm windows for a
                # fusion that can no longer happen (a parked query still
                # fuses — joiners find the pending group itself, not the
                # hint). The outer finally releases whatever never
                # dispatched (skipped groups, early quit)
                with self._lock:
                    n = self._interest.get(gkey, 0) - 1
                    if n <= 0:
                        self._interest.pop(gkey, None)
                    else:
                        self._interest[gkey] = n
                try:
                    interest.remove(gkey)
                except ValueError:
                    pass
                while len(inflight) >= self.pipeline_depth:
                    drain_one()
            while inflight:
                if results.complete:
                    inflight.clear()
                    break
                drain_one()
            # early quit leaves a lookahead pending: cancel it so a
            # not-yet-started stage doesn't burn IO+decompress+H2D (and
            # possibly evict a hotter batch) for a group nobody needs; an
            # already-running one completes harmlessly via _staged dedupe
            for f, _ev in prefetched.values():
                f.cancel()
            span.set_attributes(groups=len(groups), scan_dispatches=dispatches,
                                inspected_blocks=results.metrics.inspected_blocks,
                                skipped_blocks=results.metrics.skipped_blocks)
        if self.coalescer is None:
            # with the coalescer active the LAUNCH counters are kept at
            # flush time (mode="batched" solo, mode="coalesced" fused) —
            # counting submits here would double-book shared launches
            obs.scan_dispatches.inc(dispatches, mode="batched")
        if qs is not None:
            for k, v in stages.items():
                qs.add_stage(k, v)
        self.last_dispatches = dispatches
        self.last_scan = {
            "total_ms": round((_time.perf_counter() - t_search0) * 1000, 3),
            "stages_ms": {k: round(v * 1000, 3) for k, v in stages.items()},
            "scan_dispatches": dispatches,
            "groups": len(groups),
            "inspected_blocks": results.metrics.inspected_blocks,
            "skipped_blocks": results.metrics.skipped_blocks,
        }
        return results

    def debug_stats(self) -> dict:
        """Operator-facing snapshot for /debug/scan: the last search's
        per-stage breakdown plus cache occupancy — the numbers that
        answer "why is this query slow" without a profiler attached."""
        with self._lock:
            return {
                "last_scan": getattr(self, "last_scan", None),
                "hbm_cache": {
                    "batches": len(self._cache),
                    "bytes": self._cache_total,
                    "logical_bytes": self._cache_logical,
                    "budget_bytes": self.cache_bytes,
                },
                "host_cache": {
                    "batches": len(self._host_cache),
                    "bytes": self._host_total,
                    "logical_bytes": self._host_logical,
                    "budget_bytes": self.host_cache_bytes,
                },
                "memo": {
                    "prune_entries": len(self._prune_cache),
                    "plan_entries": len(self._plan_cache),
                    "warmed_shapes": len(self._warmed_shapes),
                },
                "coalesce": (self.coalescer.stats()
                             if self.coalescer is not None else None),
            }
