"""Serving-path batch scanning: many blocks (or page ranges), few kernels.

This is where the TPU economics land in the serving path. The reference's
production search IS its job fan-out — one goroutine per 10 MiB page range
(modules/frontend/searchsharding.go:163-306, tempodb/pool) — because on
CPU the per-job cost is the scan itself. On TPU the per-dispatch overhead
(host sync + kernel launch through a relay, ~ms) dwarfs the scan of a
single block, so the batcher inverts the shape: jobs GROUP into batches
whose pages stack along the device page axis and scan in ONE kernel call
(`multiblock.multi_scan_kernel`; with a mesh, the shard_map variant whose
collectives replace the Results funnel).

Properties the grouping keeps:
- **stable AND churn-local**: jobs sort by (block id, page range) and
  group boundaries are content-defined — a job starts a new group based
  only on a stable hash of its own key (like content-defined chunking in
  dedup stores) — so the same blocklist yields the same groups query
  after query, and a block arriving or leaving the blocklist reshapes
  only its own neighborhood up to the next hash anchor: O(1) cached
  batches invalidate per poll instead of every group downstream of the
  new uuid's sort position.
- **bucketed**: only jobs sharing page geometry (E entries/page, C kv
  slots) stack together — static shapes per bucket mean XLA compiles once
  per (bucket, n_terms, top_k).
- **prune-aware without cache churn**: header- or dictionary-pruned jobs
  stay IN the staged batch (composition never depends on the query); the
  compiled query neutralizes them (key id -1 → no page can match) and
  their entries are subtracted from inspected counts on the host.
- **pipelined with early quit**: group i+1 stages + dispatches while
  group i's results transfer; dispatch stops once the result limit is met
  (reference results.go:38-78 quit channel).
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from dataclasses import dataclass, field

import numpy as np

from tempo_tpu.observability import metrics as obs
from tempo_tpu.observability import tracing

from .engine import DEFAULT_TOP_K, start_fetch
from .multiblock import MultiBlockEngine, compile_multi
from .pipeline import matches_block_header
from .results import SearchResults


@dataclass
class ScanJob:
    """One schedulable scan unit: a page range of one block's search
    container (whole block = range [0, n_pages))."""
    key: tuple              # (block_id, start_page, n_pages) — cache identity
    pages_fn: object        # () -> ColumnarPages for this range (host)
    header: dict            # search-header rollup (pruning + sizes)
    n_pages: int
    n_entries: int
    geometry: tuple         # (entries_per_page, kv_per_entry) bucket key
    meta: object = None     # BlockMeta, for diagnostics

    @property
    def bytes_est(self) -> int:
        """Share of the block's compressed bytes this job covers — the
        inspected_bytes accounting unit (reference results.go metrics)."""
        total = max(1, self.header.get("n_pages", self.n_pages))
        return int(self.header.get("compressed_size", 0) * self.n_pages / total)


@dataclass
class _CachedBatch:
    batch: object           # multiblock.BlockBatch
    nbytes: int
    jobs: list = field(default_factory=list)


class BlockBatcher:
    """Groups ScanJobs into staged device batches and runs searches over
    them. Thread-safe; one instance per TempoDB."""

    def __init__(self, mesh=None, top_k: int = DEFAULT_TOP_K,
                 max_batch_pages: int = 4096,
                 cache_bytes: int = 4 << 30,
                 pipeline_depth: int = 2,
                 io_workers: int = 8):
        self.engine = MultiBlockEngine(top_k=top_k, mesh=mesh)
        self.max_batch_pages = max_batch_pages
        self.cache_bytes = cache_bytes
        self.pipeline_depth = max(1, pipeline_depth)
        self.io_workers = io_workers
        self._cache: OrderedDict[tuple, _CachedBatch] = OrderedDict()
        self._cache_total = 0
        self._staging: dict[tuple, threading.Event] = {}
        self._lock = threading.Lock()
        self.last_dispatches = 0  # diagnostics: kernel calls in last search

    # ------------------------------------------------------------------
    # planning

    def _cuts(self, j: ScanJob) -> bool:
        """Content-defined group boundary: depends ONLY on this job's key
        and size, never on neighbors, so group composition is a local
        property. Cut probability 1/divisor makes the expected group
        ~max_batch_pages/2, leaving headroom so churn rarely propagates
        through the hard page cap to the next anchor. plan() additionally
        guards cuts behind a min group size (max_batch_pages/4, the CDC
        min-chunk-size trick) so groups never fragment below batching
        efficiency."""
        import zlib

        divisor = max(2, self.max_batch_pages // (2 * max(1, j.n_pages)))
        return zlib.crc32(repr(j.key).encode()) % divisor == 0

    def plan(self, jobs: list[ScanJob]) -> list[list[ScanJob]]:
        buckets: dict[tuple, list[ScanJob]] = {}
        for j in sorted(jobs, key=lambda j: j.key):
            buckets.setdefault(j.geometry, []).append(j)
        groups = []
        for _geo, js in sorted(buckets.items()):
            cur: list[ScanJob] = []
            cur_pages = 0
            min_pages = self.max_batch_pages // 4
            for j in js:
                if cur and (cur_pages + j.n_pages > self.max_batch_pages
                            or (cur_pages >= min_pages and self._cuts(j))):
                    groups.append(cur)
                    cur, cur_pages = [], 0
                cur.append(j)
                cur_pages += j.n_pages
            if cur:
                groups.append(cur)
        return groups

    # ------------------------------------------------------------------
    # staging cache

    def _staged(self, group: list[ScanJob]) -> _CachedBatch:
        key = tuple(j.key for j in group)
        while True:
            with self._lock:
                hit = self._cache.get(key)
                if hit is not None:
                    self._cache.move_to_end(key)
                    obs.batch_cache_events.inc(result="hit")
                    return hit
                ev = self._staging.get(key)
                if ev is None:
                    # we are the stager for this key
                    ev = self._staging[key] = threading.Event()
                    break
            # another thread is staging this exact group: wait for it
            # rather than duplicating the IO+decompress+H2D (and
            # transiently doubling HBM for the batch)
            ev.wait()
        try:
            # load host pages outside the lock (IO + decompress dominate)
            import concurrent.futures

            if len(group) > 1:
                with concurrent.futures.ThreadPoolExecutor(
                    max_workers=min(self.io_workers, len(group))
                ) as ex:
                    pages = list(ex.map(lambda j: j.pages_fn(), group))
            else:
                pages = [group[0].pages_fn()]
            batch = self.engine.stage(pages)
            nbytes = int(sum(int(a.nbytes) for a in batch.device.values()))
            entry = _CachedBatch(batch=batch, nbytes=nbytes, jobs=list(group))
            with self._lock:
                obs.batch_cache_events.inc(result="miss")
                prev = self._cache.pop(key, None)
                if prev is not None:
                    self._cache_total -= prev.nbytes
                self._cache[key] = entry
                self._cache_total += nbytes
                while self._cache_total > self.cache_bytes and len(self._cache) > 1:
                    _, old = self._cache.popitem(last=False)
                    self._cache_total -= old.nbytes
            return entry
        finally:
            with self._lock:
                self._staging.pop(key, None)
            ev.set()

    def invalidate(self, live_block_ids: set[str]) -> None:
        """Drop cached batches containing blocks no longer in the
        blocklist (called from the poll loop)."""
        with self._lock:
            dead = [k for k in self._cache
                    if any(jk[0] not in live_block_ids for jk in k)]
            for k in dead:
                self._cache_total -= self._cache.pop(k).nbytes

    # ------------------------------------------------------------------
    # search

    def search(self, jobs: list[ScanJob], req,
               results: SearchResults | None = None) -> SearchResults:
        """Run the request over all jobs: group → stage → compile →
        dispatch (pipelined, early-quitting) → merge."""
        from .pipeline import is_exhaustive

        results = results or SearchResults.for_request(req)
        exhaustive = is_exhaustive(req)
        groups = self.plan(jobs)
        inflight: deque = deque()
        dispatches = 0

        def drain_one():
            cached, mq, skip, fut = inflight.popleft()
            count, inspected, scores, idx = fut
            inspected = int(inspected)
            for j, sk in zip(cached.jobs, skip):
                if sk:
                    inspected -= j.n_entries
                    continue
                results.metrics.inspected_blocks += 1
                results.metrics.inspected_bytes += j.bytes_est
                if j.key[1] == 0:
                    # write-time kv-slot truncation surfaces on the query
                    # it may have falsified; attributed to the page-0 job
                    # so a block split across range jobs counts once
                    results.metrics.truncated_entries += int(
                        j.header.get("truncated_entries", 0) or 0)
            results.metrics.inspected_traces += max(0, inspected)
            for m in self.engine.results(cached.batch, mq,
                                         np.asarray(scores), np.asarray(idx)):
                results.add(m)

        with tracing.start_span("batcher.Search") as span:
            for group in groups:
                if results.complete:
                    break
                skip = [not matches_block_header(j.header, req) for j in group]
                if all(skip):
                    # decidable from headers alone — no staging, no device
                    results.metrics.skipped_blocks += len(group)
                    continue
                cached = self._staged(group)
                mq = compile_multi([b for b in cached.batch.blocks], req,
                                   skip=skip)
                if mq is None:
                    # every job in the group pruned before any device work
                    results.metrics.skipped_blocks += len(group)
                    continue
                # dictionary-pruned jobs (term key -1 across all terms)
                # count as skipped; under the exhaustive flag nothing is
                # skipped — every page is scanned by definition
                if not exhaustive:
                    for i, j in enumerate(group):
                        if not skip[i] and mq.n_terms and np.all(
                            mq.term_keys[i] == -1
                        ):
                            skip[i] = True
                results.metrics.skipped_blocks += sum(skip)
                fut = self.engine.scan_async(cached.batch, mq)
                start_fetch(fut)  # D2H begins now, overlapping next groups
                dispatches += 1
                inflight.append((cached, mq, skip, fut))
                while len(inflight) >= self.pipeline_depth:
                    drain_one()
            while inflight:
                if results.complete:
                    inflight.clear()
                    break
                drain_one()
            span.set_attributes(groups=len(groups), scan_dispatches=dispatches,
                                inspected_blocks=results.metrics.inspected_blocks,
                                skipped_blocks=results.metrics.skipped_blocks)
        obs.scan_dispatches.inc(dispatches, mode="batched")
        self.last_dispatches = dispatches
        return results
