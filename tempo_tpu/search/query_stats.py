"""Per-query execution inspector: what did THIS query cost, and whose
device time was it?

PR 5 profiles every device *dispatch* and PR 6 turned those aggregates
into offload policy, but a coalesced Q-way dispatch serves N queries
from M tenants and all of its execute/h2d/compile time lands in
anonymous process aggregates. This module threads a ``QueryStats``
context through the whole search path — api/http → frontend fan-out →
querier → TempoDB → batcher/coalescer/engines → planner/dict probe —
so every request accumulates:

  - blocks scanned vs skipped, with the skip REASON (time-range,
    duration rollup, dictionary prune, meta window);
  - bytes inspected split host vs device (device kernels vs fallback
    proto scans + host dictionary probes);
  - staging-cache behavior as THIS query saw it (HBM hit vs re-stage,
    host-tier hit, probe-dict staging);
  - planner decisions taken while compiling it (target + predicted ms);
  - per-stage device-seconds attributed from its dispatches. A fused
    coalesced dispatch apportions each stage across its member queries
    by their padded predicate-table rows, with a conservation
    invariant: the attributed shares sum exactly to the dispatch total
    (the last member takes the float remainder).

Surfaces:

  - opt-in explain (``?explain=1`` / ``X-Tempo-Explain`` → SearchRequest
    .explain): the full breakdown rides SearchResponse.metrics
    .query_stats_json across process boundaries and the HTTP layer
    inlines it as a JSON object;
  - a structured slow-query log: one rate-limited JSON line per query
    slower than ``search_slow_query_log_s`` (tenant, self-trace id,
    complete stats);
  - ``/debug/querystats``: recent ring + per-tenant aggregates + top-K
    by device-seconds and by bytes;
  - per-tenant accounting metrics
    ``tempo_search_query_device_seconds_total{tenant}``,
    ``tempo_search_query_bytes_inspected_total{tenant,placement}`` and
    the ``tempo_search_query_stage_seconds{stage}`` histogram (whose
    OpenMetrics exemplars link buckets to self-traces, the PR 5
    plumbing).

Noop contract (same stance as the dispatch profiler):
``search_query_stats_enabled: false`` creates no QueryStats at all —
call sites read one contextvar, get ``None``, and branch out; results
are byte-identical either way (bench phase ``query_stats_overhead``
asserts the record protocol stays under 2% of a dispatch).

Scopes: the execution layer (TempoDB.search / search_block /
search_blocks — the querier processes, where kernels actually run)
books scope="exec" stats, which feed the per-tenant counters and
tenant aggregates; the frontend books one scope="request" entry per
external request (merged from its sub-responses) for the ring and the
slow-query log, WITHOUT re-booking counters — in single-binary mode
both layers share this registry and double counting would follow.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import threading
import time
from collections import deque

from tempo_tpu.observability import metrics as obs
from tempo_tpu.observability.flightrecorder import (RECORDER,
                                                    TRIGGER_SLOW_QUERY)
from tempo_tpu.observability.log import TenantTokenBucket, get_logger
from tempo_tpu.observability.selftrace import SELFTRACE

log = get_logger("tempo_tpu.querystats")
slow_log = get_logger("tempo_tpu.slowquery")

_ACTIVE: contextvars.ContextVar = contextvars.ContextVar(
    "tempo_query_stats", default=None)
# True on threads executing sub-requests FOR an in-process frontend
# (QueryFrontend wraps its worker-pool jobs in fronted()): exec-scope
# records born there suppress their own slow-query log line — the
# frontend's request-scope line covers the query, and two lines per
# offender would halve the limiter's effective rate
_FRONTED: contextvars.ContextVar = contextvars.ContextVar(
    "tempo_query_fronted", default=False)

_TOP_K = 10  # per-ranking entries kept for /debug/querystats


class QueryStats:
    """One query's accumulating execution record. Thread-safe: fused
    dispatch attribution arrives from coalescer flush threads while the
    owning search thread keeps draining."""

    __slots__ = ("tenant", "scope", "query", "trace_id",
                 "t0", "wall_s", "blocks_inspected", "skipped",
                 "bytes_host", "bytes_device", "cache", "stages",
                 "device_stages", "h2d_bytes", "dispatches",
                 "fused_dispatches", "coalesced_with", "planner",
                 "host_probe", "subqueries", "fronted",
                 "staged_physical", "staged_logical", "structural",
                 "_lock")

    def __init__(self, tenant: str, scope: str = "exec",
                 query: dict | None = None):
        from tempo_tpu.observability import tracing

        self.tenant = tenant
        self.scope = scope
        self.query = query or {}
        span = tracing.current_span()
        self.trace_id = (span.context.trace_id.hex()
                         if span.recording else None)
        self.t0 = time.perf_counter()
        self.wall_s = 0.0
        self.blocks_inspected = 0
        self.skipped: dict[str, int] = {}
        self.bytes_host = 0
        self.bytes_device = 0
        self.cache: dict[str, int] = {}
        self.stages: dict[str, float] = {}        # host-side wall stages
        self.device_stages: dict[str, float] = {}  # attributed dispatch
        self.h2d_bytes = 0                         # attributed h2d share
        self.dispatches = 0
        self.fused_dispatches = 0
        self.coalesced_with = 0   # peer queries sharing my dispatches
        self.planner = {"host": 0, "device": 0, "predicted_ms": 0.0}
        self.host_probe = {"count": 0, "seconds": 0.0, "bytes": 0}
        # staged bytes this query's scans read, both sides of the
        # packed-residency split (search/packing.py): physical = bytes
        # as resident (packed), logical = the unpacked equivalent
        self.staged_physical = 0
        self.staged_logical = 0
        # structural plan registration (search/structural.py): node id
        # -> {op, detail, est_bytes} accumulated across this query's
        # compiled groups; to_dict() apportions the measured device
        # execute seconds over the byte weights (one fused kernel has no
        # per-node timer — the conserved split follows the same per-byte
        # model the planner calibrates)
        self.structural: dict | None = None
        self.subqueries = 0       # request scope: sub-responses merged
        self.fronted = _FRONTED.get()
        self._lock = threading.Lock()

    # ---- recording (each O(1), called per group / per dispatch) ----

    def add_skip(self, reason: str, n: int = 1) -> None:
        with self._lock:
            self.skipped[reason] = self.skipped.get(reason, 0) + n

    def add_inspected(self, blocks: int = 0, nbytes: int = 0,
                      placement: str = "device") -> None:
        with self._lock:
            self.blocks_inspected += blocks
            if placement == "device":
                self.bytes_device += nbytes
            else:
                self.bytes_host += nbytes

    def add_cache(self, event: str, n: int = 1) -> None:
        with self._lock:
            self.cache[event] = self.cache.get(event, 0) + n

    def add_stage(self, name: str, seconds: float) -> None:
        with self._lock:
            self.stages[name] = self.stages.get(name, 0.0) + seconds

    def add_device_stages(self, stages: dict, h2d_bytes: float = 0,
                          fused_q: int = 1, count: bool = True) -> None:
        """Fold one dispatch's (possibly apportioned) stage share in.
        `fused_q`: how many real queries shared the launch; `count`:
        False for late additions to an already-counted dispatch (the
        drain-side d2h sync). Byte shares stay float so a fused
        dispatch's apportioned bytes conserve to float tolerance."""
        with self._lock:
            for k, v in stages.items():
                self.device_stages[k] = self.device_stages.get(k, 0.0) + v
            self.h2d_bytes += h2d_bytes
            if count:
                self.dispatches += 1
                if fused_q > 1:
                    self.fused_dispatches += 1
                    self.coalesced_with += fused_q - 1

    def add_planner(self, target: str, predicted_s: float) -> None:
        with self._lock:
            self.planner[target] = self.planner.get(target, 0) + 1
            self.planner["predicted_ms"] += predicted_s * 1e3

    def add_staged(self, physical: int, logical: int) -> None:
        """Staged bytes one group's scan read — the bytes-inspected
        physical/logical split the explain breakdown reports."""
        with self._lock:
            self.staged_physical += int(physical)
            self.staged_logical += int(logical)

    def add_host_probe(self, seconds: float, nbytes: int) -> None:
        with self._lock:
            self.host_probe["count"] += 1
            self.host_probe["seconds"] += seconds
            self.host_probe["bytes"] += nbytes

    def add_structural(self, compiled) -> None:
        """Register a compiled structural plan (one per scanned group;
        plans are identical across a query's groups, byte weights sum)."""
        with self._lock:
            if self.structural is None:
                self.structural = {}
            for nid, op, detail in compiled.node_info:
                node = self.structural.get(nid)
                if node is None:
                    node = self.structural[nid] = {
                        "op": op, "detail": detail, "est_bytes": 0}
                node["est_bytes"] += int(compiled.node_bytes.get(nid, 0))

    # ---- derived ----

    @property
    def device_seconds(self) -> float:
        with self._lock:
            return sum(self.device_stages.values())

    def absorb_metrics(self, m) -> None:
        """Request-scope fill from merged proto SearchMetrics when no
        explain breakdowns travelled (explain off): totals only — the
        stage split lives with the executors."""
        with self._lock:
            self.blocks_inspected += int(m.inspected_blocks)
            dev = int(m.inspected_bytes_device)
            self.bytes_device += dev
            self.bytes_host += max(0, int(m.inspected_bytes) - dev)
            if m.device_seconds:
                self.device_stages["total"] = \
                    self.device_stages.get("total", 0.0) + m.device_seconds
            if m.skipped_blocks:
                self.skipped["all"] = \
                    self.skipped.get("all", 0) + int(m.skipped_blocks)

    def merge_child(self, child: dict) -> None:
        """Fold a sub-response's explain dict into a request-scope
        record (numeric leaves sum; the frontend's merge path)."""
        with self._lock:
            self.subqueries += 1
            self.blocks_inspected += int(child.get("blocks_inspected", 0))
            b = child.get("bytes_inspected") or {}
            self.bytes_host += int(b.get("host", 0))
            self.bytes_device += int(b.get("device", 0))
            self.h2d_bytes += int(child.get("h2d_bytes", 0))
            self.dispatches += int(child.get("dispatches", 0))
            self.fused_dispatches += int(child.get("fused_dispatches", 0))
            self.coalesced_with += int(child.get("coalesced_with", 0))
            for d, mine in ((child.get("skipped_blocks"), self.skipped),
                            (child.get("cache"), self.cache)):
                for k, v in (d or {}).items():
                    mine[k] = mine.get(k, 0) + v
            for d, mine in ((child.get("stages_ms"), self.stages),
                            (child.get("device_stages_ms"),
                             self.device_stages)):
                for k, v in (d or {}).items():
                    mine[k] = mine.get(k, 0.0) + v / 1e3
            sb = child.get("staged_bytes") or {}
            self.staged_physical += int(sb.get("physical", 0))
            self.staged_logical += int(sb.get("logical", 0))
            for k, v in (child.get("planner") or {}).items():
                self.planner[k] = self.planner.get(k, 0) + v
            hp = child.get("host_probe") or {}
            self.host_probe["count"] += int(hp.get("count", 0))
            self.host_probe["seconds"] += float(hp.get("ms", 0.0)) / 1e3
            self.host_probe["bytes"] += int(hp.get("bytes", 0))
            sn = (child.get("structural") or {}).get("nodes")
            if sn:
                # sub-responses share one plan (node ids are preorder
                # positions in the same IR): bytes and measured shares sum
                if self.structural is None:
                    self.structural = {}
                for node in sn:
                    mine = self.structural.get(node["id"])
                    if mine is None:
                        mine = self.structural[node["id"]] = {
                            "op": node.get("op", "?"),
                            "detail": node.get("detail", ""),
                            "est_bytes": 0, "_device_ms": 0.0}
                    mine["est_bytes"] += int(node.get("est_bytes", 0))
                    mine["_device_ms"] = (mine.get("_device_ms", 0.0)
                                          + float(node.get("device_ms",
                                                           0.0)))

    def to_dict(self) -> dict:
        with self._lock:
            d = {
                "tenant": self.tenant,
                "scope": self.scope,
                "wall_ms": round((self.wall_s or
                                  (time.perf_counter() - self.t0)) * 1e3,
                                 3),
                "blocks_inspected": self.blocks_inspected,
                "skipped_blocks": dict(self.skipped),
                "bytes_inspected": {"host": self.bytes_host,
                                    "device": self.bytes_device},
                "device_seconds": round(
                    sum(self.device_stages.values()), 9),
                "device_stages_ms": {k: round(v * 1e3, 6)
                                     for k, v in
                                     self.device_stages.items()},
                "stages_ms": {k: round(v * 1e3, 3)
                              for k, v in self.stages.items()},
                "dispatches": self.dispatches,
                "fused_dispatches": self.fused_dispatches,
                "coalesced_with": self.coalesced_with,
                "h2d_bytes": int(round(self.h2d_bytes)),
                "cache": dict(self.cache),
            }
            if self.staged_physical or self.staged_logical:
                d["staged_bytes"] = {"physical": self.staged_physical,
                                     "logical": self.staged_logical}
            if self.structural:
                # compiled plan tree with per-node device-seconds:
                # measured execute time apportions over the registered
                # byte weights (conserved — shares sum to the total).
                # A first-seen shape books its time as "compile"; the
                # fallback to the stage total keeps the tree honest
                # rather than all-zero on cold dispatches.
                exec_s = (self.device_stages.get("execute")
                          or sum(self.device_stages.values()))
                total_b = max(1, sum(n["est_bytes"]
                                     for n in self.structural.values()))
                d["structural"] = {
                    "nodes": [
                        {"id": nid, "op": n["op"],
                         **({"detail": n["detail"]} if n["detail"]
                            else {}),
                         "est_bytes": n["est_bytes"],
                         # merged (request-scope) records carry their
                         # children's measured shares; exec-scope records
                         # apportion their own execute total
                         "device_ms": round(
                             n["_device_ms"] if "_device_ms" in n
                             else exec_s * (n["est_bytes"] / total_b)
                             * 1e3, 6)}
                        for nid, n in sorted(self.structural.items())
                    ],
                }
            if self.query:
                d["query"] = dict(self.query)
            if self.trace_id:
                d["trace_id"] = self.trace_id
            if self.planner["host"] or self.planner["device"]:
                d["planner"] = {k: (round(v, 3) if k == "predicted_ms"
                                    else v)
                                for k, v in self.planner.items()}
            if self.host_probe["count"]:
                d["host_probe"] = {
                    "count": self.host_probe["count"],
                    "ms": round(self.host_probe["seconds"] * 1e3, 3),
                    "bytes": self.host_probe["bytes"],
                }
            if self.subqueries:
                d["subqueries"] = self.subqueries
            return d

    def finish(self) -> dict:
        """Close the record: stamp wall time, publish to the registry
        (metrics, ring, slow log). Returns the final dict."""
        self.wall_s = time.perf_counter() - self.t0
        return REGISTRY.publish(self)


def apportion(totals: dict, weights: list) -> list[dict]:
    """Split per-stage totals across members proportionally to
    `weights`, conserving the sum exactly: members 0..n-2 get
    total*w/W and the LAST member takes the remainder, so per stage
    sum(shares) == total to the last float bit."""
    n = len(weights)
    if n == 1:
        return [dict(totals)]
    W = float(sum(weights)) or float(n)
    shares: list[dict] = [{} for _ in range(n)]
    for stage, total in totals.items():
        acc = 0.0
        for i in range(n - 1):
            s = total * (weights[i] / W)
            shares[i][stage] = s
            acc += s
        shares[n - 1][stage] = total - acc
    return shares


# per-tenant token buckets under a global ceiling — the slow line must
# stay pure JSON (RateLimitedLogger prefixes `tenant=...`), so the raw
# bucket class is used, not the logger wrapper. Promoted to
# observability.log so the slow-FLUSH log (ingest_telemetry) shares the
# exact limiter semantics instead of re-deriving them.
_SlowLogLimiter = TenantTokenBucket


class QueryStatsRegistry:
    """Process-wide sink (module singleton ``REGISTRY``, the PROFILER
    idiom): finished QueryStats land in a bounded ring, per-tenant
    aggregates, top-K rankings, the per-tenant counters, and — past the
    threshold — the slow-query log."""

    def __init__(self, enabled: bool = True, slow_s: float = 10.0,
                 ring_size: int = 256):
        self.enabled = enabled
        self.slow_s = slow_s
        self._ring: deque = deque(maxlen=ring_size)
        self._lock = threading.Lock()
        # tenant -> {queries, device_seconds, bytes_host, bytes_device,
        #            slow_queries}; exec scope only (see module
        # docstring — request scope would double count in-process)
        self._tenants: dict[str, dict] = {}
        self._top_device: list[tuple] = []   # (device_seconds, dict)
        self._top_bytes: list[tuple] = []    # (bytes_total, dict)
        self._limiter = _SlowLogLimiter()
        self._published = 0

    @staticmethod
    def _top_insert(top: list, key: float, d: dict) -> None:
        if key <= 0:
            return
        top.append((key, d))
        top.sort(key=lambda t: t[0], reverse=True)
        del top[_TOP_K:]

    def publish(self, qs: QueryStats) -> dict:
        # EVERYTHING below reads the locked snapshot `d`, never the
        # live QueryStats dicts: a query that early-quit on its limit
        # can still receive a late coalescer-flush attribution on the
        # window-timer thread, and iterating the live dicts here would
        # race it (dict-changed-size in the search path). Attribution
        # landing after this snapshot is dropped by design — the
        # abandoned dispatch's share has no response to ride anyway.
        d = qs.to_dict()
        if qs.scope == "request" and SELFTRACE.ingest_enabled:
            # dogfood pipeline: publish runs on the request thread, so
            # the current span IS the request-scope span — the finished
            # breakdown attaches as query.* attributes and travels into
            # _selftrace with the trace (gate off = one attribute read)
            SELFTRACE.annotate_query(d)
        dev_s = d["device_seconds"]
        b = d["bytes_inspected"]
        bytes_host, bytes_device = b["host"], b["device"]
        with self._lock:
            self._published += 1
            self._ring.append(d)
            self._top_insert(self._top_device, dev_s, d)
            self._top_insert(self._top_bytes,
                             bytes_host + bytes_device, d)
            if qs.scope == "exec":
                t = self._tenants.get(qs.tenant)
                if t is None:
                    t = self._tenants[qs.tenant] = {
                        "queries": 0, "device_seconds": 0.0,
                        "bytes_host": 0, "bytes_device": 0,
                        "slow_queries": 0}
                t["queries"] += 1
                t["device_seconds"] += dev_s
                t["bytes_host"] += bytes_host
                t["bytes_device"] += bytes_device
        if qs.scope == "exec":
            if dev_s:
                obs.query_device_seconds.inc(dev_s, tenant=qs.tenant)
            if bytes_device:
                obs.query_bytes_inspected.inc(
                    bytes_device, tenant=qs.tenant, placement="device")
            if bytes_host:
                obs.query_bytes_inspected.inc(
                    bytes_host, tenant=qs.tenant, placement="host")
            for stage, ms in d["stages_ms"].items():
                obs.query_stage_seconds.observe(ms / 1e3, stage=stage)
            for stage, ms in d["device_stages_ms"].items():
                obs.query_stage_seconds.observe(ms / 1e3,
                                                stage=f"device_{stage}")
        if self.slow_s > 0 and qs.wall_s >= self.slow_s:
            # ONE slow-query booking per query per process — counter
            # AND log use the same rule: an exec record produced UNDER
            # an in-process frontend (qs.fronted — the frontend marks
            # its worker threads) is covered by that frontend's
            # request-scope record; counting each sub-request too would
            # inflate the counter by the shard fan-out factor while the
            # log (deduped) says 1. Standalone querier processes have
            # no request scope and book their exec view.
            if qs.scope == "request" or not qs.fronted:
                obs.slow_queries.inc(tenant=qs.tenant)
                with self._lock:
                    t = self._tenants.get(qs.tenant)
                    if t is not None:
                        t["slow_queries"] += 1
                if self._limiter.allow(qs.tenant):
                    slow_log.warning("%s", json.dumps(
                        {"msg": "slow query",
                         "threshold_s": self.slow_s, **d},
                        separators=(",", ":"), sort_keys=True))
                # flight recorder: the slow query snapshots its bundle
                # with its own self-trace id, so /debug/flightrecorder
                # pivots straight to the offending trace in _selftrace.
                # NOT rate-limited like the log line — the recorder's
                # deque is the bound
                if RECORDER.enabled:
                    RECORDER.record(
                        TRIGGER_SLOW_QUERY, trace_id=qs.trace_id,
                        detail={"tenant": qs.tenant, "scope": qs.scope,
                                "wall_s": round(qs.wall_s, 3),
                                "threshold_s": self.slow_s})
        return d

    def snapshot(self, recent: int = 32) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "slow_query_log_s": self.slow_s,
                "published": self._published,
                "tenants": {k: dict(v, device_seconds=round(
                    v["device_seconds"], 6))
                    for k, v in sorted(self._tenants.items())},
                "top_by_device_seconds": [d for _, d in self._top_device],
                "top_by_bytes": [d for _, d in self._top_bytes],
                "recent": list(self._ring)[-recent:] if recent > 0 else [],
            }

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._tenants.clear()
            self._top_device.clear()
            self._top_bytes.clear()
            self._limiter = _SlowLogLimiter()
            self._published = 0


REGISTRY = QueryStatsRegistry()


def configure(enabled: bool | None = None, slow_s: float | None = None,
              ring_size: int | None = None) -> QueryStatsRegistry:
    """Apply TempoDBConfig.search_query_stats_* / search_slow_query_log_s
    to the process registry (most recent TempoDB wins, the profiler /
    metrics idiom)."""
    if enabled is not None:
        REGISTRY.enabled = bool(enabled)
    if slow_s is not None:
        REGISTRY.slow_s = float(slow_s)
    if ring_size is not None:
        with REGISTRY._lock:
            REGISTRY._ring = deque(REGISTRY._ring, maxlen=int(ring_size))
    return REGISTRY


def query_summary(req) -> dict:
    """Low-cardinality request summary for the stats record (never the
    raw tag VALUES at full fidelity — the slow log is greppable, not a
    data exfiltration channel; tags are the operator's own predicates
    though, so keep them)."""
    try:
        tags = dict(req.tags)
        out = {
            "tags": tags,
            "limit": req.limit or 20,
            "window_s": ((req.end - req.start)
                         if req.end and req.start else 0),
        }
        from .structural import STRUCTURAL_QUERY_TAG

        raw = tags.pop(STRUCTURAL_QUERY_TAG, None)
        if raw is not None:
            # the reserved transport tag is percent-quoted JSON — the
            # slow log / debug ring should show the operator's query,
            # not its wire escaping
            import urllib.parse

            out["structural_q"] = urllib.parse.unquote(raw)
        return out
    except Exception:  # noqa: BLE001 — diagnostics never fail a query
        return {}


def begin(tenant: str, req=None, scope: str = "exec") -> QueryStats | None:
    """A new QueryStats when the layer is enabled, else None — the ONE
    branch the disabled path pays. (Explain routing stays with the
    REQUEST — the finalize sites read req.explain — so the record
    carries no copy of it.)"""
    if not REGISTRY.enabled:
        return None
    return QueryStats(tenant, scope=scope,
                      query=query_summary(req) if req is not None else {})


@contextlib.contextmanager
def activate(qs: QueryStats | None):
    """Make `qs` the thread's active stats for the duration (contextvar;
    None = noop). Deep layers record via current() without any
    parameter threading."""
    if qs is None:
        yield None
        return
    token = _ACTIVE.set(qs)
    try:
        yield qs
    finally:
        _ACTIVE.reset(token)


def current() -> QueryStats | None:
    return _ACTIVE.get()


@contextlib.contextmanager
def fronted():
    """Mark this thread as executing sub-requests for an in-process
    frontend (see _FRONTED) — QueryFrontend wraps its worker-pool job
    bodies with this."""
    token = _FRONTED.set(True)
    try:
        yield
    finally:
        _FRONTED.reset(token)


# per-thread count of attributions made by nested attributed_dispatch
# contexts: an outer context must not wall-fallback when an inner one
# already billed the work (the profiler's record collector hands each
# record to the INNERMOST collector only, so the outer sees none)
_attr_local = threading.local()


@contextlib.contextmanager
def attributed_dispatch(qs: QueryStats | None = None,
                        fallback_wall: bool = True):
    """Attribute every profiler dispatch record finished inside the
    body to `qs` (default: the active stats), 100% — the non-fused
    dispatch sites (batched, mesh, single, dict-probe during query
    compile). With profiling disabled (no records), the measured wall
    time of the body is attributed as stage "execute" so device-seconds
    accounting degrades gracefully instead of to zero — unless
    `fallback_wall` is False (bodies that are mostly host work and only
    SOMETIMES dispatch, like query compilation). Nests safely: a body
    that itself runs an attributing engine (DistributedScanEngine
    self-attributes) bills once, never twice."""
    from tempo_tpu.observability import profile

    qs = qs if qs is not None else current()
    if qs is None:
        yield
        return
    before = getattr(_attr_local, "consumed", 0)
    t0 = time.perf_counter()
    with profile.collect_records() as recs:
        yield
    wall = time.perf_counter() - t0
    if recs:
        for rd in recs:
            stages = {k: v / 1e3
                      for k, v in (rd.get("stages_ms") or {}).items()}
            qs.add_device_stages(stages,
                                 h2d_bytes=rd.get("h2d_bytes", 0))
        _attr_local.consumed = before + 1
    elif fallback_wall and getattr(_attr_local, "consumed", 0) == before:
        qs.add_device_stages({"execute": wall})
        _attr_local.consumed = before + 1
