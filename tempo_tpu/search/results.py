"""Result collection: dedupe, limit, metrics.

Role-equivalent to the reference's search.Results channel funnel
(tempodb/search/results.go:14-141) and util.go result combination — here a
simple synchronous collector (the device kernel already reduces per block;
cross-block merge is cheap host work), carrying the same SearchMetrics
counters the bench harness compares (inspectedTraces/Bytes/Blocks,
skippedBlocks)."""

from __future__ import annotations

from tempo_tpu import tempopb


class SearchResults:
    def __init__(self, limit: int = 20, no_quit: bool = False):
        self.limit = limit
        # no_quit suppresses `complete` so fan-out never early-stops —
        # set by the exhaustive debug tag (reference's secret tag keeps the
        # scan from quitting by rejecting everything; here the flag is
        # explicit so real matches still come back)
        self.no_quit = no_quit
        self._by_id: dict[str, tempopb.TraceSearchMetadata] = {}
        self.metrics = tempopb.SearchMetrics()
        # explain breakdowns carried by merged sub-responses
        # (metrics.query_stats_json, present only under the explain
        # opt-in) — the frontend folds these into its request-level
        # QueryStats instead of concatenating opaque strings
        self.explain_parts: list[dict] = []
        # ?agg= aggregate payload (search/analytics.py agg_response
        # shape), merged exactly across groups and sub-responses —
        # integer counts, so fan-in order never changes the answer
        self.agg: dict | None = None

    @classmethod
    def for_request(cls, req) -> "SearchResults":
        from .analytics import agg_requested
        from .pipeline import is_exhaustive

        # an aggregation must see every contributing group: the limit
        # early-quit would freeze the aggregate at whichever groups
        # happened to drain first (cache-residency-dependent), breaking
        # the cross-route byte-identity the ?agg= contract promises
        return cls(limit=req.limit or 20,
                   no_quit=is_exhaustive(req) or agg_requested(req))

    def add_agg(self, series: dict) -> None:
        """Fold one group's decoded agg series in (AggStage.decode) —
        called per drained dispatch, device and host routes alike."""
        from .analytics import agg_response, merge_agg

        self.agg = merge_agg(self.agg, agg_response(series))

    def add(self, meta: tempopb.TraceSearchMetadata) -> None:
        prev = self._by_id.get(meta.trace_id)
        if prev is None:
            self._by_id[meta.trace_id] = meta
        else:
            # keep the earlier start / longer duration (combination rule of
            # reference util.go:27-62)
            if meta.start_time_unix_nano and (
                not prev.start_time_unix_nano
                or meta.start_time_unix_nano < prev.start_time_unix_nano
            ):
                prev.start_time_unix_nano = meta.start_time_unix_nano
            prev.duration_ms = max(prev.duration_ms, meta.duration_ms)
            if not prev.root_service_name:
                prev.root_service_name = meta.root_service_name
                prev.root_trace_name = meta.root_trace_name

    def merge_response(self, resp: tempopb.SearchResponse) -> None:
        """Fold a sub-request's response in: dedupe traces, sum metrics
        (the frontend/querier merge, reference searchsharding.go:70-124)."""
        for t in resp.traces:
            self.add(t)
        m = self.metrics
        m.inspected_traces += resp.metrics.inspected_traces
        m.inspected_bytes += resp.metrics.inspected_bytes
        m.inspected_blocks += resp.metrics.inspected_blocks
        m.skipped_blocks += resp.metrics.skipped_blocks
        m.truncated_entries += resp.metrics.truncated_entries
        m.failed_blocks += resp.metrics.failed_blocks
        # per-query accounting fields sum like the counters above —
        # this is how device-seconds attribution crosses the
        # frontend/querier process boundary
        m.device_seconds += resp.metrics.device_seconds
        m.inspected_bytes_device += resp.metrics.inspected_bytes_device
        # degraded-ness is sticky across the merge: ONE partial
        # sub-response makes the whole answer partial — a degraded
        # answer must never be indistinguishable from a complete one
        if resp.metrics.partial:
            m.partial = True
        if resp.metrics.query_stats_json:
            import json

            try:
                self.explain_parts.append(
                    json.loads(resp.metrics.query_stats_json))
            except ValueError:
                pass  # a malformed part never fails a merge
        if resp.metrics.agg_json:
            import json

            from .analytics import merge_agg

            try:
                self.agg = merge_agg(self.agg,
                                     json.loads(resp.metrics.agg_json))
            except ValueError:
                pass  # a malformed part never fails a merge

    @property
    def n_results(self) -> int:
        # deliberately NOT __len__: callers use `results or for_request`
        # to default a None argument, and a falsy empty collector would
        # silently swap in a fresh object there
        return len(self._by_id)

    @property
    def complete(self) -> bool:
        return not self.no_quit and len(self._by_id) >= self.limit

    def response(self) -> tempopb.SearchResponse:
        resp = tempopb.SearchResponse()
        # tie-break equal start times by trace id: insertion order here
        # depends on sub-result COMPLETION order (frontend shard
        # threads, host-routed groups answering inline while device
        # groups drain), and the reference sorts by start time only —
        # a deterministic secondary key makes the response (including
        # the limit cutoff) independent of where each group was served,
        # which is what lets owner-routed/breaker fallback paths assert
        # byte-identity
        metas = sorted(
            self._by_id.values(),
            key=lambda m: (-m.start_time_unix_nano, m.trace_id),
        )[: self.limit]
        resp.traces.extend(metas)
        resp.metrics.CopyFrom(self.metrics)
        if self.agg is not None:
            import json

            # sort_keys: the series dict's insertion order depends on
            # which group drained first — canonical JSON keeps the
            # byte-identity assertions across dispatch routes honest
            resp.metrics.agg_json = json.dumps(self.agg, sort_keys=True)
        return resp
