"""Per-trace search data: extraction and wire codec.

Role-equivalent to the reference's distributor search-data extraction
(modules/distributor/search_data.go:28-88) and the tempofb SearchEntry /
SearchDataMap (pkg/tempofb/searchdatamap.go): for each trace we record the
tag key→values map (resource + span attributes, span names under "name",
"error" for error-status spans), the time range, and the root
service/span-name needed to render results without decoding the trace.

Wire format (the `search_data` bytes in PushBytesRequest, and the payload
of WAL search-block entries) — little-endian, length-prefixed:

  | u32 start_s | u32 end_s | u32 dur_ms | u16 root_svc_len | root_svc
  | u16 root_name_len | root_name | u16 n_keys |
  per key: | u16 key_len | key | u16 n_vals | (u16 val_len | val)* |
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from tempo_tpu import tempopb

_U32 = struct.Struct("<I")
_U16 = struct.Struct("<H")

# hard cap per trace, cf. reference max_search_bytes_per_trace default 5KB
DEFAULT_MAX_SEARCH_BYTES = 5 << 10

# structural-query span rows: caps applied at extraction so a hostile
# trace can't explode the columnar span segment (the gate's config
# knobs search_structural_max_spans / _max_span_kvs override)
DEFAULT_MAX_SPANS = 512
DEFAULT_MAX_SPAN_KVS = 16


@dataclass
class SpanData:
    """One span's summary row for the structural query engine
    (search/structural.py): parent index WITHIN the trace's span list
    (-1 = root/unknown parent), duration, OTLP kind, and the span-level
    kv set (span attributes + "name"/"error", same derivation as the
    trace-level rollup). Only present when search_structural_enabled
    captured spans at ingest; legacy data decodes with spans == []."""

    parent: int = -1
    dur_ms: int = 0
    kind: int = 0
    kvs: dict = field(default_factory=dict)  # str -> set[str]


@dataclass
class SearchData:
    trace_id: bytes = b""
    start_s: int = 0
    end_s: int = 0
    dur_ms: int = 0
    root_service: str = ""
    root_name: str = ""
    kvs: dict = field(default_factory=dict)  # str -> set[str]
    # per-span rows (SpanData) — the structural engine's substrate;
    # empty for legacy data and whenever the gate is off
    spans: list = field(default_factory=list)

    @property
    def start_ns(self) -> int:
        # second precision is what the columnar format keeps; results carry
        # start_s * 1e9 (the oracle's exact ns start is not persisted)
        return self.start_s * 1_000_000_000

    def merge(self, other: "SearchData") -> None:
        if other.start_s and (not self.start_s or other.start_s < self.start_s):
            self.start_s = other.start_s
        if other.end_s > self.end_s:
            self.end_s = other.end_s
        self.dur_ms = max(self.dur_ms, other.dur_ms)
        if not self.root_service and other.root_service:
            self.root_service = other.root_service
            self.root_name = other.root_name
        for k, vs in other.kvs.items():
            self.kvs.setdefault(k, set()).update(vs)
        if other.spans:
            # span rows append; their intra-trace parent indices shift
            # by the rows already here (cross-push parent links are not
            # reconstructable from summaries — those parents stay -1)
            base = len(self.spans)
            for sp in other.spans:
                self.spans.append(SpanData(
                    parent=(sp.parent + base if sp.parent >= 0 else -1),
                    dur_ms=sp.dur_ms, kind=sp.kind,
                    kvs={k: set(vs) for k, vs in sp.kvs.items()}))


def clone_search_data(sd: SearchData) -> SearchData:
    """Copy-on-write clone for merge-on-append stores (live tier, WAL
    head): replacing the stored reference with a merged clone keeps
    published entries immutable, so a reader that snapshotted references
    under a lock can build/scan OUTSIDE it without torn reads. Span
    rows are shared (merge appends new rows, never mutates old ones)."""
    out = SearchData(
        trace_id=sd.trace_id, start_s=sd.start_s, end_s=sd.end_s,
        dur_ms=sd.dur_ms, root_service=sd.root_service,
        root_name=sd.root_name,
        kvs={k: set(v) for k, v in sd.kvs.items()},
        spans=list(sd.spans))
    return out


def extract_search_data(trace_id: bytes, trace: tempopb.Trace,
                        max_bytes: int = DEFAULT_MAX_SEARCH_BYTES,
                        range_ns: tuple[int, int] | None = None,
                        spans: bool = False) -> SearchData:
    """range_ns: precomputed (start_ns, end_ns) — the distributor already
    walked the spans for it; re-walking per trace was measurable on the
    ingest ack path (profiled r5). The hot kv loop below is deliberately
    inline (no closure per attribute) for the same reason.

    ``spans=True`` additionally collects per-span summary rows for the
    structural engine (collect_span_rows) — callers gate this on
    search_structural_enabled; the default keeps the legacy walk and
    wire payload exactly."""
    sd = SearchData(trace_id=trace_id)
    if range_ns is None:
        from tempo_tpu.model.matches import trace_range_ns

        range_ns = trace_range_ns(trace)
    start_ns, end_ns = range_ns
    sd.start_s = start_ns // 1_000_000_000
    sd.end_s = end_ns // 1_000_000_000
    # clamp: clock-skewed clients ship end < start (valid input); the
    # duration convention is max(0, end - start) on EVERY path — Python
    # walk, distributor fused walk, native tt_ingest_regroup — or the
    # paths diverge and the Python one crashes encode_search_data
    sd.dur_ms = (min(max(0, end_ns - start_ns) // 1_000_000, 0xFFFFFFFF)
                 if end_ns else 0)

    budget = max_bytes
    root = None
    kvs = sd.kvs
    any_str = _any_value_str
    ERROR = tempopb.Status.STATUS_CODE_ERROR

    for batch in trace.batches:
        svc = ""
        for kv in batch.resource.attributes:
            v = any_str(kv.value)
            k = kv.key
            if v:
                cost = len(k) + len(v)
                if budget >= cost:
                    s = kvs.get(k)
                    if s is None:
                        s = kvs[k] = set()
                    if v not in s:
                        s.add(v)
                        budget -= cost
            if k == "service.name":
                svc = v
        for ss in batch.scope_spans:
            for span in ss.spans:
                v = span.name
                if v:
                    cost = 4 + len(v)
                    if budget >= cost:
                        s = kvs.get("name")
                        if s is None:
                            s = kvs["name"] = set()
                        if v not in s:
                            s.add(v)
                            budget -= cost
                if span.status.code == ERROR and budget >= 9:
                    s = kvs.get("error")
                    if s is None:
                        s = kvs["error"] = set()
                    if "true" not in s:
                        s.add("true")
                        budget -= 9
                for kv in span.attributes:
                    v = any_str(kv.value)
                    if v:
                        k = kv.key
                        cost = len(k) + len(v)
                        if budget >= cost:
                            s = kvs.get(k)
                            if s is None:
                                s = kvs[k] = set()
                            if v not in s:
                                s.add(v)
                                budget -= cost
                if not span.parent_span_id and (
                    root is None or span.start_time_unix_nano < root[0]
                ):
                    root = (span.start_time_unix_nano, svc, span.name)
    if root is None:
        # fallback: earliest span overall
        for batch in trace.batches:
            svc = ""
            for kv in batch.resource.attributes:
                if kv.key == "service.name":
                    svc = kv.value.string_value
            for ss in batch.scope_spans:
                for span in ss.spans:
                    if root is None or span.start_time_unix_nano < root[0]:
                        root = (span.start_time_unix_nano, svc, span.name)
    if root is not None:
        sd.root_service, sd.root_name = root[1], root[2]
    if spans:
        from .structural import STRUCTURAL

        sd.spans = collect_span_rows(trace,
                                     max_spans=STRUCTURAL.max_spans,
                                     max_kvs=STRUCTURAL.max_span_kvs)
    return sd


def collect_span_rows(trace: tempopb.Trace,
                      max_spans: int = DEFAULT_MAX_SPANS,
                      max_kvs: int = DEFAULT_MAX_SPAN_KVS) -> list:
    """Per-span summary rows (SpanData) for the structural engine: one
    walk over a (regrouped) trace resolving parent pointers by span id.
    Called by the extraction paths ONLY when search_structural_enabled —
    the gate-off ingest path never pays this walk and the wire payload
    stays byte-identical to the legacy form. Rows cap at ``max_spans``
    in walk order; kvs per span cap at ``max_kvs``."""
    rows: list[SpanData] = []
    idx_of: dict[bytes, int] = {}       # span id -> row index
    parents: list[bytes] = []           # raw parent ids, resolved after
    ERROR = tempopb.Status.STATUS_CODE_ERROR
    for batch in trace.batches:
        svc = ""
        for kv in batch.resource.attributes:
            if kv.key == "service.name":
                svc = kv.value.string_value
        for ss in batch.scope_spans:
            for span in ss.spans:
                if len(rows) >= max_spans:
                    break
                st, en = span.start_time_unix_nano, span.end_time_unix_nano
                sp = SpanData(
                    parent=-1,
                    dur_ms=min(max(0, en - st) // 1_000_000, 0xFFFFFFFF)
                    if en else 0,
                    kind=int(span.kind),
                )
                kvs = sp.kvs
                n_kv = 0
                if svc:
                    kvs["service.name"] = {svc}
                    n_kv += 1
                if span.name and n_kv < max_kvs:
                    kvs["name"] = {span.name}
                    n_kv += 1
                if span.status.code == ERROR and n_kv < max_kvs:
                    kvs["error"] = {"true"}
                    n_kv += 1
                for kv in span.attributes:
                    if n_kv >= max_kvs:
                        break
                    v = _any_value_str(kv.value)
                    if v:
                        kvs.setdefault(kv.key, set()).add(v)
                        n_kv += 1
                if span.span_id:
                    idx_of.setdefault(bytes(span.span_id), len(rows))
                parents.append(bytes(span.parent_span_id))
                rows.append(sp)
    for i, pid in enumerate(parents):
        if pid:
            pi = idx_of.get(pid)
            # a span can never be its own parent (malformed input)
            if pi is not None and pi != i:
                rows[i].parent = pi
    return rows


def _any_value_str(v: tempopb.AnyValue) -> str:
    which = v.WhichOneof("value")
    if which == "string_value":
        return v.string_value
    if which == "int_value":
        return str(v.int_value)
    if which == "bool_value":
        return "true" if v.bool_value else "false"
    if which == "double_value":
        return repr(v.double_value)
    return ""


def search_data_matches(sd: SearchData, req) -> bool:
    """Host-side predicate over extracted search data — same semantics as
    the device kernel (substring on values, ms durations, second windows).
    Used for live/WAL scans and as the engine's correctness oracle."""
    if req.min_duration_ms and sd.dur_ms < req.min_duration_ms:
        return False
    if req.max_duration_ms and sd.dur_ms > req.max_duration_ms:
        return False
    if req.start and sd.end_s < req.start:
        return False
    if req.end and sd.start_s > req.end:
        return False
    from .analytics import AGG_QUERY_TAG
    from .pipeline import EXHAUSTIVE_SEARCH_TAG
    from .structural import STRUCTURAL_QUERY_TAG

    for k, v in req.tags.items():
        if k in (EXHAUSTIVE_SEARCH_TAG, STRUCTURAL_QUERY_TAG,
                 AGG_QUERY_TAG):
            continue  # in-band flags: not themselves tag predicates
        vs = sd.kvs.get(k)
        if not vs:
            return False
        if v and not any(v in x for x in vs):
            return False
    # structural predicate (gated: structural_query reads one attribute
    # and returns None when search_structural_enabled is off) — the
    # live/WAL scan path evaluates the host reference semantics, the
    # same eval the device kernels are differentially fuzzed against
    from . import structural as _structural

    expr = _structural.structural_query(req)
    if expr is not None and not _structural.eval_host(expr, sd):
        return False
    return True


# ---- wire codec ----

def encode_search_data(sd: SearchData) -> bytes:
    out = bytearray()
    out += _U32.pack(sd.start_s & 0xFFFFFFFF)
    out += _U32.pack(sd.end_s & 0xFFFFFFFF)
    out += _U32.pack(min(sd.dur_ms, 0xFFFFFFFF))
    for s in (sd.root_service, sd.root_name):
        b = s.encode("utf-8")[:0xFFFF]
        out += _U16.pack(len(b)) + b
    keys = sorted(sd.kvs)
    out += _U16.pack(len(keys))
    for k in keys:
        kb = k.encode("utf-8")[:0xFFFF]
        out += _U16.pack(len(kb)) + kb
        vals = sorted(sd.kvs[k])
        out += _U16.pack(len(vals))
        for v in vals:
            vb = v.encode("utf-8")[:0xFFFF]
            out += _U16.pack(len(vb)) + vb
    if sd.spans:
        # OPTIONAL trailing span section (structural engine): absent for
        # legacy/gate-off payloads, so the wire form stays byte-identical
        # whenever no spans were captured; decoders detect it by bytes
        # remaining past the kv map.
        #   | u16 n_spans | per span: u16 parent (0xFFFF = -1)
        #   | u32 dur_ms | u8 kind | u16 n_keys
        #   | per key: u16 klen k u16 n_vals (u16 vlen v)* |
        spans = sd.spans[:0xFFFF]
        out += _U16.pack(len(spans))
        for sp in spans:
            p = sp.parent if 0 <= sp.parent < 0xFFFF else 0xFFFF
            out += _U16.pack(p)
            out += _U32.pack(min(sp.dur_ms, 0xFFFFFFFF))
            out.append(sp.kind & 0xFF)
            skeys = sorted(sp.kvs)
            out += _U16.pack(len(skeys))
            for k in skeys:
                kb = k.encode("utf-8")[:0xFFFF]
                out += _U16.pack(len(kb)) + kb
                vals = sorted(sp.kvs[k])
                out += _U16.pack(len(vals))
                for v in vals:
                    vb = v.encode("utf-8")[:0xFFFF]
                    out += _U16.pack(len(vb)) + vb
    return bytes(out)


def decode_search_data(buf: bytes, trace_id: bytes = b"") -> SearchData:
    off = 0

    def u32():
        nonlocal off
        (v,) = _U32.unpack_from(buf, off)
        off += 4
        return v

    def u16():
        nonlocal off
        (v,) = _U16.unpack_from(buf, off)
        off += 2
        return v

    def s():
        nonlocal off
        n = u16()
        v = buf[off:off + n].decode("utf-8", errors="replace")
        off += n
        return v

    sd = SearchData(trace_id=trace_id)
    sd.start_s, sd.end_s, sd.dur_ms = u32(), u32(), u32()
    sd.root_service, sd.root_name = s(), s()
    for _ in range(u16()):
        k = s()
        sd.kvs[k] = {s() for _ in range(u16())}
    if off < len(buf):
        # optional span section (see encode_search_data): legacy
        # payloads end exactly at the kv map
        for _ in range(u16()):
            p = u16()
            sp = SpanData(parent=(-1 if p == 0xFFFF else p),
                          dur_ms=u32(), kind=buf[off])
            off += 1
            for _ in range(u16()):
                k = s()
                sp.kvs[k] = {s() for _ in range(u16())}
            sd.spans.append(sp)
    return sd
