"""The JAX scan engine — the north-star hot path on device.

Replaces the reference's per-entry FlatBuffer scan loops
(tempodb/search/backend_search_block.go:247-295, pipeline.go:86-97,
tempofb/searchdata_util.go:47-100) with one fused, jit-compiled kernel
over the dense columnar page layout:

  1. per kv-slot term match: (kv_key == term_key) & (kv_val in ranges)
     — value membership is an OR of inclusive [lo,hi] id-range compares;
     the host dictionary prefilter resolves substring semantics into
     sorted id sets and collapses them to ranges (pipeline.ids_to_ranges;
     a bitmap-gather variant measured 35ms/1M entries vs <5ms for ranges —
     gathers serialize on the VPU)
  2. kv → entry reduction: `any` over the per-entry kv-capacity axis —
     a lane reduction, NOT a scatter (scatters serialize on the VPU;
     this is the layout lesson baked into columnar.py)
  3. AND across terms (fori_loop, T static)
  4. duration / time-window compares on entry columns
  5. count + top-k by start time on device; only the top-k indices
     travel back to host

Shapes are static per (page-bucket, T, top_k) so XLA compiles once per
bucket and reuses; everything is int32/uint32/bool — VPU-native, no MXU
(this workload is bandwidth-bound; the win is fusion + vector width).
"""

from __future__ import annotations

import functools
import threading
from collections import OrderedDict
from dataclasses import dataclass

import time

import jax
import jax.numpy as jnp
import numpy as np

from tempo_tpu.observability import profile

from .columnar import ColumnarPages
from .pipeline import CompiledQuery
from . import packing
from .packing import duration_ok, mask_select, unpack_ids

DEFAULT_TOP_K = 128


@dataclass
class StagedPages:
    """A block's columnar arrays resident on device (the HBM cache tier),
    plus the host-side bits needed to render results."""
    device: dict          # name -> jnp array, page axis padded to bucket
    n_pages: int          # real (unpadded) page count
    pages: ColumnarPages  # host container (dicts, trace ids, header)
    # dict_probe.DeviceDict when the value dictionary cleared the
    # device-probe threshold at staging time — query compilation then
    # runs the substring probe ON DEVICE (pipeline._device_probe_tags)
    # instead of the host memmem walk
    staged_dict: object = None
    # packed-residency width descriptor (search/packing.py) — static
    # per staged block, part of the scan kernel's jit shape key; None
    # = the unpacked legacy layout
    widths: tuple | None = None
    # structural-engine span columns on device (search/structural.py),
    # staged only when search_structural_enabled AND the container
    # carries spans; None keeps the legacy kernel signature pytree
    span_device: dict | None = None


DEVICE_ARRAYS = ("kv_key", "kv_val", "entry_start", "entry_end",
                 "entry_dur", "entry_valid")


def _bucket(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


def cpu_pinned():
    """Context pinning kernel execution to the CPU backend — the host
    route's execution context, shared by the batched (batcher.host_scan)
    and single-block (backend_search_block.host_scan_single) paths so
    their byte-identity-critical plumbing cannot diverge. Two consumers
    ride it: the breaker's fallback when the device is wedged, and the
    owner-routing layer's non-owner serve (search/ownership.py — a
    process that doesn't own a block group answers from here instead of
    staging a duplicate HBM copy). Platforms without a reachable cpu
    backend degrade to the default device (still correct; the point of
    the pin is to avoid a wedged accelerator)."""
    import contextlib

    try:
        cpu = jax.devices("cpu")[0]
    except Exception:  # noqa: BLE001 — odd platform sets
        cpu = None
    return (jax.default_device(cpu) if cpu is not None
            else contextlib.nullcontext())


def pad_page_axis(pages: ColumnarPages, target: int) -> dict:
    """Numpy arrays with the page axis padded to `target` rows; padding is
    invalid entries / -1 kv slots."""
    out = {}
    P = pages.n_pages
    for name in DEVICE_ARRAYS:
        arr = getattr(pages, name)
        if target > P:
            pad = np.zeros((target - P,) + arr.shape[1:], dtype=arr.dtype)
            if name in ("kv_key", "kv_val"):
                pad -= 1
            arr = np.concatenate([arr, pad], axis=0)
        out[name] = arr
    return out


def stage(pages: ColumnarPages, page_bucket: int | None = None,
          probe_min_vals: int | None = None) -> StagedPages:
    """Move a block's columns to device, padding the page axis to a
    power-of-two bucket so jit compiles once per bucket.

    `probe_min_vals`: value-dictionary size at which the packed
    dictionary bytes stage alongside the columns for the on-device
    substring probe (None = dict_probe.DEVICE_PROBE_MIN_VALS; <= 0
    disables). The threshold is applied HERE, at staging time — query
    compilation just uses whatever was staged."""
    B = page_bucket or _bucket(pages.n_pages)
    host = pad_page_axis(pages, B)
    widths = None
    if packing.PACKING.enabled:
        # packed residency: the single-block staging packs the SAME
        # per-column widths the batched stack_host would choose for a
        # one-block batch (search/packing.py)
        widths = packing.PACKING.plan_widths(
            len(pages.key_dict), len(pages.val_dict), pages.max_dur_ms())
        if widths is not None:
            host = packing.pack_columns(host, widths)
    from .structural import STRUCTURAL

    span_host = None
    if STRUCTURAL.enabled:
        # structural span segment rides the same staging (gate off =
        # zero extra work and the identical device pytree)
        span_host = STRUCTURAL.stage_single(pages, B)
    t0 = time.perf_counter()
    dev = {k: jnp.asarray(v) for k, v in host.items()}
    span_dev = (None if span_host is None
                else {k: jnp.asarray(v) for k, v in span_host.items()})
    profile.observe_stage("h2d", "single", time.perf_counter() - t0,
                          nbytes=sum(int(v.nbytes) for v in host.values())
                          + (0 if span_host is None else
                             sum(int(v.nbytes)
                                 for v in span_host.values())))
    sd = stage_block_dict(pages, probe_min_vals)
    return StagedPages(device=dev, n_pages=pages.n_pages, pages=pages,
                       staged_dict=sd, widths=widths,
                       span_device=span_dev)


def stage_block_dict(pages: ColumnarPages, probe_min_vals: int | None,
                     n_shards: int = 1, mesh=None):
    """DeviceDict for one block's value dictionary when it clears the
    device-probe threshold, else None. Shared by the single-block stage,
    the batched stack_host staging, and the distributed engine
    (n_shards/mesh shard the value axis).

    The static threshold is the FLOOR: below it (or <= 0) the probe
    stays on host unconditionally. Above it, the offload planner — when
    enabled — can veto the staging ("host" decision), so a CPU-bound
    process never uploads hundreds of MB of dictionary bytes the probe
    kernel would lose on anyway; planner disabled keeps the static
    behavior exactly."""
    from . import dict_probe, planner
    from .pipeline import _dict_fingerprint

    mv = (dict_probe.DEVICE_PROBE_MIN_VALS if probe_min_vals is None
          else probe_min_vals)
    if mv <= 0 or len(pages.val_dict) < mv:
        return None
    fp = _dict_fingerprint(pages, pages.key_dict, pages.val_dict)
    if planner.stage_veto(pages, fp, n_shards=n_shards):
        return None
    return dict_probe.stage_val_dict(pages.val_dict, n_shards=n_shards,
                                     mesh=mesh, fingerprint=fp,
                                     cache_on=pages)


def entry_match_mask(kv_key, kv_val, entry_start, entry_end, entry_dur,
                     entry_valid, term_keys, val_ranges,
                     dur_lo, dur_hi, win_start, win_end, *, n_terms: int,
                     val_hits=None, entry_dur_res=None, widths=None):
    """The core predicate: [P,E] bool mask of matching entries. Shared by
    the single-device kernel and the shard_map distributed kernel (each
    shard evaluates it over its local page slice).

    Value membership is an OR over inclusive [lo,hi] id ranges — pure
    broadcast compares, no gather (pipeline.ids_to_ranges explains why).

    `val_hits` (bool [T, v_pad], device): the on-device dictionary
    probe's per-term value hit mask (search/dict_probe.py). When present
    the membership test is a mask LOOKUP — one [P,E,C] gather per term —
    and the range tables are the never-match padding; the probe result
    never crossed the host boundary. (bench.py's high-cardinality phases
    re-validate the lookup-vs-range tradeoff each round.)

    `widths` (STATIC at every call site) + `entry_dur_res`: the
    packed-residency column descriptor (search/packing.py) — the kv
    unpack runs inside the term body so the widening shifts/masks fuse
    into the compares; no unpacked copy materializes in HBM."""
    kw, vw, dw = widths if widths is not None else (None, None, None)
    mask = entry_valid
    if n_terms:
        def term_body(t, acc):
            kk = unpack_ids(kv_key, kw)              # fused widen
            vv = unpack_ids(kv_val, vw)
            k = term_keys[t]
            keym = kk == k                           # [P,E,C]
            if val_hits is not None:
                safe_v = jnp.maximum(vv, 0).astype(jnp.int32)
                valm = mask_select(val_hits[t], safe_v) & (vv >= 0)
            else:
                lo = val_ranges[t, :, 0]                 # [R]
                hi = val_ranges[t, :, 1]
                v = vv[..., None]                        # [P,E,C,1]
                valm = ((v >= lo) & (v <= hi)).any(-1)   # [P,E,C], fused over R
            hit = jnp.any(keym & valm, axis=-1)      # [P,E] lane reduction
            return acc & hit

        mask = jax.lax.fori_loop(0, n_terms, term_body, mask)

    mask = mask & duration_ok(entry_dur, entry_dur_res, dur_lo, dur_hi, dw)
    mask = mask & (entry_end.astype(jnp.uint32) >= win_start.astype(jnp.uint32))
    mask = mask & (entry_start.astype(jnp.uint32) <= win_end.astype(jnp.uint32))
    return mask


def start_fetch(arrays) -> None:
    """Kick off device→host copies without blocking. Through a TPU relay
    every blocking fetch is a ~65 ms round-trip regardless of size
    (measured); issuing async copies at dispatch time collapses N fetches
    into one wait and overlaps the transfer with later kernel work."""
    for a in arrays:
        copy = getattr(a, "copy_to_host_async", None)
        if copy is not None:
            try:
                copy()
            except Exception:  # noqa: BLE001 — fetch still works, just sync
                pass


def fetch_scan_out(out):
    """(count, inspected, scores, idx[, agg]) device arrays → host
    values with a single synchronization point. The optional trailing
    aggregate histogram (?agg= dispatches) rides the same sync."""
    start_fetch(out)
    count, inspected, scores, idx, *ext = out
    fetched = (int(count), int(inspected), np.asarray(scores),
               np.asarray(idx))
    if ext:
        return fetched + (np.asarray(ext[0]),)
    return fetched


def resolve_top_k(base: int, limit: int) -> int:
    """top_k must cover the request limit or results get silently
    truncated below it; bucket to pow2 to bound recompiles. Shared by
    the single-block, multi-block and coalesced dispatch paths so the
    SAME (limit → k) mapping keys every jit cache."""
    k = max(1, base)
    while k < limit:
        k *= 2
    return k


def fetch_coalesced_out(out):
    """Query-axis variant of fetch_scan_out: (counts [Q], inspected,
    scores [Q,k], idx [Q,k][, agg [Q,K]]) device arrays → host values
    with a single synchronization point. The per-query demux slices the
    host arrays — one D2H wait for the whole coalesced group, not Q."""
    start_fetch(out)
    counts, inspected, scores, idx, *ext = out
    fetched = (np.asarray(counts), int(inspected),
               np.asarray(scores), np.asarray(idx))
    if ext:
        return fetched + (np.asarray(ext[0]),)
    return fetched


_TOPK_CHUNK = 8192


def masked_topk(mask, entry_start, top_k: int):
    """Top-k most recent matches (by start second); score -1 marks
    non-matches. Returns (scores i32 [k], flat idx i32 [k]).

    Two-stage for large inputs: lax.top_k over 1M elements costs ~2ms on
    v5e (it partial-sorts the full array); chunked per-group top-k then a
    global pass over G*k candidates is ~4x cheaper. The SCORES returned
    are identical to single-stage top_k (every global winner wins its
    chunk), but tie-breaking among equal start seconds differs: lax.top_k
    breaks ties by lowest flat index, while the two-stage pass orders
    candidates by (chunk, rank) — so at the k boundary a tie may resolve
    to a different entry than the single-stage path would pick. Callers
    treat equal-start results as unordered (the reference sorts results
    by start time only, search/util.go), so this is semantically
    invisible; do not rely on index-level equality between the paths."""
    score = jnp.where(
        mask, jnp.minimum(entry_start, jnp.uint32(2**31 - 1)).astype(jnp.int32),
        jnp.int32(-1),
    ).reshape(-1)
    n = score.shape[0]
    k = min(top_k, n)
    if n > 4 * _TOPK_CHUNK and k <= _TOPK_CHUNK:
        groups = -(-n // _TOPK_CHUNK)
        padded = jnp.pad(score, (0, groups * _TOPK_CHUNK - n),
                         constant_values=-1).reshape(groups, _TOPK_CHUNK)
        s1, i1 = jax.lax.top_k(padded, k)                  # [G, k]
        base = (jnp.arange(groups, dtype=jnp.int32) * _TOPK_CHUNK)[:, None]
        cand_idx = (i1.astype(jnp.int32) + base).reshape(-1)
        s2, i2 = jax.lax.top_k(s1.reshape(-1), k)
        return s2, cand_idx[i2]
    top_scores, top_idx = jax.lax.top_k(score, k)
    return top_scores, top_idx.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("n_terms", "top_k", "widths",
                                             "plan"))
def scan_kernel(kv_key, kv_val, entry_start, entry_end, entry_dur,
                entry_valid, term_keys, val_ranges, dur_lo, dur_hi,
                win_start, win_end, val_hits=None, entry_dur_res=None,
                span_cols=None, s_tables=None,
                *, n_terms: int, top_k: int, widths=None, plan=None):
    """Returns (match_count i32, inspected i32, topk_scores i32 [k],
    topk_flat_idx i32 [k]) — flat index = page * E + entry. `val_hits`
    (None, bool [T, v_pad], or packed uint32 words) selects the
    device-probe membership path; jit treats None as pytree structure,
    so each variant compiles once. `widths` is the static packed-
    residency descriptor (search/packing.py); `plan` + span_cols/
    s_tables are the structural query lowering (search/structural.py) —
    its [P,E] verdicts AND into the same mask, one fused dispatch."""
    mask = entry_match_mask(
        kv_key, kv_val, entry_start, entry_end, entry_dur, entry_valid,
        term_keys, val_ranges, dur_lo, dur_hi, win_start, win_end,
        n_terms=n_terms, val_hits=val_hits, entry_dur_res=entry_dur_res,
        widths=widths,
    )
    if plan is not None:
        from .structural import structural_entry_mask

        page_block = jnp.zeros(entry_valid.shape[0], dtype=jnp.int32)
        mask = mask & structural_entry_mask(
            kv_key, kv_val, entry_dur, entry_valid, page_block,
            entry_dur_res, span_cols, s_tables, plan=plan, widths=widths)
    count = jnp.sum(mask, dtype=jnp.int32)
    inspected = jnp.sum(entry_valid, dtype=jnp.int32)
    top_scores, top_idx = masked_topk(mask, entry_start, top_k)
    return count, inspected, top_scores, top_idx


_SCALAR_CACHE: OrderedDict = OrderedDict()
_scalar_lock = threading.Lock()
_SCALAR_CACHE_MAX = 512


def device_scalar(v: int):
    """uint32 scalar as a device array, memoized by VALUE across
    dispatches and queries. Every compiled query uploads four of these
    (duration/window bounds) and the common values — 0 and UINT32_MAX
    for unbounded requests — recur on essentially every query; through a
    TPU relay each tiny H2D put costs ~ms (the engine.py query-param
    docstring's measured 3x), so re-putting the same four scalars per
    query was pure relay tax. Bounded LRU; jit treats equal-valued
    scalars identically, so sharing is invisible to the cache keys."""
    v = int(v)
    with _scalar_lock:
        hit = _SCALAR_CACHE.get(v)
        if hit is not None:
            _SCALAR_CACHE.move_to_end(v)
            return hit
    arr = jnp.uint32(v)
    with _scalar_lock:
        _SCALAR_CACHE[v] = arr
        while len(_SCALAR_CACHE) > _SCALAR_CACHE_MAX:
            _SCALAR_CACHE.popitem(last=False)
    return arr


class ScanEngine:
    """Single-device scan orchestration: staging cache + kernel dispatch +
    host-side result rendering. The distributed variant lives in
    tempo_tpu.parallel.dist_search."""

    def __init__(self, top_k: int = DEFAULT_TOP_K):
        self.top_k = top_k

    def _resolve_top_k(self, cq: CompiledQuery) -> int:
        return resolve_top_k(self.top_k, cq.limit)

    @staticmethod
    def query_device_params(cq: CompiledQuery):
        """Query params as device arrays, uploaded ONCE per query and
        cached on the CompiledQuery — one search fans out over many
        blocks/pages with the same query, and through a TPU relay each
        small H2D transfer costs ~ms (measured: uncached params tripled
        per-scan latency). The scalar bounds additionally memoize BY
        VALUE across queries (device_scalar), so a fresh query with the
        default unbounded window re-uploads nothing but its term
        tables."""
        cached = getattr(cq, "_device_params", None)
        if cached is None:
            cached = (
                jnp.asarray(cq.term_keys), jnp.asarray(cq.val_ranges),
                device_scalar(cq.dur_lo),
                device_scalar(min(cq.dur_hi, 0xFFFFFFFF)),
                device_scalar(cq.win_start),
                device_scalar(min(cq.win_end, 0xFFFFFFFF)),
            )
            object.__setattr__(cq, "_device_params", cached)
        return cached

    def scan_staged_async(self, sp: StagedPages, cq: CompiledQuery,
                          _rec=profile.NOOP_DISPATCH):
        """Dispatch the kernel without forcing device→host transfers;
        returns device arrays (count, inspected, scores, idx). Use when
        pipelining many blocks/queries — convert only at the end.

        `_rec`: a profile.Dispatch record when the caller owns one (the
        sync scan_staged wrapper); the default noop keeps this enqueue
        hot loop free of per-call profiling cost."""
        d = sp.device
        with _rec.stage("build"):
            tk, vr, dlo, dhi, ws, we = self.query_device_params(cq)
        vh = getattr(cq, "val_hits", None)
        widths = getattr(sp, "widths", None)
        # structural plan (search/structural.py): compiled against this
        # block and attached to the CompiledQuery; None = the legacy
        # pytree, same executables as before
        st = getattr(cq, "structural", None)
        plan = None if st is None else st.plan
        s_tables = None if st is None else st.device_tables()
        span_cols = getattr(sp, "span_device", None) if st is not None \
            else None
        k = self._resolve_top_k(cq)
        miss = _rec.compile_check(
            ("scan_kernel", d["kv_key"].shape, str(d["kv_key"].dtype),
             str(d["kv_val"].dtype), vr.shape,
             None if vh is None else (tuple(vh.shape), str(vh.dtype)),
             widths, cq.n_terms, k,
             None if st is None else st.shape_sig(),
             None if span_cols is None else
             tuple(sorted((n, tuple(a.shape))
                          for n, a in span_cols.items()))))
        with _rec.stage("compile" if miss else "execute"):
            out = scan_kernel(
                d["kv_key"], d["kv_val"],
                d["entry_start"], d["entry_end"], d["entry_dur"],
                d["entry_valid"],
                tk, vr, dlo, dhi, ws, we, vh, d.get("entry_dur_res"),
                span_cols, s_tables,
                n_terms=cq.n_terms, top_k=k, widths=widths, plan=plan,
            )
            _rec.fence(out)
        return out

    def scan_staged(self, sp: StagedPages, cq: CompiledQuery):
        # watchdog-bounded (robustness.GUARD): a hang/backend error here
        # books a breaker fault and raises DeviceFault instead of
        # wedging the caller; a disabled breaker makes this a direct
        # call (the noop contract)
        from tempo_tpu.robustness import GUARD

        return GUARD.run("single", lambda: self._scan_staged_sync(sp, cq))

    def _scan_staged_sync(self, sp: StagedPages, cq: CompiledQuery):
        with profile.dispatch("single") as rec:
            out = self.scan_staged_async(sp, cq, _rec=rec)
            with rec.stage("d2h"):
                res = fetch_scan_out(out)
            rec.add_bytes(d2h=res[2].nbytes + res[3].nbytes + 8)
            # scan_bytes feeds the planner's per-byte scan rate (physical
            # staged bytes — packed when packed residency is on)
            rec.set(n_pages=sp.n_pages,
                    scan_bytes=sum(int(a.nbytes)
                                   for a in sp.device.values()))
        return res

    def scan(self, pages: ColumnarPages, cq: CompiledQuery):
        return self.scan_staged(stage(pages), cq)

    # ---- host-side result rendering ----

    def results(self, sp: StagedPages, cq: CompiledQuery,
                scores: np.ndarray, idx: np.ndarray) -> list:
        """Map top-k flat indices back to TraceSearchMetadata."""
        from tempo_tpu import tempopb

        pages = sp.pages
        E = pages.geometry.entries_per_page
        out = []
        limit = cq.limit
        for s, i in zip(scores.tolist(), idx.tolist()):
            if s < 0 or len(out) >= limit:
                break
            p, e = divmod(i, E)
            if p >= pages.n_pages:
                continue
            m = tempopb.TraceSearchMetadata()
            m.trace_id = bytes(pages.trace_ids[p, e]).hex()
            m.start_time_unix_nano = int(pages.entry_start[p, e]) * 1_000_000_000
            m.duration_ms = int(pages.entry_dur[p, e])
            svc = int(pages.entry_root_svc[p, e])
            name = int(pages.entry_root_name[p, e])
            if svc >= 0:
                m.root_service_name = pages.val_dict[svc]
            if name >= 0:
                m.root_trace_name = pages.val_dict[name]
            out.append(m)
        return out
