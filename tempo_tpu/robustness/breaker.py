"""Device circuit breaker: closed → open → half-open → closed.

One hung dispatch is a fault; N faults inside a window mean the device
tunnel itself is gone, and every further dispatch would burn a watchdog
timeout learning the same thing. The breaker aggregates the faults the
dispatch guard books and flips the whole serving path to the host route
in one place:

  closed     normal: every dispatch allowed; faults accumulate in the
             sliding window; threshold trips to open.
  open       device blocked: ``allow_device()`` is False so the batcher
             host-routes groups, ``planner.stage_veto`` /
             ``pipeline._use_device_probe`` keep dictionaries on the
             host path, and staging uploads stop. After ``cooldown_s``
             the next ``allow_device()`` transitions to half-open.
  half-open  recovery probing: a LIMITED number of dispatches (probe
             tokens) are allowed through the real device path. One
             success closes the breaker (and clears the window); one
             fault re-opens it and restarts the cooldown.

Transitions emit ``tempo_search_device_breaker_transitions_total``,
update the state gauge, annotate the active self-trace span, and log —
``/status``'s device block and bench's ``device_wedged`` headline read
:meth:`snapshot` instead of ad-hoc probing.

Hot-path contract: with the breaker disabled (or closed),
``allow_device`` / ``record_success`` are attribute reads — no lock, no
clock. Only faults and non-closed states pay for bookkeeping.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from tempo_tpu.observability import metrics as obs
from tempo_tpu.observability import tracing
from tempo_tpu.observability.flightrecorder import (RECORDER,
                                                    TRIGGER_BREAKER)
from tempo_tpu.observability.log import get_logger

log = get_logger("tempo_tpu.breaker")

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    def __init__(self, threshold: int = 3, window_s: float = 30.0,
                 cooldown_s: float = 5.0, half_open_probes: int = 1,
                 enabled: bool = False):
        self.enabled = enabled
        self.threshold = threshold
        self.window_s = window_s
        self.cooldown_s = cooldown_s
        self.half_open_probes = half_open_probes
        self._state = CLOSED
        self._faults: deque[float] = deque()   # monotonic fault times
        self._opened_t: float | None = None
        self._probe_tokens = 0
        self._probe_granted_t = 0.0
        self._transitions: dict[str, int] = {}
        self._last_fault: dict[str, object] | None = None
        self._last_fault_t: float | None = None
        self._lock = threading.Lock()

    # ---- hot-path reads ----

    @property
    def state(self) -> str:
        return self._state

    def allow_device(self) -> bool:
        """May the caller start a NEW device dispatch/staging? Closed
        (or disabled) answers True from attribute reads alone. Open
        answers False until the cooldown elapses, then flips to
        half-open and hands out probe tokens; half-open answers True
        only while a probe token is available, so recovery probing never
        stampedes a device that just came back."""
        if not self.enabled or self._state == CLOSED:
            return True
        with self._lock:
            now = time.monotonic()
            if self._state == OPEN:
                if (self._opened_t is not None
                        and now - self._opened_t >= self.cooldown_s):
                    self._transition(HALF_OPEN)
                    self._probe_tokens = self.half_open_probes
                    self._probe_granted_t = now
                else:
                    return False
            if self._state == HALF_OPEN:
                if self._probe_tokens > 0:
                    self._probe_tokens -= 1
                    self._probe_granted_t = now
                    return True
                if now - self._probe_granted_t >= self.cooldown_s:
                    # every granted probe went silent — its group pruned
                    # away, its request early-quit or deadlined before
                    # dispatching — so neither success nor fault ever
                    # reported back. Re-grant after a cooldown rather
                    # than wedging in half-open forever.
                    self._probe_granted_t = now
                    return True
                return False
            return self._state == CLOSED  # raced a concurrent close

    def blocking(self) -> bool:
        """True while the breaker diverts work off the device — the
        stage-veto / probe-placement gate. Half-open still blocks
        STAGING decisions (only allow_device's counted probes run on
        device) so a recovering chip isn't immediately handed a 720MB
        dictionary upload."""
        return self.enabled and self._state != CLOSED

    # ---- event booking (dispatch guard + lock timeout call these) ----

    def record_fault(self, kind: str, mode: str = "") -> None:
        """Book one device fault (kind=timeout|error|lock_timeout,
        mode = the profiler's dispatch mode for stage context). Counted
        even when the breaker is disabled — the operator still sees the
        faults; only the state machine is gated."""
        obs.device_faults.inc(kind=kind, mode=mode or "unknown")
        span = tracing.current_span()
        if span.recording:
            span.add_event("device.fault", kind=kind, mode=mode)
        if not self.enabled:
            return
        now = time.monotonic()
        tripped = False
        with self._lock:
            self._last_fault = {"kind": kind, "mode": mode}
            self._last_fault_t = now
            if self._state == HALF_OPEN:
                # the recovery probe failed: straight back to open,
                # cooldown restarts
                self._transition(OPEN)
                self._opened_t = now
                self._probe_tokens = 0
                tripped = True
            else:
                self._faults.append(now)
                while (self._faults
                       and now - self._faults[0] > self.window_s):
                    self._faults.popleft()
                if (self._state == CLOSED
                        and len(self._faults) >= self.threshold):
                    self._transition(OPEN)
                    self._opened_t = now
                    tripped = True
        # the flight-recorder snapshot happens OUTSIDE the breaker lock
        # (it re-reads BREAKER.snapshot among others — the recorder's
        # lock must stay a leaf in the process lock graph)
        if tripped and RECORDER.enabled:
            RECORDER.record(TRIGGER_BREAKER,
                            detail={"kind": kind, "mode": mode})

    def record_success(self, mode: str = "") -> None:
        """Book one successful device dispatch. Closed state returns on
        attribute reads (the per-dispatch steady-state cost); a success
        in half-open closes the breaker and clears the fault window."""
        if not self.enabled or self._state == CLOSED:
            return
        with self._lock:
            if self._state == HALF_OPEN:
                self._transition(CLOSED)
                self._faults.clear()
                self._opened_t = None
                self._probe_tokens = 0

    def reset(self) -> None:
        """Test/bench hook: back to closed with an empty window."""
        with self._lock:
            if self._state != CLOSED:
                self._transition(CLOSED)
            self._faults.clear()
            self._opened_t = None
            self._probe_tokens = 0
            self._probe_granted_t = 0.0
            self._last_fault = None
            self._last_fault_t = None

    # ---- internals ----

    def _transition(self, to: str) -> None:
        """Caller holds self._lock."""
        frm = self._state
        if frm == to:
            return
        self._state = to
        self._transitions[f"{frm}->{to}"] = \
            self._transitions.get(f"{frm}->{to}", 0) + 1
        obs.breaker_transitions.inc(**{"from": frm, "to": to})
        obs.breaker_state.set(_STATE_CODE[to])
        span = tracing.current_span()
        if span.recording:
            span.add_event("breaker.transition", **{"from": frm, "to": to})
        log.warning("device circuit breaker: %s -> %s "
                    "(faults_in_window=%d threshold=%d)",
                    frm, to, len(self._faults), self.threshold)

    # ---- operator surface ----

    def snapshot(self) -> dict[str, object]:
        """The /status device-block + /debug/faults breaker view, and
        what bench's ``device_wedged`` headline reads."""
        with self._lock:
            now = time.monotonic()
            last: dict[str, object] | None = None
            if self._last_fault is not None \
                    and self._last_fault_t is not None:
                last = dict(self._last_fault)
                last["age_s"] = round(now - self._last_fault_t, 3)
            return {
                "enabled": self.enabled,
                "state": self._state,
                "faults_in_window": sum(
                    1 for t in self._faults if now - t <= self.window_s),
                "threshold": self.threshold,
                "window_s": self.window_s,
                "cooldown_s": self.cooldown_s,
                "open_age_s": (round(now - self._opened_t, 3)
                               if self._opened_t is not None
                               and self._state != CLOSED else None),
                "transitions": dict(self._transitions),
                "last_fault": last,
            }


BREAKER = CircuitBreaker()
