"""Fault-injection harness: named faultpoints, true noop when disarmed.

The breaker/watchdog/fallback machinery is only trustworthy if tier-1
can PROVE it — which needs deterministic, targeted failures. This module
is the process-wide registry of named faultpoints: each is a site in the
real code (``FAULTS.hit("device_dispatch_hang")``) that, when ARMED,
injects a delay and/or raises :class:`InjectedFault`; when disarmed it
costs nothing (call sites branch out on ONE attribute read,
``FAULTS.active`` — the PROFILER/TELEMETRY idiom; ``hit`` itself is
never reached).

Arming:
  - test fixture / code: ``FAULTS.arm("flush_error", count=2)`` or the
    ``with FAULTS.armed("device_dispatch_hang", delay_s=5):`` context
  - config: ``storage.robustness_faults: "poll_error:count=1"``
  - env: ``TEMPO_FAULTS="device_dispatch_raise:p=0.5;h2d_delay:delay=0.2"``

Spec grammar: ``name[:k=v[,k=v...]][;name...]`` with keys ``p``
(probability, default 1), ``count`` (fires before auto-disarm, default
unlimited), ``delay`` (seconds slept on fire, default 0), ``raise``
(0/1; default from the catalog — *_raise/*_error faultpoints raise,
*_hang/*_delay ones sleep).

Every faultpoint must be registered in :data:`CATALOG` (description +
wired site) — ``tests/test_faults.py`` asserts the catalog matches
``docs/robustness.md``, the config-docs drift pattern. ``/debug/faults``
renders the live arming state.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from typing import Any, Iterator


class InjectedFault(Exception):
    """The error an armed *_raise/*_error faultpoint throws. A plain
    Exception (not a DeviceFault): non-device sites (backend read,
    flush, poll) must surface it exactly like the IO error it stands in
    for; the dispatch guard classifies it as a device fault only at
    device sites."""


# name -> (description, wired-at). The single source of truth the docs
# drift test checks docs/robustness.md against.
CATALOG: dict[str, tuple[str, str]] = {
    "device_dispatch_raise": (
        "raise from inside the watchdogged device dispatch (backend "
        "error path: breaker fault kind=error, host fallback)",
        "robustness/dispatch.py DispatchGuard.run worker"),
    "device_dispatch_hang": (
        "sleep inside the watchdogged device dispatch (wedged-tunnel "
        "path: watchdog timeout, breaker fault kind=timeout, host "
        "fallback); arm with delay= past the watchdog deadline",
        "robustness/dispatch.py DispatchGuard.run worker"),
    "h2d_delay": (
        "sleep inside the host->device staging put (slow/wedged relay; "
        "with delay past the watchdog deadline the staging dispatch "
        "times out and the group host-routes)",
        "search/multiblock.py place_batch"),
    "dispatch_lock_hang": (
        "sleep while HOLDING the process-wide collective dispatch lock "
        "— makes every other mesh dispatch wait, driving "
        "dispatch-lock timeouts (the PR 1 rendezvous-deadlock class, "
        "now detectable at runtime)",
        "parallel/mesh.py locked_collective"),
    "backend_read_error": (
        "raise from an object-store read (replica/backend flake: the "
        "querier books a partial result instead of failing the query)",
        "backend/local.py + backend/mock.py read"),
    "flush_error": (
        "raise from the ingester's block completion (flush retries + "
        "backoff path; the freshness gauges age instead of lying)",
        "modules/ingester.py TenantInstance.complete_one"),
    "poll_error": (
        "raise from the blocklist poll (a reader that stops seeing new "
        "blocks; the canary and freshness gauges surface it)",
        "db/tempodb.py TempoDB.poll"),
    "replica_error": (
        "raise from an ingester-replica search fan-out leg (partial "
        "results counter reason=replica, SearchMetrics.partial set)",
        "modules/querier.py Querier.search_recent"),
}

# names whose default effect is to RAISE when armed without raise=/delay=
_RAISE_DEFAULT = tuple(
    n for n in CATALOG if n.endswith(("_raise", "_error")))


class _Faultpoint:
    __slots__ = ("name", "probability", "count", "delay_s", "raises",
                 "fired")

    def __init__(self, name: str, probability: float = 1.0,
                 count: int | None = None, delay_s: float = 0.0,
                 raises: bool | None = None):
        self.name = name
        self.probability = float(probability)
        self.count = None if count is None else int(count)
        self.delay_s = float(delay_s)
        self.raises = (name in _RAISE_DEFAULT if raises is None
                       else bool(raises))
        self.fired = 0

    def as_dict(self) -> dict[str, object]:
        return {
            "probability": self.probability,
            "count": self.count,
            "delay_s": self.delay_s,
            "raises": self.raises,
            "fired": self.fired,
        }


class FaultRegistry:
    """Process-wide armed-faultpoint set. ``active`` is the one-word
    fast path every call site reads; it is True only while at least one
    faultpoint is armed, so the disarmed steady state never takes the
    lock or even calls ``hit``."""

    def __init__(self) -> None:
        self.active = False
        self._armed: dict[str, _Faultpoint] = {}
        self._fired_total: dict[str, int] = {}
        self._lock = threading.Lock()
        self._rng = random.Random(0x7e3)  # deterministic under seeding

    # ---- arming ----

    def arm(self, name: str, probability: float = 1.0,
            count: int | None = None, delay_s: float = 0.0,
            raises: bool | None = None) -> None:
        if name not in CATALOG:
            raise ValueError(
                f"unknown faultpoint {name!r}; registered: "
                f"{sorted(CATALOG)}")
        with self._lock:
            self._armed[name] = _Faultpoint(
                name, probability=probability, count=count,
                delay_s=delay_s, raises=raises)
            self.active = True

    def arm_spec(self, spec: str) -> None:
        """Arm from the config/env grammar (module docstring)."""
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            name, _, args = part.partition(":")
            kw: dict[str, Any] = {}
            for kv in args.split(","):
                kv = kv.strip()
                if not kv:
                    continue
                k, _, v = kv.partition("=")
                k = k.strip()
                if k in ("p", "probability"):
                    kw["probability"] = float(v)
                elif k == "count":
                    kw["count"] = int(v)
                elif k in ("delay", "delay_s"):
                    kw["delay_s"] = float(v)
                elif k in ("raise", "raises"):
                    kw["raises"] = v.strip() not in ("0", "false", "")
                else:
                    raise ValueError(
                        f"unknown faultpoint param {k!r} in {part!r}")
            self.arm(name.strip(), **kw)

    def disarm(self, name: str) -> None:
        with self._lock:
            self._armed.pop(name, None)
            self.active = bool(self._armed)

    def disarm_all(self) -> None:
        with self._lock:
            self._armed.clear()
            self.active = False

    def seed(self, seed: int) -> None:
        """Re-seed the probability rolls (deterministic chaos tests)."""
        with self._lock:
            self._rng = random.Random(seed)

    @contextlib.contextmanager
    def armed(self, name: str, **kw: Any) -> Iterator["FaultRegistry"]:
        """Test-fixture arming: disarms on exit even on failure."""
        self.arm(name, **kw)
        try:
            yield self
        finally:
            self.disarm(name)

    # ---- the injection site ----

    def hit(self, name: str) -> None:
        """Fire faultpoint `name` if armed: sleep its delay, then raise
        if it is a raising point. Call sites guard with ``if
        FAULTS.active:`` so this is never reached while disarmed."""
        with self._lock:
            fp = self._armed.get(name)
            if fp is None:
                return
            if fp.probability < 1.0 and self._rng.random() >= fp.probability:
                return
            fp.fired += 1
            self._fired_total[name] = self._fired_total.get(name, 0) + 1
            if fp.count is not None and fp.fired >= fp.count:
                del self._armed[name]
                self.active = bool(self._armed)
            delay, raises = fp.delay_s, fp.raises
        from tempo_tpu.observability import metrics as obs

        obs.faults_injected.inc(faultpoint=name)
        if delay > 0:
            time.sleep(delay)
        if raises:
            raise InjectedFault(f"injected fault: {name}")

    # ---- operator surface ----

    def snapshot(self) -> dict[str, object]:
        """/debug/faults payload: catalog + live arming state."""
        with self._lock:
            armed = {n: fp.as_dict() for n, fp in self._armed.items()}
            fired = dict(self._fired_total)
        return {
            "active": self.active,
            "armed": armed,
            "fired_total": fired,
            "catalog": {n: {"description": d, "site": s}
                        for n, (d, s) in sorted(CATALOG.items())},
        }


FAULTS = FaultRegistry()
