"""Deadline-bounded device dispatch: the watchdog around every kernel.

A wedged device tunnel hangs the CALLING thread at the dispatch (or its
H2D/D2H transfer) with no way to interrupt it from Python. The guard
therefore runs the dispatch body on a watchdog worker thread and bounds
the WAIT: past ``search_device_dispatch_timeout_s`` (clamped to the
request deadline's remaining budget) the caller abandons the worker,
books a breaker fault with the dispatch's profiler mode as stage
context, and raises :class:`DeviceDispatchTimeout` — which the batcher
catches and answers through the byte-identical host path. A backend
error from the dispatch (XLA runtime / injected) books the same way as
kind=error.

The abandoned worker thread finishes (or never does) on its own; the
pool bounds how many can leak — and after ``threshold`` faults the
breaker is open, so nothing new is submitted at a wedged device anyway.

Noop contract: with the breaker disabled and no faultpoint armed,
``run`` is two attribute reads and a direct call — no thread handoff,
no clock, byte-identical results (bench phase ``chaos`` asserts <2%
dispatch overhead). With the guard active but the watchdog disabled
(``timeout_s <= 0`` and no request deadline) the body runs inline too:
faults are still classified, only the hang-bounding needs the thread.

Thread-local plumbing: profiler records finish on the thread that runs
the dispatch, and query-stats attribution collects them via a
THREAD-LOCAL collector stack (observability/profile.collect_records).
The guard propagates the submitter's open collector stack into the
worker so a guarded dispatch attributes exactly like an inline one.
"""

from __future__ import annotations

import concurrent.futures
import contextvars
import threading
from typing import Callable, TypeVar

from tempo_tpu.observability.flightrecorder import (RECORDER,
                                                    TRIGGER_WATCHDOG)

from . import deadline as _deadline
from .breaker import BREAKER
from .faults import FAULTS, InjectedFault


class DeviceFault(Exception):
    """A device dispatch failed in a way the host path can absorb."""


class DeviceDispatchTimeout(DeviceFault):
    """The watchdog deadline elapsed with the dispatch still running."""


class DeviceDispatchError(DeviceFault):
    """The dispatch raised a backend/runtime (or injected) error."""


class DispatchLockTimeout(DeviceFault):
    """The collective dispatch-lock wait exceeded its bound — some other
    dispatch is wedged while holding it (the PR 1 rendezvous-deadlock
    class, detectable at runtime instead of merely avoided)."""


T = TypeVar("T")


def _is_device_error(e: BaseException) -> bool:
    """Errors the host path can absorb: injected faults, jax/XLA
    runtime errors, bare RuntimeErrors from the backend. Anything else
    (ValueError from a shape bug, a real KeyError) is a BUG and must
    propagate un-wrapped — silently host-retrying it would mask it."""
    if isinstance(e, InjectedFault):
        return True
    mod = type(e).__module__ or ""
    if mod.startswith(("jax", "jaxlib")):
        return True
    return isinstance(e, RuntimeError)


class DispatchGuard:
    """Process-wide dispatch watchdog (module singleton ``GUARD``, the
    PROFILER idiom). ``run(mode, fn)`` executes one device dispatch
    body; ``mode`` is the profiler's dispatch mode (single | batched |
    coalesced | mesh | dict_probe | h2d | d2h) and becomes the fault's
    stage context."""

    # bounds leaked hung workers between breaker trips; the breaker
    # opens after `threshold` faults, so steady-state leakage is zero
    _MAX_WORKERS = 32

    def __init__(self) -> None:
        self.timeout_s = 30.0       # search_device_dispatch_timeout_s
        self.lock_timeout_s = 60.0  # search_dispatch_lock_timeout_s
        self._pool: concurrent.futures.ThreadPoolExecutor | None = None
        self._pool_lock = threading.Lock()

    @property
    def active(self) -> bool:
        """Whether dispatches route through the guard at all — the one
        condition of the noop contract: breaker off + faults disarmed
        means every dispatch site runs exactly the historical inline
        code after two attribute reads."""
        return BREAKER.enabled or FAULTS.active

    def _ensure_pool(self) -> concurrent.futures.ThreadPoolExecutor:
        pool = self._pool
        if pool is None:
            with self._pool_lock:
                pool = self._pool
                if pool is None:
                    pool = self._pool = \
                        concurrent.futures.ThreadPoolExecutor(
                            max_workers=self._MAX_WORKERS,
                            thread_name_prefix="device-dispatch")
        return pool

    def run(self, mode: str, fn: Callable[[], T]) -> T:
        """Execute one device dispatch body under the watchdog. Returns
        fn()'s result; raises DeviceFault (timeout / classified backend
        error, breaker fault booked) or DeadlineExceeded (the request's
        budget ran out before the dispatch could start)."""
        if not (BREAKER.enabled or FAULTS.active):
            return fn()
        from tempo_tpu.observability import profile

        timeout = self.timeout_s if self.timeout_s > 0 else None
        dl = _deadline.current()
        if dl is not None:
            rem = dl.remaining()
            if rem <= 0:
                raise _deadline.DeadlineExceeded(
                    f"request deadline expired before {mode} dispatch")
            timeout = rem if timeout is None else min(timeout, rem)

        if timeout is None:
            # no watchdog wanted: inline, but still inject + classify
            try:
                if FAULTS.active:
                    FAULTS.hit("device_dispatch_raise")
                    FAULTS.hit("device_dispatch_hang")
                out = fn()
            except DeviceFault:
                raise  # already booked at its source (lock timeout)
            except _deadline.DeadlineExceeded:
                raise
            except Exception as e:
                if _is_device_error(e):
                    BREAKER.record_fault("error", mode=mode)
                    raise DeviceDispatchError(
                        f"{mode}: {type(e).__name__}: {e}") from e
                raise
            BREAKER.record_success(mode=mode)
            return out

        # the submitter's open profiler-record collectors (thread-local)
        # follow the dispatch onto the worker thread — see module doc
        stack = getattr(profile._collect_local, "stack", None)
        ctx = contextvars.copy_context()

        def worker() -> T:
            if stack is not None:
                profile._collect_local.stack = stack
            try:
                if FAULTS.active:
                    FAULTS.hit("device_dispatch_raise")
                    FAULTS.hit("device_dispatch_hang")
                return ctx.run(fn)
            finally:
                if stack is not None:
                    profile._collect_local.stack = None

        fut = self._ensure_pool().submit(worker)
        try:
            out = fut.result(timeout=timeout)
        except concurrent.futures.TimeoutError:
            fut.cancel()  # no-op if running; the worker is abandoned
            BREAKER.record_fault("timeout", mode=mode)
            # flight recorder: a watchdog fire means a dispatch is
            # wedged RIGHT NOW — snapshot before the abandonment
            # propagates (no lock held here)
            if RECORDER.enabled:
                RECORDER.record(TRIGGER_WATCHDOG,
                                detail={"mode": mode,
                                        "timeout_s": round(timeout, 3)})
            raise DeviceDispatchTimeout(
                f"device dispatch ({mode}) exceeded its "
                f"{timeout:.3f}s watchdog deadline") from None
        except DeviceFault:
            raise  # booked at its source (e.g. dispatch-lock timeout)
        except _deadline.DeadlineExceeded:
            raise
        except Exception as e:
            if _is_device_error(e):
                BREAKER.record_fault("error", mode=mode)
                raise DeviceDispatchError(
                    f"{mode}: {type(e).__name__}: {e}") from e
            raise
        BREAKER.record_success(mode=mode)
        return out


GUARD = DispatchGuard()
