"""Robustness substrate: graceful degradation when the device dies.

A wedged device tunnel has already cost two bench rounds (BENCH r04/r05
recorded zeroed CPU-fallback headlines), and until this package the
SERVING path had no defense at all — only bench.py's preflight knew how
to fall back to CPU; a production query hitting a hung or erroring
device dispatch just hung with it. Because the engine keeps
byte-identical host paths for every scan and probe variant (the
dual-path premise of "To GPU or Not to GPU", arxiv 2605.15957), graceful
degradation is purely a CONTROL-PLANE problem, solved by three
cooperating pieces:

  deadline.py   request deadlines (contextvar, http → frontend →
                querier → TempoDB via the worker pool's context copy)
                so sharded sub-queries stop queueing behind a dead
                device instead of stacking.
  dispatch.py   a watchdog around every device dispatch
                (``search_device_dispatch_timeout_s``): a dispatch that
                exceeds it — or raises a backend error — is recorded as
                a device fault with its profiler stage context and
                surfaces as a catchable :class:`DeviceFault` instead of
                a hang.
  breaker.py    the device circuit breaker: N faults within a window
                trip it (closed → open → half-open with probe
                dispatches to recover); while it blocks,
                ``planner.stage_veto`` / ``pipeline._use_device_probe``
                / the batcher route everything through the existing
                host paths and ``/status``'s device block + bench's
                ``device_wedged`` headline read breaker state instead
                of ad-hoc probing.
  faults.py     the fault-injection harness proving all of the above in
                tier-1: named faultpoints armable by config/env/test
                fixture, compiled to a true noop when disarmed (the
                PROFILER idiom), exposed at ``/debug/faults``.

Noop contract: breaker off + faults disarmed costs one attribute read
per dispatch site and results are byte-identical (bench phase ``chaos``
asserts both, the PR 5/7/8 pattern). Imports here stay LEAF-LEVEL
(stdlib + observability only) so search/parallel/db can all depend on
this package without cycles.
"""

from __future__ import annotations

from .breaker import BREAKER, CircuitBreaker
from .deadline import Deadline, DeadlineExceeded
from .dispatch import (
    GUARD,
    DeviceDispatchError,
    DeviceDispatchTimeout,
    DeviceFault,
    DispatchLockTimeout,
)
from .faults import FAULTS, InjectedFault
from . import deadline

__all__ = [
    "BREAKER", "CircuitBreaker", "Deadline", "DeadlineExceeded",
    "DeviceDispatchError", "DeviceDispatchTimeout", "DeviceFault",
    "DispatchLockTimeout", "FAULTS", "GUARD", "InjectedFault",
    "configure", "deadline",
]


def configure(breaker_enabled: bool | None = None,
              fault_threshold: int | None = None,
              window_s: float | None = None,
              cooldown_s: float | None = None,
              dispatch_timeout_s: float | None = None,
              lock_timeout_s: float | None = None,
              faults_spec: str | None = None) -> None:
    """Apply config (TempoDBConfig.search_breaker_* /
    search_device_dispatch_timeout_s / search_dispatch_lock_timeout_s /
    robustness_faults) to the process-wide breaker, dispatch guard and
    fault registry — the most recent TempoDB wins, matching how the
    profiler/planner/query-stats configure. The ``TEMPO_FAULTS`` env
    spec is applied in ADDITION to the config spec so a triage session
    can arm a faultpoint without a config rollout."""
    import os

    if fault_threshold is not None:
        BREAKER.threshold = max(1, int(fault_threshold))
    if window_s is not None:
        BREAKER.window_s = float(window_s)
    if cooldown_s is not None:
        BREAKER.cooldown_s = float(cooldown_s)
    if breaker_enabled is not None:
        BREAKER.enabled = bool(breaker_enabled)
    if dispatch_timeout_s is not None:
        GUARD.timeout_s = float(dispatch_timeout_s)
    if lock_timeout_s is not None:
        GUARD.lock_timeout_s = float(lock_timeout_s)
    if faults_spec is not None:
        if faults_spec:
            FAULTS.arm_spec(faults_spec)
        env = os.environ.get("TEMPO_FAULTS", "")
        if env:
            FAULTS.arm_spec(env)
