"""Request deadlines: a contextvar budget the whole search path honors.

A query against a wedged device used to stack sub-request after
sub-request behind the dead dispatch — the frontend's fan-out kept
queueing work no one would ever drain. A :class:`Deadline` set at the
HTTP layer (``X-Tempo-Timeout-S`` header, or the
``search_request_timeout_s`` config default) rides the contextvar into
every in-process layer for free: the frontend's QueueWorkerPool runs
each sub-request under a copy of the caller's context
(modules/queue.py), so frontend → querier → TempoDB → batcher all see
the same budget without any parameter threading. Consumers:

  - the batcher stops dispatching new groups once the deadline expires
    (the response goes out PARTIAL instead of stacking),
  - the dispatch guard clamps its per-dispatch watchdog to the
    remaining budget,
  - the frontend fails remaining sub-requests fast with
    :class:`DeadlineExceeded` (counted as partial, never retried),
  - the querier's replica fan-out stops waiting for stragglers.

The coalescer's window/flush threads do NOT inherit a submitter's
deadline (deliberate: one request's budget must not bound a fused
dispatch serving seven others); the watchdog's own
``search_device_dispatch_timeout_s`` bounds those.
"""

from __future__ import annotations

import contextlib
import contextvars
import time
from typing import Iterator


class DeadlineExceeded(Exception):
    """The request's deadline expired before this step could run."""


class Deadline:
    __slots__ = ("t_end", "timeout_s")

    def __init__(self, timeout_s: float):
        self.timeout_s = float(timeout_s)
        self.t_end = time.monotonic() + self.timeout_s

    def remaining(self) -> float:
        return self.t_end - time.monotonic()

    @property
    def expired(self) -> bool:
        return time.monotonic() >= self.t_end


_ACTIVE: contextvars.ContextVar[Deadline | None] = contextvars.ContextVar(
    "tempo_request_deadline", default=None)


def current() -> Deadline | None:
    return _ACTIVE.get()


def remaining() -> float | None:
    """Seconds left on the active deadline, or None when none is set."""
    dl = _ACTIVE.get()
    return None if dl is None else dl.remaining()


def expired() -> bool:
    """True only when a deadline is set AND it has passed — no deadline
    means unbounded, exactly the pre-deadline behavior."""
    dl = _ACTIVE.get()
    return dl is not None and dl.expired


@contextlib.contextmanager
def start(timeout_s: float | None) -> Iterator[Deadline | None]:
    """Install a request deadline for the body; <= 0 / None is a no-op
    (no deadline — the historical behavior)."""
    if not timeout_s or timeout_s <= 0:
        yield None
        return
    dl = Deadline(timeout_s)
    token = _ACTIVE.set(dl)
    try:
        yield dl
    finally:
        _ACTIVE.reset(token)
