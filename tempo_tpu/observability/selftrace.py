"""Dogfood pipeline gate: self-traces become first-class ingested data.

`selftrace_ingest_enabled` (a `self_tracing:` key, default off) closes
the "tempo traces tempo" loop: tracing.InProcessExporter pushes every
finished self-trace span through the normal distributor/TenantInstance
ingest path into the reserved ``_selftrace`` tenant, and THIS module
enriches those traces at two points the plain exporter cannot see:

  - ``lower_dispatch``: a finished profiler dispatch record
    (observability/profile.Dispatch) is lowered into per-stage CHILD
    spans — build/h2d/compile/execute/d2h/lock_wait — under the span
    that was active when the dispatch closed, with transfer bytes and
    the jit-cache verdict as attributes. Stage times are reconstructed
    (laid back-to-back ending at the lowering instant), not observed
    live, so structural queries like
    ``{ span.stage = "h2d" && duration > 50ms }`` work over real
    dispatch telemetry.
  - ``annotate_query``: a finished request-scope QueryStats breakdown
    attaches as ``query.*`` attributes on the request span, so the
    trace of a slow search carries its own cost accounting.

Noop contract (the PR 9 stance, statically checked by the
NoopContractChecker): with the gate off every call site pays ONE
attribute read — no allocation, no clock, no lock — and outputs are
byte-identical. Feedback safety: the ingest-of-self-spans path runs
under tracing._suppressed, so the spans describing the self-ingest are
never themselves traced; additionally both hooks bail when the current
span is not recording, which covers suppressed and sampled-out paths.

The anomaly flight recorder (observability/flightrecorder.RECORDER)
shares this gate: breaker trips, watchdog fires and slow queries
snapshot bounded diagnostic bundles whose trace ids resolve in
``_selftrace``.
"""

from __future__ import annotations

import time

from . import tracing

# lowering order — stages are laid back-to-back in the order the
# dispatch path actually runs them (profile.STAGES minus the reorder:
# lock_wait precedes the guarded body on mesh paths)
_STAGE_ORDER = ("lock_wait", "build", "h2d", "compile", "execute", "d2h")


class SelfTraceGate:
    """Process-wide gate (module singleton ``SELFTRACE``, the PROFILER
    idiom): tracing.init_tracing flips ``ingest_enabled`` from the
    ``self_tracing:`` config block; hot call sites read the one
    attribute and branch out when the dogfood loop is off."""

    def __init__(self) -> None:
        self.ingest_enabled = False

    def lower_dispatch(self, rec, parent=None) -> None:
        """Lower a finished profiler ``Dispatch`` record into per-stage
        child spans of `parent` (default: the current span). The record
        holds durations, not timestamps, so the children are synthesized
        back-to-back ending now — inside the real dispatch window to
        clock resolution, and honest about per-stage duration, which is
        what structural duration predicates query."""
        if not self.ingest_enabled:
            return
        tracer = tracing.get_tracer()
        if tracer is None:
            return
        if parent is None:
            parent = tracing.current_span()
        if not parent.recording or not rec.stages:
            return
        end_ns = time.time_ns()
        cursor = end_ns - int(sum(rec.stages.values()) * 1e9)
        for stage in _STAGE_ORDER:
            sec = rec.stages.get(stage)
            if sec is None:
                continue
            dur_ns = int(sec * 1e9)
            span = tracer.start_span(f"dispatch.{stage}",
                                     parent=parent.context,
                                     stage=stage, mode=rec.mode)
            if span.recording:
                if stage == "h2d" and rec.h2d_bytes:
                    span.set_attribute("bytes", rec.h2d_bytes)
                elif stage == "d2h" and rec.d2h_bytes:
                    span.set_attribute("bytes", rec.d2h_bytes)
                if stage in ("compile", "execute") and rec.jit is not None:
                    span.set_attribute("jit_cache", rec.jit)
                span.start_ns = cursor
                span.end(end_ns=cursor + dur_ns)
            cursor += dur_ns

    def annotate_query(self, d: dict) -> None:
        """Attach a finished request-scope QueryStats dict (to_dict
        form) as flat ``query.*`` attributes on the current span — the
        request-scope span when called from the registry's publish on
        the request thread. Scalars only: nested breakdowns stay in the
        explain payload; the span carries the headline costs a trace
        reader triages by."""
        if not self.ingest_enabled:
            return
        span = tracing.current_span()
        if not span.recording:
            return
        span.set_attribute("query.wall_ms", d.get("wall_ms", 0.0))
        span.set_attribute("query.device_seconds",
                           d.get("device_seconds", 0.0))
        span.set_attribute("query.blocks_inspected",
                           d.get("blocks_inspected", 0))
        b = d.get("bytes_inspected") or {}
        span.set_attribute("query.bytes_host", b.get("host", 0))
        span.set_attribute("query.bytes_device", b.get("device", 0))
        span.set_attribute("query.dispatches", d.get("dispatches", 0))
        if d.get("fused_dispatches"):
            span.set_attribute("query.fused_dispatches",
                               d["fused_dispatches"])
        if d.get("subqueries"):
            span.set_attribute("query.subqueries", d["subqueries"])


SELFTRACE = SelfTraceGate()


def configure(ingest_enabled: bool | None = None,
              flight_recorder_max: int | None = None) -> SelfTraceGate:
    """Apply the self_tracing config block to the process gate AND the
    flight recorder (one gate, two surfaces — the recorder's triggers
    are only meaningful while the triggering trace is queryable)."""
    from . import flightrecorder

    if ingest_enabled is not None:
        SELFTRACE.ingest_enabled = bool(ingest_enabled)
        flightrecorder.RECORDER.enabled = bool(ingest_enabled)
    if flight_recorder_max is not None:
        flightrecorder.RECORDER.resize(int(flight_recorder_max))
    return SELFTRACE
