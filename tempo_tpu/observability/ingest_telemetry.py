"""Write-path telemetry: how stale is search, and where is the time?

The read path is deeply observable (the dispatch profiler, the
per-query inspector); this module gives the WRITE path that feeds it
the same treatment. An ingest-time record threads through

    push ack -> live-trace cut -> block cut -> backend flush
             -> blocklist-poll visibility

and every hand-off lands in ``tempo_ingest_stage_seconds{stage}`` so
"push->searchable" decomposes into the stage that actually ate it.
Three layers:

1. **Stage timestamps.** The distributor times the ack; the ingester
   stamps each live trace's first push (reusing the clock read the ack
   path already pays), carries the oldest stamp through the head block
   and every ``_Completing`` entry, and the flush books cut->flushed.
   The reader's poll pairs newly visible block ids against flush
   records registered here and closes the loop with ``poll_visible``
   and the end-to-end ``push_to_searchable`` observation.

2. **Backlog visibility.** Flush queue depth, retry/backoff attempts,
   WAL replay duration/bytes, poll cycle duration + per-tenant
   blocklist length + tenant-index staleness, compaction outstanding
   bytes + per-run duration — with self-trace spans on flush/poll/
   compaction so a slow cycle links to an exemplar trace.

3. **The freshness canary** (:class:`IngestCanary`, opt-in): a real
   tagged trace pushed per interval and polled through real search
   until visible — the black-box check that catches a wedged
   flush/poll loop that every white-box stage metric individually
   misses (each stage looks "idle", none looks "stuck").

Noop contract (the profiler / query-stats stance):
``ingest_telemetry_enabled: false`` means record sites branch out on
one attribute read — no clock reads beyond the ones ingest already
makes, no locks, and byte-identical ingest output (the bench
``freshness`` phase asserts both the noop and the <2% enabled ack
overhead).

Surfaces: ``/debug/ingest`` (per-tenant live/unflushed/backlog + last
flush/poll ages + canary state), the ``/status`` ``ingest`` block, a
rate-limited slow-flush JSON log on ``tempo_tpu.slowflush`` past
``ingest_slow_flush_log_s`` (the slow-query log's token-bucket
limiter, shared class), and the bench ``freshness`` phase.
"""

from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict, deque

from . import metrics as obs
from .log import TenantTokenBucket, get_logger

log = get_logger("tempo_tpu.ingest")
slow_flush_log = get_logger("tempo_tpu.slowflush")

# flush->visibility pairing entries kept (tenant, block_id) -> record.
# Bounded: a reader that never polls (write-only process) must not
# grow this forever; dropped entries just lose one histogram point.
_PENDING_MAX = 4096
_SLOW_RING = 32


def _attempt_bucket(attempt: int) -> str:
    return str(attempt) if attempt < 4 else "4+"


class IngestTelemetry:
    """Process-wide write-path telemetry sink (module singleton
    ``TELEMETRY``, the PROFILER/REGISTRY idiom: the most recent App's
    config wins)."""

    def __init__(self):
        self.enabled = True
        self.slow_flush_log_s = 30.0
        self._lock = threading.Lock()
        # (tenant, block_id) -> (flush_done_mono, oldest_ingest_mono)
        self._pending: OrderedDict[tuple, tuple] = OrderedDict()
        # tenant -> {t_mono, duration_s, block_id, objects}
        self._last_flush: dict[str, dict] = {}
        # tenant -> {queue_length, oldest_unflushed_s, t_mono}
        self._queues: dict[str, dict] = {}
        self._last_poll: dict = {}
        self._wal_replay: dict = {}
        self._freshness: dict[str, float] = {}
        self._polled_tenants: set[str] = set()
        self._slow_flushes: deque = deque(maxlen=_SLOW_RING)
        self._limiter = TenantTokenBucket()
        self.canary = None  # IngestCanary, attached by the App

    # ---- write-path recording (callers gate on .enabled) ----

    # stage observations are deliberately tenant-UNLABELED (the stage
    # histogram's cardinality is |stages|, not |stages| x |tenants|),
    # so these take no tenant — per-tenant write-path state lives in
    # the gauges (queue length, oldest unflushed, freshness)

    def record_push_ack(self, seconds: float) -> None:
        obs.ingest_stage_seconds.observe(seconds, stage="push_ack")

    def record_live_cut(self, age_s: float) -> None:
        obs.ingest_stage_seconds.observe(age_s, stage="live_cut")

    def record_block_cut(self, age_s: float) -> None:
        obs.ingest_stage_seconds.observe(age_s, stage="block_cut")

    def record_flush(self, tenant: str, block_id: str, *,
                     write_s: float, cut_to_flush_s: float,
                     oldest_ingest: float | None, objects: int = 0,
                     attempts: int = 0, trace_id: str | None = None
                     ) -> None:
        """One SUCCESSFUL block completion. Registers the block for the
        poll-visibility pairing and emits the slow-flush log line past
        the threshold."""
        now = time.monotonic()
        obs.flush_duration_seconds.observe(write_s, tenant=tenant)
        obs.ingest_stage_seconds.observe(write_s, stage="flush_write")
        if cut_to_flush_s >= 0:
            obs.ingest_stage_seconds.observe(cut_to_flush_s, stage="flush")
        entry = None
        if self.slow_flush_log_s > 0 and write_s >= self.slow_flush_log_s:
            entry = {"msg": "slow flush", "tenant": tenant,
                     "block_id": block_id,
                     "threshold_s": self.slow_flush_log_s,
                     "duration_s": round(write_s, 3),
                     "cut_to_flush_s": round(max(0.0, cut_to_flush_s), 3),
                     "objects": objects, "attempts": attempts}
            if trace_id:
                entry["trace_id"] = trace_id
        with self._lock:
            self._pending[(tenant, block_id)] = (now, oldest_ingest)
            while len(self._pending) > _PENDING_MAX:
                self._pending.popitem(last=False)
            self._last_flush[tenant] = {
                "t_mono": now, "duration_s": write_s,
                "block_id": block_id, "objects": objects,
            }
            if entry is not None:
                self._slow_flushes.append(entry)
        if entry is not None:
            obs.slow_flushes.inc(tenant=tenant)
            if self._limiter.allow(tenant):
                slow_flush_log.warning("%s", json.dumps(
                    entry, separators=(",", ":"), sort_keys=True))

    def record_flush_retry(self, attempt: int) -> None:
        # attempt-bucket only, no tenant: the failure itself is already
        # tenant-attributed by tempo_ingester_failed_flushes_total
        obs.flush_retries.inc(attempt=_attempt_bucket(attempt))

    def set_queue_state(self, tenant: str, queue_length: int,
                        oldest_unflushed_s: float) -> None:
        obs.flush_queue_length.set(queue_length, tenant=tenant)
        obs.oldest_unflushed.set(round(oldest_unflushed_s, 3),
                                 tenant=tenant)
        with self._lock:
            self._queues[tenant] = {
                "queue_length": queue_length,
                "oldest_unflushed_s": round(oldest_unflushed_s, 3),
                "t_mono": time.monotonic(),
            }

    def record_wal_replay(self, duration_s: float, blocks: int,
                          nbytes: int, corrupt_records: int = 0) -> None:
        obs.wal_replay_seconds.set(round(duration_s, 6))
        obs.wal_replayed_blocks.set(blocks)
        obs.wal_replayed_bytes.set(nbytes)
        with self._lock:
            self._wal_replay = {
                "duration_s": round(duration_s, 6), "blocks": blocks,
                "bytes": nbytes, "corrupt_records": corrupt_records,
            }

    # ---- read-side recording (poller / compaction feed) ----

    def record_poll(self, duration_s: float, metas: dict) -> None:
        """One blocklist poll cycle: duration + per-tenant blocklist
        length + freshness gauge, and resolve flush->visibility pairs
        for block ids this poll made searchable. Tenants that vanished
        from the poll (or lost all blocks) get their per-tenant series
        REMOVED — a frozen last value would read as 'fresh' for a
        tenant whose searchable data is gone."""
        now = time.monotonic()
        now_unix = time.time()
        obs.blocklist_poll_seconds.observe(duration_s)
        live_by_tenant: dict[str, set] = {}
        fresh_now: dict[str, float] = {}
        for tenant, ms in metas.items():
            live_by_tenant[tenant] = {m.block_id for m in ms}
            obs.blocklist_length.set(len(ms), tenant=tenant)
            newest = max((m.end_time for m in ms), default=0)
            if newest:
                fresh_now[tenant] = round(max(0.0, now_unix - newest), 3)
                obs.search_freshness.set(fresh_now[tenant], tenant=tenant)
        with self._lock:
            fresh_gone = [t for t in self._freshness if t not in fresh_now]
            self._freshness = fresh_now
            tenants_gone = self._polled_tenants - set(metas)
            self._polled_tenants = set(metas)
            resolved = [k for k in self._pending
                        if k[1] in live_by_tenant.get(k[0], ())]
            pairs = [(k, self._pending.pop(k)) for k in resolved]
            self._last_poll = {
                "t_mono": now, "duration_s": round(duration_s, 6),
                "tenants": len(metas),
                "blocks": sum(len(ms) for ms in metas.values()),
            }
        for t in fresh_gone:
            obs.search_freshness.remove(tenant=t)
        for t in tenants_gone:
            # drop EVERY per-tenant series this sink owns: a tenant
            # that lost its backend presence must not keep exporting
            # frozen index-age/backlog values (the ingester re-sets the
            # queue gauges on its next sweep for instances it still
            # holds, so a live-but-unflushed tenant self-heals)
            obs.blocklist_length.remove(tenant=t)
            obs.blocklist_index_age.remove(tenant=t)
            obs.flush_queue_length.remove(tenant=t)
            obs.oldest_unflushed.remove(tenant=t)
            with self._lock:
                self._queues.pop(t, None)
        for (_tenant, _bid), (flush_t, oldest_ingest) in pairs:
            obs.ingest_stage_seconds.observe(max(0.0, now - flush_t),
                                             stage="poll_visible")
            if oldest_ingest is not None:
                obs.ingest_stage_seconds.observe(
                    max(0.0, now - oldest_ingest),
                    stage="push_to_searchable")

    def record_index_age(self, tenant: str, age_s: float) -> None:
        obs.blocklist_index_age.set(round(max(0.0, age_s), 3),
                                    tenant=tenant)

    def record_compaction_backlog(self, tenant: str, nbytes: int,
                                  blocks: int = 0) -> None:
        obs.compaction_outstanding_bytes.set(nbytes, tenant=tenant)
        obs.compaction_outstanding_blocks.set(blocks, tenant=tenant)

    def record_compaction_run(self, duration_s: float) -> None:
        obs.compaction_duration_seconds.observe(duration_s)

    # ---- surfaces ----

    def status(self) -> dict:
        """The compact /status ``ingest`` block: freshness + backlog at
        a glance (ages relative to now, so the block is directly
        readable)."""
        now = time.monotonic()
        with self._lock:
            tenants = sorted(set(self._freshness) | set(self._queues))
            out = {
                "freshness_seconds": dict(self._freshness),
                "oldest_unflushed_seconds": {
                    t: self._queues[t]["oldest_unflushed_s"]
                    for t in tenants if t in self._queues},
                "last_poll_age_s": (
                    round(now - self._last_poll["t_mono"], 3)
                    if self._last_poll else None),
            }
            if self.canary is not None:
                out["canary"] = self.canary.state()
        return out

    def debug_snapshot(self, app=None) -> dict:
        """The full /debug/ingest document. `app` (when this process
        runs ingesters) contributes the LIVE view — tenants' in-memory
        traces and completing queues — next to the history this sink
        holds."""
        now = time.monotonic()
        with self._lock:
            out = {
                "enabled": self.enabled,
                "slow_flush_log_s": self.slow_flush_log_s,
                "freshness_seconds": dict(self._freshness),
                "queues": {
                    t: {"queue_length": q["queue_length"],
                        "oldest_unflushed_s": q["oldest_unflushed_s"],
                        "age_s": round(now - q["t_mono"], 3)}
                    for t, q in sorted(self._queues.items())},
                "last_flush": {
                    t: {"age_s": round(now - f["t_mono"], 3),
                        "duration_s": round(f["duration_s"], 6),
                        "block_id": f["block_id"],
                        "objects": f["objects"]}
                    for t, f in sorted(self._last_flush.items())},
                "last_poll": (
                    {k: v for k, v in dict(
                        self._last_poll,
                        age_s=round(now - self._last_poll["t_mono"], 3)
                    ).items() if k != "t_mono"}
                    if self._last_poll else None),
                "wal_replay": dict(self._wal_replay) or None,
                "pending_visibility": len(self._pending),
                "slow_flushes": list(self._slow_flushes),
            }
        if self.canary is not None:
            out["canary"] = self.canary.state()
        if app is not None and getattr(app, "ingesters", None):
            live = {}
            for iid, ing in app.ingesters.items():
                for tenant in ing.tenants():
                    inst = ing.instance(tenant)
                    with inst.lock:
                        d = live.setdefault(tenant, {
                            "live_traces": 0, "head_objects": 0,
                            "completing_blocks": 0, "recent_blocks": 0})
                        d["live_traces"] += len(inst.live)
                        d["head_objects"] += len(inst.head)
                        d["completing_blocks"] += len(inst.completing)
                        d["recent_blocks"] += len(inst.recent)
            out["live"] = live
        return out

    def reset(self) -> None:
        with self._lock:
            self._pending.clear()
            self._last_flush.clear()
            self._queues.clear()
            self._last_poll = {}
            self._wal_replay = {}
            self._freshness = {}
            self._polled_tenants = set()
            self._slow_flushes.clear()
            self._limiter = TenantTokenBucket()


TELEMETRY = IngestTelemetry()


def configure(enabled: bool | None = None,
              slow_flush_log_s: float | None = None) -> IngestTelemetry:
    """Apply AppConfig.ingest_telemetry_enabled / ingest_slow_flush_log_s
    to the process sink (most recent App wins, the profiler idiom)."""
    if enabled is not None:
        TELEMETRY.enabled = bool(enabled)
    if slow_flush_log_s is not None:
        TELEMETRY.slow_flush_log_s = float(slow_flush_log_s)
    return TELEMETRY


class IngestCanary:
    """Synthetic freshness prober: push one tagged trace per interval,
    poll BACKEND search until it is visible, export the measured
    push->searchable. Deliberately black-box — it exercises the same
    distributor -> ingester -> WAL -> flush -> poll -> scan pipeline a
    tenant's data takes (the search_fn the App wires is the reader
    TempoDB, which sees a trace only after flush+poll; the ingester
    live path would report ~0 and mask the very wedge this exists to
    catch).

    Off by default (``ingest_canary_enabled``): it writes real blocks
    into its tenant and keeps a poll loop running. Tests and the bench
    drive :meth:`probe_once` directly instead of the thread."""

    def __init__(self, push_fn, search_fn, tenant: str = "canary",
                 interval_s: float = 30.0, timeout_s: float | None = None,
                 poll_step_s: float = 0.25):
        self.push_fn = push_fn
        self.search_fn = search_fn
        self.tenant = tenant
        self.interval_s = interval_s
        # a probe that outlives flush tick + poll tick + margin is a
        # failure; default scales with the probe interval so operators
        # tightening the interval tighten the alarm with it
        self.timeout_s = timeout_s if timeout_s else max(60.0,
                                                         2 * interval_s)
        self.poll_step_s = poll_step_s
        self.probes = 0
        self.failures = 0
        self.last_freshness_s: float | None = None
        self.last_error: str | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _make_batch(self, canary_id: str):
        """One single-span trace stamped NOW, tagged canary.id=<id> —
        the unique tag is what the probe searches for; real wall-clock
        times keep the freshness gauge honest for the canary tenant."""
        import os

        from tempo_tpu import tempopb

        rs = tempopb.ResourceSpans()
        kv = rs.resource.attributes.add()
        kv.key = "service.name"
        kv.value.string_value = "tempo-canary"
        ss = rs.scope_spans.add()
        ss.scope.name = "ingest-canary"
        span = ss.spans.add()
        span.trace_id = os.urandom(16)
        span.span_id = os.urandom(8)
        span.name = "canary-probe"
        now_ns = time.time_ns()
        span.start_time_unix_nano = now_ns - 1_000_000
        span.end_time_unix_nano = now_ns
        kv = span.attributes.add()
        kv.key = "canary.id"
        kv.value.string_value = canary_id
        return rs

    def probe_once(self, timeout_s: float | None = None) -> float | None:
        """One full round trip. Returns the measured push->searchable
        seconds, or None on timeout/error (failure counter bumped)."""
        import uuid

        from tempo_tpu import tempopb

        canary_id = uuid.uuid4().hex
        deadline_s = timeout_s if timeout_s is not None else self.timeout_s
        self.probes += 1
        # each probe reports its OWN failure cause — a stale error from
        # the previous round must not masquerade as this timeout's
        self.last_error = None
        t0 = time.monotonic()
        try:
            self.push_fn(self.tenant, [self._make_batch(canary_id)])
        except Exception as e:  # noqa: BLE001 — a refused push IS a signal
            self.failures += 1
            self.last_error = f"push: {type(e).__name__}: {e}"
            obs.canary_failures.inc()
            return None
        req = tempopb.SearchRequest()
        req.tags["canary.id"] = canary_id
        req.limit = 1
        while time.monotonic() - t0 < deadline_s:
            try:
                res = self.search_fn(self.tenant, req)
                # TempoDB.search returns a SearchResults collector; the
                # frontend returns the SearchResponse proto — accept both
                if hasattr(res, "response"):
                    res = res.response()
            except Exception as e:  # noqa: BLE001 — keep polling; a
                self.last_error = f"search: {type(e).__name__}: {e}"
                res = None  # transient reader error is not a verdict
            if res is not None and len(getattr(res, "traces", ())) > 0:
                freshness = time.monotonic() - t0
                self.last_freshness_s = round(freshness, 3)
                self.last_error = None  # a transient mid-probe error healed
                obs.canary_freshness.set(self.last_freshness_s)
                return freshness
            if self._stop.wait(self.poll_step_s):
                break  # shutdown mid-probe: not a pipeline failure
        else:
            self.failures += 1
            if self.last_error is None:
                self.last_error = (
                    f"not searchable after {deadline_s:.1f}s")
            obs.canary_failures.inc()
        return None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.probe_once()
            except Exception:  # noqa: BLE001 — the prober never dies
                log.exception("canary probe")

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="ingest-canary", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)

    def state(self) -> dict:
        return {
            "tenant": self.tenant,
            "interval_s": self.interval_s,
            "running": bool(self._thread and self._thread.is_alive()),
            "probes": self.probes,
            "failures": self.failures,
            "last_freshness_s": self.last_freshness_s,
            "last_error": self.last_error,
        }
