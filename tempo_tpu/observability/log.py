"""Structured logging with per-tenant rate limiting.

Role-equivalent to the reference's go-kit logger + rate-limited tenant
logger (pkg/util/log/log.go:157).
"""

from __future__ import annotations

import logging
import sys
import threading
import time


def get_logger(name: str = "tempo_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter(
            'ts=%(asctime)s level=%(levelname)s logger=%(name)s msg="%(message)s"',
            datefmt="%Y-%m-%dT%H:%M:%S",
        ))
        logger.addHandler(h)
        logger.setLevel(logging.INFO)
    return logger


class TenantTokenBucket:
    """PER-TENANT token buckets (at most `rate` events/s, burst `burst`,
    each) under a process-wide ceiling: a pathological tenant must not
    turn a diagnostic channel into the incident, AND must not starve
    every OTHER tenant's events — during tenant A's flood, tenant B's
    occasional line is exactly the diagnostic the channel exists for.
    Bucket state is bounded LRU. Shared by the slow-query log
    (search/query_stats.py) and the slow-flush log
    (observability/ingest_telemetry.py)."""

    _MAX_TENANTS = 1024

    def __init__(self, rate: float = 1.0, burst: int = 5,
                 global_rate: float = 10.0, global_burst: int = 20):
        from collections import OrderedDict

        self.rate = rate
        self.burst = burst
        self.global_rate = global_rate
        self.global_burst = global_burst
        # true LRU (move-to-end on every allow): FIFO eviction would let
        # a flooding tenant's depleted bucket be pushed out by newcomer
        # tenants and re-created with a fresh burst — exceeding the
        # advertised per-tenant rate under tenant churn
        self._buckets: "OrderedDict[str, list]" = OrderedDict()
        self._global = [float(global_burst), time.monotonic()]
        self._lock = threading.Lock()

    @staticmethod
    def _take(bucket: list, rate: float, burst: float, now: float) -> bool:
        bucket[0] = min(burst, bucket[0] + (now - bucket[1]) * rate)
        bucket[1] = now
        if bucket[0] >= 1.0:
            bucket[0] -= 1.0
            return True
        return False

    def allow(self, tenant: str) -> bool:
        with self._lock:
            now = time.monotonic()
            b = self._buckets.get(tenant)
            if b is None:
                if len(self._buckets) >= self._MAX_TENANTS:
                    self._buckets.popitem(last=False)
                b = self._buckets[tenant] = [float(self.burst), now]
            else:
                self._buckets.move_to_end(tenant)
            # tenant bucket first: a per-tenant refusal must not burn a
            # global token another tenant could have used
            return (self._take(b, self.rate, self.burst, now)
                    and self._take(self._global, self.global_rate,
                                   self.global_burst, now))


class RateLimitedLogger:
    """At most `rate` messages/sec per tenant; the rest are dropped with a
    drop counter (prevents one noisy tenant from flooding logs)."""

    def __init__(self, logger: logging.Logger, rate: float = 10.0):
        self.logger = logger
        self.rate = rate
        self._state: dict[str, tuple[float, float]] = {}  # tenant -> (tokens, t)
        self.dropped = 0

    def log(self, tenant: str, msg: str, level: int = logging.WARNING) -> None:
        now = time.monotonic()
        tokens, t = self._state.get(tenant, (self.rate, now))
        tokens = min(self.rate, tokens + (now - t) * self.rate)
        if tokens >= 1:
            self._state[tenant] = (tokens - 1, now)
            self.logger.log(level, "tenant=%s %s", tenant, msg)
        else:
            self._state[tenant] = (tokens, now)
            self.dropped += 1
