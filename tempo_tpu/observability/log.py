"""Structured logging with per-tenant rate limiting.

Role-equivalent to the reference's go-kit logger + rate-limited tenant
logger (pkg/util/log/log.go:157).
"""

from __future__ import annotations

import logging
import sys
import time


def get_logger(name: str = "tempo_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter(
            'ts=%(asctime)s level=%(levelname)s logger=%(name)s msg="%(message)s"',
            datefmt="%Y-%m-%dT%H:%M:%S",
        ))
        logger.addHandler(h)
        logger.setLevel(logging.INFO)
    return logger


class RateLimitedLogger:
    """At most `rate` messages/sec per tenant; the rest are dropped with a
    drop counter (prevents one noisy tenant from flooding logs)."""

    def __init__(self, logger: logging.Logger, rate: float = 10.0):
        self.logger = logger
        self.rate = rate
        self._state: dict[str, tuple[float, float]] = {}  # tenant -> (tokens, t)
        self.dropped = 0

    def log(self, tenant: str, msg: str, level: int = logging.WARNING) -> None:
        now = time.monotonic()
        tokens, t = self._state.get(tenant, (self.rate, now))
        tokens = min(self.rate, tokens + (now - t) * self.rate)
        if tokens >= 1:
            self._state[tenant] = (tokens - 1, now)
            self.logger.log(level, "tenant=%s %s", tenant, msg)
        else:
            self._state[tenant] = (tokens, now)
            self.dropped += 1
