"""Self-tracing: the framework traces itself, like the reference does.

Role-equivalent to the reference's OpenTracing/OTel tracer init
(cmd/tempo/main.go:76-87, installOpenTelemetryTracer) and spanlogger
(pkg/util/spanlogger): every layer annotates its work with spans
(store.Find tempodb/tempodb.go:291, BackendBlock.find backend_block.go:40,
searchsharding.go:189), and the resulting trace is exported — here either
via OTLP/HTTP to any collector, or *into the framework itself* (the
classic "tempo traces tempo" deployment) through an in-process push.

Design notes (deliberately not a port of opentelemetry-sdk):
- contextvars carry the active span, so spans parent correctly across
  threads started with a copied context and across the in-process module
  graph without any plumbing.
- A zero-overhead noop path: when no tracer is installed, ``start_span``
  returns a shared immutable noop span; hot loops pay one dict lookup.
- Export suppression: while an exporter is pushing spans into the
  framework itself, tracing is suppressed on that thread — otherwise the
  self-ingest path would trace itself recursively forever.
"""

from __future__ import annotations

import contextvars
import logging
import os
import queue
import random
import struct
import threading
import time
import urllib.request

_current_span: contextvars.ContextVar = contextvars.ContextVar(
    "tempo_tpu_current_span", default=None)
_suppressed: contextvars.ContextVar = contextvars.ContextVar(
    "tempo_tpu_trace_suppressed", default=False)

# span kinds (OTLP numbering, trace.proto Span.SpanKind)
KIND_INTERNAL = 1
KIND_SERVER = 2
KIND_CLIENT = 3
KIND_PRODUCER = 4
KIND_CONSUMER = 5

STATUS_UNSET = 0
STATUS_OK = 1
STATUS_ERROR = 2


class SpanContext:
    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: bytes, span_id: bytes, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled


class Span:
    """A mutable in-flight span. Context-manager; ends on __exit__."""

    __slots__ = ("name", "context", "parent_span_id", "kind", "start_ns",
                 "end_ns", "attributes", "events", "status_code",
                 "status_message", "_tracer", "_token")

    def __init__(self, tracer, name: str, context: SpanContext,
                 parent_span_id: bytes | None, kind: int):
        self.name = name
        self.context = context
        self.parent_span_id = parent_span_id
        self.kind = kind
        self.start_ns = time.time_ns()
        self.end_ns = 0
        self.attributes: dict = {}
        self.events: list = []
        self.status_code = STATUS_UNSET
        self.status_message = ""
        self._tracer = tracer
        self._token = None

    @property
    def recording(self) -> bool:
        return True

    def set_attribute(self, key: str, value) -> "Span":
        self.attributes[key] = value
        return self

    def set_attributes(self, **kv) -> "Span":
        self.attributes.update(kv)
        return self

    def add_event(self, name: str, **attributes) -> "Span":
        self.events.append((time.time_ns(), name, attributes))
        return self

    def set_status(self, code: int, message: str = "") -> "Span":
        self.status_code = code
        self.status_message = message
        return self

    def record_exception(self, exc: BaseException) -> "Span":
        self.add_event("exception",
                       **{"exception.type": type(exc).__name__,
                          "exception.message": str(exc)})
        return self.set_status(STATUS_ERROR, str(exc))

    def end(self, end_ns: int | None = None) -> None:
        """end_ns: explicit end timestamp for synthesized spans (the
        dogfood pipeline lowers profiler stage records into child spans
        whose times are reconstructed, not observed live)."""
        if self.end_ns:
            return
        self.end_ns = end_ns or time.time_ns()
        if self.context.sampled:
            self._tracer._on_end(self)

    def __enter__(self) -> "Span":
        self._token = _current_span.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc is not None:
            self.record_exception(exc)
        if self._token is not None:
            _current_span.reset(self._token)
            self._token = None
        self.end()
        return False


class _NoopSpan:
    """Shared, immutable, free — the no-tracer / suppressed path."""

    __slots__ = ()
    recording = False
    context = SpanContext(b"\x00" * 16, b"\x00" * 8, sampled=False)

    def set_attribute(self, key, value):
        return self

    def set_attributes(self, **kv):
        return self

    def add_event(self, name, **attributes):
        return self

    def set_status(self, code, message=""):
        return self

    def record_exception(self, exc):
        return self

    def end(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


NOOP_SPAN = _NoopSpan()


class NonRecordingSpan:
    """A sampled-OUT span: records nothing, but *does* become the current
    span so descendants inherit the not-sampled decision instead of
    re-rolling the dice (which would emit orphan mid-stack spans)."""

    __slots__ = ("context", "_token")
    recording = False

    def __init__(self, context: SpanContext):
        self.context = context
        self._token = None

    def set_attribute(self, key, value):
        return self

    def set_attributes(self, **kv):
        return self

    def add_event(self, name, **attributes):
        return self

    def set_status(self, code, message=""):
        return self

    def record_exception(self, exc):
        return self

    def end(self):
        pass

    def __enter__(self):
        self._token = _current_span.set(self)
        return self

    def __exit__(self, *a):
        if self._token is not None:
            _current_span.reset(self._token)
            self._token = None
        return False


class Tracer:
    """Probabilistic-sampling tracer feeding a span processor."""

    def __init__(self, processor, service_name: str = "tempo-tpu",
                 sample_ratio: float = 1.0,
                 instance_id: str | None = None):
        self.processor = processor
        self.service_name = service_name
        self.sample_ratio = sample_ratio
        self.instance_id = instance_id or f"pid-{os.getpid()}"
        self._rng = random.Random()

    def start_span(self, name: str, kind: int = KIND_INTERNAL,
                   parent: SpanContext | None = None, **attributes):
        if _suppressed.get():
            return NOOP_SPAN
        cur = _current_span.get()
        if parent is None and cur is not None:
            parent = cur.context
        if parent is not None:
            trace_id, parent_id, sampled = (parent.trace_id, parent.span_id,
                                            parent.sampled)
        else:
            trace_id = self._rng.getrandbits(128).to_bytes(16, "big")
            parent_id = None
            sampled = self._rng.random() < self.sample_ratio
        if not sampled:
            # keep the negative decision on the context stack
            return NonRecordingSpan(SpanContext(trace_id, parent_id
                                                or b"\x00" * 8, False))
        ctx = SpanContext(trace_id,
                          self._rng.getrandbits(64).to_bytes(8, "big"), True)
        span = Span(self, name, ctx, parent_id, kind)
        if attributes:
            span.attributes.update(attributes)
        return span

    def _on_end(self, span: Span) -> None:
        self.processor.on_end(span)

    def shutdown(self) -> None:
        self.processor.shutdown()


class BatchProcessor:
    """Buffers finished spans; a daemon thread flushes them to the
    exporter every ``interval_s`` or at ``max_batch`` (reference: OTel
    BatchSpanProcessor role)."""

    def __init__(self, exporter, max_batch: int = 512,
                 max_queue: int = 8192, interval_s: float = 2.0):
        self.exporter = exporter
        self.max_batch = max_batch
        self.interval_s = interval_s
        self._q: queue.Queue = queue.Queue(maxsize=max_queue)
        # dropped-span accounting lives in ONE place — the labeled
        # counter; the instance view derives from it (before, the bare
        # `self.dropped += 1` int and the unlabeled counter could drift,
        # and the counter could not distinguish exporters)
        self._exporter_label = type(exporter).__name__
        from . import metrics as obs

        self._dropped_base = obs.selftrace_dropped_spans.value(
            exporter=self._exporter_label)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tempo-tpu-trace-export")
        self._thread.start()

    @property
    def dropped(self) -> int:
        """Spans THIS processor dropped: derived from the labeled
        counter (single source of truth) minus the baseline captured at
        construction, so concurrent processors over the same exporter
        class cannot make a fresh one report history it never had."""
        from . import metrics as obs

        return int(obs.selftrace_dropped_spans.value(
            exporter=self._exporter_label) - self._dropped_base)

    def on_end(self, span: Span) -> None:
        try:
            self._q.put_nowait(span)
        except queue.Full:
            # visible, not just instance state: a saturated exporter was
            # previously indistinguishable from a healthy quiet one —
            # and labeled by exporter, like selftrace_export_failures
            from . import metrics as obs

            obs.selftrace_dropped_spans.inc(exporter=self._exporter_label)

    def _drain(self) -> list:
        out = []
        while len(out) < self.max_batch:
            try:
                out.append(self._q.get_nowait())
            except queue.Empty:
                break
        return out

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._flush_once()
        self._flush_once()

    def _flush_once(self) -> None:
        while True:
            batch = self._drain()
            if not batch:
                return
            tok = _suppressed.set(True)
            try:
                self.exporter.export(batch)
            except Exception:  # noqa: BLE001 — never kill the loop, but
                # COUNT it: a dead collector endpoint silently eating
                # every batch must show up on /metrics
                from . import metrics as obs

                obs.selftrace_export_failures.inc(
                    exporter=type(self.exporter).__name__)
            finally:
                _suppressed.reset(tok)

    def force_flush(self) -> None:
        self._flush_once()

    def shutdown(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
        self._flush_once()


class SyncProcessor:
    """Export on end, inline (tests / short-lived CLIs)."""

    def __init__(self, exporter):
        self.exporter = exporter

    def on_end(self, span: Span) -> None:
        tok = _suppressed.set(True)
        try:
            self.exporter.export([span])
        finally:
            _suppressed.reset(tok)

    def force_flush(self) -> None:
        pass

    def shutdown(self) -> None:
        pass


# ---------------------------------------------------------------- export


def _any_value(v):
    from tempo_tpu import tempopb

    av = tempopb.AnyValue()
    if isinstance(v, bool):
        av.bool_value = v
    elif isinstance(v, int):
        av.int_value = v
    elif isinstance(v, float):
        av.double_value = v
    elif isinstance(v, bytes):
        av.bytes_value = v
    else:
        av.string_value = str(v)
    return av


def spans_to_resource_spans(spans: list, service_name: str,
                            instance_id: str):
    """Convert finished Spans → one tempopb.ResourceSpans (OTLP wire)."""
    from tempo_tpu import tempopb

    rs = tempopb.ResourceSpans()
    kv = rs.resource.attributes.add()
    kv.key = "service.name"
    kv.value.string_value = service_name
    kv = rs.resource.attributes.add()
    kv.key = "service.instance.id"
    kv.value.string_value = instance_id
    ss = rs.scope_spans.add()
    ss.scope.name = "tempo_tpu.observability.tracing"
    for s in spans:
        p = ss.spans.add()
        p.trace_id = s.context.trace_id
        p.span_id = s.context.span_id
        if s.parent_span_id:
            p.parent_span_id = s.parent_span_id
        p.name = s.name
        p.kind = s.kind
        p.start_time_unix_nano = s.start_ns
        p.end_time_unix_nano = s.end_ns
        for k, v in s.attributes.items():
            kv = p.attributes.add()
            kv.key = k
            kv.value.CopyFrom(_any_value(v))
        for ts, name, attrs in s.events:
            ev = p.events.add()
            ev.time_unix_nano = ts
            ev.name = name
            for k, v in attrs.items():
                kv = ev.attributes.add()
                kv.key = k
                kv.value.CopyFrom(_any_value(v))
        p.status.code = s.status_code
        if s.status_message:
            p.status.message = s.status_message
    return rs


class SelfExporter:
    """Push the framework's own spans back into the framework — the
    "tempo traces tempo" loop, minus the network: calls
    ``push(tenant, [ResourceSpans])`` (Distributor/App signature)."""

    def __init__(self, push, tenant: str = "self",
                 service_name: str = "tempo-tpu",
                 instance_id: str = "self"):
        self.push = push
        self.tenant = tenant
        self.service_name = service_name
        self.instance_id = instance_id

    def export(self, spans: list) -> None:
        rs = spans_to_resource_spans(spans, self.service_name,
                                     self.instance_id)
        self.push(self.tenant, [rs])


# the dogfood pipeline's reserved tenant: self-trace spans ingested
# through the normal distributor path land here, away from user data.
# A leading underscore passes tenant validation (utils/pathsafe allows
# it) while making the reservation visually obvious in blocklists.
SELFTRACE_TENANT = "_selftrace"


class InProcessExporter(SelfExporter):
    """The dogfood ingest exporter (`selftrace_ingest_enabled`):
    finished self-trace spans become the existing push wire format and
    ride the normal distributor/TenantInstance ingest path into the
    reserved ``_selftrace`` tenant — every search request, device
    dispatch, flush, poll and compaction becomes a real trace queryable
    via trace-by-ID, tag search, structural ``?q=``, ``?agg=`` and live
    tail. The surrounding BatchProcessor/SyncProcessor suppression
    covers the whole ingest-of-self-spans path, so the loop cannot feed
    back (test_self_export_suppression_no_recursion)."""

    def __init__(self, push, service_name: str = "tempo-tpu",
                 instance_id: str = "self"):
        super().__init__(push, tenant=SELFTRACE_TENANT,
                         service_name=service_name,
                         instance_id=instance_id)


class OTLPHTTPExporter:
    """OTLP/HTTP protobuf export to any collector (or another tempo-tpu's
    /v1/traces receiver)."""

    def __init__(self, endpoint: str, tenant: str | None = None,
                 service_name: str = "tempo-tpu",
                 instance_id: str = "self", timeout_s: float = 5.0):
        self.endpoint = endpoint.rstrip("/")
        if not self.endpoint.endswith("/v1/traces"):
            self.endpoint += "/v1/traces"
        self.tenant = tenant
        self.service_name = service_name
        self.instance_id = instance_id
        self.timeout_s = timeout_s

    def export(self, spans: list) -> None:
        from tempo_tpu import tempopb

        rs = spans_to_resource_spans(spans, self.service_name,
                                     self.instance_id)
        trace = tempopb.Trace()
        trace.batches.append(rs)
        req = urllib.request.Request(
            self.endpoint, data=trace.SerializeToString(), method="POST",
            headers={"Content-Type": "application/x-protobuf"})
        if self.tenant:
            req.add_header("X-Scope-OrgID", self.tenant)
        urllib.request.urlopen(req, timeout=self.timeout_s).read()


class CollectExporter:
    """Test exporter: keeps everything."""

    def __init__(self):
        self.spans: list = []
        self.lock = threading.Lock()

    def export(self, spans: list) -> None:
        with self.lock:
            self.spans.extend(spans)


# ----------------------------------------------------------- global state

_tracer: Tracer | None = None


def set_tracer(tracer: Tracer | None) -> None:
    global _tracer
    _tracer = tracer


def get_tracer() -> Tracer | None:
    return _tracer


def start_span(name: str, kind: int = KIND_INTERNAL,
               parent: SpanContext | None = None, **attributes):
    """Module-level convenience: noop when no tracer is installed."""
    t = _tracer
    if t is None:
        return NOOP_SPAN
    return t.start_span(name, kind=kind, parent=parent, **attributes)


def current_span():
    s = _current_span.get()
    return s if s is not None else NOOP_SPAN


def force_flush() -> None:
    t = _tracer
    if t is not None:
        t.processor.force_flush()


def init_tracing(cfg: dict, push=None) -> Tracer | None:
    """Build + install a tracer from config::

        self_tracing:
          enabled: true
          exporter: self | otlp        # default self when push given
          endpoint: http://host:3200   # for otlp
          tenant: self
          sample_ratio: 1.0
          service_name: tempo-tpu
          selftrace_ingest_enabled: false   # dogfood pipeline: ingest
                                            # into _selftrace, stage
                                            # child spans, querystats
                                            # span attrs, flight recorder
          selftrace_flight_recorder_max: 32
    """
    cfg = cfg or {}
    # the dogfood gate + flight recorder configure HERE — the one entry
    # point every App/test uses — so gate state always tracks the most
    # recently installed tracer config (the REGISTRY idiom). Tracing
    # disabled forces the gate off: there are no spans to dogfood.
    ingest_on = bool(cfg.get("enabled")) and bool(
        cfg.get("selftrace_ingest_enabled", False))
    from . import selftrace as _selftrace

    _selftrace.configure(
        ingest_enabled=ingest_on,
        flight_recorder_max=int(
            cfg.get("selftrace_flight_recorder_max", 32)))
    if not cfg.get("enabled"):
        return None
    service = cfg.get("service_name", "tempo-tpu")
    tenant = cfg.get("tenant", "self")
    exporter_kind = cfg.get("exporter", "self" if push is not None else "otlp")
    if exporter_kind == "self":
        if push is None:
            raise ValueError("self exporter needs an in-process push target")
        if ingest_on:
            # dogfood pipeline: the reserved tenant wins over any
            # configured one — user tenants must not receive self-spans
            exporter = InProcessExporter(push, service_name=service)
        else:
            exporter = SelfExporter(push, tenant=tenant,
                                    service_name=service)
    elif exporter_kind == "otlp":
        endpoint = cfg.get("endpoint")
        if not endpoint:
            raise ValueError(
                "self_tracing: exporter 'otlp' requires an 'endpoint' "
                "(e.g. http://collector:3200)")
        exporter = OTLPHTTPExporter(endpoint, tenant=tenant,
                                    service_name=service)
    else:
        raise ValueError(f"unknown trace exporter {exporter_kind!r}")
    proc = BatchProcessor(exporter,
                          interval_s=float(cfg.get("flush_interval_s", 2.0)))
    tracer = Tracer(proc, service_name=service,
                    sample_ratio=float(cfg.get("sample_ratio", 1.0)))
    set_tracer(tracer)
    return tracer


# ------------------------------------------------------- W3C propagation


def inject_traceparent(headers: dict) -> dict:
    """Add a `traceparent` header for the active span (outgoing RPC).
    A sampled-out span still injects (flags 00) so downstream processes
    honor the negative decision instead of re-sampling."""
    s = _current_span.get()
    if s is not None and s.context.trace_id != b"\x00" * 16:
        c = s.context
        span_id = c.span_id if s.recording else b"\x00" * 8
        if span_id == b"\x00" * 8:
            # W3C forbids zero parent-id; reuse the trace id tail
            span_id = c.trace_id[8:]
        headers["traceparent"] = (
            f"00-{c.trace_id.hex()}-{span_id.hex()}-"
            f"{'01' if c.sampled else '00'}")
    return headers


def extract_traceparent(headers) -> SpanContext | None:
    """Parse an incoming `traceparent`; returns a remote parent context."""
    try:
        get = headers.get
    except AttributeError:
        return None
    v = get("traceparent") or get("Traceparent")
    if not v:
        return None
    parts = v.strip().split("-")
    if (len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16
            or len(parts[3]) != 2):
        return None
    try:
        trace_id = bytes.fromhex(parts[1])
        span_id = bytes.fromhex(parts[2])
        sampled = bool(int(parts[3], 16) & 1)
    except ValueError:
        return None
    # W3C: all-zero trace-id or parent-id is invalid
    if trace_id == b"\x00" * 16 or span_id == b"\x00" * 8:
        return None
    return SpanContext(trace_id, span_id, sampled)


# ------------------------------------------------------------ spanlogger


class SpanLogger:
    """Couples a logger to a span: every log line also lands on the span
    as an event, so traces carry their own narration (reference:
    pkg/util/spanlogger)."""

    def __init__(self, name: str, logger: logging.Logger | None = None,
                 tenant: str | None = None, **attributes):
        from .log import get_logger

        self.logger = logger or get_logger()
        self.span = start_span(name, **attributes)
        if tenant is not None:
            self.span.set_attribute("tenant", tenant)
        self.tenant = tenant

    def log(self, msg: str, level: int = logging.DEBUG, **kv) -> None:
        self.span.add_event(msg, **kv)
        if kv:
            msg = msg + " " + " ".join(f"{k}={v}" for k, v in kv.items())
        if self.tenant:
            msg = f"tenant={self.tenant} {msg}"
        self.logger.log(level, msg)

    def error(self, exc: BaseException, msg: str = "") -> None:
        self.span.record_exception(exc)
        self.logger.error("%s: %s", msg or "error", exc)

    def __enter__(self):
        self.span.__enter__()
        return self

    def __exit__(self, *a):
        return self.span.__exit__(*a)
