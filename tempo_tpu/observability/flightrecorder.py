"""Anomaly flight recorder: a bounded black box for the bad moments.

When something notable goes wrong — a circuit-breaker trip, a watchdog
timeout abandoning a dispatch, a query past the slow-query threshold —
the metrics rings still hold the evidence, but only until they roll
over, and correlating them after the fact means scraping four /debug
endpoints and hoping the windows overlap. The flight recorder snapshots
them TOGETHER, at the moment of the anomaly, into one bounded bundle:

  { seq, unix_ts, trigger, trace_id, detail,
    profile:   recent profiler ring + aggregates,
    breaker:   breaker state machine snapshot,
    planner:   offload-planner calibration snapshot,
    ownership: HBM ownership-map snapshot }

``trace_id`` is the offending request's own self-trace id (the current
span's, or passed explicitly by the trigger site) — with the dogfood
pipeline on (observability/selftrace, the shared gate) that trace is
ingested into the reserved ``_selftrace`` tenant, so the bundle's id
resolves via ordinary trace-by-ID and the operator pivots from "what
tripped" straight to "what that request was doing".

Bundles land in a deque bounded by ``selftrace_flight_recorder_max``
(oldest evicted) and render at ``/debug/flightrecorder``.

Lock discipline: every subsystem snapshot is taken BEFORE the
recorder's own lock is acquired, and trigger sites call ``record``
outside their own locks (breaker.record_fault fires after releasing
the breaker lock), so ``FlightRecorder._lock`` is a leaf in the
process lock graph — the LockOrderChecker's clean-package test pins
this. Noop contract: disabled ``record`` is one attribute read.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from . import tracing

TRIGGER_BREAKER = "breaker_trip"
TRIGGER_WATCHDOG = "watchdog_timeout"
TRIGGER_SLOW_QUERY = "slow_query"

_PROFILE_RECENT = 8  # profiler-ring entries captured per bundle


def _safe(fn):
    """Snapshot helpers must never fail a trigger site: a process with
    a subsystem half-configured (tests, standalone roles) records what
    it can and omits the rest."""
    try:
        return fn()
    except Exception:  # noqa: BLE001 — diagnostics never raise upward
        return None


def _snapshots() -> dict:
    from tempo_tpu.observability import profile

    out = {
        "profile": _safe(lambda: profile.PROFILER.snapshot(
            recent=_PROFILE_RECENT)),
    }

    def _breaker():
        from tempo_tpu.robustness import BREAKER

        return BREAKER.snapshot()

    def _planner():
        from tempo_tpu.search.planner import PLANNER

        return PLANNER.snapshot(recent=_PROFILE_RECENT)

    def _ownership():
        from tempo_tpu.search.ownership import OWNERSHIP

        return OWNERSHIP.snapshot()

    out["breaker"] = _safe(_breaker)
    out["planner"] = _safe(_planner)
    out["ownership"] = _safe(_ownership)
    return out


class FlightRecorder:
    """Process-wide recorder (module singleton ``RECORDER``, the
    PROFILER idiom); ``enabled`` tracks selftrace.configure's
    ``ingest_enabled`` — one dogfood gate for the whole subsystem."""

    def __init__(self, max_bundles: int = 32) -> None:
        self.enabled = False
        self._bundles: deque = deque(maxlen=max_bundles)
        self._lock = threading.Lock()
        self._seq = 0
        self._by_trigger: dict[str, int] = {}

    def record(self, trigger: str, trace_id: str | None = None,
               detail: dict | None = None) -> dict | None:
        """Snapshot one diagnostic bundle. `trace_id`: the offending
        self-trace id (hex); defaults to the current span's — trigger
        sites running on the request thread get it for free. Returns
        the bundle (tests), None when disabled."""
        if not self.enabled:
            return None
        if trace_id is None:
            span = tracing.current_span()
            trace_id = (span.context.trace_id.hex()
                        if span.recording else None)
        bundle = {
            "trigger": trigger,
            "unix_ts": round(time.time(), 3),
            "trace_id": trace_id,
            "detail": dict(detail or {}),
        }
        bundle.update(_snapshots())
        with self._lock:
            self._seq += 1
            bundle["seq"] = self._seq
            self._by_trigger[trigger] = self._by_trigger.get(trigger, 0) + 1
            self._bundles.append(bundle)
        return bundle

    def resize(self, max_bundles: int) -> None:
        with self._lock:
            self._bundles = deque(self._bundles, maxlen=max(1, max_bundles))

    def snapshot(self, recent: int = 32) -> dict:
        """The /debug/flightrecorder payload."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "max_bundles": self._bundles.maxlen,
                "recorded": self._seq,
                "by_trigger": dict(self._by_trigger),
                "bundles": list(self._bundles)[-recent:]
                if recent > 0 else [],
            }

    def reset(self) -> None:
        """Test hook: drop bundles, keep configuration."""
        with self._lock:
            self._bundles.clear()
            self._by_trigger.clear()
            self._seq = 0


RECORDER = FlightRecorder()
