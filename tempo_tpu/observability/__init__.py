from .metrics import REGISTRY, Counter, Gauge, Histogram
from .log import get_logger, RateLimitedLogger

__all__ = ["REGISTRY", "Counter", "Gauge", "Histogram", "get_logger",
           "RateLimitedLogger", "profile"]

from . import profile  # noqa: E402 — imports metrics+tracing above
