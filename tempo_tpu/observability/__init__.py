from .metrics import REGISTRY, Counter, Gauge, Histogram
from .log import get_logger, RateLimitedLogger, TenantTokenBucket

__all__ = ["REGISTRY", "Counter", "Gauge", "Histogram", "get_logger",
           "RateLimitedLogger", "TenantTokenBucket", "profile",
           "ingest_telemetry"]

from . import profile  # noqa: E402 — imports metrics+tracing above
from . import ingest_telemetry  # noqa: E402 — same ordering constraint
