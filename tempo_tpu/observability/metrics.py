"""Prometheus-style metrics registry.

Role-equivalent to the reference's promauto counters/gauges/histograms
registered at var-init in every component with `tempo_`/`tempodb_`
namespaces (SURVEY.md §5 observability), exposed in text format at
/metrics. Labels are per-series (cardinality-aware: the label set lives
in the series key).

Exemplars ("tempo traces tempo", closed loop): a Histogram observation
made while a SAMPLED self-trace span is active records that span's
trace_id against the bucket the value fell in. ``/metrics`` negotiates
OpenMetrics via ``Accept`` (api/http.py) and ``expose(openmetrics=True)``
emits the exemplars per the OpenMetrics 1.0 text format — latency
buckets become clickable into the self-traces that produced them. The
classic Prometheus text format (0.0.4) is byte-identical to before.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left

OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8")
PROM_CONTENT_TYPE = "text/plain; version=0.0.4"

# label values arrive as strings or numbers (mode="mesh", le=0.5); the
# series key is the sorted (name, value) tuple
LabelValue = str | int | float
SeriesKey = tuple[tuple[str, LabelValue], ...]
# (metric_name, series_key, value) — the remote-write drain format
Sample = tuple[str, SeriesKey, float]
# (trace_id_hex, observed value, unix_ts) — one bucket exemplar
Exemplar = tuple[str, float, float]


def _exemplar_ref() -> str | None:
    """trace_id (hex) of the active sampled self-trace span, or None.
    Imported lazily: tracing imports this module at load for its own
    counters; the call path here only runs post-import."""
    from . import tracing

    s = tracing.current_span()
    if s.recording and s.context.sampled:
        return s.context.trace_id.hex()
    return None


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_: str = "",
                 registry: "Registry | None" = None):
        self.name = name
        self.help = help_
        self._series: dict[SeriesKey, float] = {}
        self._lock = threading.Lock()
        (registry or REGISTRY)._register(self)

    def _key(self, labels: dict[str, LabelValue] | None) -> SeriesKey:
        return tuple(sorted((labels or {}).items()))

    def _om_base(self) -> str:
        """OpenMetrics metric-family name: counters are named WITHOUT the
        `_total` suffix in HELP/TYPE lines (the suffix belongs to the
        sample), everything else is unchanged."""
        if self.kind == "counter" and self.name.endswith("_total"):
            return self.name[: -len("_total")]
        return self.name

    def expose(self, openmetrics: bool = False) -> str:
        name = self._om_base() if openmetrics else self.name
        lines = [f"# HELP {name} {self.help}",
                 f"# TYPE {name} {self.kind}"]
        with self._lock:
            for key, val in sorted(self._series.items()):
                lbl = ",".join(f'{k}="{v}"' for k, v in key)
                lines.append(f"{self.name}{{{lbl}}} {val}" if lbl
                             else f"{self.name} {val}")
        return "\n".join(lines)

    def samples(self) -> list[Sample]:
        """[(metric_name, ((label, value), ...), float)] — the
        remote-write drain format."""
        with self._lock:
            return [(self.name, key, val)
                    for key, val in sorted(self._series.items())]


class Counter(_Metric):
    kind = "counter"

    def inc(self, n: float = 1, **labels: LabelValue) -> None:
        k = self._key(labels)
        with self._lock:
            self._series[k] = self._series.get(k, 0) + n

    def labels(self, **labels: LabelValue) -> "_BoundCounter":
        """Precomputed-key handle for per-span hot paths: the sorted
        label-tuple build per inc() was measurable on the ingest ack
        path (profiled r5) — cache the handle, pay it once."""
        return _BoundCounter(self, self._key(labels))

    def value(self, **labels: LabelValue) -> float:
        # locked like every writer: a bare dict read races resize-in-
        # progress under free-threading and misses published updates
        with self._lock:
            return self._series.get(self._key(labels), 0)


class _BoundCounter:
    __slots__ = ("_m", "_k")

    def __init__(self, m: Counter, k: SeriesKey):
        self._m, self._k = m, k

    def inc(self, n: float = 1) -> None:
        m = self._m
        with m._lock:
            m._series[self._k] = m._series.get(self._k, 0) + n


class Gauge(_Metric):
    kind = "gauge"

    def set(self, v: float, **labels: LabelValue) -> None:
        with self._lock:
            self._series[self._key(labels)] = v

    def add(self, n: float, **labels: LabelValue) -> None:
        """Delta update (negative to decrement) — for gauges tracking
        in-flight counts with no single owner to re-derive them from
        (e.g. SSE response bodies draining on server writer threads)."""
        k = self._key(labels)
        with self._lock:
            self._series[k] = self._series.get(k, 0) + n

    def remove(self, **labels: LabelValue) -> None:
        """Drop one labeled series. A per-tenant gauge whose tenant
        vanished must stop exporting its last value — a frozen
        'freshness: 2.1s' for a tenant with no searchable data left is
        worse than no series at all."""
        with self._lock:
            self._series.pop(self._key(labels), None)

    def value(self, **labels: LabelValue) -> float:
        with self._lock:
            return self._series.get(self._key(labels), 0)


class Histogram(_Metric):
    kind = "histogram"
    DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10)

    def __init__(self, name: str, help_: str = "",
                 buckets: tuple[float, ...] | None = None,
                 registry: "Registry | None" = None):
        super().__init__(name, help_, registry)
        self.buckets: tuple[float, ...] = tuple(
            buckets or self.DEFAULT_BUCKETS)
        self._counts: dict[SeriesKey, list[int]] = {}
        self._sums: dict[SeriesKey, float] = {}
        # series key -> {bin index: (trace_id_hex, value, unix_ts)}:
        # the newest sampled-span observation per bucket — OpenMetrics
        # exemplars linking latency buckets to self-traces
        self._exemplars: dict[SeriesKey, dict[int, Exemplar]] = {}

    def observe(self, v: float, **labels: LabelValue) -> None:
        self._observe_key(self._key(labels), v)

    def _observe_key(self, k: SeriesKey, v: float) -> None:
        # counts holds per-BIN tallies (bin i = first bucket >= v, last =
        # +Inf only); expose()/samples() cumsum into the prometheus
        # cumulative-le form. One bisect + one increment beats the old
        # O(buckets) cumulative walk on the per-span ingest path.
        i = bisect_left(self.buckets, v)
        ex = _exemplar_ref()  # before the lock: reads a contextvar only
        with self._lock:
            counts = self._counts.get(k)
            if counts is None:
                counts = self._counts[k] = [0] * (len(self.buckets) + 1)
            counts[i] += 1
            self._sums[k] = self._sums.get(k, 0) + v
            if ex is not None:
                self._exemplars.setdefault(k, {})[i] = (ex, v, time.time())

    def observe_bulk(self, bins: list[int], vals: list[float],
                     **labels: LabelValue) -> None:
        self._observe_bulk_key(self._key(labels), bins, vals)

    def _observe_bulk_key(self, k: SeriesKey, bins: list[int],
                          vals: list[float]) -> None:
        # batched drain for the device analytics path: per-bin tallies
        # arrive pre-counted, and the float sum folds sequentially in
        # row order under one lock hold — the resulting series is
        # byte-identical to the same values through observe() one by one
        ex = _exemplar_ref()
        with self._lock:
            counts = self._counts.get(k)
            if counts is None:
                counts = self._counts[k] = [0] * (len(self.buckets) + 1)
            for i, n in enumerate(bins):
                counts[i] += n
            s = self._sums.get(k, 0)
            for v in vals:
                s = s + v
            self._sums[k] = s
            if ex is not None and vals:
                exs = self._exemplars.setdefault(k, {})
                for v in vals:
                    exs[bisect_left(self.buckets, v)] = (ex, v,
                                                         time.time())

    def labels(self, **labels: LabelValue) -> "_BoundHistogram":
        return _BoundHistogram(self, self._key(labels))

    def time(self, **labels: LabelValue) -> "_Timer":
        return _Timer(self, labels)

    @staticmethod
    def _exemplar_suffix(ex: Exemplar | None) -> str:
        """OpenMetrics exemplar: ` # {labels} value timestamp`."""
        if ex is None:
            return ""
        trace_id, value, ts = ex
        return f' # {{trace_id="{trace_id}"}} {value} {round(ts, 3)}'

    def expose(self, openmetrics: bool = False) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} histogram"]
        with self._lock:
            for key, counts in sorted(self._counts.items()):
                base = dict(key)
                exs = self._exemplars.get(key, {}) if openmetrics else {}
                cum = 0
                for i, b in enumerate(self.buckets):
                    cum += counts[i]
                    # OpenMetrics requires float-formatted thresholds
                    le = float(b) if openmetrics else b
                    lbl = ",".join(f'{k}="{v}"' for k, v in
                                   sorted({**base, "le": le}.items()))
                    lines.append(f"{self.name}_bucket{{{lbl}}} {cum}"
                                 + self._exemplar_suffix(exs.get(i)))
                total = cum + counts[-1]
                lbl = ",".join(f'{k}="{v}"' for k, v in
                               sorted({**base, "le": "+Inf"}.items()))
                lines.append(f"{self.name}_bucket{{{lbl}}} {total}"
                             + self._exemplar_suffix(
                                 exs.get(len(self.buckets))))
                blbl = ",".join(f'{k}="{v}"' for k, v in key)
                suffix = f"{{{blbl}}}" if blbl else ""
                lines.append(f"{self.name}_sum{suffix} {self._sums.get(key, 0)}")
                lines.append(f"{self.name}_count{suffix} {total}")
        return "\n".join(lines)

    def samples(self) -> list[Sample]:
        out: list[Sample] = []
        with self._lock:
            for key, counts in sorted(self._counts.items()):
                base = dict(key)
                cum = 0
                for i, b in enumerate(self.buckets):
                    cum += counts[i]
                    out.append((f"{self.name}_bucket",
                                tuple(sorted({**base, "le": str(b)}.items())),
                                cum))
                total = cum + counts[-1]
                out.append((f"{self.name}_bucket",
                            tuple(sorted({**base, "le": "+Inf"}.items())),
                            total))
                out.append((f"{self.name}_sum", key, self._sums.get(key, 0)))
                out.append((f"{self.name}_count", key, total))
        return out


class _BoundHistogram:
    __slots__ = ("_m", "_k")

    def __init__(self, m: Histogram, k: SeriesKey):
        self._m, self._k = m, k

    def observe(self, v: float) -> None:
        self._m._observe_key(self._k, v)

    def observe_bulk(self, bins: list[int], vals: list[float]) -> None:
        self._m._observe_bulk_key(self._k, bins, vals)


class _Timer:
    def __init__(self, hist: Histogram, labels: dict[str, LabelValue]):
        self.hist = hist
        self.labels = labels
        self.t0 = 0.0

    def __enter__(self) -> "_Timer":
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc: object) -> None:
        self.hist.observe(time.perf_counter() - self.t0, **self.labels)


class Registry:
    def __init__(self) -> None:
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _register(self, m: _Metric) -> None:
        with self._lock:
            if m.name in self._metrics:
                raise ValueError(f"metric {m.name} already registered")
            self._metrics[m.name] = m

    def get(self, name: str) -> _Metric | None:
        return self._metrics.get(name)

    def expose(self, openmetrics: bool = False) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        body = "\n".join(m.expose(openmetrics) for m in metrics) + "\n"
        if openmetrics:
            body += "# EOF\n"
        return body

    def samples(self) -> list[Sample]:
        with self._lock:
            metrics = list(self._metrics.values())
        out: list[Sample] = []
        for m in metrics:
            out.extend(m.samples())
        return out


REGISTRY = Registry()

# core framework metrics (registered once, labelled per tenant/status)
ingest_spans = Counter("tempo_distributor_spans_received_total",
                       "spans received by the distributor")
ingest_bytes = Counter("tempo_distributor_bytes_received_total",
                       "bytes received by the distributor")
push_failures = Counter("tempo_distributor_push_failures_total",
                        "failed pushes")
live_traces = Gauge("tempo_ingester_live_traces", "live traces per tenant")
flush_failures = Counter("tempo_ingester_failed_flushes_total",
                         "block completions that failed and were backed off")
blocks_completed = Counter("tempo_ingester_blocks_completed_total",
                           "blocks completed to the backend")
query_seconds = Histogram("tempo_query_seconds", "query latency")
search_inspected = Counter("tempo_search_inspected_traces_total",
                           "traces inspected by search")
compactions = Counter("tempodb_compaction_runs_total", "compaction runs")
retention_deleted = Counter("tempodb_retention_deleted_total",
                            "blocks hard-deleted by retention")
scan_dispatches = Counter("tempo_search_scan_dispatches_total",
                          "device scan kernel dispatches")
batch_cache_events = Counter("tempo_search_batch_cache_events_total",
                             "staged-batch HBM cache hits/misses/evictions")
coalesced_queries = Counter(
    "tempo_search_coalesced_queries_total",
    "queries served through fused multi-query scan dispatches; the "
    "coalesce ratio is this over scan_dispatches{mode=coalesced}")
coalesce_wait_seconds = Histogram(
    "tempo_search_coalesce_wait_seconds",
    "time a query spent waiting in the coalescing window before its "
    "fused dispatch launched",
    buckets=(0.0005, 0.001, 0.002, 0.005, 0.01, 0.025, 0.05, 0.1))
fallback_scans = Counter("tempo_search_fallback_scans_total",
                         "trace-block proto scans for blocks lacking "
                         "search data")
truncated_tag_entries = Counter(
    "tempo_search_truncated_entries_total",
    "entries whose tag set exceeded the kv-slot capacity at block build")

# ---- dispatch profiler (observability/profile.py) ----
dispatch_stage_seconds = Histogram(
    "tempo_search_dispatch_stage_seconds",
    "per-dispatch stage wall time: stage=build|h2d|compile|execute|d2h|"
    "lock_wait, mode=single|batched|coalesced|mesh|dict_probe|host_probe",
    buckets=(0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1,
             5, 30))
jit_cache_events = Counter(
    "tempo_search_jit_cache_events_total",
    "dispatch-shape compile-cache outcomes (result=hit|miss); a miss "
    "means that dispatch paid XLA trace+compile")
h2d_bytes = Counter("tempo_search_h2d_bytes_total",
                    "bytes staged host->device (pages, dictionaries, "
                    "query tables)")
d2h_bytes = Counter("tempo_search_d2h_bytes_total",
                    "bytes fetched device->host (scan results/demux)")
hbm_cache_bytes = Gauge("tempo_search_hbm_cache_bytes",
                        "staged-batch HBM cache occupancy (bytes)")
host_cache_bytes = Gauge("tempo_search_host_cache_bytes",
                         "host-RAM stacked-batch tier occupancy (bytes)")
probe_dict_bytes = Gauge("tempo_search_probe_dict_bytes",
                         "HBM held by staged device-probe dictionaries "
                         "across resident batches (bytes)")
hbm_logical_bytes = Gauge("tempo_search_hbm_logical_bytes",
                          "unpacked-layout equivalent of the staged-batch "
                          "HBM occupancy — equals tempo_search_hbm_cache_"
                          "bytes unless search_packed_residency narrows "
                          "the resident columns")
host_logical_bytes = Gauge("tempo_search_host_logical_bytes",
                           "unpacked-layout equivalent of the host-RAM "
                           "stacked-batch tier occupancy")
coalesce_pending = Gauge("tempo_search_coalesce_pending_queries",
                         "queries parked in coalescing windows right now "
                         "(the coalescer queue depth)")
structural_stack_events = Counter(
    "tempo_search_structural_stack_events_total",
    "structural-query stacking outcomes at coalescer flush: "
    "result=stacked (member of a fused same-plan dispatch), "
    "stacked_bucketed (member of a fused MIXED-plan dispatch whose "
    "plans canonicalized into one bucket shape — "
    "search_structural_bucket_enabled), solo_shape (no peer shared "
    "the plan shape within the window), solo_disabled "
    "(search_structural_stack_enabled off) — unstackable plan shapes "
    "are visible here instead of silently flushing solo")

# ---- hot-tier live search (search/live_tier.py) ----
live_tier_entries = Gauge(
    "tempo_search_live_tier_entries",
    "in-flight traces held in the hot tier's per-tenant live stage "
    "(absorbed at push, evicted at cut)")
live_tier_scans = Counter(
    "tempo_search_live_tier_scans_total",
    "hot-tier live-stage scan outcomes (result=scan: answered by the "
    "fused kernel; fallback_overflow: stage past "
    "search_live_tier_max_entries, legacy walk ran; fallback: scan "
    "declined, legacy walk ran)")
live_tier_rebuilds = Counter(
    "tempo_search_live_tier_rebuilds_total",
    "columnar stage rebuilds (one per absorbed/evicted epoch actually "
    "searched — consecutive mutations between searches coalesce into "
    "one rebuild)")
live_tier_evictions = Counter(
    "tempo_search_live_tier_evictions_total",
    "entries leaving the live stage (reason=cut: trace cut to the WAL "
    "head, where the hot scan still covers it)")
live_tail_subscriptions = Gauge(
    "tempo_search_live_tail_subscriptions",
    "standing tail subscriptions registered per tenant")
live_tail_notifications = Counter(
    "tempo_search_live_tail_notifications_total",
    "tail notifications delivered to standing-query subscribers")
live_tail_dropped = Counter(
    "tempo_search_live_tail_dropped_total",
    "tail notifications/registrations dropped per tenant (reason=queue: "
    "a slow consumer's bounded queue overflowed, oldest dropped; cap: "
    "subscribe rejected at search_live_tail_max_subscriptions)")

# ---- SSE streaming surfaces (api/http.py /api/search/stream, /api/tail)
sse_active_streams = Gauge(
    "tempo_sse_active_streams",
    "SSE responses currently being written per tenant "
    "(endpoint=search_stream|tail) — live-tail SUBSCRIPTIONS are "
    "tempo_search_live_tail_subscriptions; this counts the HTTP legs, "
    "including ones draining after their subscription lapsed")
sse_events_streamed = Counter(
    "tempo_sse_events_total",
    "SSE events written to clients per tenant "
    "(endpoint=search_stream|tail, event = the SSE event name: "
    "result|trace|summary|subscribed|end|error|keepalive)")

# ---- device-side aggregate analytics (search/analytics.py) ----
search_analytics_dispatches = Counter(
    "tempo_search_analytics_dispatches_total",
    "aggregate-analytics count dispatches (route=device: the dense "
    "count kernel ran on the accelerator; host: breaker-open or "
    "overflow fallback computed the byte-identical numpy counts)")
search_analytics_staged_bytes = Gauge(
    "tempo_search_analytics_staged_bytes",
    "bytes staged to the device for the most recent analytics "
    "micro-batch (pow2-tier padded row columns)")
# ---- owner-routed HBM (search/ownership.py) ----
hbm_owner_generation = Gauge(
    "tempo_search_hbm_owner_generation",
    "ownership-map membership generation this process placed against; "
    "fleet members disagreeing here are mid-rebalance")
hbm_owner_groups = Gauge(
    "tempo_search_hbm_owner_groups",
    "placement groups this member owns under the current generation")
hbm_owner_rebalance_moves = Counter(
    "tempo_search_hbm_owner_rebalance_moves_total",
    "placement groups whose owner changed at a membership generation "
    "bump — the rebalance is a placement diff, never a cache flush")
hbm_owner_routed = Counter(
    "tempo_search_hbm_owner_routed_total",
    "batcher group routing decisions while ownership is enabled "
    "(route=owner|non_owner_host: device-resident serve vs the "
    "byte-identical host route on a non-owner)")
hbm_owner_rebalance_evictions = Counter(
    "tempo_search_hbm_owner_rebalance_evictions_total",
    "HBM batches released because a rebalance moved their group away "
    "(result=dropped|deferred; deferred batches drop at unpin)")
hbm_replica_promotions = Counter(
    "tempo_search_hbm_replica_promotions_total",
    "heat-table replica-set transitions (dir=up: a placement group's "
    "access rate crossed search_hbm_ownership_hot_rate and promoted to "
    "its rf-deep replica set; dir=down: rate decayed below the "
    "hysteresis floor and the group demoted back to its single owner)")
hedged_dispatches = Counter(
    "tempo_search_hedged_dispatches_total",
    "frontend hedged-dispatch outcomes over promoted groups "
    "(result=primary: primary answered inside the hedge delay; "
    "hedge_won: the replica's duplicate answered first; cancelled: a "
    "losing in-flight attempt was expired through its deadline)")

# ---- offload planner (search/planner.py) ----
offload_decisions = Counter(
    "tempo_search_offload_decisions_total",
    "offload-planner probe placements (target=host|device, "
    "site=stage|compile|offline); only counted while the planner is "
    "enabled — the static-threshold path books nothing")
offload_predict_error = Histogram(
    "tempo_search_offload_predict_error_ratio",
    "relative |predicted - actual| / actual of the planner's chosen-side "
    "probe cost, resolved when the matching probe run is observed",
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 5.0))

# ---- per-query execution inspector (search/query_stats.py) ----
query_device_seconds = Counter(
    "tempo_search_query_device_seconds_total",
    "device-seconds attributed to queries per tenant: fused coalesced "
    "dispatches apportion their stage times across member queries by "
    "padded predicate rows (shares sum to the dispatch total), so this "
    "is the fleet's device-time bill by tenant")
query_bytes_inspected = Counter(
    "tempo_search_query_bytes_inspected_total",
    "bytes inspected by queries per tenant, split by placement=device "
    "(scan kernels over staged batches) vs placement=host (fallback "
    "proto scans, host dictionary probes)")
query_stage_seconds = Histogram(
    "tempo_search_query_stage_seconds",
    "per-QUERY stage wall time: host stages (header_prune|staging|"
    "prepare|dispatch|drain|fallback_scan) plus attributed device "
    "stages (device_build|device_h2d|device_compile|device_execute|"
    "device_d2h|device_lock_wait); exemplars link buckets to "
    "self-traces",
    buckets=(0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1,
             5, 30))
slow_queries = Counter(
    "tempo_search_slow_queries_total",
    "queries slower than search_slow_query_log_s per tenant, booked "
    "ONCE per query per process (in-process sub-requests of a slow "
    "request don't re-count); the log line is additionally rate-limited "
    "per tenant")

# ---- write-path telemetry (observability/ingest_telemetry.py) ----
ingest_stage_seconds = Histogram(
    "tempo_ingest_stage_seconds",
    "write-path stage latency: stage=push_ack (distributor accept+"
    "replicate wall time) | live_cut (trace first-push -> cut into the "
    "WAL head) | block_cut (head-block age when cut for completion) | "
    "flush (block cut -> backend flush success, queue wait included) | "
    "flush_write (the backend completion write itself) | poll_visible "
    "(flush success -> first poll that lists the block) | "
    "push_to_searchable (oldest trace push -> poll visibility, the "
    "end-to-end freshness a reader actually experiences)",
    buckets=(0.001, 0.005, 0.025, 0.1, 0.5, 1, 5, 15, 60, 300, 1800))
search_freshness = Gauge(
    "tempo_search_freshness_seconds",
    "per-tenant search staleness: now - max end_time over the tenant's "
    "newest SEARCHABLE (polled) block; refreshed every poll cycle")
oldest_unflushed = Gauge(
    "tempo_ingest_oldest_unflushed_seconds",
    "per-tenant age of the oldest trace not yet flushed to the backend "
    "— live (uncut), WAL head, or completing blocks; 0 when everything "
    "is flushed")
flush_duration_seconds = Histogram(
    "tempo_ingester_flush_duration_seconds",
    "successful block completion (WAL -> backend) wall time per flush",
    buckets=(0.01, 0.05, 0.25, 1, 5, 30, 120, 600))
flush_queue_length = Gauge(
    "tempo_ingester_flush_queue_length",
    "per-tenant blocks cut and waiting for (or in) backend completion")
flush_retries = Counter(
    "tempo_ingester_flush_retries_total",
    "flush attempts that failed and were backed off, labeled by "
    "attempt bucket (attempt=1|2|3|4+) — distinguishes a one-off "
    "backend flake from a block stuck in exponential backoff")
wal_replay_seconds = Gauge(
    "tempo_ingester_wal_replay_seconds",
    "duration of the WAL replay this process performed at startup")
wal_replayed_blocks = Gauge(
    "tempo_ingester_wal_replayed_blocks",
    "WAL blocks replayed at startup")
wal_replayed_bytes = Gauge(
    "tempo_ingester_wal_replayed_bytes",
    "WAL bytes re-scanned at startup")
slow_flushes = Counter(
    "tempo_ingester_slow_flushes_total",
    "flushes slower than ingest_slow_flush_log_s per tenant (every one "
    "counts; the JSON log line is additionally rate-limited per tenant)")
blocklist_poll_seconds = Histogram(
    "tempodb_blocklist_poll_duration_seconds",
    "blocklist poll cycle wall time (backend list + meta reads + apply)",
    buckets=(0.005, 0.025, 0.1, 0.5, 2, 10, 60, 300))
blocklist_length = Gauge(
    "tempodb_blocklist_length",
    "per-tenant live blocks in this reader's blocklist after the last "
    "poll")
blocklist_index_age = Gauge(
    "tempodb_blocklist_index_age_seconds",
    "per-tenant age of the tenant index this poller last consumed "
    "(now - builder created_at); a growing value means the elected "
    "index builder stopped writing")
compaction_duration_seconds = Histogram(
    "tempodb_compaction_duration_seconds",
    "one compaction run (k-way merge + search rebuild) wall time",
    buckets=(0.05, 0.25, 1, 5, 30, 120, 600))
compaction_outstanding_bytes = Gauge(
    "tempodb_compaction_outstanding_bytes",
    "per-tenant bytes sitting in compactable input groups (>= "
    "min_inputs same-window blocks) — the compactor's input backlog")
compaction_outstanding_blocks = Gauge(
    "tempodb_compaction_outstanding_blocks",
    "per-tenant block count behind "
    "tempodb_compaction_outstanding_bytes — backlog in selector units "
    "(one run consumes at most compaction_max_inputs of these)")
canary_freshness = Gauge(
    "tempo_ingest_canary_freshness_seconds",
    "last MEASURED push->searchable latency of the synthetic ingest "
    "canary (black-box: a real push polled through real search)")
canary_failures = Counter(
    "tempo_ingest_canary_failures_total",
    "canary probes that never became searchable before their deadline "
    "— the wedged-flush/poll alarm")

# ---- robustness: breaker / watchdog / fault injection ----
device_faults = Counter(
    "tempo_search_device_faults_total",
    "device dispatch faults booked into the circuit breaker "
    "(kind=timeout|error|lock_timeout, mode = the profiler dispatch "
    "mode giving the fault its stage context); counted even with the "
    "breaker disabled")
breaker_transitions = Counter(
    "tempo_search_device_breaker_transitions_total",
    "circuit-breaker state transitions (from/to = "
    "closed|open|half_open); open means every scan/probe is routed "
    "through the byte-identical host path")
breaker_state = Gauge(
    "tempo_search_device_breaker_state",
    "current breaker state as a code: 0=closed 1=half_open 2=open")
dispatch_lock_timeouts = Counter(
    "tempo_search_dispatch_lock_timeouts_total",
    "bounded waits on the process-wide collective dispatch lock that "
    "timed out — some dispatch is wedged while holding it (each books "
    "a breaker fault kind=lock_timeout)")
partial_results = Counter(
    "tempo_search_partial_results_total",
    "sub-answers swallowed into a DEGRADED response, by why "
    "(reason=replica|backend|subrequest|deadline), booked at the "
    "swallow site — a failure past tolerate_failed_blocks still "
    "counts here even though the request then errors. The "
    "response-level twin is SearchMetrics.partial, which survives the "
    "frontend merge so a degraded answer is never indistinguishable "
    "from a complete one")
faults_injected = Counter(
    "tempo_robustness_faults_injected_total",
    "fault-injection firings per faultpoint (chaos/test harness only; "
    "always zero in production unless a faultpoint is armed)")

# ---- self-tracing health (observability/tracing.py) ----
selftrace_dropped_spans = Counter(
    "tempo_selftrace_dropped_spans_total",
    "self-trace spans dropped because the batch processor queue was "
    "full, labeled by exporter class like selftrace_export_failures — "
    "and the SINGLE source of truth: BatchProcessor.dropped derives "
    "from this series")
selftrace_export_failures = Counter(
    "tempo_selftrace_export_failures_total",
    "self-trace export batches that raised (swallowed to protect the "
    "flush loop; this counter is the only visible signal)")

# ---- build identity ----
build_info = Gauge(
    "tempo_build_info",
    "constant 1; the process's build/runtime identity rides the labels "
    "(version = tempo_tpu package version, jax = jax version or "
    "'absent', backend = initialized jax backend or "
    "uninitialized/unknown at set time, native = native libtempotpu.so "
    "state: loaded|present|absent|unknown) — the standard *_build_info "
    "idiom, set once at App init and mirrored live in /status")
