"""Device-path dispatch profiler: per-dispatch stage telemetry.

PRs 1-4 (coalescing, HBM tiering, device dict probe) each had to infer
where device time went from bench wall-clocks — there was no first-class
visibility into the stages the TPU lift actually changes. This module
gives every device dispatch path (single-block, multi-block batched,
coalesced, mesh-sharded, and the dict-probe kernel) a stage breakdown:

  build    host-side predicate/table build (device-param upload prep,
           query-table asarray; `mode=host_probe` records the host
           dictionary prefilter — PR4's motivating cost)
  h2d      host→device staging puts (bytes counted separately)
  compile  the dispatch call when the jit cache missed for this shape
           signature — tracing + XLA compile dominate that call
  execute  the dispatch call on a cache hit, plus the
           ``block_until_ready`` fence that attributes true kernel time
  d2h      device→host result fetch / fused-group demux
  lock_wait  time queued on the process-wide collective dispatch lock
           (parallel.mesh.dispatch_lock) — mesh paths only

Records land in a bounded ring buffer (``/debug/profile`` renders the
recent ones) and aggregate into metrics:

  tempo_search_dispatch_stage_seconds{stage,mode}   (histogram)
  tempo_search_jit_cache_events_total{result}       (counter)
  tempo_search_h2d_bytes_total / tempo_search_d2h_bytes_total

Stage events also annotate the active self-trace span, so a slow
query's own trace shows which stage ate the time.

Design constraints (mirrors tracing.py's noop stance):
- A TRUE noop path: with profiling disabled every call site pays one
  attribute check and gets back a shared immutable noop object — no
  allocation, no clock reads, no lock. `search_profiling_enabled: false`
  must cost nothing measurable on the dispatch hot path.
- Jit-compile detection needs no jax internals: the profiler keeps its
  own bounded set of shape signatures per dispatch site; a first-seen
  signature is a compile-cache miss (jit caches key on exactly these
  statics — the call sites pass the same tuple the kernel's
  static_argnames + array shapes/dtypes imply).
- The ``execute`` fence (``block_until_ready`` after the dispatch call)
  attributes TRUE kernel time, but converts the async enqueue into a
  synchronous wait — which breaks the batcher's dispatch/drain
  pipelining. It is therefore OPT-IN (``search_profiling_fence``,
  default off): unfenced, "execute" measures the dispatch call (enqueue
  + any synchronous work) and the device wait lands in the "d2h" stage
  at the sync point, which still answers "which stage ate the time" at
  dispatch granularity. Bench phase ``profile_overhead`` re-measures
  the enabled-vs-disabled delta every round; the noop path is the <2%
  contract.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque

from . import metrics as obs
from . import selftrace
from . import tracing

STAGES = ("build", "h2d", "compile", "execute", "d2h", "lock_wait")

# per-thread stack of record sinks (collect_records): a dispatch record
# finishing on this thread is ALSO handed to the innermost open
# collector. Thread-local rather than a contextvar: dispatch + close
# always happen on the thread that ran the engine call, and the
# coalescer's flush threads must not inherit a submitter's collector.
_collect_local = threading.local()


@contextlib.contextmanager
def collect_records():
    """Collect the dispatch records (as_dict form) finished on THIS
    thread inside the body — the query-stats attribution hook: the
    caller apportions the record's stages to the query (or queries)
    the dispatch served. Nests; profiling disabled yields no records
    (the noop dispatch never finishes)."""
    stack = getattr(_collect_local, "stack", None)
    if stack is None:
        stack = _collect_local.stack = []
    recs: list[dict] = []
    stack.append(recs)
    try:
        yield recs
    finally:
        stack.pop()

_COMPILE_SEEN_MAX = 4096  # shape signatures tracked before reset


class _NoopStage:
    """Shared, immutable, free — the disabled-profiler stage context."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


_NOOP_STAGE = _NoopStage()


class _NoopDispatch:
    """Shared noop dispatch record: every method is a cheap no-op so the
    call sites never branch on `enabled` themselves."""

    __slots__ = ()
    enabled = False

    def stage(self, name):
        return _NOOP_STAGE

    def add_stage(self, name, seconds):
        return self

    def add_bytes(self, h2d=0, d2h=0):
        return self

    def compile_check(self, key) -> bool:
        return False

    def fence(self, arrays):
        return self

    def set(self, **kv):
        return self

    def close(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
        return False


NOOP_DISPATCH = _NoopDispatch()


class _StageTimer:
    __slots__ = ("_rec", "_name", "_t0")

    def __init__(self, rec, name):
        self._rec = rec
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *a):
        self._rec.add_stage(self._name,
                            time.perf_counter() - self._t0)
        return False


class Dispatch:
    """One in-flight dispatch's profile record. Context-manager; the
    record is published (ring + metrics + span event) on close()."""

    __slots__ = ("mode", "stages", "h2d_bytes", "d2h_bytes", "jit",
                 "attrs", "t0", "_prof", "_closed")
    enabled = True

    def __init__(self, prof, mode: str):
        self.mode = mode
        self.stages: dict[str, float] = {}
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        self.jit = None       # None (no kernel), "hit" or "miss"
        self.attrs: dict = {}
        self.t0 = time.perf_counter()
        self._prof = prof
        self._closed = False

    def stage(self, name: str) -> _StageTimer:
        return _StageTimer(self, name)

    def add_stage(self, name: str, seconds: float) -> "Dispatch":
        self.stages[name] = self.stages.get(name, 0.0) + seconds
        return self

    def add_bytes(self, h2d: int = 0, d2h: int = 0) -> "Dispatch":
        self.h2d_bytes += int(h2d)
        self.d2h_bytes += int(d2h)
        return self

    def compile_check(self, key) -> bool:
        """First sighting of this shape signature = jit cache miss. The
        caller times the dispatch call under stage "compile" on a miss
        (tracing + XLA compile dominate it) and "execute" on a hit."""
        miss = self._prof._compile_miss(key)
        self.jit = "miss" if miss else "hit"
        return miss

    def fence(self, arrays) -> "Dispatch":
        """block_until_ready the kernel outputs when the profiler's
        fence is on — called inside the "execute" stage so kernel time
        is attributed there instead of at the later sync point."""
        if self._prof.fence:
            fence_arrays(arrays)
        return self

    def set(self, **kv) -> "Dispatch":
        self.attrs.update(kv)
        return self

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._prof._finish(self)

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
        return False

    def as_dict(self) -> dict:
        d = {
            "mode": self.mode,
            "stages_ms": {k: round(v * 1e3, 3)
                          for k, v in self.stages.items()},
            "total_ms": round(sum(self.stages.values()) * 1e3, 3),
        }
        if self.h2d_bytes:
            d["h2d_bytes"] = self.h2d_bytes
        if self.d2h_bytes:
            d["d2h_bytes"] = self.d2h_bytes
        if self.jit is not None:
            d["jit_cache"] = self.jit
        if self.attrs:
            d["attrs"] = self.attrs
        return d


class DispatchProfiler:
    """Process-wide profiler (module singleton ``PROFILER``, the
    REGISTRY idiom): config flips ``enabled``; dispatch sites call
    ``dispatch(mode)`` and get either a recording ``Dispatch`` or the
    shared noop."""

    def __init__(self, ring_size: int = 256, enabled: bool = True,
                 fence: bool = False):
        self.enabled = enabled
        # fence=True adds a block_until_ready after each profiled kernel
        # call (true kernel-time attribution, at the cost of the async
        # dispatch pipelining — see module docstring)
        self.fence = fence
        self._ring: deque = deque(maxlen=ring_size)
        self._lock = threading.Lock()
        self._compile_seen: set = set()
        # aggregates over the process lifetime (cheap dict sums — the
        # histogram has the full distribution, this answers /debug/profile
        # without a metrics scrape); values are [n, total_s, total_bytes]
        # so byte-carrying stages expose a replayable rate (the offload
        # planner's offline calibration, scripts/calibrate_offload.py)
        self._agg: dict[tuple, list] = {}   # (mode, stage) -> [n, s, bytes]
        self._jit = {"hit": 0, "miss": 0}
        self._bytes = {"h2d": 0, "d2h": 0}
        self._dispatches = 0
        # consumers of finished records / stage observations (the offload
        # planner's live feed, search/planner.py) — called OUTSIDE the
        # lock, exceptions swallowed, only when profiling is enabled
        self._listeners: list = []
        self._stage_listeners: list = []
        # unix time of the last successfully finished dispatch/stage —
        # the /status device block's "is the chip still answering"
        # signal (None until the first device op of the process)
        self.last_dispatch_t: float | None = None

    # ---- call-site API ----

    def dispatch(self, mode: str):
        # liveness stamp even when profiling is off: /status's
        # wedge-vs-idle signal (device_status) must not depend on the
        # profiling knob — one coarse clock read; the noop contract's
        # no-locks/no-allocation still holds and the record protocol
        # itself stays free
        self.last_dispatch_t = time.time()
        if not self.enabled:
            return NOOP_DISPATCH
        return Dispatch(self, mode)

    def add_listener(self, fn) -> None:
        """Subscribe to finished dispatch records (called with the
        record's as_dict form). The offload planner's live feed."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def add_stage_listener(self, fn) -> None:
        """Subscribe to out-of-record stage observations; called with
        (stage, mode, seconds, nbytes)."""
        with self._lock:
            if fn not in self._stage_listeners:
                self._stage_listeners.append(fn)

    def observe_stage(self, stage: str, mode: str, seconds: float,
                      nbytes: int = 0) -> None:
        """Record one stage observation outside a dispatch record (e.g.
        staging H2D that serves many later dispatches, or the drain-side
        D2H fetch). Noop when disabled. `nbytes` feeds the transfer
        counters only for the transfer stages; other stages (the host
        prefilter's scanned bytes) keep it in the aggregates alone."""
        # liveness stamp (see dispatch()) — but NOT for host-only work:
        # mode=host_probe runs with the device wedged just fine, and a
        # fresh last_dispatch_age_s fed by host scans would mask exactly
        # the wedge the /status device block exists to expose
        if mode != "host_probe":
            self.last_dispatch_t = time.time()
        if not self.enabled:
            return
        obs.dispatch_stage_seconds.observe(seconds, stage=stage, mode=mode)
        transfer = stage in ("h2d", "d2h")
        with self._lock:
            k = (mode, stage)
            a = self._agg.get(k)
            if a is None:
                a = self._agg[k] = [0, 0.0, 0]
            a[0] += 1
            a[1] += seconds
            a[2] += nbytes
            if nbytes and transfer:
                self._bytes[stage] += nbytes
        if nbytes and transfer:
            (obs.h2d_bytes if stage == "h2d" else obs.d2h_bytes).inc(nbytes)
        for fn in self._stage_listeners:
            try:
                fn(stage, mode, seconds, nbytes)
            except Exception:  # noqa: BLE001 — listeners never fail a scan
                pass
        span = tracing.current_span()
        if span.recording:
            span.add_event("profile.stage", stage=stage, mode=mode,
                           ms=round(seconds * 1e3, 3))

    # ---- internals ----

    def seen(self, key) -> bool:
        """Whether this shape signature has been dispatched before —
        WITHOUT recording it. The offload planner uses this to predict
        whether a device decision would pay an XLA compile."""
        with self._lock:
            return key in self._compile_seen

    def _compile_miss(self, key) -> bool:
        with self._lock:
            if key in self._compile_seen:
                miss = False
            else:
                if len(self._compile_seen) >= _COMPILE_SEEN_MAX:
                    self._compile_seen.clear()
                self._compile_seen.add(key)
                miss = True
        obs.jit_cache_events.inc(result="miss" if miss else "hit")
        return miss

    def _finish(self, rec: Dispatch) -> None:
        for stage, sec in rec.stages.items():
            obs.dispatch_stage_seconds.observe(sec, stage=stage,
                                               mode=rec.mode)
        if rec.h2d_bytes:
            obs.h2d_bytes.inc(rec.h2d_bytes)
        if rec.d2h_bytes:
            obs.d2h_bytes.inc(rec.d2h_bytes)
        rd = rec.as_dict()
        stack = getattr(_collect_local, "stack", None)
        if stack:
            stack[-1].append(rd)
        with self._lock:
            self._dispatches += 1
            self.last_dispatch_t = time.time()
            if rec.jit is not None:
                self._jit[rec.jit] += 1
            self._bytes["h2d"] += rec.h2d_bytes
            self._bytes["d2h"] += rec.d2h_bytes
            for stage, sec in rec.stages.items():
                k = (rec.mode, stage)
                a = self._agg.get(k)
                if a is None:
                    a = self._agg[k] = [0, 0.0, 0]
                a[0] += 1
                a[1] += sec
                if stage == "h2d":
                    a[2] += rec.h2d_bytes
                elif stage == "d2h":
                    a[2] += rec.d2h_bytes
            self._ring.append(rd)
        for fn in self._listeners:
            try:
                fn(rd)
            except Exception:  # noqa: BLE001 — listeners never fail a scan
                pass
        span = tracing.current_span()
        if span.recording:
            span.add_event(
                "dispatch.profile", mode=rec.mode,
                jit_cache=rec.jit or "",
                **{f"{k}_ms": round(v * 1e3, 3)
                   for k, v in rec.stages.items()})
            # dogfood pipeline: the record additionally lowers into
            # per-stage child spans of the active span, so structural
            # queries over span.stage see real dispatch telemetry
            # (observability/selftrace; gate off = one attribute read)
            if selftrace.SELFTRACE.ingest_enabled:
                selftrace.SELFTRACE.lower_dispatch(rec, parent=span)

    # ---- operator surface ----

    def snapshot(self, recent: int = 32) -> dict:
        """/debug/profile payload: recent dispatches + aggregates."""
        with self._lock:
            ring = list(self._ring)[-recent:] if recent > 0 else []
            agg = {}
            for (mode, stage), (n, total, nbytes) in sorted(
                    self._agg.items()):
                entry = {
                    "count": n,
                    "total_ms": round(total * 1e3, 3),
                    "mean_ms": round(total / n * 1e3, 3),
                }
                if nbytes:
                    entry["bytes"] = nbytes
                agg.setdefault(mode, {})[stage] = entry
            return {
                "enabled": self.enabled,
                "dispatches": self._dispatches,
                "jit_cache": dict(self._jit),
                "bytes": dict(self._bytes),
                "aggregates": agg,
                "recent": ring,
            }

    def reset(self) -> None:
        """Test/bench hook: clear ring + aggregates (metrics counters
        are process-lifetime and stay)."""
        with self._lock:
            self._ring.clear()
            self._agg.clear()
            self._compile_seen.clear()
            self._jit = {"hit": 0, "miss": 0}
            self._bytes = {"h2d": 0, "d2h": 0}
            self._dispatches = 0


PROFILER = DispatchProfiler()


def configure(enabled: bool | None = None, fence: bool | None = None,
              ring_size: int | None = None) -> DispatchProfiler:
    """Apply config (TempoDBConfig.search_profiling_enabled) to the
    process profiler. Ring resize preserves nothing (the ring is
    diagnostics, not state)."""
    if enabled is not None:
        PROFILER.enabled = bool(enabled)
    if fence is not None:
        PROFILER.fence = bool(fence)
    if ring_size is not None:
        with PROFILER._lock:
            PROFILER._ring = deque(PROFILER._ring, maxlen=int(ring_size))
    return PROFILER


_persist_watch_registered = False


def watch_persistent_compile_cache() -> bool:
    """Register a jax.monitoring listener that books every persistent-
    compilation-cache HIT as jit_cache_events{result=persisted} — the
    operator-visible proof that a cold process is replaying first-seen-
    shape compiles from disk (utils.jaxenv.enable_compile_cache wires
    the cache itself; TempoDBConfig.search_compile_cache_dir /
    host_state_dir turn it on). Idempotent; returns False when the
    running jax build lacks the monitoring hooks."""
    global _persist_watch_registered
    if _persist_watch_registered:
        return True
    try:
        from jax import monitoring as _monitoring

        def _on_event(event: str, **kw) -> None:
            # jax 0.4.x records '/jax/compilation_cache/cache_hits'
            # per retrieval; match loosely so minor renames keep the
            # signal rather than silently zeroing it
            if "compilation_cache" in event and "hit" in event:
                obs.jit_cache_events.inc(result="persisted")

        _monitoring.register_event_listener(_on_event)
    except Exception:  # noqa: BLE001 — observability extra, never fatal
        return False
    _persist_watch_registered = True
    return True


def dispatch(mode: str):
    """Module-level convenience mirroring tracing.start_span."""
    return PROFILER.dispatch(mode)


def observe_stage(stage: str, mode: str, seconds: float,
                  nbytes: int = 0) -> None:
    PROFILER.observe_stage(stage, mode, seconds, nbytes=nbytes)


def build_info() -> dict:
    """Build/runtime identity: package version, jax version, backend,
    native-.so state. Feeds the `tempo_build_info` gauge labels (set
    once at App init) and the /status "build" block (re-evaluated per
    probe). Shares device_status's stance: NEVER initializes a jax
    backend, never triggers a native build — reporting identity must
    not claim a chip or fork a compiler."""
    import os

    import tempo_tpu

    info: dict = {"version": tempo_tpu.__version__}
    try:
        import jax

        info["jax"] = jax.__version__
    except Exception:  # noqa: BLE001 — identity, never fatal
        info["jax"] = "absent"
    try:
        from jax._src import xla_bridge as _xb

        if getattr(_xb, "_backends", None):
            import jax

            info["backend"] = jax.default_backend()
        else:
            info["backend"] = "uninitialized"
    except Exception:  # noqa: BLE001 — internal API moves across versions
        info["backend"] = "unknown"
    try:
        from tempo_tpu.ops import native as _native

        if _native._TRIED:
            info["native"] = ("loaded" if _native._LIB is not None
                              else "absent")
        else:
            # not probed yet: report file presence without loading —
            # _load() may BUILD the .so, and /metrics must not
            info["native"] = ("present" if any(
                os.path.exists(os.path.abspath(p))
                for p in _native._SO_PATHS) else "absent")
    except Exception:  # noqa: BLE001
        info["native"] = "unknown"
    return info


def device_status() -> dict:
    """The /status "device" block: accelerator backend + device count
    (WITHOUT initializing a backend — write-only processes must never
    claim a chip for a status probe) and the age of the last successful
    dispatch, the operator's first wedge-vs-idle signal (bench r04/r05
    recorded zeroed CPU-fallback headlines that were indistinguishable
    from a regression because nothing surfaced this)."""
    out: dict = {
        "dispatches": PROFILER._dispatches,
        "profiling_enabled": PROFILER.enabled,
    }
    t = PROFILER.last_dispatch_t
    out["last_dispatch_age_s"] = (round(time.time() - t, 3)
                                  if t is not None else None)
    try:
        # the circuit breaker's verdict IS the wedge signal now: /status
        # and bench's device_wedged headline read this instead of
        # ad-hoc probing (tempo_tpu/robustness/breaker.py)
        from tempo_tpu.robustness import BREAKER

        out["breaker"] = BREAKER.snapshot()
        out["wedged"] = BREAKER.blocking()
    except Exception:  # noqa: BLE001 — status must never 500
        pass
    try:
        from jax._src import xla_bridge as _xb

        initialized = bool(getattr(_xb, "_backends", None))
    except Exception:  # noqa: BLE001 — internal API moves across versions
        # can't tell whether a backend exists: report unknown rather
        # than probe — jax.default_backend() would INITIALIZE one, and
        # on TPU that claims the chip out from under the serving process
        out["backend"] = "unknown"
        return out
    if not initialized:
        out["backend"] = "uninitialized"
        return out
    try:
        import jax

        out["backend"] = jax.default_backend()
        out["device_count"] = jax.device_count()
    except Exception as e:  # noqa: BLE001 — a wedged tunnel must not 500 /status
        out["backend"] = "error"
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def fence_arrays(arrays) -> None:
    """block_until_ready every device array in `arrays` (tuples from the
    scan kernels) — the execute-stage fence. Tolerates host scalars and
    None leaves so call sites can pass kernel outputs verbatim."""
    for a in arrays:
        wait = getattr(a, "block_until_ready", None)
        if wait is not None:
            try:
                wait()
            except Exception:  # noqa: BLE001 — profiling must never fail a scan
                pass
