"""Proto-level search matching over full trace objects.

Role-equivalent to the reference's pkg/model/trace/matches.go:33-184: the
querier's trace-block scan path evaluates a SearchRequest directly against
the unmarshalled proto (tag substring semantics on string attributes,
numeric equality on int attrs, duration and time-window filters), and
extracts TraceSearchMetadata (root service/span, start, duration).

This is the CPU fallback / correctness oracle for the TPU columnar engine —
both must agree on match semantics (tests assert this).
"""

from __future__ import annotations

from tempo_tpu import tempopb


def _attr_matches(kv: tempopb.KeyValue, want_key: str, want_val: str) -> bool:
    if kv.key != want_key:
        return False
    which = kv.value.WhichOneof("value")
    if which == "string_value":
        return want_val in kv.value.string_value  # substring, like bytes.Contains
    if which == "int_value":
        return want_val == str(kv.value.int_value)
    if which == "bool_value":
        return want_val == ("true" if kv.value.bool_value else "false")
    if which == "double_value":
        return want_val == repr(kv.value.double_value)
    return False


def _iter_all_attrs(trace: tempopb.Trace):
    for batch in trace.batches:
        for kv in batch.resource.attributes:
            yield kv
        for ss in batch.scope_spans:
            for span in ss.spans:
                for kv in span.attributes:
                    yield kv
                # well-known derived tags, as the reference's search data
                # extraction records name and status error
                nk = tempopb.KeyValue()
                nk.key = "name"
                nk.value.string_value = span.name
                yield nk
                if span.status.code == tempopb.Status.STATUS_CODE_ERROR:
                    ek = tempopb.KeyValue()
                    ek.key = "error"
                    ek.value.string_value = "true"
                    yield ek


def trace_range_ns(trace: tempopb.Trace) -> tuple[int, int]:
    start, end = 2**63, 0
    for batch in trace.batches:
        for ss in batch.scope_spans:
            for span in ss.spans:
                start = min(start, span.start_time_unix_nano)
                end = max(end, span.end_time_unix_nano)
    if end == 0:
        return 0, 0
    return start, end


def matches(trace: tempopb.Trace, req: tempopb.SearchRequest) -> bool:
    start_ns, end_ns = trace_range_ns(trace)
    dur_ms = (end_ns - start_ns) // 1_000_000
    if req.min_duration_ms and dur_ms < req.min_duration_ms:
        return False
    if req.max_duration_ms and dur_ms > req.max_duration_ms:
        return False
    if req.start and end_ns // 1_000_000_000 < req.start:
        return False
    if req.end and start_ns // 1_000_000_000 > req.end:
        return False
    if req.tags:
        from tempo_tpu.search.analytics import AGG_QUERY_TAG
        from tempo_tpu.search.pipeline import EXHAUSTIVE_SEARCH_TAG
        from tempo_tpu.search.structural import STRUCTURAL_QUERY_TAG

        attrs = None
        for k, v in req.tags.items():
            if k in (EXHAUSTIVE_SEARCH_TAG, STRUCTURAL_QUERY_TAG,
                     AGG_QUERY_TAG):
                continue  # in-band flags, not tag predicates
            if attrs is None:
                attrs = list(_iter_all_attrs(trace))
            if not any(_attr_matches(kv, k, v) for kv in attrs):
                return False
    # structural predicate over the full proto (container-less blocks'
    # fallback scan): extract the span rows and run the host reference
    # evaluator — the same semantics the compiled kernels answer
    from tempo_tpu.search import structural as _structural

    expr = _structural.structural_query(req)
    if expr is not None:
        from tempo_tpu.search.data import collect_span_rows, SearchData

        from tempo_tpu.search.data import _any_value_str

        sd = SearchData(dur_ms=min(max(0, dur_ms), 0xFFFFFFFF))
        for kv in _iter_all_attrs(trace):
            v = _any_value_str(kv.value)
            if v:
                sd.kvs.setdefault(kv.key, set()).add(v)
        sd.spans = collect_span_rows(
            trace, max_spans=_structural.STRUCTURAL.max_spans,
            max_kvs=_structural.STRUCTURAL.max_span_kvs)
        if not _structural.eval_host(expr, sd):
            return False
    return True


def trace_search_metadata(trace_id: bytes, trace: tempopb.Trace) -> tempopb.TraceSearchMetadata:
    m = tempopb.TraceSearchMetadata()
    m.trace_id = trace_id.hex()
    start_ns, end_ns = trace_range_ns(trace)
    m.start_time_unix_nano = start_ns if start_ns < 2**63 else 0
    m.duration_ms = min(max(0, (end_ns - start_ns)) // 1_000_000, 0xFFFFFFFF)
    # one pass tracking both the best parentless span and the earliest span
    # (fallback when the root was dropped/sampled away)
    root, root_service = None, ""
    earliest, earliest_service = None, ""
    for batch in trace.batches:
        svc = ""
        for kv in batch.resource.attributes:
            if kv.key == "service.name":
                svc = kv.value.string_value
        for ss in batch.scope_spans:
            for span in ss.spans:
                t = span.start_time_unix_nano
                if not span.parent_span_id and (
                    root is None or t < root.start_time_unix_nano
                ):
                    root, root_service = span, svc
                if earliest is None or t < earliest.start_time_unix_nano:
                    earliest, earliest_service = span, svc
    if root is None:
        root, root_service = earliest, earliest_service
    if root is not None:
        m.root_trace_name = root.name
        m.root_service_name = root_service
    return m
