"""Span/batch sorting by start time (reference pkg/model/trace/sort.go)."""

from __future__ import annotations

from tempo_tpu import tempopb


def sort_trace(trace: tempopb.Trace) -> tempopb.Trace:
    for batch in trace.batches:
        for ss in batch.scope_spans:
            spans = sorted(ss.spans, key=lambda s: s.start_time_unix_nano)
            del ss.spans[:]
            ss.spans.extend(spans)
    return trace
