from .codec import (
    CURRENT_ENCODING,
    ALL_ENCODINGS,
    ObjectCodec,
    SegmentCodec,
    codec_for,
    segment_codec_for,
)
from .combine import combine_trace_protos, combine_trace_bytes
from .matches import matches, trace_search_metadata
from .sort import sort_trace

__all__ = [
    "CURRENT_ENCODING", "ALL_ENCODINGS", "ObjectCodec", "SegmentCodec",
    "codec_for", "segment_codec_for", "combine_trace_protos",
    "combine_trace_bytes", "matches", "trace_search_metadata", "sort_trace",
]
