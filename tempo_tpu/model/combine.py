"""Trace combination: merge partial traces for the same id, deduping spans.

Role-equivalent to the reference's pkg/model/trace/combine.go (span-id
hashing dedupe) — used on read (partials from several ingesters/blocks) and
during compaction (same trace object in two input blocks).
"""

from __future__ import annotations

from tempo_tpu import tempopb


def combine_trace_protos(traces: list[tempopb.Trace]) -> tempopb.Trace:
    if not traces:
        return tempopb.Trace()
    if len(traces) == 1:
        # copy: callers own the result and may mutate it (sort, dedupe)
        out = tempopb.Trace()
        out.CopyFrom(traces[0])
        return out
    out = tempopb.Trace()
    seen: set[bytes] = set()
    for t in traces:
        for batch in t.batches:
            kept = None
            for ss in batch.scope_spans:
                new_spans = [s for s in ss.spans if _span_key(s) not in seen]
                for s in new_spans:
                    seen.add(_span_key(s))
                if new_spans:
                    if kept is None:
                        kept = out.batches.add()
                        kept.resource.CopyFrom(batch.resource)
                        kept.schema_url = batch.schema_url
                    nss = kept.scope_spans.add()
                    nss.scope.CopyFrom(ss.scope)
                    nss.schema_url = ss.schema_url
                    nss.spans.extend(new_spans)
    return out


def combine_trace_bytes(objs: list[bytes], encoding: str) -> bytes:
    from tempo_tpu.model.codec import codec_for

    return codec_for(encoding).combine(*objs)


def _span_key(span: tempopb.Span) -> bytes:
    return span.span_id or span.SerializeToString()
