"""Trace object codecs — how a trace is framed as bytes inside blocks/WAL.

Role-equivalent to the reference's pkg/model:
  - v1: raw Trace proto bytes (object_decoder.go, model/v1).
  - v2: ``|u32 start|u32 end|Trace proto|`` — start/end unix seconds
    prepended so readers can range-prune without a proto unmarshal
    (model/v2/object_decoder.go:20-135, "FastRange").
  - SegmentDecoder: the push-path framing the distributor applies before
    gRPC so the ingester can append without re-marshalling
    (model/segment_decoder.go).

CURRENT_ENCODING = "v2" (object_decoder.go:12).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

from tempo_tpu import tempopb

CURRENT_ENCODING = "v2"
ALL_ENCODINGS = ("v1", "v2")

_HDR = struct.Struct("<II")  # start, end unix seconds


class DecodeError(Exception):
    pass


@dataclass(frozen=True)
class ObjectCodec:
    """Encode/decode one stored trace object."""

    encoding: str

    def marshal(self, trace: tempopb.Trace, start: int = 0, end: int = 0) -> bytes:
        body = trace.SerializeToString()
        if self.encoding == "v1":
            return body
        return _HDR.pack(start & 0xFFFFFFFF, end & 0xFFFFFFFF) + body

    def prepare_for_read(self, obj: bytes) -> tempopb.Trace:
        t = tempopb.Trace()
        t.ParseFromString(self.trace_bytes(obj))
        return t

    def trace_bytes(self, obj: bytes) -> bytes:
        if self.encoding == "v1":
            return obj
        if len(obj) < _HDR.size:
            raise DecodeError("v2 object too short")
        return obj[_HDR.size:]

    def fast_range(self, obj: bytes) -> tuple[int, int] | None:
        """(start, end) unix seconds without a proto unmarshal; None if the
        encoding carries no range (v1)."""
        if self.encoding == "v1":
            return None
        if len(obj) < _HDR.size:
            raise DecodeError("v2 object too short")
        return _HDR.unpack_from(obj)

    def combine(self, *objs: bytes) -> bytes:
        """Combine duplicate trace objects (same id seen in several blocks /
        segments) — dedupe spans, merge ranges. Reference:
        model.ObjectCombiner / trace/combine.go."""
        from tempo_tpu.model.combine import combine_trace_protos

        objs = [o for o in objs if o]
        if not objs:
            return self.marshal(tempopb.Trace())
        if len(objs) == 1:
            return objs[0]
        ranges = [self.fast_range(o) for o in objs]
        traces = [self.prepare_for_read(o) for o in objs]
        merged = combine_trace_protos(traces)
        if self.encoding == "v1":
            return merged.SerializeToString()
        start = min(r[0] for r in ranges if r)
        end = max(r[1] for r in ranges if r)
        return self.marshal(merged, start, end)


@dataclass(frozen=True)
class SegmentCodec:
    """Push-path framing: distributor marshals per-ingester segments once;
    ingester appends them to live traces and later to the WAL without
    re-encoding (reference segment_decoder.go, PrepareForWrite)."""

    encoding: str

    def prepare_for_write(self, trace: tempopb.Trace, start: int, end: int) -> bytes:
        return ObjectCodec(self.encoding).marshal(trace, start, end)

    def prepare_for_read(self, segments: list[bytes]) -> tempopb.Trace:
        codec = ObjectCodec(self.encoding)
        out = tempopb.Trace()
        for seg in segments:
            t = codec.prepare_for_read(seg)
            out.batches.extend(t.batches)
        return out

    def to_object(self, segments: list[bytes]) -> bytes:
        """Concatenate segments into one stored object (merging ranges)."""
        codec = ObjectCodec(self.encoding)
        if len(segments) == 1:
            return segments[0]
        start, end = 0xFFFFFFFF, 0
        if self.encoding != "v1":
            for seg in segments:
                s, e = codec.fast_range(seg)
                start, end = min(start, s), max(end, e)
        t = self.prepare_for_read(segments)
        return codec.marshal(t, start if start != 0xFFFFFFFF else 0, end)

    def fast_range(self, segment: bytes) -> tuple[int, int] | None:
        return ObjectCodec(self.encoding).fast_range(segment)


def codec_for(encoding: str) -> ObjectCodec:
    if encoding not in ALL_ENCODINGS:
        raise ValueError(f"unknown trace encoding {encoding!r}")
    return ObjectCodec(encoding)


def segment_codec_for(encoding: str) -> SegmentCodec:
    if encoding not in ALL_ENCODINGS:
        raise ValueError(f"unknown trace encoding {encoding!r}")
    return SegmentCodec(encoding)
