"""Wire model: protoc-generated OTLP-compatible trace protos + service
messages (sources in /root/repo/protos, regenerate with protos/gen.sh).

Role-equivalent to the reference's pkg/tempopb (gogo-proto generated types,
tempo.proto services) — see SURVEY.md §2.4.
"""

from . import trace_pb2
from . import tempo_pb2

Trace = tempo_pb2.Trace
PushBytesRequest = tempo_pb2.PushBytesRequest
PushResponse = tempo_pb2.PushResponse
TraceByIDRequest = tempo_pb2.TraceByIDRequest
TraceByIDResponse = tempo_pb2.TraceByIDResponse
TraceByIDMetrics = tempo_pb2.TraceByIDMetrics
SearchRequest = tempo_pb2.SearchRequest
SearchBlockRequest = tempo_pb2.SearchBlockRequest
SearchBlocksRequest = tempo_pb2.SearchBlocksRequest
BlockSearchJob = tempo_pb2.BlockSearchJob
SearchResponse = tempo_pb2.SearchResponse
TraceSearchMetadata = tempo_pb2.TraceSearchMetadata
SearchMetrics = tempo_pb2.SearchMetrics
SearchTagsRequest = tempo_pb2.SearchTagsRequest
SearchTagsResponse = tempo_pb2.SearchTagsResponse
SearchTagValuesRequest = tempo_pb2.SearchTagValuesRequest
SearchTagValuesResponse = tempo_pb2.SearchTagValuesResponse
PartialsResponse = tempo_pb2.PartialsResponse
ProcessJob = tempo_pb2.ProcessJob
ProcessResult = tempo_pb2.ProcessResult
PushSpansRequest = tempo_pb2.PushSpansRequest

ResourceSpans = trace_pb2.ResourceSpans
ScopeSpans = trace_pb2.ScopeSpans
Span = trace_pb2.Span
Status = trace_pb2.Status
Resource = trace_pb2.Resource
KeyValue = trace_pb2.KeyValue
AnyValue = trace_pb2.AnyValue

__all__ = [
    "Trace", "PushBytesRequest", "PushResponse", "TraceByIDRequest",
    "TraceByIDResponse", "TraceByIDMetrics", "SearchRequest",
    "SearchBlockRequest", "SearchBlocksRequest", "BlockSearchJob",
    "SearchResponse", "TraceSearchMetadata",
    "SearchMetrics", "SearchTagsRequest", "SearchTagsResponse",
    "SearchTagValuesRequest", "SearchTagValuesResponse", "PartialsResponse",
    "ProcessJob", "ProcessResult", "PushSpansRequest",
    "ResourceSpans", "ScopeSpans", "Span", "Status", "Resource",
    "KeyValue", "AnyValue", "trace_pb2", "tempo_pb2",
]
