"""One-way importer for reference-format v2 blocks (VERDICT r4 #5).

Reads a block written by the Go implementation and re-writes it as a
native block (vT1 data + columnar search), so an existing store can
migrate without replay. Format studied from the spec, not translated:

- data file: a sequence of pages, each
  ``[u32 totalLen][u16 hdrLen=0][compressed object stream]``
  (/root/reference/tempodb/encoding/v2/page.go:22-57); the decompressed
  stream is objects ``[u32 totalLen][u32 idLen][id][bytes]``
  (object.go:20-47).
- index file: fixed ``indexPageSize``-byte pages, each
  ``[u32 totalLen = page size][u16 hdrLen=8][u64 xxhash64][records +
  zero padding]`` — the checksum covers the ENTIRE post-header area
  including padding, and records are located positionally:
  ``recordsPerPage = (pageSize - 14) // 28``, bounded by the meta's
  ``totalRecords`` (record.go:13-84, index_writer.go:24-77,
  index_reader.go:40-140, page.go:148-165). A record is
  ``[16B max-id][u64 page offset][u32 page length]``.
- meta.json: camelCase fields (backend/block_meta.go json tags);
  ``dataEncoding`` "v2" objects are ``[u32 start][u32 end][Trace proto]``
  — byte-compatible with our own v2 segment framing (the reference's
  pkg/model/v2/segment_decoder.go:14-18 and our model/codec.py agree) —
  while "v1"/"" objects are bare Trace protos.
- the FlatBuffer search file (pkg/tempofb/tempo.fbs) is NOT parsed:
  it is derived data in the reference too, and regenerating search
  entries from the imported trace protos (extract_search_data) yields
  identical results through our engine.

Compression caveat: page payloads decompress per ``meta.encoding``.
zstd / gzip / zlib / none are bit-standard formats and import directly;
the reference's "snappy"/"s2" (golang framing) and "lz4-*" (pierrec
frame) streams are rejected up-front — re-encode such blocks to zstd
with the reference's own tooling first (documented in PARITY.md).
"""

from __future__ import annotations

import json
import struct
from dataclasses import dataclass

from tempo_tpu import tempopb
from tempo_tpu.encoding.v2.compression import decompress
from tempo_tpu.model.matches import trace_range_ns

_U32 = struct.Struct("<I")
_U16 = struct.Struct("<H")
_U64 = struct.Struct("<Q")
_RECORD = struct.Struct("<16sQI")  # max id, page offset, page length
_RECORD_LEN = 28
_INDEX_HDR_LEN = 8  # u64 xxhash64 of the page's record bytes


class ImportError_(ValueError):
    """Malformed reference block (framing, checksum, or proto)."""


@dataclass
class RefBlockMeta:
    block_id: str
    encoding: str
    data_encoding: str
    index_page_size: int
    total_records: int
    total_objects: int


_IMPORTABLE_ENCODINGS = {"none", "gzip", "zlib", "zstd"}


def parse_ref_meta(raw: bytes) -> RefBlockMeta:
    try:
        doc = json.loads(raw)
    except ValueError as e:
        raise ImportError_(f"bad meta.json: {e}") from None
    enc = str(doc.get("encoding", "none"))
    if enc not in _IMPORTABLE_ENCODINGS:
        # the reference's snappy/s2 and lz4-* page streams use golang
        # framing variants (pierrec/lz4 frames, golang snappy framing)
        # this importer does not speak — fail up-front with the remedy,
        # never mid-block with a codec error (code-review r5)
        raise ImportError_(
            f"block encoding {enc!r} is not importable — re-encode the "
            f"block to zstd with the reference's tooling first "
            f"(supported: {sorted(_IMPORTABLE_ENCODINGS)})")
    return RefBlockMeta(
        block_id=str(doc.get("blockID", "")),
        encoding=str(doc.get("encoding", "none")),
        data_encoding=str(doc.get("dataEncoding", "")),
        index_page_size=int(doc.get("indexPageSize", 0)),
        total_records=int(doc.get("totalRecords", 0)),
        total_objects=int(doc.get("totalObjects", 0)),
    )


def parse_index(raw: bytes, page_size: int, total_records: int) -> list:
    """[(max_id, start, length)] from the fixed-size index pages, each
    checksum-verified (xxhash64 over the WHOLE post-header area, padding
    included — index_writer.go:66-68). Records are positional:
    (pageSize-14)//28 slots per page, bounded by meta.totalRecords; with
    a zero/absent totalRecords (hand-built block), records parse until
    the first all-zero slot."""
    import xxhash

    if page_size < 14 + _RECORD_LEN:
        raise ImportError_(f"bad indexPageSize {page_size}")
    if len(raw) % page_size:
        raise ImportError_(
            f"index size {len(raw)} not a multiple of page size {page_size}")
    rpp = (page_size - 14) // _RECORD_LEN
    records = []
    for off in range(0, len(raw), page_size):
        page = raw[off:off + page_size]
        (total_len,) = _U32.unpack_from(page, 0)
        (hdr_len,) = _U16.unpack_from(page, 4)
        if total_len != page_size or hdr_len != _INDEX_HDR_LEN:
            raise ImportError_(
                f"index page framing ({total_len}/{page_size}, hdr {hdr_len})")
        (checksum,) = _U64.unpack_from(page, 6)
        data = page[14:]
        if xxhash.xxh64_intdigest(bytes(data)) != checksum:
            raise ImportError_("index page checksum mismatch")
        want = (min(rpp, total_records - len(records)) if total_records
                else rpp)
        for roff in range(0, want * _RECORD_LEN, _RECORD_LEN):
            rid, start, length = _RECORD.unpack_from(data, roff)
            if not total_records and length == 0 and rid == b"\x00" * 16:
                break  # zero padding past the final record
            records.append((rid, start, length))
    if total_records and len(records) != total_records:
        raise ImportError_(
            f"index has {len(records)} records, meta says {total_records}")
    return records


def iter_page_objects(page_bytes: bytes, encoding: str):
    """Objects of ONE data page: [u32 totalLen][u16 hdrLen=0][payload];
    payload decompresses to [u32 totalLen][u32 idLen][id][obj]*."""
    if len(page_bytes) < 6:
        raise ImportError_("data page too small")
    (total_len,) = _U32.unpack_from(page_bytes, 0)
    (hdr_len,) = _U16.unpack_from(page_bytes, 4)
    if total_len != len(page_bytes) or hdr_len != 0:
        raise ImportError_(
            f"data page framing mismatch ({total_len}/{len(page_bytes)}, "
            f"hdr {hdr_len})")
    try:
        payload = decompress(bytes(page_bytes[6:]), encoding)
    except Exception as e:  # noqa: BLE001 — codec-specific errors
        raise ImportError_(f"page decompress ({encoding}): {e}") from None
    off = 0
    while off < len(payload):
        if off + 8 > len(payload):
            raise ImportError_("torn object header")
        (obj_total,) = _U32.unpack_from(payload, off)
        (id_len,) = _U32.unpack_from(payload, off + 4)
        if obj_total < 8 + id_len or off + obj_total > len(payload):
            raise ImportError_("object framing out of bounds")
        oid = payload[off + 8:off + 8 + id_len]
        obj = payload[off + 8 + id_len:off + obj_total]
        yield bytes(oid), bytes(obj)
        off += obj_total


def iter_reference_block(read, meta: RefBlockMeta | None = None):
    """Yield (trace_id, our-v2 segment bytes, start_s, end_s,
    tempopb.Trace) for every object in a reference block. `read(name)`
    returns the raw bytes of "meta.json" / "data" / "index"; pass an
    already-parsed `meta` to skip a second fetch (remote readers)."""
    if meta is None:
        meta = parse_ref_meta(read("meta.json"))
    index = parse_index(read("index"), meta.index_page_size,
                        meta.total_records)
    data = read("data")
    for _max_id, start, length in index:
        if start + length > len(data):
            raise ImportError_("index record past end of data file")
        for oid, obj in iter_page_objects(
                memoryview(data)[start:start + length], meta.encoding):
            if meta.data_encoding == "v2":
                if len(obj) < 8:
                    raise ImportError_("v2 object too short")
                start_s, end_s = struct.unpack_from("<II", obj)
                body = obj[8:]
                seg = obj  # byte-compatible with our segment framing
            else:  # "v1"/"": bare Trace proto
                body = obj
                seg = None
            trace = tempopb.Trace()
            try:
                trace.ParseFromString(body)
            except Exception as e:  # noqa: BLE001 — DecodeError subclass
                raise ImportError_(f"object proto: {e}") from None
            if seg is None:
                from tempo_tpu.model.codec import segment_codec_for

                s_ns, e_ns = trace_range_ns(trace)
                start_s, end_s = s_ns // 10**9, e_ns // 10**9
                seg = segment_codec_for("v2").prepare_for_write(
                    trace, start_s, end_s)
            yield oid, seg, start_s, end_s, trace


def import_reference_block(read, db, tenant: str):
    """Import one reference block into `db` (TempoDB) for `tenant`:
    objects re-frame into a native block, search data regenerates from
    the trace protos. Returns the new BlockMeta. Raises ImportError_
    when the imported object count disagrees with meta.totalObjects —
    a silently-partial migration must never look like success."""
    from tempo_tpu.search.data import extract_search_data
    from tempo_tpu.search.structural import STRUCTURAL
    from tempo_tpu.utils.ids import pad_trace_id

    meta = parse_ref_meta(read("meta.json"))
    objects = []
    entries = []
    # structural gate on: migrated blocks carry the span segment too,
    # so structural queries see imported traces exactly like ingested
    # ones (gate off keeps the legacy extraction byte-identical)
    want_spans = STRUCTURAL.enabled
    for oid, seg, start_s, end_s, trace in iter_reference_block(read, meta):
        tid = pad_trace_id(oid)
        objects.append((tid, seg, start_s, end_s))
        entries.append(extract_search_data(tid, trace, spans=want_spans))
    if meta.total_objects and len(objects) != meta.total_objects:
        raise ImportError_(
            f"imported {len(objects)} objects, meta.totalObjects says "
            f"{meta.total_objects} — refusing a partial migration")
    order = sorted(range(len(objects)), key=lambda i: objects[i][0])
    return db.write_block_direct(
        tenant, [objects[i] for i in order],
        search_entries=[entries[i] for i in order])


def dir_reader(path: str):
    """read(name) over a local directory holding a reference block."""
    import os

    def read(name: str) -> bytes:
        with open(os.path.join(path, name), "rb") as f:
            return f.read()

    return read
