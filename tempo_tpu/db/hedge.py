"""Request hedging for tail-latency control.

Role-equivalent to the reference's cristalhq/hedgedhttp usage (querier
external endpoints querier.go:103-109, backend instrumentation
hedged_requests.go): launch the call; if it hasn't answered within
`hedge_after_s`, launch up to `max_hedges` duplicates and take the first
result. Wasted duplicates are abandoned (their threads finish and are
discarded).
"""

from __future__ import annotations

import queue as _queue
import threading


def hedged_call(fn, *args, hedge_after_s: float = 0.5, max_hedges: int = 2):
    """Run fn(*args), hedging duplicates after a delay; first completion
    (result or raise) wins. max_hedges counts EXTRA attempts.

    Each attempt gets its own daemon thread (no shared pool: a pool's
    workers block on slow endpoints and then hedge submissions queue
    behind the very calls they were meant to race — starvation exactly
    when hedging matters). Losing attempts run to completion and are
    discarded."""
    results: _queue.Queue = _queue.Queue()

    def attempt():
        try:
            results.put((True, fn(*args)))
        except Exception as e:  # noqa: BLE001 — relayed to the caller
            results.put((False, e))

    total = 1 + max_hedges
    launched = 1
    failures = 0
    threading.Thread(target=attempt, daemon=True).start()
    while True:
        try:
            ok, val = results.get(
                timeout=hedge_after_s if launched < total else None
            )
        except _queue.Empty:
            threading.Thread(target=attempt, daemon=True).start()
            launched += 1
            continue
        if ok:
            return val
        failures += 1
        if failures >= launched:
            # every launched attempt failed — hedge once more if allowed,
            # otherwise surface the error
            if launched < total:
                threading.Thread(target=attempt, daemon=True).start()
                launched += 1
                continue
            raise val
        # other attempts still in flight: keep waiting for one to succeed


class HedgedBackend:
    """RawBackend wrapper hedging read/read_range (object-store tail
    latency is the reason hedging exists)."""

    def __init__(self, inner, hedge_after_s: float = 0.5, max_hedges: int = 2):
        self.inner = inner
        self.hedge_after_s = hedge_after_s
        self.max_hedges = max_hedges

    def read(self, tenant, block_id, name):
        return hedged_call(self.inner.read, tenant, block_id, name,
                           hedge_after_s=self.hedge_after_s,
                           max_hedges=self.max_hedges)

    def read_range(self, tenant, block_id, name, offset, length):
        return hedged_call(self.inner.read_range, tenant, block_id, name,
                           offset, length, hedge_after_s=self.hedge_after_s,
                           max_hedges=self.max_hedges)

    def __getattr__(self, name):
        return getattr(self.inner, name)
