"""Retention: two-phase block deletion.

Role-equivalent to the reference's tempodb/retention.go:14-88: (1) mark
live blocks past the retention window compacted (soft delete — queriers
stop listing them), (2) hard-delete compacted blocks past the compacted
retention window.
"""

from __future__ import annotations

from tempo_tpu.backend.raw import RawBackend
from .blocklist import Blocklist
from .pool import run_jobs


def apply_retention(backend: RawBackend, blocklist: Blocklist, tenant: str,
                    now_s: int, retention_s: int,
                    compacted_retention_s: int = 3600,
                    concurrency: int = 10) -> tuple[int, int]:
    """Returns (marked, deleted)."""
    marked = deleted = 0

    if retention_s:
        to_mark = [m for m in blocklist.metas(tenant)
                   if m.end_time and now_s - m.end_time > retention_s]

        def mark(m):
            backend.mark_compacted(m)
            return m

        done, _ = run_jobs(to_mark, mark, workers=concurrency)
        marked = len(done)
        if done:
            from tempo_tpu.backend.types import CompactedBlockMeta

            blocklist.update(tenant, remove=done,
                             add_compacted=[CompactedBlockMeta.from_meta(m)
                                            for m in done])

    to_delete = [c for c in blocklist.compacted(tenant)
                 if now_s - c.compacted_time > compacted_retention_s]

    def delete(c):
        backend.clear_block(tenant, c.meta.block_id)
        return c

    done, _ = run_jobs(to_delete, delete, workers=concurrency)
    deleted = len(done)
    return marked, deleted
