"""Bounded worker pool for per-block fan-out.

Role-equivalent to the reference's tempodb/pool (pool.go:58-196): run one
job per block with bounded concurrency; for point lookups, stop early on
the first hit (trace-by-ID needs only one block to answer).
"""

from __future__ import annotations

import concurrent.futures
import contextvars
import threading


def run_jobs(jobs, fn, workers: int = 50, stop_on_first: bool = False,
             collect_errors: bool = True, stop_event: threading.Event | None = None):
    """Run fn(job) for each job. Returns (results, errors) where results
    excludes None. With stop_on_first, pending jobs are skipped after the
    first non-None result. A caller-owned stop_event cancels remaining
    jobs when set (the reference's Results quit channel, results.go:38-78
    — search stops dispatching once the limit is met).

    Jobs run under a copy of the caller's contextvars context, so the
    active tracing span parents the per-block spans across the pool."""
    results = []
    errors = []
    if not jobs:
        return results, errors
    stop = stop_event if stop_event is not None else threading.Event()
    lock = threading.Lock()
    caller_ctx = contextvars.copy_context()

    def _run_in_ctx(job):
        caller_ctx.copy().run(_run, job)

    def _run(job):
        if stop.is_set():
            return
        try:
            r = fn(job)
        except Exception as e:  # noqa: BLE001 — per-block failures are partial results
            if collect_errors:
                with lock:
                    errors.append(e)
            return
        if r is not None:
            with lock:
                results.append(r)
            if stop_on_first:
                stop.set()

    workers = max(1, min(workers, len(jobs)))
    with concurrent.futures.ThreadPoolExecutor(max_workers=workers) as ex:
        list(ex.map(_run_in_ctx, jobs))
    return results, errors
