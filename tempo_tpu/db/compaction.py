"""Compaction: block selection + streaming k-way merge.

Role-equivalent to the reference's compaction engine:
  - timeWindowBlockSelector (tempodb/compaction_block_selector.go:48-156):
    group blocks by (compaction level, time window) inside the active
    window, pick 2..max contiguous same-level blocks under object/byte
    caps;
  - v2.Compactor (tempodb/encoding/v2/compactor.go:30-137 +
    iterator_multiblock.go:38): open all input iterators, k-way merge by
    object id, Combine duplicate trace objects, stream into a new block at
    compaction_level+1.

Improvement over the reference: the merged block's columnar search data is
rebuilt from the inputs (the reference drops search data of compacted-away
blocks — SURVEY.md §3.5 note), so search coverage survives compaction.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass

from tempo_tpu.backend.raw import RawBackend, BackendError
from tempo_tpu.backend.types import BlockMeta
from tempo_tpu.encoding.v2 import BackendBlock, StreamingBlock
from tempo_tpu.model.codec import codec_for

DEFAULT_WINDOW_S = 3600
DEFAULT_MAX_INPUTS = 8
DEFAULT_MIN_INPUTS = 2
DEFAULT_MAX_BLOCK_BYTES = 100 << 30


@dataclass
class TimeWindowBlockSelector:
    window_s: int = DEFAULT_WINDOW_S
    min_inputs: int = DEFAULT_MIN_INPUTS
    max_inputs: int = DEFAULT_MAX_INPUTS
    max_block_bytes: int = DEFAULT_MAX_BLOCK_BYTES
    active_window_s: int = 24 * 3600

    def blocks_to_compact(self, metas: list[BlockMeta], now_s: int) -> list[BlockMeta]:
        """Pick one compaction job: the first group of >= min_inputs
        same-(level, window) blocks, most-populated window first. Inside
        the active window blocks group by (level, window); outside, by
        window only (levels mix — cf. reference selector)."""
        groups: dict[tuple, list[BlockMeta]] = {}
        for m in metas:
            window = m.end_time // self.window_s if self.window_s else 0
            active = (now_s - m.end_time) < self.active_window_s
            key = (m.compaction_level if active else -1, window)
            groups.setdefault(key, []).append(m)

        def order(item):
            (_level, window), blocks = item
            return (-len(blocks), -window)

        for (_key, blocks) in sorted(groups.items(), key=order):
            if len(blocks) < self.min_inputs:
                continue
            blocks.sort(key=lambda m: (m.min_id, m.block_id))
            picked: list[BlockMeta] = []
            total = 0
            for m in blocks:
                if len(picked) >= self.max_inputs:
                    break
                if total + m.size > self.max_block_bytes and picked:
                    break
                picked.append(m)
                total += m.size
            if len(picked) >= self.min_inputs:
                return picked
        return []


def compact_blocks(backend: RawBackend, tenant: str, inputs: list[BlockMeta],
                   page_size: int = 1 << 20,
                   compact_search: bool = True,
                   search_geometry=None,
                   search_encoding: str | None = None) -> BlockMeta:
    """Merge input blocks into one new block at level+1, combining
    duplicate trace objects; mark inputs compacted."""
    codec = codec_for(inputs[0].data_encoding)
    out_meta = BlockMeta(
        tenant_id=tenant,
        encoding=inputs[0].encoding,
        data_encoding=inputs[0].data_encoding,
        compaction_level=max(m.compaction_level for m in inputs) + 1,
    )
    out = StreamingBlock(out_meta, page_size=page_size)

    iters = [BackendBlock(backend, m).iter_objects() for m in inputs]
    merged = heapq.merge(*iters, key=lambda kv: kv[0])

    pending_id: bytes | None = None
    pending: list[bytes] = []

    def flush():
        if pending_id is None:
            return
        obj = pending[0] if len(pending) == 1 else codec.combine(*pending)
        r = codec.fast_range(obj) or (0, 0)
        out.add_object(pending_id, obj, r[0], r[1])

    for oid, data in merged:
        if oid != pending_id:
            flush()
            pending_id, pending = oid, [data]
        else:
            pending.append(data)  # same trace in 2+ blocks → combine
    flush()

    new_meta = out.complete(backend)

    if compact_search:
        _compact_search_blocks(backend, tenant, inputs, new_meta,
                               search_geometry, search_encoding)

    for m in inputs:
        backend.mark_compacted(m)
    return new_meta


def _compact_search_blocks(backend: RawBackend, tenant: str,
                           inputs: list[BlockMeta], new_meta: BlockMeta,
                           search_geometry=None,
                           search_encoding: str | None = None) -> None:
    from tempo_tpu.search.backend_search_block import write_search_block
    from tempo_tpu.search.columnar import ColumnarPages, PageGeometry
    from tempo_tpu.search.data import SearchData
    from tempo_tpu.backend.types import NAME_SEARCH
    from tempo_tpu.encoding.v2.compression import decompress
    import json

    merged: dict[bytes, SearchData] = {}
    for m in inputs:
        try:
            hdr = json.loads(backend.read(tenant, m.block_id, "search-header.json"))
            raw = decompress(backend.read(tenant, m.block_id, NAME_SEARCH),
                             hdr.get("encoding", "zstd"))
            for sd in ColumnarPages.from_bytes(raw).to_entries():
                cur = merged.get(sd.trace_id)
                if cur is None:
                    merged[sd.trace_id] = sd
                else:
                    cur.merge(sd)
        except (BackendError, ValueError):
            continue  # inputs without search data contribute nothing
    if merged:
        entries = [merged[t] for t in sorted(merged)]
        write_search_block(backend, new_meta, entries,
                           geometry=search_geometry or PageGeometry(),
                           encoding=search_encoding or "zstd")
