"""Compaction: block selection + streaming k-way merge.

Role-equivalent to the reference's compaction engine:
  - timeWindowBlockSelector (tempodb/compaction_block_selector.go:48-156):
    group blocks by (compaction level, time window) inside the active
    window, pick 2..max contiguous same-level blocks under object/byte
    caps;
  - v2.Compactor (tempodb/encoding/v2/compactor.go:30-137 +
    iterator_multiblock.go:38): open all input iterators, k-way merge by
    object id, Combine duplicate trace objects, stream into a new block at
    compaction_level+1.

Improvement over the reference: the merged block's columnar search data is
rebuilt from the inputs (the reference drops search data of compacted-away
blocks — SURVEY.md §3.5 note), so search coverage survives compaction.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass

from tempo_tpu.backend.raw import RawBackend, BackendError
from tempo_tpu.backend.types import BlockMeta
from tempo_tpu.encoding.v2 import BackendBlock, StreamingBlock
from tempo_tpu.model.codec import codec_for

DEFAULT_WINDOW_S = 3600
DEFAULT_MAX_INPUTS = 8
DEFAULT_MIN_INPUTS = 2
DEFAULT_MAX_BLOCK_BYTES = 100 << 30


@dataclass
class TimeWindowBlockSelector:
    window_s: int = DEFAULT_WINDOW_S
    min_inputs: int = DEFAULT_MIN_INPUTS
    max_inputs: int = DEFAULT_MAX_INPUTS
    max_block_bytes: int = DEFAULT_MAX_BLOCK_BYTES
    active_window_s: int = 24 * 3600

    def blocks_to_compact(self, metas: list[BlockMeta], now_s: int,
                          groups: dict | None = None) -> list[BlockMeta]:
        """Pick one compaction job: the first group of >= min_inputs
        same-(level, window) blocks, most-populated window first. Inside
        the active window blocks group by (level, window); outside, by
        window only (levels mix — cf. reference selector). `groups`: a
        precomputed _groups(metas, now_s) result, so a caller that also
        reads outstanding() pays the O(blocks) grouping once."""
        if groups is None:
            groups = self._groups(metas, now_s)

        def order(item):
            (_level, window), blocks = item
            return (-len(blocks), -window)

        for (_key, blocks) in sorted(groups.items(), key=order):
            if len(blocks) < self.min_inputs:
                continue
            blocks.sort(key=lambda m: (m.min_id, m.block_id))
            picked: list[BlockMeta] = []
            total = 0
            for m in blocks:
                if len(picked) >= self.max_inputs:
                    break
                if total + m.size > self.max_block_bytes and picked:
                    break
                picked.append(m)
                total += m.size
            if len(picked) >= self.min_inputs:
                return picked
        return []

    def _groups(self, metas: list[BlockMeta], now_s: int) -> dict:
        groups: dict[tuple, list[BlockMeta]] = {}
        for m in metas:
            window = m.end_time // self.window_s if self.window_s else 0
            active = (now_s - m.end_time) < self.active_window_s
            key = (m.compaction_level if active else -1, window)
            groups.setdefault(key, []).append(m)
        return groups

    def outstanding(self, metas: list[BlockMeta], now_s: int,
                    groups: dict | None = None) -> tuple[int, int]:
        """The compactor's input backlog: (blocks, bytes) across ALL
        groups that have enough members to compact — what
        blocks_to_compact would eventually chew through if no new data
        arrived. One job per tick against a growing value means the
        compaction loop is behind the write rate."""
        n_blocks = n_bytes = 0
        if groups is None:
            groups = self._groups(metas, now_s)
        for blocks in groups.values():
            if len(blocks) < self.min_inputs:
                continue
            n_blocks += len(blocks)
            n_bytes += sum(m.size for m in blocks)
        return n_blocks, n_bytes


def compact_blocks(backend: RawBackend, tenant: str, inputs: list[BlockMeta],
                   page_size: int = 1 << 20,
                   compact_search: bool = True,
                   search_geometry=None,
                   search_encoding: str | None = None,
                   flush_size: int | None = None) -> BlockMeta:
    """Merge input blocks into one new block at level+1, combining
    duplicate trace objects; mark inputs compacted. The output streams to
    the backend every `flush_size` bytes (30 MB default, reference
    compactor.go:109-115) so compaction memory is bounded by the flush
    size + one input page per block, not the output block size."""
    from tempo_tpu.encoding.v2.streaming_block import DEFAULT_FLUSH_SIZE

    codec = codec_for(inputs[0].data_encoding)
    out_meta = BlockMeta(
        tenant_id=tenant,
        encoding=inputs[0].encoding,
        data_encoding=inputs[0].data_encoding,
        compaction_level=max(m.compaction_level for m in inputs) + 1,
    )
    out = StreamingBlock(out_meta, page_size=page_size, backend=backend,
                         flush_size=flush_size or DEFAULT_FLUSH_SIZE)

    iters = [BackendBlock(backend, m).iter_objects() for m in inputs]
    merged = heapq.merge(*iters, key=lambda kv: kv[0])

    pending_id: bytes | None = None
    pending: list[bytes] = []

    def flush():
        if pending_id is None:
            return
        obj = pending[0] if len(pending) == 1 else codec.combine(*pending)
        r = codec.fast_range(obj) or (0, 0)
        out.add_object(pending_id, obj, r[0], r[1])

    try:
        for oid, data in merged:
            if oid != pending_id:
                flush()
                pending_id, pending = oid, [data]
            else:
                pending.append(data)  # same trace in 2+ blocks → combine
        flush()

        new_meta = out.complete()
    except BaseException:
        out.abort()  # release the in-progress append (next cycle retries)
        raise

    if compact_search:
        _compact_search_blocks(backend, tenant, inputs, new_meta,
                               search_geometry, search_encoding)

    for m in inputs:
        backend.mark_compacted(m)
    return new_meta


def _spill_block_entries_sorted(backend: RawBackend, tenant: str,
                                m: BlockMeta):
    """One input block's search entries, sorted by trace id and SPILLED to
    a temp file (u32-framed wire codec), then streamed back one entry at a
    time. Only one input container is ever decoded in memory; during the
    k-way merge each stream costs a single entry — the heap heads — so
    merge memory is O(inputs), not O(total entries)."""
    import json
    import struct
    import tempfile

    from tempo_tpu.backend.types import NAME_SEARCH
    from tempo_tpu.encoding.v2.compression import decompress
    from tempo_tpu.search.columnar import ColumnarPages
    from tempo_tpu.search.data import decode_search_data, encode_search_data

    hdr = json.loads(backend.read(tenant, m.block_id, "search-header.json"))
    raw = decompress(backend.read(tenant, m.block_id, NAME_SEARCH),
                     hdr.get("encoding", "zstd"))
    entries = ColumnarPages.from_bytes(raw).to_entries()
    entries.sort(key=lambda sd: sd.trace_id)

    u32 = struct.Struct("<I")
    spill = tempfile.TemporaryFile()
    for sd in entries:
        payload = sd.trace_id + encode_search_data(sd)
        spill.write(u32.pack(len(payload)) + payload)
    del entries, raw
    spill.seek(0)

    def stream():
        with spill:
            while True:
                frame = spill.read(4)
                if len(frame) < 4:
                    return
                (n,) = u32.unpack(frame)
                payload = spill.read(n)
                yield decode_search_data(payload[16:], payload[:16])

    return stream()


def _compact_search_blocks(backend: RawBackend, tenant: str,
                           inputs: list[BlockMeta], new_meta: BlockMeta,
                           search_geometry=None,
                           search_encoding: str | None = None) -> None:
    """K-way merge over per-block sorted entry streams spilled to disk:
    duplicates combine as they meet at the heap head. Peak memory is one
    input container during its spill + the heap heads + the merged OUTPUT
    entries (the one-block floor the columnar array build requires; each
    entry is capped at 5 KB by extraction, reference limits.go) — never
    all inputs at once as in round 1."""
    from tempo_tpu.search.backend_search_block import write_search_block
    from tempo_tpu.search.columnar import PageGeometry

    streams = []
    for m in inputs:
        try:
            streams.append(_spill_block_entries_sorted(backend, tenant, m))
        except (BackendError, ValueError):
            continue  # inputs without search data contribute nothing

    entries = []
    pending = None
    for sd in heapq.merge(*streams, key=lambda sd: sd.trace_id):
        if pending is not None and pending.trace_id == sd.trace_id:
            pending.merge(sd)  # same trace across blocks
            continue
        if pending is not None:
            entries.append(pending)
        pending = sd
    if pending is not None:
        entries.append(pending)
    if entries:
        write_search_block(backend, new_meta, entries,
                           geometry=search_geometry or PageGeometry(),
                           encoding=search_encoding or "zstd")
