from .tempodb import TempoDB, TempoDBConfig
from .blocklist import Blocklist
from .poller import Poller
from .pool import run_jobs
from .compaction import TimeWindowBlockSelector, compact_blocks
from .retention import apply_retention

__all__ = [
    "TempoDB", "TempoDBConfig", "Blocklist", "Poller", "run_jobs",
    "TimeWindowBlockSelector", "compact_blocks", "apply_retention",
]
