"""Per-tenant in-memory block lists with staged updates.

Role-equivalent to the reference's tempodb/blocklist/list.go: pollers
replace the lists wholesale; between polls, compaction stages its own
add/remove updates so the view stays coherent until the next poll
confirms them.
"""

from __future__ import annotations

import threading

from tempo_tpu.backend.types import BlockMeta, CompactedBlockMeta


class Blocklist:
    def __init__(self):
        self._lock = threading.Lock()
        self._metas: dict[str, list[BlockMeta]] = {}
        self._compacted: dict[str, list[CompactedBlockMeta]] = {}
        # bumped on every membership change: readers key derived caches
        # (job lists, group plans) on (tenant, epoch) so a 10K-block
        # tenant doesn't rebuild O(blocks) plumbing per query
        self._epoch = 0

    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def tenants(self) -> list[str]:
        with self._lock:
            return sorted(self._metas)

    def metas(self, tenant: str) -> list[BlockMeta]:
        with self._lock:
            return list(self._metas.get(tenant, []))

    def compacted(self, tenant: str) -> list[CompactedBlockMeta]:
        with self._lock:
            return list(self._compacted.get(tenant, []))

    def apply_poll_results(self, metas: dict, compacted: dict) -> None:
        with self._lock:
            new_m = {t: list(ms) for t, ms in metas.items()}
            new_c = {t: list(cs) for t, cs in compacted.items()}
            # bump the epoch ONLY on real change: every epoch-keyed memo
            # downstream (frontend job templates, batcher plans) dies on
            # a bump, so an unconditional bump made each steady-state
            # poll re-pay the O(blocks) planning the memos exist to
            # avoid. Metas are dataclasses; equality is field-wise.
            if new_m != self._metas or new_c != self._compacted:
                self._epoch += 1
            self._metas = new_m
            self._compacted = new_c

    def update(self, tenant: str, add=None, remove=None, add_compacted=None) -> None:
        """Staged update between polls (compaction results)."""
        with self._lock:
            ms = self._metas.setdefault(tenant, [])
            removed = {m.block_id for m in (remove or [])}
            ms[:] = [m for m in ms if m.block_id not in removed]
            ms.extend(add or [])
            if add_compacted:
                self._compacted.setdefault(tenant, []).extend(add_compacted)
            self._epoch += 1
