"""Blocklist poller + tenant index builder.

Role-equivalent to the reference's tempodb/blocklist/poller.go:105-265:
list tenants and blocks from the backend, read each block's meta (or
compacted meta) with bounded concurrency, and — when this instance is the
elected builder — write the gzipped tenant index so other instances can
read one object instead of N metas. Readers fall back to a full poll when
the index is stale or missing.
"""

from __future__ import annotations

import gzip
import re
import time
import zlib

from tempo_tpu.backend.raw import RawBackend, BackendError, DoesNotExist
from tempo_tpu.backend.types import (
    BlockMeta,
    CompactedBlockMeta,
    TenantIndex,
    NAME_TENANT_INDEX,
)
from .pool import run_jobs

# head of the builder-written index document: content digest (dedupes
# reader re-parses) then created_at (the builder heartbeat). Coupled to
# TenantIndex.to_bytes's layout — a round-trip test in test_db pins it,
# so a serializer change fails loudly instead of silently disabling the
# dedupe
INDEX_HEAD_RE = re.compile(
    rb'^\{"content_digest": "([0-9a-f]{64})", "created_at": (\d+)')


class Poller:
    def __init__(self, backend: RawBackend, build_index: bool = True,
                 stale_index_s: int = 0, concurrency: int = 50):
        self.backend = backend
        self.build_index = build_index
        self.stale_index_s = stale_index_s
        self.concurrency = concurrency
        # tenant → (raw index digest, parsed TenantIndex): a reader's
        # steady-state poll re-reads an UNCHANGED index object — hash
        # the bytes and reuse the parse instead of re-building 10K
        # BlockMeta objects every 30s
        self._index_cache: dict[str, tuple[bytes, TenantIndex]] = {}

    def poll(self) -> tuple[dict, dict]:
        """Returns ({tenant: [BlockMeta]}, {tenant: [CompactedBlockMeta]})."""
        metas: dict[str, list[BlockMeta]] = {}
        compacted: dict[str, list[CompactedBlockMeta]] = {}
        tenants = list(self.backend.list_tenants())
        # deleted tenants must not pin their parsed indexes forever
        for gone in set(self._index_cache) - set(tenants):
            del self._index_cache[gone]
        for tenant in tenants:
            m, c = self.poll_tenant(tenant)
            metas[tenant] = m
            compacted[tenant] = c
        return metas, compacted

    def poll_tenant(self, tenant: str):
        if not self.build_index:
            idx = self._read_index(tenant)
            if idx is not None:
                # shallow copies: consumers may sort/mutate their lists;
                # the cached parse must stay pristine (its digest would
                # still match, so corruption would never self-heal)
                return list(idx.metas), list(idx.compacted)
            # stale/missing index: fall through to a direct poll
        m, c = self._poll_tenant_blocks(tenant)
        if self.build_index:
            idx = TenantIndex(created_at=int(time.time()), metas=m, compacted=c)
            self.backend.write(tenant, None, NAME_TENANT_INDEX, idx.to_bytes())
        return m, c

    def _read_index(self, tenant: str) -> TenantIndex | None:
        try:
            raw = self.backend.read(tenant, None, NAME_TENANT_INDEX)
        except BackendError:
            return None
        try:
            text = gzip.decompress(raw)
        except (OSError, EOFError, zlib.error):
            return None  # torn/corrupt index: fall back to direct poll
        # extract content_digest + created_at from the document HEAD (the
        # builder writes them first) — created_at advances every builder
        # cycle as a heartbeat, so only the digest can dedupe re-parses
        m = INDEX_HEAD_RE.match(text[:128])
        created_at = None
        idx = None
        if m is not None:
            digest, created_at = m.group(1), int(m.group(2))
            hit = self._index_cache.get(tenant)
            if hit is not None and hit[0] == digest:
                idx = hit[1]
        if idx is None:
            try:
                idx = TenantIndex.from_json_bytes(text)
            except ValueError:
                return None
            if m is not None:
                self._index_cache[tenant] = (digest, idx)
            created_at = idx.created_at
        from tempo_tpu.observability.ingest_telemetry import TELEMETRY

        if TELEMETRY.enabled:
            # index staleness: a growing age means the elected builder
            # stopped writing — readers keep serving an old blocklist
            # long before stale_index_s forces the expensive direct poll
            TELEMETRY.record_index_age(tenant, time.time() - created_at)
        if self.stale_index_s and time.time() - created_at > self.stale_index_s:
            return None
        return idx

    def _poll_tenant_blocks(self, tenant: str):
        def read_one(block_id: str):
            try:
                return ("live", self.backend.read_block_meta(tenant, block_id))
            except DoesNotExist:
                pass
            try:
                return ("compacted", self.backend.read_compacted_meta(tenant, block_id))
            except DoesNotExist:
                return None  # torn block: objects without (any) meta — skip

        results, _ = run_jobs(self.backend.list_blocks(tenant), read_one,
                              workers=self.concurrency)
        metas = [m for kind, m in results if kind == "live"]
        compacted = [m for kind, m in results if kind == "compacted"]
        metas.sort(key=lambda m: (m.start_time, m.block_id))
        compacted.sort(key=lambda c: (c.meta.start_time, c.meta.block_id))
        return metas, compacted
