"""Blocklist poller + tenant index builder.

Role-equivalent to the reference's tempodb/blocklist/poller.go:105-265:
list tenants and blocks from the backend, read each block's meta (or
compacted meta) with bounded concurrency, and — when this instance is the
elected builder — write the gzipped tenant index so other instances can
read one object instead of N metas. Readers fall back to a full poll when
the index is stale or missing.
"""

from __future__ import annotations

import time

from tempo_tpu.backend.raw import RawBackend, BackendError, DoesNotExist
from tempo_tpu.backend.types import (
    BlockMeta,
    CompactedBlockMeta,
    TenantIndex,
    NAME_TENANT_INDEX,
)
from .pool import run_jobs


class Poller:
    def __init__(self, backend: RawBackend, build_index: bool = True,
                 stale_index_s: int = 0, concurrency: int = 50):
        self.backend = backend
        self.build_index = build_index
        self.stale_index_s = stale_index_s
        self.concurrency = concurrency

    def poll(self) -> tuple[dict, dict]:
        """Returns ({tenant: [BlockMeta]}, {tenant: [CompactedBlockMeta]})."""
        metas: dict[str, list[BlockMeta]] = {}
        compacted: dict[str, list[CompactedBlockMeta]] = {}
        for tenant in self.backend.list_tenants():
            m, c = self.poll_tenant(tenant)
            metas[tenant] = m
            compacted[tenant] = c
        return metas, compacted

    def poll_tenant(self, tenant: str):
        if not self.build_index:
            idx = self._read_index(tenant)
            if idx is not None:
                return idx.metas, idx.compacted
            # stale/missing index: fall through to a direct poll
        m, c = self._poll_tenant_blocks(tenant)
        if self.build_index:
            idx = TenantIndex(created_at=int(time.time()), metas=m, compacted=c)
            self.backend.write(tenant, None, NAME_TENANT_INDEX, idx.to_bytes())
        return m, c

    def _read_index(self, tenant: str) -> TenantIndex | None:
        try:
            idx = TenantIndex.from_bytes(
                self.backend.read(tenant, None, NAME_TENANT_INDEX)
            )
        except (BackendError, ValueError):
            return None
        if self.stale_index_s and time.time() - idx.created_at > self.stale_index_s:
            return None
        return idx

    def _poll_tenant_blocks(self, tenant: str):
        def read_one(block_id: str):
            try:
                return ("live", self.backend.read_block_meta(tenant, block_id))
            except DoesNotExist:
                pass
            try:
                return ("compacted", self.backend.read_compacted_meta(tenant, block_id))
            except DoesNotExist:
                return None  # torn block: objects without (any) meta — skip

        results, _ = run_jobs(self.backend.list_blocks(tenant), read_one,
                              workers=self.concurrency)
        metas = [m for kind, m in results if kind == "live"]
        compacted = [m for kind, m in results if kind == "compacted"]
        metas.sort(key=lambda m: (m.start_time, m.block_id))
        compacted.sort(key=lambda c: (c.meta.start_time, c.meta.block_id))
        return metas, compacted
