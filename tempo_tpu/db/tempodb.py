"""TempoDB facade: the storage engine's public Reader/Writer/Compactor.

Role-equivalent to the reference's tempodb/tempodb.go:70-520: block
completion from WAL blocks, trace-by-ID fan-out over the blocklist with a
bounded pool, search across backend search blocks (device engine, staged
cache), poller/compaction/retention enablement, and block inclusion
predicates (id-range shard + time window).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from tempo_tpu import tempopb
from tempo_tpu.backend.raw import RawBackend
from tempo_tpu.backend.types import BlockMeta
from tempo_tpu.encoding.v2 import BackendBlock, StreamingBlock
from tempo_tpu.model.codec import codec_for
from tempo_tpu.search import SearchResults, write_search_block
from tempo_tpu.search.pipeline import matches_block_header
from tempo_tpu.search.backend_search_block import BackendSearchBlock
from tempo_tpu.search.columnar import PageGeometry
from tempo_tpu.search.engine import ScanEngine
from tempo_tpu.observability import metrics as obs
from tempo_tpu.observability import tracing
from tempo_tpu.utils.ids import pad_trace_id
from tempo_tpu.wal import WAL, AppendBlock

from .blocklist import Blocklist
from .compaction import TimeWindowBlockSelector, compact_blocks
from .poller import Poller
from .pool import run_jobs
from .retention import apply_retention


@dataclass
class TempoDBConfig:
    block_encoding: str = "zstd"          # reference: block zstd
    search_encoding: str = "zstd"         # reference: search snappy
    block_page_size: int = 1 << 20
    pool_workers: int = 50                # reference: pool 50 workers
    blocklist_poll_s: int = 30
    compaction_window_s: int = 3600
    compaction_max_inputs: int = 8
    retention_s: int = 14 * 24 * 3600
    compacted_retention_s: int = 3600
    search_geometry: PageGeometry = field(default_factory=PageGeometry)
    tenant_index_builder: bool = True
    search_cache_blocks: int = 64         # staged (HBM) blocks kept hot
    search_prefetch_blocks: int = 2       # blocks staged ahead of the scan
                                          # (0 = stage synchronously)


class TempoDB:
    """Reader + Writer + Compactor over one backend."""

    def __init__(self, backend: RawBackend, wal_dir: str,
                 cfg: TempoDBConfig | None = None):
        self.backend = backend
        self.cfg = cfg or TempoDBConfig()
        self.wal = WAL(wal_dir)
        self.blocklist = Blocklist()
        self.poller = Poller(backend, build_index=self.cfg.tenant_index_builder)
        self.selector = TimeWindowBlockSelector(
            window_s=self.cfg.compaction_window_s,
            max_inputs=self.cfg.compaction_max_inputs,
        )
        self.engine = ScanEngine()
        self._search_blocks: dict[str, BackendSearchBlock] = {}
        self._search_lock = threading.Lock()

    # ------------------------------------------------------------------
    # Writer

    def complete_block(self, block: AppendBlock, search_entries=None) -> BlockMeta:
        """WAL block → immutable backend block (+ columnar search block).
        Reference flow: instance.CompleteBlock → tempodb.CompleteBlock...
        (SURVEY.md §3.2)."""
        codec = codec_for(block.meta.data_encoding)
        meta = BlockMeta(
            tenant_id=block.meta.tenant_id,
            block_id=block.meta.block_id,
            encoding=self.cfg.block_encoding,
            data_encoding=block.meta.data_encoding,
        )
        sb = StreamingBlock(meta, page_size=self.cfg.block_page_size)
        for oid, obj in block.iterator():
            r = codec.fast_range(obj) or (0, 0)
            sb.add_object(oid, obj, r[0], r[1])
        out = sb.complete(self.backend)
        if search_entries:
            write_search_block(self.backend, out, search_entries,
                               geometry=self.cfg.search_geometry,
                               encoding=self.cfg.search_encoding)
        self.blocklist.update(out.tenant_id, add=[out])
        return out

    def write_block_direct(self, tenant: str, objects, search_entries=None,
                           data_encoding: str = "v2") -> BlockMeta:
        """Write a complete block from (id, obj, start, end) tuples —
        used by tests/benchmarks and the compactor path."""
        meta = BlockMeta(tenant_id=tenant, encoding=self.cfg.block_encoding,
                         data_encoding=data_encoding)
        sb = StreamingBlock(meta, page_size=self.cfg.block_page_size)
        for oid, obj, s, e in objects:
            sb.add_object(oid, obj, s, e)
        out = sb.complete(self.backend)
        if search_entries:
            write_search_block(self.backend, out, search_entries,
                               geometry=self.cfg.search_geometry,
                               encoding=self.cfg.search_encoding)
        self.blocklist.update(tenant, add=[out])
        return out

    # ------------------------------------------------------------------
    # Reader

    def poll(self) -> None:
        metas, compacted = self.poller.poll()
        self.blocklist.apply_poll_results(metas, compacted)
        with self._search_lock:
            live = {m.block_id for ms in metas.values() for m in ms}
            for bid in [b for b in self._search_blocks if b not in live]:
                del self._search_blocks[bid]

    @staticmethod
    def _include_block(m: BlockMeta, block_start: str, block_end: str,
                       start_s: int = 0, end_s: int = 0) -> bool:
        """Inclusion predicate (reference tempodb.go:492-520): block id in
        the [block_start, block_end] shard range, time windows overlap."""
        if block_start and m.block_id < block_start:
            return False
        if block_end and m.block_id > block_end:
            return False
        if start_s and m.end_time and m.end_time < start_s:
            return False
        if end_s and m.start_time and m.start_time > end_s:
            return False
        return True

    def find_trace_by_id(self, tenant: str, trace_id: bytes,
                         block_start: str = "", block_end: str = "") -> tuple[bytes | None, int]:
        """Fan out over candidate blocks; combine partial objects (the same
        trace can live in several blocks until compaction dedupes it).
        Returns (object bytes or None, failed_block_count)."""
        key = pad_trace_id(trace_id)
        metas = [m for m in self.blocklist.metas(tenant)
                 if self._include_block(m, block_start, block_end)]

        def job(m: BlockMeta):
            return BackendBlock(self.backend, m).find_by_id(key)

        # reference: store.Find span w/ inspected-block tags tempodb.go:291
        with tracing.start_span("tempodb.Find", tenant=tenant) as span:
            found, errors = run_jobs(metas, job, workers=self.cfg.pool_workers)
            span.set_attributes(candidate_blocks=len(metas),
                                failed_blocks=len(errors),
                                partials=len(found))
            if not found:
                return None, len(errors)
            codec = codec_for(metas[0].data_encoding if metas else "v2")
            return (found[0] if len(found) == 1
                    else codec.combine(*found)), len(errors)

    def _search_block_for(self, meta: BlockMeta) -> BackendSearchBlock:
        with self._search_lock:
            bsb = self._search_blocks.get(meta.block_id)
            if bsb is None:
                bsb = BackendSearchBlock(self.backend, meta)
                self._search_blocks[meta.block_id] = bsb
                # bounded HBM cache: evict oldest staged blocks
                while len(self._search_blocks) > self.cfg.search_cache_blocks:
                    self._search_blocks.pop(next(iter(self._search_blocks)))
            return bsb

    def search(self, tenant: str, req: tempopb.SearchRequest,
               results: SearchResults | None = None) -> SearchResults:
        """Search all (time-pruned) blocks of a tenant through the device
        engine, early-stopping at the result limit."""
        results = results or SearchResults(limit=req.limit or 20)
        with obs.query_seconds.time(op="search"), \
                tracing.start_span("tempodb.Search", tenant=tenant) as span:
            metas = []
            for m in self.blocklist.metas(tenant):
                if not self._include_block(m, "", "", req.start, req.end):
                    results.metrics.skipped_blocks += 1
                    continue
                metas.append(m)
            for bsb in self._staged_blocks(metas, req):
                bsb.search(req, results, engine=self.engine)
                if results.complete:
                    break
            span.set_attributes(
                inspected_traces=results.metrics.inspected_traces,
                inspected_blocks=results.metrics.inspected_blocks,
                skipped_blocks=results.metrics.skipped_blocks)
        obs.search_inspected.inc(results.metrics.inspected_traces, tenant=tenant)
        return results

    def _staged_blocks(self, metas, req=None):
        """Yield search blocks with staging (IO + decompress + H2D
        dispatch) pipelined N blocks ahead of the scan — the SURVEY §7
        double-buffering requirement: while the device scans block i, the
        host prepares block i+1..i+N so the TPU never starves on IO.
        Depth 0 falls back to synchronous staging."""
        depth = self.cfg.search_prefetch_blocks
        if depth <= 0 or len(metas) <= 1:
            for m in metas:
                yield self._search_block_for(m)
            return

        import queue as _queue

        q: _queue.Queue = _queue.Queue(maxsize=depth)
        stop = threading.Event()

        def producer():
            for m in metas:
                if stop.is_set():
                    return
                try:
                    bsb = self._search_block_for(m)
                    # stage only blocks the header rollup can't prune —
                    # bsb.search re-checks and skips without staging
                    if req is None or matches_block_header(bsb.header(), req):
                        bsb.staged()  # async H2D dispatch happens here
                    item = (bsb, None)
                except Exception as e:  # noqa: BLE001 — surfaced below
                    item = (None, e)
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except _queue.Full:
                        continue
                if item[1] is not None:
                    return
            if not stop.is_set():
                try:
                    q.put(None, timeout=1.0)
                except _queue.Full:
                    pass

        t = threading.Thread(target=producer, daemon=True,
                             name="search-prefetch")
        t.start()
        served = 0
        try:
            while served < len(metas):
                item = q.get()
                if item is None:
                    return
                bsb, err = item
                if err is not None:
                    raise err
                served += 1
                yield bsb
        finally:
            stop.set()

    def search_block(self, req: tempopb.SearchBlockRequest) -> SearchResults:
        """One search job (the SearchBlockRequest protocol unit). The block
        meta travels in the request, as in the reference querier
        (internalSearchBlock rebuilding BlockMeta from params)."""
        meta = BlockMeta(
            tenant_id=req.tenant_id, block_id=req.block_id,
            encoding=req.encoding or "zstd", version=req.version or "vT1",
            data_encoding=req.data_encoding or "v2",
        )
        results = SearchResults(limit=req.search_req.limit or 20)
        self._search_block_for(meta).search(req.search_req, results,
                                            engine=self.engine)
        return results

    # ------------------------------------------------------------------
    # Compactor

    def compact_tenant_once(self, tenant: str, now_s: int | None = None) -> BlockMeta | None:
        now_s = int(time.time()) if now_s is None else now_s
        inputs = self.selector.blocks_to_compact(self.blocklist.metas(tenant), now_s)
        if not inputs:
            return None
        new_meta = compact_blocks(self.backend, tenant, inputs,
                                  page_size=self.cfg.block_page_size,
                                  search_geometry=self.cfg.search_geometry,
                                  search_encoding=self.cfg.search_encoding)
        obs.compactions.inc(tenant=tenant)
        from tempo_tpu.backend.types import CompactedBlockMeta

        self.blocklist.update(
            tenant, add=[new_meta], remove=inputs,
            add_compacted=[CompactedBlockMeta.from_meta(m) for m in inputs],
        )
        return new_meta

    def retain_tenant(self, tenant: str, now_s: int | None = None) -> tuple[int, int]:
        now_s = int(time.time()) if now_s is None else now_s
        return apply_retention(
            self.backend, self.blocklist, tenant, now_s,
            retention_s=self.cfg.retention_s,
            compacted_retention_s=self.cfg.compacted_retention_s,
        )
