"""TempoDB facade: the storage engine's public Reader/Writer/Compactor.

Role-equivalent to the reference's tempodb/tempodb.go:70-520: block
completion from WAL blocks, trace-by-ID fan-out over the blocklist with a
bounded pool, search across backend search blocks (device engine, staged
cache), poller/compaction/retention enablement, and block inclusion
predicates (id-range shard + time window).
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field

from tempo_tpu import tempopb
from tempo_tpu.backend.raw import RawBackend
from tempo_tpu.backend.types import BlockMeta
from tempo_tpu.encoding.v2 import BackendBlock, StreamingBlock
from tempo_tpu.model.codec import codec_for
from tempo_tpu.search import SearchResults, write_search_block
from tempo_tpu.search.backend_search_block import BackendSearchBlock
from tempo_tpu.search.batcher import BlockBatcher, ScanJob
from tempo_tpu.search.columnar import PageGeometry
from tempo_tpu.search.engine import ScanEngine
from tempo_tpu.observability import metrics as obs
from tempo_tpu.observability import tracing
from tempo_tpu.observability.log import get_logger
from tempo_tpu.utils.ids import pad_trace_id
from tempo_tpu.utils.lru import BoundedCache
from tempo_tpu.wal import WAL, AppendBlock

from .blocklist import Blocklist
from .compaction import TimeWindowBlockSelector, compact_blocks
from .poller import Poller
from .pool import run_jobs
from .retention import apply_retention


log = get_logger("tempo_tpu.tempodb")


@dataclass
class TempoDBConfig:
    block_encoding: str = "zstd"          # reference: block zstd
    # WAL record compression (reference: snappy v2 pages, wal.go:54-97).
    # "auto" = native snappy if built, zlib otherwise; "none" disables
    wal_encoding: str = "auto"
    search_encoding: str = "zstd"         # reference: search snappy
    block_page_size: int = 1 << 20
    pool_workers: int = 50                # reference: pool 50 workers
    blocklist_poll_s: int = 30
    compaction_window_s: int = 3600
    compaction_max_inputs: int = 8
    compaction_flush_bytes: int = 30 << 20   # reference FlushSizeBytes
    complete_flush_bytes: int = 30 << 20     # completion streams at the same cadence
    retention_s: int = 14 * 24 * 3600
    compacted_retention_s: int = 3600
    search_geometry: PageGeometry = field(default_factory=PageGeometry)
    tenant_index_builder: bool = True
    search_cache_blocks: int = 64         # open search-block objects kept
    # serving-path batching (the TPU inversion of the reference's per-job
    # fan-out, searchsharding.go): blocks group into one kernel dispatch
    search_max_batch_pages: int = 4096    # pages stacked per dispatch
    search_batch_cache_bytes: int = 4 << 30   # staged-batch HBM budget
    # host-RAM overflow tier for stacked batches: HBM-evicted batches
    # re-stage with one H2D copy instead of IO+decompress+restack.
    # None = auto: min(32 GB, half of physical RAM) — this tier RETAINS
    # memory, so a fixed default would OOM small hosts
    search_host_cache_bytes: int | None = None
    search_pipeline_depth: int = 2        # dispatches in flight
    # cross-request query coalescing: concurrent searches whose dispatch
    # hits the same staged batch within this window fuse into ONE
    # multi-query kernel launch. A solo search skips the window (no peer
    # to wait for), so serial latency is unchanged. max_queries <= 1
    # disables coalescing entirely
    search_coalesce_window_s: float = 0.003
    search_coalesce_max_queries: int = 8
    # device-resident dictionary probe: value dictionaries at/above this
    # many distinct values stage their packed bytes to HBM and run the
    # substring prefilter ON DEVICE (search/dict_probe.py) instead of
    # the host memmem walk — at 10M distinct values the host walk is
    # ~312 ms per fresh tag-set vs single-digit-ms on chip (BENCH_r05).
    # Mirrors pipeline.NATIVE_SCAN_THRESHOLD (the same scale at which
    # the HOST scan moves to the native memmem path); <= 0 keeps every
    # probe on the exact host path. None = the dict_probe default (50k).
    search_device_probe_min_vals: int | None = None
    # adaptive host/device offload planner (search/planner.py): above
    # the search_device_probe_min_vals floor, a cost model over the live
    # dispatch-profiler observations chooses host vs device for the
    # dictionary substring prefilter per block group at plan time —
    # self-calibrating (EWMA over recent dispatches, seeded by a
    # one-shot microbenchmark on first decision). False (default) is
    # behavior-identical to the static-threshold path. Decisions +
    # predicted-vs-actual error at /debug/planner. Both placements are
    # exact, so results never depend on this flag.
    search_offload_planner_enabled: bool = False
    # EWMA smoothing for the planner's observed rates (higher = adapt
    # faster, noisier) and the decision ring rendered by /debug/planner
    search_offload_planner_ewma: float = 0.25
    search_offload_planner_ring: int = 256
    # owner-routed HBM (search/ownership.py,
    # docs/search-hbm-ownership.md): block placement groups get
    # consistent-hash ownership across the fleet — the frontend routes a
    # group's sub-queries to its owner (the one process holding it
    # device-resident, where cross-request coalescing fuses tenants'
    # dashboards), a non-owner serves the byte-identical host route
    # instead of staging a duplicate HBM copy, and a membership change
    # moves only the affected groups (eviction becomes a placement
    # change). False (default) is a true noop: one attribute read per
    # site, byte-identical routing.
    search_hbm_ownership_enabled: bool = False
    # comma-separated fleet member ids ("host-0,host-1"); empty = auto
    # from the multihost env contract (TEMPO_NUM_PROCESSES /
    # TEMPO_PROCESS_ID), a single-member "self" fleet otherwise
    search_hbm_ownership_members: str = ""
    # this process's member id; empty = auto (matches the member
    # auto-derivation above)
    search_hbm_ownership_self: str = ""
    # placement-group count block ids hash onto (the ownership and
    # rebalance unit): more groups = finer rebalance granularity at a
    # larger /debug/ownership map
    search_hbm_ownership_groups: int = 64
    # heat-adaptive replication factor: > 1 promotes a placement group
    # whose access rate crosses the hot-rate threshold to the first rf
    # distinct members the ownership ring yields for its token —
    # replicas serve it device-resident and the frontend hedges their
    # dispatches. 1 (default) keeps single-owner placement bit for bit:
    # the heat table, replica lookups and the hedge timer are each one
    # attribute read.
    search_hbm_ownership_rf: int = 1
    # per-group access rate (scans/second, EWMA over a 30 s window)
    # that promotes a group to its replica set; demotion is hysteretic
    # at half this rate. Only meaningful with rf > 1.
    search_hbm_ownership_hot_rate: float = 50.0
    # hedge delay for replicated dispatch, in milliseconds: how long
    # the frontend waits on a promoted group's primary before firing
    # the same batch at the next replica. 0 (default) auto-derives a
    # p99-ish bound from observed dispatch walls (mean + 3*dev, seeded
    # by the dispatch profiler's stage EWMAs).
    search_hedge_delay_ms: float = 0.0
    # structural query engine (search/ir.py + search/structural.py,
    # docs/search-structural-queries.md): a typed query IR — span-level
    # predicates, AND/OR/NOT, parent-child / descendant relations,
    # count and duration-quantile aggregates — parsed from ?q= on the
    # search API and COMPILED into the fused scan kernels (parent-
    # pointer joins + segment reductions over per-trace span segments).
    # Enabling also captures per-span summary rows at ingest (the span
    # segment of new search containers). False (default) is a true
    # noop: legacy tag/duration requests read one attribute and take
    # the existing byte-identical path; requests carrying ?q= get a 400.
    search_structural_enabled: bool = False
    # span rows captured per trace at ingest (walk-order truncation —
    # the span segment's max_search_bytes analog)
    search_structural_max_spans: int = 512
    # kv pairs captured per span at ingest
    search_structural_max_span_kvs: int = 16
    # plan-shape query stacking: concurrent structural queries that
    # lowered to the SAME static plan descriptor stack along the
    # coalescer's query axis (parameter tables pad to the group max)
    # and execute as ONE fused dispatch — N dashboards running the
    # same saved query cost ~1 kernel launch per coalescing window.
    # Unstackable shapes flush solo and surface in
    # tempo_search_structural_stack_events_total. False (default) is a
    # true noop: structural queries keep the solo-flush behavior
    # exactly (one attribute read at the coalescer).
    search_structural_stack_enabled: bool = False
    # segment-aligned span sharding on mesh/dist staging: the span
    # segment reshards so each trace's contiguous span run lands whole
    # on its page's shard (parent pointers and segment ranges rebased
    # shard-local), making the child gather and desc pointer-doubling
    # shard-local — parent joins scale with the mesh and per-shard span
    # HBM drops to ~1/P of the replicated layout. False (default) is a
    # true noop: span columns replicate exactly as before (one
    # attribute read at the placement sites).
    search_structural_shard_spans: bool = False
    # shape-bucketed cross-plan stacking: concurrent structural queries
    # whose DIFFERENT plans canonicalize into the same bucket shape
    # (node count rounded to a pow2 tier, relation/aggregate slots
    # masked per member) stack into ONE coalesced dispatch — mixed
    # dashboard traffic fuses instead of flushing one short dispatch
    # per plan. Inactive slots evaluate as identity, so results stay
    # byte-identical to solo execution. False (default) is a true noop:
    # stack_group_key keeps exact-plan grouping (one attribute read).
    search_structural_bucket_enabled: bool = False
    # largest flattened slot count (span + trace nodes) a plan may
    # occupy and still bucket; bigger plans keep exact-plan grouping
    search_structural_bucket_max_nodes: int = 16
    # remainder-shard mesh layout: stage to the smallest multiple of
    # n_shards instead of the next pow2, with the ragged tail recorded
    # as a static per-shard valid length in the jit key — a 9-page
    # block on 8 shards stages 16 pages today, 2x the bytes it needs.
    # False (default) is a true noop: pow2 staging exactly as before
    # (one attribute read at the staging site).
    search_structural_remainder_pages: bool = False
    # hot-tier live search (search/live_tier.py,
    # docs/search-live-tail.md): the ingesters' in-flight traces absorb
    # into a per-tenant rolling columnar stage scanned by the SAME
    # fused kernel as backend blocks (pow2-capacity tiers keep the jit
    # key shape-only), the WAL head/completing generations kernel-scan
    # through the identical machinery, and standing tail subscriptions
    # evaluate per push micro-batch — push→searchable drops from
    # flush+poll (seconds) to one absorb+scan (sub-100ms on chip).
    # False (default) is a true noop: every hook reads one attribute;
    # live/WAL search keeps the per-entry host walk byte-identically.
    search_live_tier_enabled: bool = False
    # live-stage entry ceiling per tenant: past it a search falls back
    # to the legacy walk (counted in
    # tempo_search_live_tier_scans_total{result=fallback_overflow})
    search_live_tier_max_entries: int = 4096
    # standing tail subscriptions allowed per tenant; registration past
    # the cap is rejected (429 on /api/tail)
    search_live_tail_max_subscriptions: int = 16
    # packed HBM residency (search/packing.py,
    # docs/search-packed-residency.md): staged value-id columns narrow
    # to the width the per-block dictionary cardinality allows (4-bit/
    # uint8/uint16/uint32 codes), durations quantize to uint16 buckets
    # with an exact residual check at bucket boundaries, and device-
    # probe hit masks bit-pack to uint32 words — kernels unpack
    # in-register (the width descriptor is part of the jit shape key),
    # so ~2x more blocks fit a given HBM budget at byte-identical
    # results. False (default) is a true noop: one attribute read per
    # staging site, byte-identical layout and results.
    search_packed_residency: bool = False
    # device-side aggregate analytics (search/analytics.py,
    # docs/search-analytics.md): the metrics generator's native
    # summary-row feed batches into rolling pow2-tier device
    # micro-batches — calls/errors by (service, span_name, kind,
    # status), exact latency-bucket counts, and service-graph edge
    # counts compute as ONE dense sorted-key reduction per push, and
    # the host drains per-series deltas into the same ManagedRegistry
    # handles (byte-identical to the per-span walk); at query time
    # ?agg=red compiles group-by-service RED answers onto the fused
    # scan dispatch. False (default) is a true noop: one attribute
    # read per push / per search, walk and response byte-identical.
    search_analytics_enabled: bool = False
    # blobs under this many rows stay on the per-span walk (batch
    # setup costs more than it saves on tiny pushes)
    search_analytics_min_rows: int = 64
    # persistent XLA compilation cache directory for the SEARCH kernels
    # (jax_compilation_cache_dir): a cold process replays first-seen-
    # shape compiles from disk instead of re-paying XLA. Empty
    # (default) = off. Hits surface as jit_cache_events{result=
    # persisted}. (host_state_dir's auto mode already wires this for
    # full TempoDB deployments; this knob reaches the same machinery
    # without the rest of host state.)
    search_compile_cache_dir: str = ""
    # stage + compile-warm hot batches in the background after each poll
    # so the first query pays neither (off by default: polls in tests and
    # write-only processes must not spin up device work)
    search_prewarm_on_poll: bool = False
    # dispatch profiler (observability/profile.py): per-dispatch stage
    # breakdown (build/h2d/compile/execute/d2h/lock_wait) into
    # tempo_search_dispatch_stage_seconds + /debug/profile. False is a
    # TRUE noop — dispatch sites get a shared noop record, no clock
    # reads, no locks (the <2% overhead contract is benchmarked every
    # round by bench.py's profile_overhead phase)
    search_profiling_enabled: bool = True
    # block_until_ready fence after each profiled kernel call: attributes
    # TRUE kernel time to the execute stage, at the cost of the async
    # dispatch/drain pipelining — triage sessions only
    search_profiling_fence: bool = False
    # recent-dispatch ring rendered by /debug/profile
    search_profiling_ring: int = 256
    # per-query execution inspector (search/query_stats.py): every
    # search accumulates blocks scanned/skipped (and why), bytes split
    # host vs device, cache hits vs re-stages, planner decisions, and
    # per-stage device-seconds attributed from its (possibly fused)
    # dispatches — feeding the per-tenant accounting counters, the
    # slow-query log, /debug/querystats, and the opt-in ?explain=1
    # response breakdown. False is a true noop on the search path
    # (bench phase query_stats_overhead asserts the contract);
    # results are byte-identical either way.
    search_query_stats_enabled: bool = True
    # slow-query log threshold (seconds): a query slower than this
    # emits ONE structured JSON log line (tenant, self-trace id, the
    # complete QueryStats), rate-limited process-wide. <= 0 disables
    # the log; the tempo_search_slow_queries_total counter still counts.
    search_slow_query_log_s: float = 10.0
    # recent-query ring rendered by /debug/querystats
    search_query_stats_ring: int = 256
    # ---- robustness (tempo_tpu/robustness/, docs/robustness.md) ----
    # watchdog deadline per DEVICE dispatch (single/batched/coalesced/
    # mesh/dict-probe kernels, staging H2D puts, drain D2H syncs): a
    # dispatch that exceeds it is abandoned, booked as a device fault,
    # and answered through the byte-identical host path. <= 0 disables
    # the watchdog (faults are still classified). Only consulted while
    # the breaker is enabled or a faultpoint is armed — breaker off +
    # faults disarmed is a true noop on the dispatch path.
    search_device_dispatch_timeout_s: float = 30.0
    # bounded wait on the process-wide collective dispatch lock
    # (parallel.mesh.dispatch_lock): a timeout books a breaker fault
    # instead of blocking the submitter forever (the PR 1
    # rendezvous-deadlock class, detectable at runtime). <= 0 = wait
    # forever (the historical behavior)
    search_dispatch_lock_timeout_s: float = 60.0
    # default request deadline for /api/search and /api/traces when the
    # client sends no X-Tempo-Timeout-S header; propagates http →
    # frontend → querier → TempoDB so sharded sub-queries stop queueing
    # once the budget is spent (the answer goes out PARTIAL). 0 = no
    # default deadline
    search_request_timeout_s: float = 0.0
    # device circuit breaker: search_breaker_fault_threshold faults
    # within search_breaker_window_s trip it open; while open every
    # scan/probe runs the byte-identical host path; after
    # search_breaker_cooldown_s it half-opens and probes the device
    # with real dispatches until one succeeds (closed) or fails (open
    # again). False disables the whole robustness layer (the noop
    # contract bench's chaos phase asserts).
    search_breaker_enabled: bool = True
    search_breaker_fault_threshold: int = 3
    search_breaker_window_s: float = 30.0
    search_breaker_cooldown_s: float = 5.0
    # fault-injection arming spec ("name:p=1,count=2,delay=0.5;..." —
    # see tempo_tpu/robustness/faults.py); the TEMPO_FAULTS env var arms
    # in addition. Empty (default) = nothing armed, true noop.
    robustness_faults: str = ""
    # shard batches over the device mesh when >1 device is visible
    auto_mesh: bool = True
    # restartable host state (VERDICT r4 #3): None = auto (persistent
    # XLA compile cache + header snapshot under <wal_dir>/host-state);
    # "" disables; a path overrides the location. A cold restart then
    # replays compiles from disk and loads header rollups without one
    # backend read per block.
    host_state_dir: str | None = None


class TempoDB:
    """Reader + Writer + Compactor over one backend."""

    def __init__(self, backend: RawBackend, wal_dir: str,
                 cfg: TempoDBConfig | None = None, mesh=None):
        """mesh: a jax.sharding.Mesh to shard batched scans over; when
        None and cfg.auto_mesh is set, a 1-axis mesh over all visible
        devices is built automatically if more than one is present."""
        self.backend = backend
        self.cfg = cfg or TempoDBConfig()
        # degrade unusable codecs up front: a host without the native
        # build AND without the zstandard wheel cannot zstd — writing
        # must fall back to an always-available codec (data is labeled
        # with the codec that actually wrote it; READS of existing zstd
        # blocks still fail loudly, which is correct)
        from tempo_tpu.encoding.v2.compression import best_available

        import dataclasses

        for _field in dataclasses.fields(self.cfg):
            if _field.name not in ("block_encoding", "search_encoding"):
                continue
            _enc = getattr(self.cfg, _field.name)
            if _enc != _field.default:
                # an explicit non-default codec choice fails fast on
                # first use — silently rewriting it would mask a broken
                # deployment (missing native lib the operator asked for)
                continue
            _use = best_available(_enc)
            if _use != _enc:
                log.warning("%s %r unusable on this host (no native lib/"
                            "wheel); degrading to %r", _field.name, _enc,
                            _use)
                # degrade a COPY: the caller's config object is theirs —
                # writing into it would leak this host's fallback into
                # other TempoDBs built from the same config
                self.cfg = dataclasses.replace(
                    self.cfg, **{_field.name: _use})
        self.wal = WAL(wal_dir, encoding=self.cfg.wal_encoding)
        self.blocklist = Blocklist()
        self.poller = Poller(backend, build_index=self.cfg.tenant_index_builder)
        self.selector = TimeWindowBlockSelector(
            window_s=self.cfg.compaction_window_s,
            max_inputs=self.cfg.compaction_max_inputs,
        )
        self.engine = ScanEngine()
        self.mesh = mesh
        # auto-mesh resolves lazily on the first search: jax.devices()
        # initializes the backend (and on TPU hosts claims the chip), which
        # write/compact-only processes must never pay for
        self._mesh_resolved = mesh is not None
        self.batcher = BlockBatcher(
            mesh=mesh,
            max_batch_pages=self.cfg.search_max_batch_pages,
            cache_bytes=self.cfg.search_batch_cache_bytes,
            host_cache_bytes=self.cfg.search_host_cache_bytes,
            pipeline_depth=self.cfg.search_pipeline_depth,
            coalesce_window_s=self.cfg.search_coalesce_window_s,
            coalesce_max_queries=self.cfg.search_coalesce_max_queries,
            device_probe_min_vals=self.cfg.search_device_probe_min_vals,
        )
        # the profiler is process-wide (like REGISTRY): the most recent
        # TempoDB's config wins, matching how metrics/tracing configure
        from tempo_tpu.observability import profile as _profile

        _profile.configure(enabled=self.cfg.search_profiling_enabled,
                           fence=self.cfg.search_profiling_fence,
                           ring_size=self.cfg.search_profiling_ring)
        # per-query stats: process-wide like the profiler (most recent
        # TempoDB's config wins, the REGISTRY idiom)
        from tempo_tpu.search import query_stats as _query_stats

        _query_stats.configure(
            enabled=self.cfg.search_query_stats_enabled,
            slow_s=self.cfg.search_slow_query_log_s,
            ring_size=self.cfg.search_query_stats_ring)
        # robustness layer: breaker + dispatch watchdog + fault
        # registry, process-wide like the profiler (most recent
        # TempoDB's config wins, the REGISTRY idiom)
        from tempo_tpu import robustness as _robustness

        _robustness.configure(
            breaker_enabled=self.cfg.search_breaker_enabled,
            fault_threshold=self.cfg.search_breaker_fault_threshold,
            window_s=self.cfg.search_breaker_window_s,
            cooldown_s=self.cfg.search_breaker_cooldown_s,
            dispatch_timeout_s=self.cfg.search_device_dispatch_timeout_s,
            lock_timeout_s=self.cfg.search_dispatch_lock_timeout_s,
            faults_spec=self.cfg.robustness_faults)
        # offload planner: process-wide like the profiler it feeds from
        from tempo_tpu.search import planner as _planner

        _planner.configure(enabled=self.cfg.search_offload_planner_enabled,
                           alpha=self.cfg.search_offload_planner_ewma,
                           ring_size=self.cfg.search_offload_planner_ring)
        # packed HBM residency: process-wide gate like the layers above
        # (docs/search-packed-residency.md)
        from tempo_tpu.search import packing as _packing

        _packing.configure(enabled=self.cfg.search_packed_residency)
        # structural query engine: process-wide gate like the layers
        # above (docs/search-structural-queries.md)
        from tempo_tpu.search import structural as _structural

        _structural.configure(
            enabled=self.cfg.search_structural_enabled,
            max_spans=self.cfg.search_structural_max_spans,
            max_span_kvs=self.cfg.search_structural_max_span_kvs,
            stack_enabled=self.cfg.search_structural_stack_enabled,
            shard_spans=self.cfg.search_structural_shard_spans,
            bucket_enabled=self.cfg.search_structural_bucket_enabled,
            bucket_max_nodes=self.cfg.search_structural_bucket_max_nodes,
            remainder_pages=self.cfg.search_structural_remainder_pages)
        # hot-tier live search: process-wide gate like the layers above
        # (docs/search-live-tail.md)
        from tempo_tpu.search.live_tier import LIVE_TIER as _live_tier

        _live_tier.configure(
            enabled=self.cfg.search_live_tier_enabled,
            max_entries=self.cfg.search_live_tier_max_entries,
            max_subscriptions=self.cfg.search_live_tail_max_subscriptions)
        # device-side aggregate analytics: process-wide gate like the
        # layers above (docs/search-analytics.md)
        from tempo_tpu.search.analytics import ANALYTICS as _analytics

        _analytics.configure(
            enabled=self.cfg.search_analytics_enabled,
            min_rows=self.cfg.search_analytics_min_rows)
        # owner-routed HBM placement: process-wide like the layers above
        # (docs/search-hbm-ownership.md)
        from tempo_tpu.search import ownership as _ownership

        _ownership.configure(
            enabled=self.cfg.search_hbm_ownership_enabled,
            members=self.cfg.search_hbm_ownership_members or None,
            self_id=self.cfg.search_hbm_ownership_self or None,
            groups=self.cfg.search_hbm_ownership_groups,
            rf=self.cfg.search_hbm_ownership_rf,
            hot_rate=self.cfg.search_hbm_ownership_hot_rate,
            hedge_delay_ms=self.cfg.search_hedge_delay_ms)
        # heat promotions/demotions pre-stage or release residency
        # through THIS db's batcher (most recent TempoDB wins — the
        # REGISTRY idiom every process-wide layer above follows)
        _ownership.OWNERSHIP.set_change_hook(self._ownership_heat_change)
        if (self.cfg.search_offload_planner_enabled
                and not self.cfg.search_profiling_enabled):
            # the planner's device-side feed (device-probe rate, compile/
            # collective costs, h2d staging rate, jit shape-signature
            # set) arrives exclusively through the dispatch profiler —
            # with profiling off, decisions freeze at the one-shot
            # microbenchmark seed and every compile-site device
            # prediction keeps paying the compile penalty, biasing the
            # planner toward host forever. Results stay correct either
            # way, so warn rather than override the operator's config.
            log.warning(
                "search_offload_planner_enabled without "
                "search_profiling_enabled: the planner cannot "
                "self-calibrate (no dispatch-profiler feed) and will "
                "decide from its microbenchmark seed only; enable "
                "search_profiling_enabled for cost-model calibration")
        self._prewarm_stop = None  # Event cancelling the running prewarm
        self._prewarm_thread = None
        self._prewarm_atexit = False
        self._search_blocks: dict[str, BackendSearchBlock] = {}
        # header rollups cached separately from the container-holding
        # block objects: a header is ~1KB and every query's job planning
        # reads it for EVERY block — at 10K blocks the old shared 64-slot
        # LRU forced 10K disk reads + json parses per query (profiled as
        # the dominant serving cost, VERDICT r2 #1)
        self._headers: OrderedDict[str, dict] = OrderedDict()
        self._headers_max = 131_072
        # (epoch, jobs, fallback_metas) per tenant — see search()
        self._jobs_cache: dict[str, tuple] = {}
        # (epoch, jobs, fallback, missing_ranges, groups) per full job
        # signature — the SearchBlocksRequest protocol path's equivalent
        # (search_blocks)
        self._breq_jobs_cache = BoundedCache(32)
        self._search_lock = threading.Lock()
        # explicit search-kernel compile cache (search_compile_cache_dir):
        # applied BEFORE the host-state auto wiring below so an
        # operator's explicit location wins (enable_compile_cache keeps
        # the first configured dir)
        if self.cfg.search_compile_cache_dir:
            from tempo_tpu.utils.jaxenv import enable_compile_cache

            enable_compile_cache(self.cfg.search_compile_cache_dir)
        # restartable host state: header snapshot + persistent XLA
        # compile cache. Auto default lives under the WAL dir — per-node
        # durable storage that already must survive restarts. The
        # snapshot sits in a SUBDIR because WAL replay deletes unknown
        # files in its root.
        sd = self.cfg.host_state_dir
        self._state_dir = (os.path.join(wal_dir, "host-state")
                          if sd is None else (sd or None))
        if self._state_dir:
            from tempo_tpu.utils.jaxenv import enable_compile_cache

            enable_compile_cache(os.path.join(self._state_dir, "xla-cache"))
            self._load_host_state()

    def _ensure_mesh(self) -> None:
        if self._mesh_resolved:
            return
        # serialized, flag set LAST: a concurrent first search must never
        # see a half-configured engine (unsharded batch → dist kernel)
        with self._search_lock:
            if self._mesh_resolved:
                return
            if self.cfg.auto_mesh:
                import jax

                if len(jax.devices()) > 1:
                    from tempo_tpu.parallel.mesh import make_mesh

                    self.mesh = make_mesh()
                    self.batcher.engine.mesh = self.mesh
                    self.batcher.engine.n_shards = int(self.mesh.devices.size)
            self._mesh_resolved = True

    # ------------------------------------------------------------------
    # Writer

    def complete_block(self, block: AppendBlock, search_entries=None) -> BlockMeta:
        """WAL block → immutable backend block (+ columnar search block).
        Reference flow: instance.CompleteBlock → tempodb.CompleteBlock...
        (SURVEY.md §3.2)."""
        codec = codec_for(block.meta.data_encoding)
        meta = BlockMeta(
            tenant_id=block.meta.tenant_id,
            block_id=block.meta.block_id,
            encoding=self.cfg.block_encoding,
            data_encoding=block.meta.data_encoding,
        )
        # stream through backend.append every complete_flush_bytes so a
        # max_block_bytes-sized completion never holds the whole compressed
        # block in RAM (reference streaming_block.go:27-155 flushes 30 MB)
        sb = StreamingBlock(meta, page_size=self.cfg.block_page_size,
                            backend=self.backend,
                            flush_size=self.cfg.complete_flush_bytes)
        try:
            for oid, obj in block.iterator():
                r = codec.fast_range(obj) or (0, 0)
                sb.add_object(oid, obj, r[0], r[1])
            out = sb.complete(self.backend)
        except BaseException:
            sb.abort()  # release the in-progress append before the retry
            raise
        if search_entries:
            write_search_block(self.backend, out, search_entries,
                               geometry=self.cfg.search_geometry,
                               encoding=self.cfg.search_encoding)
        self.blocklist.update(out.tenant_id, add=[out])
        return out

    def write_block_direct(self, tenant: str, objects, search_entries=None,
                           data_encoding: str = "v2") -> BlockMeta:
        """Write a complete block from (id, obj, start, end) tuples —
        used by tests/benchmarks and the compactor path."""
        meta = BlockMeta(tenant_id=tenant, encoding=self.cfg.block_encoding,
                         data_encoding=data_encoding)
        sb = StreamingBlock(meta, page_size=self.cfg.block_page_size,
                            backend=self.backend,
                            flush_size=self.cfg.complete_flush_bytes)
        try:
            for oid, obj, s, e in objects:
                sb.add_object(oid, obj, s, e)
            out = sb.complete(self.backend)
        except BaseException:
            sb.abort()
            raise
        if search_entries:
            write_search_block(self.backend, out, search_entries,
                               geometry=self.cfg.search_geometry,
                               encoding=self.cfg.search_encoding)
        self.blocklist.update(tenant, add=[out])
        return out

    # ------------------------------------------------------------------
    # Reader

    def poll(self) -> None:
        from tempo_tpu.observability.ingest_telemetry import TELEMETRY
        from tempo_tpu.robustness import FAULTS

        if FAULTS.active:
            FAULTS.hit("poll_error")  # a reader that stops seeing blocks
        t0 = time.perf_counter()
        with tracing.start_span("tempodb.Poll") as span:
            metas, compacted = self.poller.poll()
            self.blocklist.apply_poll_results(metas, compacted)
            span.set_attributes(
                tenants=len(metas),
                blocks=sum(len(ms) for ms in metas.values()))
        if TELEMETRY.enabled:
            # duration + per-tenant blocklist length + the freshness
            # gauge, and the flush->poll_visible pairing that closes the
            # push->searchable stage record (ingest_telemetry)
            TELEMETRY.record_poll(time.perf_counter() - t0, metas)
        # hot-tier eviction signal: blocks this poll made reader-visible
        # retire from the ingester's recently-flushed search leg (the
        # reader leg answers for them now — see live_tier.py)
        from tempo_tpu.search.live_tier import LIVE_TIER

        if LIVE_TIER.enabled:
            LIVE_TIER.mark_poll_visible(metas)
        live = {m.block_id for ms in metas.values() for m in ms}
        with self._search_lock:
            for bid in [b for b in self._search_blocks if b not in live]:
                del self._search_blocks[bid]
            for bid in [b for b in self._headers if b not in live]:
                del self._headers[bid]
        # cancel any running prewarm BEFORE invalidating: a thread
        # mid-_staged could otherwise re-insert a dead block's batch
        # after the invalidate and pin HBM until the next poll. The join
        # happens inside the new prewarm thread (or here if prewarm is
        # off) so poll itself stays fast.
        if self._prewarm_stop is not None:
            self._prewarm_stop.set()
        if self.cfg.search_prewarm_on_poll:
            self.batcher.invalidate(live)
            self.prewarm(tenants=list(metas), reinvalidate=live)
        else:
            self.stop_prewarm()
            self.batcher.invalidate(live)
        self.save_host_state()

    def prewarm(self, tenants: list[str], background: bool = True,
                reinvalidate: set | None = None) -> "threading.Thread | int":
        """Stage (host tier + HBM, up to budget) and compile-warm every
        tenant's batch groups so the first query after a poll pays
        neither staging nor the ~30s XLA compile (VERDICT r3 #2). Runs
        in a background thread by default; a newer poll's prewarm
        cancels the running one. `reinvalidate`: live block-id set to
        re-apply after the PREVIOUS prewarm thread has fully stopped —
        closes the window where its in-flight staging re-inserted a
        dead block's batch."""
        self._ensure_mesh()
        if self._prewarm_stop is not None:
            self._prewarm_stop.set()
        prev_thread = self._prewarm_thread
        stop = self._prewarm_stop = threading.Event()
        if not self._prewarm_atexit:
            # a daemon thread killed mid-device-op tears down the PJRT
            # runtime from under C++ and aborts the process; stop + join
            # (bounded) before interpreter teardown instead. Weakref so
            # the atexit registry does not pin this TempoDB (and its
            # multi-GB caches) for the life of the process.
            import atexit
            import weakref

            ref = weakref.ref(self)
            atexit.register(lambda: getattr(ref(), "stop_prewarm",
                                            lambda: None)())
            self._prewarm_atexit = True

        def run() -> int:
            from tempo_tpu.backend.raw import DoesNotExist

            if prev_thread is not None and prev_thread.is_alive():
                prev_thread.join()
            if reinvalidate is not None:
                self.batcher.invalidate(reinvalidate)
            staged = 0
            for tenant in tenants:
                if stop.is_set():
                    break
                jobs = []
                for m in self.blocklist.metas(tenant):
                    try:
                        jobs.append(self._scan_job(m))
                    except DoesNotExist:
                        continue
                groups = self.batcher.plan(jobs)
                staged += self.batcher.prewarm(groups, stop=stop)
            # job planning above read EVERY live block's header — persist
            # the now-complete rollup set for the next process
            if not stop.is_set():
                self.save_host_state()
            return staged

        if not background:
            return run()
        t = threading.Thread(target=run, name="search-prewarm", daemon=True)
        t.start()
        self._prewarm_thread = t
        return t

    def stop_prewarm(self, timeout_s: float = 120.0) -> None:
        """Cancel a running background prewarm and wait for it to reach a
        safe point (between groups; an in-flight XLA compile must finish
        — it is not interruptible)."""
        if self._prewarm_stop is not None:
            self._prewarm_stop.set()
        t = self._prewarm_thread
        if t is not None and t.is_alive():
            t.join(timeout=timeout_s)

    def rebalance_ownership(self, members, self_id: str | None = None,
                            prestage: bool = True) -> dict:
        """Apply a fleet membership change to the HBM ownership map and
        treat the resulting evictions as a PLACEMENT change
        (docs/search-hbm-ownership.md): the generation bumps and only
        the moved groups change owner; groups this member no longer owns
        drop their HBM residency now (or at unpin, while a search holds
        them pinned); groups it newly owns pre-stage in the background
        from the cached job plans so the first owner-routed query after
        the rebalance pays no staging. Returns the rebalance summary
        (generation, moved groups, drops/deferrals)."""
        from tempo_tpu.search.ownership import OWNERSHIP

        moved = OWNERSHIP.set_members(members, self_id=self_id)
        out = {"generation": OWNERSHIP.generation, "moved_groups": moved}
        out.update(self.batcher.rebalance_ownership())
        if prestage and OWNERSHIP.enabled:

            def _prestage() -> None:
                if not OWNERSHIP.enabled:
                    return
                gen = OWNERSHIP.generation
                with self._search_lock:
                    cached = list(self._jobs_cache.values())
                for hit in cached:
                    if OWNERSHIP.generation != gen:
                        return  # a newer rebalance superseded this one
                    groups = self.batcher.plan(list(hit[1]))
                    # prewarm() itself skips non-owned groups; no
                    # compile warm — the new owner wants residency, the
                    # jit cache is already hot for these shapes
                    self.batcher.prewarm(groups, warm_compile=False)

            threading.Thread(target=_prestage, name="ownership-prestage",
                             daemon=True).start()
        return out

    def _ownership_heat_change(self, group: int, direction: str,
                               replicas) -> None:
        """Heat-table promotion/demotion hook (runs on the ownership
        map's background thread, never a serving thread). A DEMOTION
        releases replica residency through the ordinary rebalance walk
        — owns_group stopped answering true for the dropped replica, so
        the deferred-evict path applies unchanged. A PROMOTION on a
        NEW replica (this member, not the primary) pre-stages the
        group's batches from the cached job plans so the frontend's
        hedged dispatch never races a cold stage — the hedge delay is
        p99-derived, and a cold H2D on the hedge path would lose every
        race it was meant to win."""
        from tempo_tpu.search.ownership import OWNERSHIP

        if direction == "down":
            self.batcher.rebalance_ownership()
            return
        me = OWNERSHIP.self_id
        reps = tuple(replicas or ())
        if not reps or me not in reps or reps[0] == me:
            return  # not a replica here, or already the serving primary
        gen = OWNERSHIP.generation
        with self._search_lock:
            cached = list(self._jobs_cache.values())
        for hit in cached:
            if OWNERSHIP.generation != gen:
                return  # a rebalance superseded this promotion
            groups = self.batcher.plan(list(hit[1]))
            mine = [g for g in groups
                    if OWNERSHIP.group_of(str(g[0].key[0])) == group]
            if mine:
                self.batcher.prewarm(mine, warm_compile=False)

    @staticmethod
    def _include_block(m: BlockMeta, block_start: str, block_end: str,
                       start_s: int = 0, end_s: int = 0) -> bool:
        """Inclusion predicate (reference tempodb.go:492-520): block id in
        the [block_start, block_end] shard range, time windows overlap."""
        if block_start and m.block_id < block_start:
            return False
        if block_end and m.block_id > block_end:
            return False
        if start_s and m.end_time and m.end_time < start_s:
            return False
        if end_s and m.start_time and m.start_time > end_s:
            return False
        return True

    def find_trace_by_id(self, tenant: str, trace_id: bytes,
                         block_start: str = "", block_end: str = "") -> tuple[bytes | None, int]:
        """Fan out over candidate blocks; combine partial objects (the same
        trace can live in several blocks until compaction dedupes it).
        Returns (object bytes or None, failed_block_count)."""
        key = pad_trace_id(trace_id)
        metas = [m for m in self.blocklist.metas(tenant)
                 if self._include_block(m, block_start, block_end)]

        def job(m: BlockMeta):
            return BackendBlock(self.backend, m).find_by_id(key)

        # reference: store.Find span w/ inspected-block tags tempodb.go:291
        with tracing.start_span("tempodb.Find", tenant=tenant) as span:
            found, errors = run_jobs(metas, job, workers=self.cfg.pool_workers)
            span.set_attributes(candidate_blocks=len(metas),
                                failed_blocks=len(errors),
                                partials=len(found))
            if not found:
                return None, len(errors)
            codec = codec_for(metas[0].data_encoding if metas else "v2")
            return (found[0] if len(found) == 1
                    else codec.combine(*found)), len(errors)

    def _search_block_for(self, meta: BlockMeta) -> BackendSearchBlock:
        with self._search_lock:
            bsb = self._search_blocks.get(meta.block_id)
            if bsb is None:
                bsb = BackendSearchBlock(
                    self.backend, meta,
                    header=self._headers.get(meta.block_id),
                    probe_min_vals=self.cfg.search_device_probe_min_vals)
                self._search_blocks[meta.block_id] = bsb
                # bounded HBM cache: evict oldest staged blocks
                while len(self._search_blocks) > self.cfg.search_cache_blocks:
                    self._search_blocks.pop(next(iter(self._search_blocks)))
            return bsb

    def _snapshot_path(self) -> str | None:
        return (os.path.join(self._state_dir, "search-headers.json.gz")
                if self._state_dir else None)

    def _load_host_state(self) -> None:
        """Load the header-rollup snapshot a previous process saved —
        job planning over a 10K-block tenant then costs zero backend
        header reads on the first query after a restart. Stale entries
        (blocks since deleted) are pruned by the next poll()."""
        import gzip
        import json as _json

        path = self._snapshot_path()
        if not path:
            return
        try:
            with open(path, "rb") as f:
                doc = _json.loads(gzip.decompress(f.read()))
            headers = doc["headers"] if doc.get("v") == 1 else {}
        except (OSError, EOFError, ValueError, KeyError, TypeError):
            return  # torn/corrupt snapshot: a cache, rebuild lazily
        with self._search_lock:
            for bid, hdr in headers.items():
                if isinstance(bid, str) and isinstance(hdr, dict):
                    self._headers[bid] = hdr
            while len(self._headers) > self._headers_max:
                self._headers.popitem(last=False)

    def save_host_state(self) -> None:
        """Snapshot the header cache next to the WAL (atomic rename).
        Called after every poll and prewarm; cheap (~100 KB gz at 10K
        blocks), so no debouncing needed."""
        import gzip
        import json as _json

        path = self._snapshot_path()
        if not path:
            return
        with self._search_lock:
            doc = {"v": 1, "headers": dict(self._headers)}
        try:
            os.makedirs(self._state_dir, exist_ok=True)
            blob = gzip.compress(
                _json.dumps(doc).encode(), compresslevel=1)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
        except OSError:
            pass  # snapshot is an optimization, never a failure

    def _header_for(self, m: BlockMeta) -> dict:
        """Block search-header rollup, cached by block id (immutable once
        written). Raises DoesNotExist when the block has no container."""
        import json as _json

        from tempo_tpu.backend.types import NAME_SEARCH_HEADER

        with self._search_lock:
            hdr = self._headers.get(m.block_id)
            if hdr is not None:
                self._headers.move_to_end(m.block_id)
                return hdr
        hdr = _json.loads(self.backend.read(
            m.tenant_id, m.block_id, NAME_SEARCH_HEADER))
        with self._search_lock:
            self._headers[m.block_id] = hdr
            while len(self._headers) > self._headers_max:
                self._headers.popitem(last=False)
        return hdr

    def _scan_job(self, m: BlockMeta, start_page: int = 0,
                  pages: int | None = None) -> ScanJob:
        """A batcher job covering pages [start_page, start_page+pages) of
        the block's search container (whole block by default). Raises if
        the block has no search container (caller falls back to the
        trace-block proto scan). The block OBJECT (container holder) is
        only instantiated inside pages_fn — at staging time — so job
        planning over a 10K-block list touches nothing but the header
        cache."""
        hdr = self._header_for(m)
        total = hdr["n_pages"]
        n = total - start_page if pages is None else min(pages, total - start_page)
        n = max(0, n)
        if start_page == 0 and n == total:
            def pages_fn(self=self, m=m):
                return self._search_block_for(m).pages()
            n_entries = hdr["n_entries"]
        else:
            def pages_fn(self=self, m=m, s=start_page, c=n):
                return self._search_block_for(m).pages().slice_pages(s, c)
            # exact slice occupancy: entries fill pages densely in build
            # order, so page p holds min(E, total_entries - p*E) entries —
            # the batcher subtracts this from kernel counts when a sliced
            # job is pruned, and an estimate would corrupt the metrics
            E = hdr["entries_per_page"]
            n_entries = sum(
                max(0, min(E, hdr["n_entries"] - p * E))
                for p in range(start_page, start_page + n)
            )
        return ScanJob(
            key=(m.block_id, start_page, n),
            pages_fn=pages_fn, header=hdr, n_pages=n, n_entries=n_entries,
            geometry=(hdr["entries_per_page"], hdr["kv_per_entry"]),
            meta=m,
        )

    def search(self, tenant: str, req: tempopb.SearchRequest,
               results: SearchResults | None = None) -> SearchResults:
        """Search all (time-pruned) blocks of a tenant through the batched
        device engine — few kernel dispatches for many blocks, sharded
        over the mesh when one is configured — early-stopping at the
        result limit. Blocks without a search container fall back to the
        trace-block proto scan (reference backend_block.go:159-209)."""
        from tempo_tpu.backend.raw import DoesNotExist
        from tempo_tpu.search import query_stats

        results = results or SearchResults.for_request(req)
        self._ensure_mesh()
        qs = query_stats.begin(tenant, req)
        with obs.query_seconds.time(op="search"), \
                tracing.start_span("tempodb.Search", tenant=tenant) as span, \
                query_stats.activate(qs):
            # the job list is a function of the blocklist alone (time
            # pruning happens in the batcher's memoized header prune, so
            # stale-window blocks cost a cached skip, not staging): cache
            # it per (tenant, blocklist epoch) — rebuilding 10K ScanJobs
            # per query was a measured ~70 ms of pure host overhead
            epoch = self.blocklist.epoch()
            with self._search_lock:
                hit = self._jobs_cache.get(tenant)
            if hit is not None and hit[0] == epoch:
                jobs, fallback = hit[1], hit[2]
                if fallback:
                    # a DoesNotExist may have been transient (read-after-
                    # write lag): re-probe the few fallback blocks so one
                    # flake doesn't pin them to the slow path all epoch
                    promoted, still = [], []
                    for m in fallback:
                        try:
                            promoted.append(self._scan_job(m))
                        except DoesNotExist:
                            still.append(m)
                    if promoted:
                        jobs = jobs + promoted
                        fallback = still
                        with self._search_lock:
                            self._jobs_cache[tenant] = (epoch, jobs, fallback)
            else:
                jobs, fallback = [], []
                for m in self.blocklist.metas(tenant):
                    try:
                        jobs.append(self._scan_job(m))
                    except DoesNotExist:
                        fallback.append(m)  # no search container
                with self._search_lock:
                    self._jobs_cache[tenant] = (epoch, jobs, fallback)
            # len(jobs) in the plan key: fallback promotion grows the job
            # list within an epoch and the memoized plan must not drop it
            self.batcher.search(jobs, req, results,
                                plan_key=(tenant, epoch, len(jobs)))
            if fallback and not results.complete:
                # container-less blocks have no header rollup to prune on
                # — apply the meta time filter here
                live = [m for m in fallback
                        if self._include_block(m, "", "", req.start, req.end)]
                results.metrics.skipped_blocks += len(fallback) - len(live)
                if qs is not None and len(fallback) > len(live):
                    qs.add_skip("time_range", len(fallback) - len(live))
                if live:
                    self._fallback_search(live, req, results)
            span.set_attributes(
                inspected_traces=results.metrics.inspected_traces,
                inspected_blocks=results.metrics.inspected_blocks,
                skipped_blocks=results.metrics.skipped_blocks,
                fallback_blocks=len(fallback))
            if qs is not None:
                self._finalize_query_stats(qs, req, results)
        obs.search_inspected.inc(results.metrics.inspected_traces, tenant=tenant)
        return results

    @staticmethod
    def _finalize_query_stats(qs, req, results) -> None:
        """Close the per-query record and surface it on the response:
        the device-seconds / device-bytes totals ALWAYS ride the
        SearchMetrics proto (they cross the frontend/querier process
        boundary and sum in the frontend merge); the full JSON
        breakdown rides only under the explain opt-in. finish() also
        publishes to the registry: per-tenant counters, the
        /debug/querystats ring, and the slow-query log."""
        import json as _json

        d = qs.finish()
        m = results.metrics
        m.device_seconds += d["device_seconds"]
        m.inspected_bytes_device += int(qs.bytes_device)
        if getattr(req, "explain", False):
            m.query_stats_json = _json.dumps(d, separators=(",", ":"),
                                             sort_keys=True)

    def _fallback_search(self, metas: list[BlockMeta], req,
                         results: SearchResults) -> None:
        """Whole-block trace proto scan for blocks lacking search data:
        decode every object and evaluate the request against the full
        proto (reference encoding/v2/backend_block.go:159-209 +
        pkg/model/trace/matches.go:33-184). Always whole-block: search
        page ranges address the container's page space, not this one."""
        from tempo_tpu.model.matches import matches as proto_matches
        from tempo_tpu.model.matches import trace_search_metadata
        from tempo_tpu.search import query_stats

        qs = query_stats.current()
        t0 = time.perf_counter()
        try:
            for m in metas:
                block = BackendBlock(self.backend, m)
                codec = codec_for(m.data_encoding)
                obs.fallback_scans.inc(tenant=m.tenant_id)
                results.metrics.inspected_blocks += 1
                nbytes = block.bytes_in_pages(0, None)
                results.metrics.inspected_bytes += nbytes
                if qs is not None:
                    # whole-block proto decode: pure HOST work
                    qs.add_inspected(blocks=1, nbytes=nbytes,
                                     placement="host")
                for oid, obj in block.iter_objects():
                    results.metrics.inspected_traces += 1
                    trace = codec.prepare_for_read(obj)
                    if proto_matches(trace, req):
                        results.add(trace_search_metadata(oid, trace))
                    if results.complete:
                        return
        finally:
            if qs is not None:
                qs.add_stage("fallback_scan", time.perf_counter() - t0)

    def search_block(self, req: tempopb.SearchBlockRequest) -> SearchResults:
        """One search job (the SearchBlockRequest protocol unit). The block
        meta travels in the request, as in the reference querier
        (internalSearchBlock rebuilding BlockMeta from params); start_page/
        pages_to_search scope the job to a page range of the search
        container (reference searchsharding.go page math). Runs through
        the batcher so repeated jobs hit the staged cache and shard over
        the mesh."""
        meta = BlockMeta(
            tenant_id=req.tenant_id, block_id=req.block_id,
            encoding=req.encoding or "zstd", version=req.version or "vT1",
            data_encoding=req.data_encoding or "v2",
            start_time=req.start_time, end_time=req.end_time,
        )
        from tempo_tpu.backend.raw import DoesNotExist
        from tempo_tpu.search import query_stats

        results = SearchResults.for_request(req.search_req)
        self._ensure_mesh()
        qs = query_stats.begin(req.tenant_id, req.search_req)
        with query_stats.activate(qs):
            start = req.start_page
            count = req.pages_to_search or None
            try:
                job = self._scan_job(meta, start, count)
            except DoesNotExist:
                # No search container. Page ranges address CONTAINER
                # pages, a different page space from trace-block pages,
                # so a range is meaningless here: the start_page==0 job
                # scans the whole trace block once; sibling range jobs
                # contribute nothing (coverage stays exactly-once across
                # the job set).
                sr = req.search_req
                if start == 0:
                    if self._include_block(meta, "", "", sr.start, sr.end):
                        self._fallback_search([meta], sr, results)
                    else:
                        results.metrics.skipped_blocks += 1
                        if qs is not None:
                            qs.add_skip("time_range")
                if qs is not None:
                    self._finalize_query_stats(qs, req.search_req, results)
                return results
            if job.n_pages > 0:
                self.batcher.search([job], req.search_req, results)
            if qs is not None:
                self._finalize_query_stats(qs, req.search_req, results)
        return results

    def search_blocks(self, breq: tempopb.SearchBlocksRequest) -> SearchResults:
        """A batched job request (many page-range jobs, one kernel
        dispatch per geometry group) — the TPU-native protocol unit the
        frontend emits. Jobs whose blocks lack a search container run the
        proto fallback scan after the batched pass.

        The ScanJob list and the batcher's group plan are memoized on the
        request's job signature: the frontend re-sends the same job set
        every query over a stable blocklist, and rebuilding + re-sorting
        10K jobs per request is the kind of O(blocks) host cost the north
        star forbids (VERDICT r3 #1)."""
        from tempo_tpu.search import query_stats

        results = SearchResults.for_request(breq.search_req)
        self._ensure_mesh()
        qs = query_stats.begin(breq.tenant_id, breq.search_req)
        with query_stats.activate(qs):
            self._search_blocks_impl(breq, results, qs)
            if qs is not None:
                self._finalize_query_stats(qs, breq.search_req, results)
        return results

    def _search_blocks_impl(self, breq, results, qs) -> None:
        from tempo_tpu.backend.raw import DoesNotExist

        # full-fidelity key (every job field that shapes the ScanJob) used
        # AS the map key: a bare hash() would let a collision or an
        # encoding/version-only difference silently serve another
        # request's jobs; tuple equality removes both
        sig = (breq.tenant_id,
               tuple((j.block_id, j.start_page, j.pages_to_search,
                      j.encoding, j.version, j.data_encoding)
                     for j in breq.jobs))
        epoch = self.blocklist.epoch()
        hit = self._breq_jobs_cache.get(sig)
        if hit is not None and hit[0] == epoch:
            jobs, fallback, missing, groups = hit[1], hit[2], hit[3], hit[4]
            if fallback or missing:
                # a DoesNotExist may have been transient (read-after-write
                # lag): re-probe so one flake doesn't pin a block to the
                # slow proto scan — or a dropped page-range job to
                # nothing — for the whole epoch (mirrors search()'s
                # fallback promotion)
                promoted = []
                still_fb, still_miss = [], []
                for meta in fallback:
                    try:
                        promoted.append(self._scan_job(meta))
                    except DoesNotExist:
                        still_fb.append(meta)
                for meta, sp, pp in missing:
                    try:
                        job = self._scan_job(meta, sp, pp or None)
                        if job.n_pages > 0:
                            promoted.append(job)
                    except DoesNotExist:
                        still_miss.append((meta, sp, pp))
                if promoted:
                    jobs = jobs + promoted
                    fallback, missing = still_fb, still_miss
                    groups = self.batcher.plan(jobs)
                    self._breq_jobs_cache.put(
                        sig, (epoch, jobs, fallback, missing, groups))
        else:
            jobs, fallback, missing = [], [], []
            for j in breq.jobs:
                meta = BlockMeta(
                    tenant_id=breq.tenant_id, block_id=j.block_id,
                    encoding=j.encoding or "zstd", version=j.version or "vT1",
                    data_encoding=j.data_encoding or "v2",
                    start_time=j.start_time, end_time=j.end_time,
                )
                try:
                    job = self._scan_job(meta, j.start_page,
                                         j.pages_to_search or None)
                    # zero-page jobs (stale meta, start_page past the
                    # container) would stage an empty batch — drop them, as
                    # search_block does
                    if job.n_pages > 0:
                        jobs.append(job)
                except DoesNotExist:
                    # container missing: only the 0-start job scans (whole
                    # trace block, its own page space) — see search_block;
                    # range jobs are remembered for promotion, not lost
                    if j.start_page == 0:
                        fallback.append(meta)
                    else:
                        missing.append((meta, j.start_page,
                                        j.pages_to_search))
            # the group plan is a pure function of the job list — cached
            # WITH it, so the per-query batcher path neither re-sorts 10K
            # jobs nor hashes a plan key
            groups = self.batcher.plan(jobs)
            self._breq_jobs_cache.put(
                sig, (epoch, jobs, fallback, missing, groups))
        self.batcher.search(jobs, breq.search_req, results, groups=groups)
        # container-less blocks have no header rollup: apply the meta
        # window carried in the job before paying a whole-block proto
        # decode (same gate as search(); the frontend no longer
        # pre-filters metas by window)
        sr = breq.search_req
        for meta in fallback:
            if results.complete:
                break
            if not self._include_block(meta, "", "", sr.start, sr.end):
                results.metrics.skipped_blocks += 1
                if qs is not None:
                    qs.add_skip("time_range")
                continue
            self._fallback_search([meta], sr, results)

    # ------------------------------------------------------------------
    # Compactor

    def compact_tenant_once(self, tenant: str, now_s: int | None = None) -> BlockMeta | None:
        from tempo_tpu.observability.ingest_telemetry import TELEMETRY

        now_s = int(time.time()) if now_s is None else now_s
        metas = self.blocklist.metas(tenant)
        # one grouping pass serves both the job pick and the backlog
        # gauge — _groups is O(blocks) and this runs per tenant per tick
        groups = self.selector._groups(metas, now_s)  # noqa: SLF001
        inputs = self.selector.blocks_to_compact(metas, now_s,
                                                 groups=groups)
        if TELEMETRY.enabled:
            # input backlog BEFORE the run: bytes sitting in compactable
            # groups — a gauge that keeps climbing means the compactor
            # loop can't keep up with the write rate
            n_blocks, n_bytes = self.selector.outstanding(metas, now_s,
                                                          groups=groups)
            TELEMETRY.record_compaction_backlog(tenant, n_bytes, n_blocks)
        if not inputs:
            return None
        t0 = time.perf_counter()
        with tracing.start_span("tempodb.Compact", tenant=tenant) as span:
            new_meta = compact_blocks(
                self.backend, tenant, inputs,
                page_size=self.cfg.block_page_size,
                search_geometry=self.cfg.search_geometry,
                search_encoding=self.cfg.search_encoding,
                flush_size=self.cfg.compaction_flush_bytes)
            span.set_attributes(inputs=len(inputs),
                                input_bytes=sum(m.size for m in inputs),
                                out_block=new_meta.block_id)
        if TELEMETRY.enabled:
            TELEMETRY.record_compaction_run(time.perf_counter() - t0)
        obs.compactions.inc(tenant=tenant)
        from tempo_tpu.backend.types import CompactedBlockMeta

        self.blocklist.update(
            tenant, add=[new_meta], remove=inputs,
            add_compacted=[CompactedBlockMeta.from_meta(m) for m in inputs],
        )
        return new_meta

    def retain_tenant(self, tenant: str, now_s: int | None = None) -> tuple[int, int]:
        now_s = int(time.time()) if now_s is None else now_s
        return apply_retention(
            self.backend, self.blocklist, tenant, now_s,
            retention_s=self.cfg.retention_s,
            compacted_retention_s=self.cfg.compacted_retention_s,
        )
