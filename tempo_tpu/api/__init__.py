from .params import (
    build_search_request,
    parse_search_request,
    parse_trace_by_id_params,
)
from .http import HTTPApi, serve_http
from .grpc_service import (
    make_grpc_server,
    PusherClient,
    QuerierClient,
    OTLP_EXPORT_METHOD,
)

__all__ = [
    "build_search_request", "parse_search_request",
    "parse_trace_by_id_params", "HTTPApi", "serve_http",
    "make_grpc_server", "PusherClient", "QuerierClient",
    "OTLP_EXPORT_METHOD",
]
