"""Jaeger ingest receivers: thrift UDP agent + collector HTTP.

Role-equivalent to the reference's jaeger receiver (embedded
otel-collector factory, modules/distributor/receiver/shim.go:95-138):

  - UDP agent — jaeger clients emit ``emitBatch(Batch)`` oneway thrift
    messages, compact protocol on :6831 / binary on :6832 (both decoded
    here by protocol sniffing).
  - Collector HTTP — ``POST /api/traces`` with a TBinaryProtocol-encoded
    Batch body (jaeger collector :14268 contract); routed from api/http.

Translation follows the OTel jaeger→OTLP conventions: Process.serviceName
→ resource ``service.name``, tags → typed attributes, logs → events,
CHILD_OF reference / parentSpanId → parent_span_id, ``span.kind`` tag →
Span.kind, timestamps µs → ns.

jaeger.thrift field ids (the schema is interpreted here, over the generic
codec in thriftproto.py):
  Tag{1:key 2:vType 3:vStr 4:vDouble 5:vBool 6:vLong 7:vBinary}
  Log{1:timestamp 2:fields}          SpanRef{1:refType 2:idLow 3:idHigh 4:spanId}
  Span{1:traceIdLow 2:traceIdHigh 3:spanId 4:parentSpanId 5:operationName
       6:references 7:flags 8:startTime 9:duration 10:tags 11:logs}
  Process{1:serviceName 2:tags}      Batch{1:process 2:spans}
"""

from __future__ import annotations

import socket
import struct
import threading

from tempo_tpu import tempopb
from tempo_tpu.observability.log import get_logger

from . import thriftproto as tp

_KIND_MAP = {
    "client": tempopb.Span.SPAN_KIND_CLIENT,
    "server": tempopb.Span.SPAN_KIND_SERVER,
    "producer": tempopb.Span.SPAN_KIND_PRODUCER,
    "consumer": tempopb.Span.SPAN_KIND_CONSUMER,
    "internal": tempopb.Span.SPAN_KIND_INTERNAL,
}

REF_CHILD_OF = 0


def _i64_bytes(v: int) -> bytes:
    # varints can decode to values outside i64 range; 8-byte-truncate
    # rather than let struct.error escape the receiver's decode guards
    return struct.pack(">Q", (v or 0) & 0xFFFFFFFFFFFFFFFF)


def _trace_id(low: int, high: int) -> bytes:
    return _i64_bytes(high) + _i64_bytes(low)


def _tag_value(tag: dict) -> "tempopb.AnyValue":
    av = tempopb.AnyValue()
    if 3 in tag:
        av.string_value = bytes(tag[3]).decode("utf-8", "replace")
    elif 4 in tag:
        av.double_value = float(tag[4])
    elif 5 in tag:
        av.bool_value = bool(tag[5])
    elif 6 in tag:
        av.int_value = int(tag[6])
    elif 7 in tag:
        av.bytes_value = bytes(tag[7])
    return av


def batch_to_resource_spans(batch: dict) -> "tempopb.ResourceSpans":
    """One decoded jaeger Batch struct → one OTLP ResourceSpans."""
    rs = tempopb.ResourceSpans()
    process = batch.get(1) or {}
    svc = process.get(1)
    kv = rs.resource.attributes.add()
    kv.key = "service.name"
    kv.value.string_value = (bytes(svc).decode("utf-8", "replace")
                             if svc else "unknown")
    for tag in process.get(2) or []:
        kv = rs.resource.attributes.add()
        kv.key = bytes(tag.get(1, b"")).decode("utf-8", "replace")
        kv.value.CopyFrom(_tag_value(tag))
    ss = rs.scope_spans.add()
    ss.scope.name = "jaeger-receiver"

    for js in batch.get(2) or []:
        s = ss.spans.add()
        s.trace_id = _trace_id(js.get(1, 0), js.get(2, 0))
        s.span_id = _i64_bytes(js.get(3, 0))
        s.name = bytes(js.get(5, b"")).decode("utf-8", "replace")
        start_us = js.get(8, 0)
        s.start_time_unix_nano = start_us * 1000
        s.end_time_unix_nano = (start_us + js.get(9, 0)) * 1000
        parent = js.get(4, 0)
        if parent:
            s.parent_span_id = _i64_bytes(parent)
        for ref in js.get(6) or []:
            if ref.get(1, REF_CHILD_OF) == REF_CHILD_OF and not parent:
                s.parent_span_id = _i64_bytes(ref.get(4, 0))
                parent = ref.get(4, 0)
            else:
                link = s.links.add()
                link.trace_id = _trace_id(ref.get(2, 0), ref.get(3, 0))
                link.span_id = _i64_bytes(ref.get(4, 0))
        for tag in js.get(10) or []:
            key = bytes(tag.get(1, b"")).decode("utf-8", "replace")
            if key == "span.kind" and 3 in tag:
                s.kind = _KIND_MAP.get(
                    bytes(tag[3]).decode("utf-8", "replace").lower(),
                    tempopb.Span.SPAN_KIND_UNSPECIFIED)
                continue
            if key == "error" and tag.get(5) is True:
                s.status.code = 2  # STATUS_CODE_ERROR
            kv = s.attributes.add()
            kv.key = key
            kv.value.CopyFrom(_tag_value(tag))
        for log in js.get(11) or []:
            ev = s.events.add()
            ev.time_unix_nano = log.get(1, 0) * 1000
            name = "log"
            for f in log.get(2) or []:
                key = bytes(f.get(1, b"")).decode("utf-8", "replace")
                if key in ("event", "message") and 3 in f:
                    name = bytes(f[3]).decode("utf-8", "replace")
                    continue
                kv = ev.attributes.add()
                kv.key = key
                kv.value.CopyFrom(_tag_value(f))
            ev.name = name
    return rs


def jaeger_thrift_http_to_batches(body: bytes) -> list:
    """Collector contract: body is ONE TBinaryProtocol Batch struct."""
    batch = tp.decode_struct(body, "binary")
    if 2 not in batch and 1 not in batch:
        raise ValueError("thrift body is not a jaeger Batch")
    return [batch_to_resource_spans(batch)]


def decode_agent_datagram(data: bytes) -> list:
    """One UDP datagram = one ``emitBatch`` message (compact or binary).
    Returns list[ResourceSpans]."""
    name, _, _, args = tp.decode_message(data)
    if name != "emitBatch":
        raise ValueError(f"unexpected agent rpc {name!r}")
    batch = args.get(1)
    if not isinstance(batch, dict):
        raise ValueError("emitBatch args carry no Batch")
    return [batch_to_resource_spans(batch)]


class JaegerAgentUDP:
    """The jaeger-agent ingest socket: a daemon thread decoding
    emitBatch datagrams into ``push(tenant, batches)``."""

    def __init__(self, push, host: str = "0.0.0.0", port: int = 6831,
                 tenant: str | None = None):
        from .params import DEFAULT_TENANT

        self.push = push
        self.tenant = tenant or DEFAULT_TENANT
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind((host, port))
        self.port = self.sock.getsockname()[1]
        self.accepted = 0
        self.rejected = 0
        self._log = get_logger("tempo_tpu.jaeger")
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"jaeger-agent-udp-{self.port}")
        self._thread.start()

    def _run(self) -> None:
        self.sock.settimeout(0.5)
        while not self._stop.is_set():
            try:
                data, _ = self.sock.recvfrom(65535)
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                batches = decode_agent_datagram(data)
            except Exception as e:  # noqa: BLE001 — a bad datagram must
                # never kill the receiver thread (decode guards cover the
                # known shapes; anything else is still just one datagram)
                self.rejected += 1
                self._log.warning("jaeger agent: dropped datagram: %s", e)
                continue
            try:
                self.push(self.tenant, batches)
                self.accepted += 1
            except Exception as e:  # noqa: BLE001 — ingest limits etc.
                self.rejected += 1
                self._log.warning("jaeger agent: push failed: %s", e)

    def close(self) -> None:
        self._stop.set()
        self.sock.close()
        self._thread.join(timeout=2)
