"""HTTP API: the external read surface + operational endpoints.

Role-equivalent to the reference's HTTP routes (pkg/api/http.go:49-55,
cmd/tempo/app/app.go:380-511): /api/traces/{id}, /api/search,
/api/search/tags, /api/search/tag/{name}/values, /api/echo, plus /ready,
/metrics, /status, /flush and /shutdown. Multi-tenant via X-Scope-OrgID
(fake-auth default tenant when absent, reference fake_auth.go). JSON
bodies via protobuf json_format.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlparse, parse_qs

from google.protobuf import json_format

from tempo_tpu.modules.distributor import RateLimited
from tempo_tpu.modules.queue import TooManyRequests
from tempo_tpu.utils.ids import hex_to_trace_id
from .params import (
    DEFAULT_TENANT,
    HEADER_TENANT,
    PATH_ECHO,
    PATH_SEARCH,
    PATH_SEARCH_STREAM,
    PATH_SEARCH_TAGS,
    PATH_SEARCH_TAG_VALUES,
    PATH_TAIL,
    PATH_TRACES,
    InvalidArgument,
    parse_search_request,
    parse_trace_by_id_params,
)


def _hex_trace_id(s: str) -> bytes:
    """URL trace ids are client input: bad hex is a 400, not a 500."""
    try:
        return hex_to_trace_id(s)
    except ValueError as e:
        raise InvalidArgument(str(e)) from None


class TextBody(str):
    """A text response body carrying its own Content-Type. A str
    subclass, so handle() callers that compare/parse the body are
    unaffected — only the wire serializer (_reply) looks at the
    attribute. /metrics uses it: Prometheus scrapers key the parser off
    `text/plain; version=0.0.4` vs the OpenMetrics media type."""

    __slots__ = ("content_type",)

    def __new__(cls, s: str, content_type: str):
        self = super().__new__(cls, s)
        self.content_type = content_type
        return self


class SSEBody:
    """A streaming response body: an iterator of pre-rendered
    Server-Sent-Event frames. Unlike TextBody this is NOT a str — the
    whole point is that the wire serializer must not buffer it. _reply
    writes each frame as it arrives (Content-Type: text/event-stream, no
    Content-Length, flush per event); handle() callers in tests iterate
    `.events` directly. close() closes the underlying generator so its
    `finally` blocks run (tail routes unsubscribe there) even when the
    client hangs up mid-stream."""

    content_type = "text/event-stream"

    def __init__(self, events):
        self.events = events

    def close(self) -> None:
        close = getattr(self.events, "close", None)
        if close is not None:
            close()


def _sse_event(name: str, doc: dict) -> str:
    """One SSE frame. data: is a single line — json.dumps never emits
    raw newlines — so the event ends at the blank line per the spec."""
    return f"event: {name}\ndata: {json.dumps(doc)}\n\n"


def _int_param(query: dict, key: str, default: int) -> int:
    """Non-negative int query param with a default (the /debug routes'
    `recent` knob); garbage falls back rather than 500s a debug page."""
    try:
        return max(0, int(query.get(key, default)))
    except (TypeError, ValueError):
        return default


def _route_template(path: str) -> str:
    """Collapse variable path segments so span names stay low-cardinality
    (OTel convention: name by route, real path in http.target)."""
    parts = path.split("/")
    if len(parts) >= 4 and parts[1] == "api" and parts[2] == "traces":
        parts[3] = "{id}"
    elif (len(parts) >= 5 and parts[1] == "api" and parts[2] == "search"
          and parts[3] == "tag"):
        parts[4] = "{tag}"
    elif len(parts) >= 5 and parts[1] == "jaeger" and parts[3] == "traces":
        parts[4] = "{id}"
    elif len(parts) >= 5 and parts[1] == "jaeger" and parts[3] == "services":
        parts[4] = "{service}"
    return "/".join(parts)


class HTTPApi:
    """Routes HTTP requests onto an App (modules/app.py)."""

    def __init__(self, app, multitenancy: bool = True,
                 debug_endpoints: bool = True):
        self.app = app
        self.multitenancy = multitenancy
        # /debug/* dumps full stacks (file paths, internals) to anyone
        # who can reach the port; deployments keep it off the public
        # port unless server.debug_endpoints says otherwise (ADVICE r4).
        # Library/test default stays on — there is no network exposure
        # until someone serves this object.
        self.debug_endpoints = debug_endpoints

    def tenant(self, headers) -> str:
        from .params import validate_tenant

        if not self.multitenancy:
            return DEFAULT_TENANT
        # ValueError → the handle() 400 path: a tenant id is the one
        # header that reaches filesystem joins
        return validate_tenant(headers.get(HEADER_TENANT) or DEFAULT_TENANT)

    def handle(self, method: str, path: str, query: dict, headers,
               body: bytes = b"") -> tuple[int, dict | str]:
        from tempo_tpu.observability import tracing

        parent = tracing.extract_traceparent(headers)
        with tracing.start_span(f"HTTP {method} {_route_template(path)}",
                                kind=tracing.KIND_SERVER,
                                parent=parent) as span:
            span.set_attribute("http.target", path)
            try:
                if method == "POST" and path in ("/v1/traces", "/api/v2/spans",
                                                 "/api/traces"):
                    code, resp = self._ingest(path, body, headers)
                else:
                    code, resp = self._route(method, path, query, headers)
            except InvalidArgument as e:
                # ONLY the dedicated client-data type maps to 400; a
                # plain ValueError (corrupt WAL entry, object framing)
                # is server-side and falls through to the 500 handler —
                # same split as the gRPC layer (ADVICE r4)
                code, resp = 400, {"error": str(e)}
            except TooManyRequests as e:
                # tenant's fair-queue is full (reference frontend v1
                # max-outstanding → HTTP 429)
                code, resp = 429, {"error": f"too many outstanding requests: {e}"}
            except RateLimited as e:
                # ingest pushback (rate / live-traces / trace-bytes
                # limits) is retryable tenant backpressure — the
                # reference answers ResourceExhausted/FailedPrecondition,
                # i.e. 429 on the HTTP write path, never 500
                code, resp = 429, {"error": str(e)}
            except Exception as e:  # noqa: BLE001 — surface as 500
                span.record_exception(e)
                code, resp = 500, {"error": f"{type(e).__name__}: {e}"}
            span.set_attribute("http.status_code", code)
            if code >= 500:
                span.set_status(tracing.STATUS_ERROR)
            return code, resp

    def _ingest(self, path: str, body: bytes, headers):
        """HTTP ingest receivers: OTLP/HTTP protobuf and Zipkin v2 JSON
        (api/receivers.py). Malformed payloads are CLIENT errors — a 500
        would make exporters retry their own bad bodies forever."""
        import json as _json

        from google.protobuf.message import DecodeError

        from .jaeger import jaeger_thrift_http_to_batches
        from .receivers import otlp_http_to_batches, zipkin_json_to_batches
        from .thriftproto import ThriftError

        tenant = self.tenant(headers)
        try:
            if path == "/v1/traces":
                batches = otlp_http_to_batches(body)
            elif path == "/api/traces":
                # jaeger collector contract: thrift-binary Batch body
                batches = jaeger_thrift_http_to_batches(body)
            else:
                batches = zipkin_json_to_batches(body)
        except (DecodeError, KeyError, TypeError, AttributeError,
                ThriftError, ValueError, _json.JSONDecodeError) as e:
            # ValueError here is a DECODER error (bad hex id, non-array
            # zipkin body) — client payload, unlike the serving path
            # where bare ValueError means server-side corruption
            return 400, {"error": f"malformed payload: {type(e).__name__}: {e}"}
        if batches:
            self.app.push(tenant, batches)
        return 200, {"accepted_batches": len(batches)}

    def _route(self, method, path, query, headers):
        tenant = self.tenant(headers)
        if path == PATH_ECHO:
            return 200, "echo"
        if path == "/ready":
            return (200, "ready") if self.app.ready() else (503, "not ready")
        if path == "/metrics":
            from tempo_tpu.observability.metrics import (
                OPENMETRICS_CONTENT_TYPE, PROM_CONTENT_TYPE, REGISTRY)

            # OpenMetrics negotiation: scrapers that Accept the
            # openmetrics media type get exemplars (histogram buckets →
            # self-trace ids); everyone else gets the classic 0.0.4 text
            # format, byte-identical to before
            accept = (headers.get("Accept") or "") \
                if hasattr(headers, "get") else ""
            om = "application/openmetrics-text" in accept
            return 200, TextBody(
                REGISTRY.expose(openmetrics=om),
                OPENMETRICS_CONTENT_TYPE if om else PROM_CONTENT_TYPE)
        if path == "/status" or path.startswith("/status/"):
            return 200, self._status(path, query)
        if path == "/flush":
            completed = self.app.flush_tick(force=True)
            return 200, {"completed_blocks": len(completed)}
        if path.startswith("/debug/"):
            # ONE gate + ONE registry for every /debug route: a route
            # registered in DEBUG_ROUTES is automatically covered by the
            # server.debug_endpoints gate and by the tier-1 contract
            # test (tests/test_debug_routes.py — every route must answer
            # valid JSON when enabled and 404 when gated off)
            if not self.debug_endpoints:
                return 404, {"error": "debug endpoints disabled "
                                      "(server.debug_endpoints: true "
                                      "enables)"}
            handler = DEBUG_ROUTES.get(path)
            if handler is not None:
                return handler(self, query)
        if path == "/shutdown":
            threading.Thread(target=self.app.shutdown, daemon=True).start()
            return 200, "shutting down"

        # content negotiation (reference querier/frontend internal proto
        # marshalling, frontend.go:121-127): a client that Accepts
        # application/protobuf gets the wire message, not its JSON form
        accept = (headers.get("Accept") or "") if hasattr(headers, "get") \
            else ""
        want_proto = "application/protobuf" in accept

        if path.startswith(PATH_TRACES + "/"):
            trace_id = _hex_trace_id(path[len(PATH_TRACES) + 1:])
            mode, bs, be = parse_trace_by_id_params(query)
            with self._request_deadline(headers):
                resp = self.app.find_trace(tenant, trace_id)
            if not resp.trace.batches:
                return 404, {"error": "trace not found"}
            code = 206 if resp.metrics.failed_blocks else 200
            if want_proto:
                return code, resp.trace.SerializeToString()
            return code, json_format.MessageToDict(resp.trace)
        if path == PATH_SEARCH_STREAM:
            return self._search_stream(tenant, query, headers)
        if path == PATH_TAIL:
            return self._tail_stream(tenant, query)
        if path == PATH_SEARCH:
            req = self._parse_search(query, headers)
            # request deadline: X-Tempo-Timeout-S header, else the
            # search_request_timeout_s config default — propagates
            # http → frontend → querier → TempoDB via the worker
            # pool's contextvars copy (robustness/deadline.py), so
            # sharded sub-queries stop queueing behind a dead device
            with self._request_deadline(headers):
                resp = self.app.search(tenant, req)
            # tolerated block failures / deadline-clipped answers =
            # partial (reference frontend.go:144-146 semantics,
            # extended to search)
            code = 206 if (resp.metrics.failed_blocks
                           or resp.metrics.partial) else 200
            if want_proto:
                return code, resp.SerializeToString()
            doc = json_format.MessageToDict(resp)
            if resp.metrics.query_stats_json:
                # inline the breakdown as a real JSON object instead of
                # an escaped string riding the metrics message
                try:
                    doc["queryStats"] = json.loads(
                        resp.metrics.query_stats_json)
                    doc.get("metrics", {}).pop("queryStatsJson", None)
                except ValueError:
                    pass
            if resp.metrics.agg_json:
                # the ?agg= aggregate, inlined as a real JSON object
                # like queryStats above
                try:
                    doc["aggregates"] = json.loads(resp.metrics.agg_json)
                    doc.get("metrics", {}).pop("aggJson", None)
                except ValueError:
                    pass
            return code, doc
        if path == PATH_SEARCH_TAGS:
            resp = self.app.queriers[0].search_tags(tenant)
            return 200, json_format.MessageToDict(resp)
        if path.startswith(PATH_SEARCH_TAG_VALUES + "/"):
            rest = path[len(PATH_SEARCH_TAG_VALUES) + 1:]
            if rest.endswith("/values"):
                tag = rest[: -len("/values")]
                if not tag:
                    return 400, {"error": "empty tag name"}
                resp = self.app.queriers[0].search_tag_values(tenant, tag)
                return 200, json_format.MessageToDict(resp)
        if path.startswith("/jaeger/api/"):
            return self._jaeger_query(tenant, path[len("/jaeger/api"):], query)
        return 404, {"error": f"no route {path}"}

    def _jaeger_query(self, tenant, sub, query):
        """Jaeger query-service JSON API (cmd/tempo-query role)."""
        from .jaeger_query import JaegerQueryBridge

        bridge = JaegerQueryBridge(self.app)
        if sub == "/services":
            return 200, bridge.services(tenant)
        if sub.startswith("/services/") and sub.endswith("/operations"):
            svc = sub[len("/services/"): -len("/operations")]
            return 200, bridge.operations(tenant, svc)
        if sub == "/operations":
            return 200, bridge.operations(tenant, query.get("service", ""))
        if sub == "/dependencies":
            return 200, bridge.dependencies()
        if sub == "/traces":
            return 200, bridge.search(tenant, query)
        if sub.startswith("/traces/"):
            data = bridge.trace_by_id(tenant,
                                      _hex_trace_id(sub[len("/traces/"):]))
            if data is None:
                return 404, {"errors": [{"msg": "trace not found"}]}
            return 200, data
        return 404, {"error": f"no jaeger route {sub}"}

    def _request_deadline(self, headers):
        """The query routes' request deadline: the X-Tempo-Timeout-S
        header wins (bad values ignored — a garbage header must not 400
        a query that never asked for a deadline), else the
        search_request_timeout_s config default; <= 0 / absent = no
        deadline, the historical unbounded behavior."""
        from tempo_tpu.robustness import deadline as rdeadline

        timeout = None
        raw = (headers.get("X-Tempo-Timeout-S")
               if hasattr(headers, "get") else None)
        if raw:
            try:
                timeout = float(raw)
            except (TypeError, ValueError):
                timeout = None
        if timeout is None:
            db_cfg = getattr(getattr(self.app, "cfg", None), "db", None)
            timeout = getattr(db_cfg, "search_request_timeout_s", 0.0)
        return rdeadline.start(timeout)

    # ---- streaming search + live tail (docs/search-live-tail.md) ----

    def _parse_search(self, query, headers):
        """Shared request prep for /api/search and /api/search/stream:
        parse, structural-gate check, explain opt-in."""
        req = parse_search_request(query)
        from tempo_tpu.search.structural import (STRUCTURAL,
                                                 STRUCTURAL_QUERY_TAG)

        if STRUCTURAL_QUERY_TAG in req.tags and not STRUCTURAL.enabled:
            # structural queries are gated per deployment
            # (docs/search-structural-queries.md): a clear client
            # error, not a silent legacy-scan answer
            raise InvalidArgument("structural queries disabled "
                                  "(storage.search_structural_"
                                  "enabled: true enables)")
        from tempo_tpu.search.analytics import ANALYTICS, AGG_QUERY_TAG

        if AGG_QUERY_TAG in req.tags and not ANALYTICS.enabled:
            # ?agg= is gated per deployment (docs/search-analytics.md):
            # a clear client error, not a silent plain-search answer
            # missing the aggregate the caller asked for
            raise InvalidArgument("search aggregation disabled "
                                  "(storage.search_analytics_"
                                  "enabled: true enables)")
        # explain opt-in: ?explain=1 (parse_search_request) or the
        # X-Tempo-Explain header — the response then carries the
        # full per-query execution breakdown. Same value set as the
        # query param: "X-Tempo-Explain: 0" must NOT opt in
        if hasattr(headers, "get") and \
                (headers.get("X-Tempo-Explain") or "").strip().lower() \
                in ("1", "true", "yes"):
            req.explain = True
        return req

    def _search_stream(self, tenant, query, headers):
        """Progressive search: the same fan-out as /api/search, but each
        sub-response merge that grew the result set streams a `result`
        snapshot event immediately — hot-tier/ingester legs answer in
        milliseconds while backend block groups are still scanning. The
        final `done` event carries the complete merged response
        (byte-equivalent to what /api/search would have returned)."""
        import contextvars
        import queue as _queue

        from tempo_tpu.observability import metrics as obs
        from tempo_tpu.observability import tracing

        req = self._parse_search(query, headers)
        q: _queue.Queue = _queue.Queue()

        # copied context: the worker's frontend/search spans parent
        # under the HTTP request span instead of starting orphan traces
        ctx = contextvars.copy_context()

        def run():
            # worker thread: contextvars are thread-local, so the
            # request deadline must be entered HERE for the frontend's
            # pool-copy propagation to pick it up
            try:
                with self._request_deadline(headers):
                    resp = self.app.search(
                        tenant, req,
                        on_progress=lambda r: q.put(("result", r)))
                q.put(("done", resp))
            except Exception as e:  # noqa: BLE001 — ship to the stream
                q.put(("error", e))

        threading.Thread(target=ctx.run, args=(run,), daemon=True,
                         name="search-stream").start()

        # the generator drains AFTER handle()'s request span closed (the
        # server writes frames as they arrive), so the streaming leg
        # gets its own span parented under the request — ended manually,
        # never made current: the consuming thread/context is not ours
        parent = tracing.current_span().context

        def events():
            obs.sse_active_streams.add(1, endpoint="search_stream",
                                       tenant=tenant)
            span = tracing.start_span("sse.search_stream", parent=parent,
                                      tenant=tenant)
            n = 0
            try:
                while True:
                    kind, payload = q.get()
                    if kind == "error":
                        obs.sse_events_streamed.inc(
                            endpoint="search_stream", tenant=tenant,
                            event="error")
                        if span.recording:
                            span.set_status(
                                tracing.STATUS_ERROR, str(payload))
                        yield _sse_event("error", {
                            "error":
                                f"{type(payload).__name__}: {payload}"})
                        return
                    doc = json_format.MessageToDict(payload)
                    obs.sse_events_streamed.inc(
                        endpoint="search_stream", tenant=tenant,
                        event=kind)
                    n += 1
                    yield _sse_event(kind, doc)
                    if kind == "done":
                        return
            finally:
                if span.recording:
                    span.set_attribute("events", n)
                span.end()
                obs.sse_active_streams.add(-1, endpoint="search_stream",
                                           tenant=tenant)

        return 200, SSEBody(events())

    def _tail_stream(self, tenant, query):
        """Live tail: a standing query at the ingest path. Every pushed
        trace that matches streams a `trace` event within the push's
        micro-batch — no poll loop against /api/search needed."""
        import time as _time

        from tempo_tpu.observability import metrics as obs
        from tempo_tpu.observability import tracing

        req = self._parse_search(query, headers={})
        sub = self.app.tail_subscribe(tenant, req)
        if sub is None:
            from tempo_tpu.search.live_tier import LIVE_TIER

            if not LIVE_TIER.enabled:
                return 400, {"error": "live tail disabled "
                                      "(storage.search_live_tier_"
                                      "enabled: true enables)"}
            return 429, {"error": "tail subscription cap reached for "
                                  "tenant"}
        # bounded by default: an abandoned curl must not hold a
        # subscription slot forever (the cap is per tenant)
        seconds = min(_int_param(query, "seconds", 30), 3600)
        deadline = _time.monotonic() + seconds
        # streaming-leg span: same stance as _search_stream — ended
        # manually, never made current (the generator drains on the
        # server writer thread after the request span closed)
        parent = tracing.current_span().context

        def events():
            obs.sse_active_streams.add(1, endpoint="tail", tenant=tenant)
            span = tracing.start_span("sse.tail", parent=parent,
                                      tenant=tenant, seconds=seconds)
            booked = obs.sse_events_streamed
            n = 0
            try:
                booked.inc(endpoint="tail", tenant=tenant,
                           event="subscribed")
                yield _sse_event("subscribed", {"seconds": seconds})
                while True:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        booked.inc(endpoint="tail", tenant=tenant,
                                   event="done")
                        yield _sse_event("done", {"reason": "duration"})
                        return
                    metas = sub.poll(min(remaining, 1.0))
                    if not metas:
                        # SSE comment = keepalive; proxies and clients
                        # see bytes flowing on an idle tail
                        booked.inc(endpoint="tail", tenant=tenant,
                                   event="keepalive")
                        yield ": keepalive\n\n"
                        continue
                    for m in metas:
                        booked.inc(endpoint="tail", tenant=tenant,
                                   event="trace")
                        n += 1
                        yield _sse_event(
                            "trace", json_format.MessageToDict(m))
            finally:
                # runs on generator close() too — client hangup mid-
                # stream must release the tenant's subscription slot
                self.app.tail_unsubscribe(sub)
                if span.recording:
                    span.set_attribute("events", n)
                    span.set_attribute("dropped", sub.dropped)
                span.end()
                obs.sse_active_streams.add(-1, endpoint="tail",
                                           tenant=tenant)

        return 200, SSEBody(events())

    # ---- /debug/* route handlers (registered in DEBUG_ROUTES) ----

    def _debug_threads_route(self, query):
        # faulthandler-style all-thread stack dump (reference pprof
        # goroutine profile role, cmd/tempo/main.go:54-115): the
        # first tool for "this process is stuck where?"
        return 200, self._debug_threads()

    def _debug_scan_route(self, query):
        # per-stage breakdown of the last scan + cache occupancy
        db = getattr(self.app, "reader_db", None)
        if db is None:
            return 404, {"error": "no storage reader in this target"}
        return 200, db.batcher.debug_stats()

    def _debug_profile_route(self, query):
        # dispatch profiler: recent per-dispatch stage breakdowns +
        # process-lifetime aggregates (observability/profile.py)
        from tempo_tpu.observability.profile import PROFILER

        return 200, PROFILER.snapshot(
            recent=_int_param(query, "recent", 32))

    def _debug_planner_route(self, query):
        # offload planner: decision ring, cost-model rates,
        # predicted-vs-actual calibration (search/planner.py)
        from tempo_tpu.search.planner import PLANNER

        return 200, PLANNER.snapshot(
            recent=_int_param(query, "recent", 32))

    def _debug_querystats_route(self, query):
        # per-query inspector: recent queries, per-tenant
        # device-seconds/bytes aggregates, top-K by cost
        # (search/query_stats.py)
        from tempo_tpu.search.query_stats import REGISTRY

        return 200, REGISTRY.snapshot(
            recent=_int_param(query, "recent", 32))

    def _debug_faults_route(self, query):
        # robustness state: the fault-injection registry (catalog +
        # live arming) and the device circuit breaker's state machine
        # (tempo_tpu/robustness/)
        from tempo_tpu.robustness import BREAKER, FAULTS, GUARD

        return 200, {
            "faults": FAULTS.snapshot(),
            "breaker": BREAKER.snapshot(),
            "dispatch_guard": {
                "active": GUARD.active,
                "timeout_s": GUARD.timeout_s,
                "lock_timeout_s": GUARD.lock_timeout_s,
            },
        }

    def _debug_ownership_route(self, query):
        # owner-routed HBM: the placement map (group -> owner),
        # membership generation, and this process's per-group residency
        # (search/ownership.py + the batcher's staged-cache view)
        from tempo_tpu.search.ownership import OWNERSHIP

        snap = OWNERSHIP.snapshot()
        db = getattr(self.app, "reader_db", None)
        if db is not None:
            snap["residency"] = db.batcher.ownership_residency()
        return 200, snap

    def _debug_flightrecorder_route(self, query):
        # anomaly flight recorder: bounded diagnostic bundles captured
        # at breaker trips / watchdog fires / slow queries, each with
        # the offending self-trace id — resolvable in _selftrace while
        # the dogfood pipeline (selftrace_ingest_enabled) is on
        # (observability/flightrecorder.py)
        from tempo_tpu.observability.flightrecorder import RECORDER

        return 200, RECORDER.snapshot(
            recent=_int_param(query, "recent", 32))

    def _debug_ingest_route(self, query):
        # write-path telemetry: per-tenant live/unflushed/backlog state,
        # last flush/poll ages, WAL replay, slow-flush ring, canary
        # (observability/ingest_telemetry.py)
        from tempo_tpu.observability.ingest_telemetry import TELEMETRY

        return 200, TELEMETRY.debug_snapshot(app=self.app)

    def _debug_threads(self) -> str:
        """All-thread stack dump as plain text. Pure-Python equivalent of
        faulthandler.dump_traceback (which needs a real fd, not a
        response body): name each thread and format its current frame
        stack, so a hung flush/scan/stream shows exactly where it sits."""
        import sys
        import traceback

        names = {t.ident: t.name for t in threading.enumerate()}
        out = []
        for ident, frame in sorted(sys._current_frames().items()):
            out.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
            out.extend(line.rstrip()
                       for line in traceback.format_stack(frame))
        return "\n".join(out) + "\n"

    def _status(self, path, query: dict | None = None) -> dict:
        app = self.app
        if path == "/status/config":
            # reference /status/config?mode=diff|defaults (app.go:332-378)
            return self._status_config((query or {}).get("mode", ""))
        from tempo_tpu.observability.ingest_telemetry import TELEMETRY
        from tempo_tpu.observability.profile import build_info, device_status

        out = {
            "ready": app.ready(),
            # build/runtime identity (the tempo_build_info gauge's
            # labels, re-evaluated live — backend/native may have
            # initialized since the gauge was set at App init)
            "build": build_info(),
            "ring": {
                "instances": app.ring.instance_ids(),
                "healthy": app.ring.healthy_count(),
                "replication_factor": app.ring.rf,
            },
            # accelerator health at a glance: backend, device count,
            # age of the last successful dispatch — the wedge-vs-idle
            # signal bench r04/r05 lacked (never initializes a backend
            # on processes that haven't touched the device)
            "device": device_status(),
            # search freshness at a glance (the write-path twin of the
            # device block): per-tenant staleness, oldest unflushed
            # trace age, last poll age, canary verdict
            "ingest": TELEMETRY.status(),
        }
        db = getattr(app, "reader_db", None)
        if db is not None:  # targets without a storage reader (distributor)
            out["tenants"] = db.blocklist.tenants()
            out["blocks"] = {t: len(db.blocklist.metas(t))
                             for t in db.blocklist.tenants()}
        dispatcher = getattr(app, "dispatcher", None)
        if dispatcher is not None:  # query-frontend pull dispatch
            out["pull_dispatch"] = {
                "workers": dispatcher.workers(),
                "queued": dispatcher.queued(),
                "delivered": dispatcher.delivered,
                "requeued": dispatcher.requeued,
            }
        return out

    _SECRET_KEY_RE = None  # compiled lazily below

    @classmethod
    def _redact(cls, node):
        """Secrets must not leak on the tenant-facing port: any key that
        looks credential-bearing gets its whole value replaced."""
        import re

        if cls._SECRET_KEY_RE is None:
            cls._SECRET_KEY_RE = re.compile(
                r"secret|password|token|credential|authorization|headers"
                r"|access_key|account_key|sasl", re.I)
        if isinstance(node, dict):
            return {
                k: ("<redacted>" if cls._SECRET_KEY_RE.search(str(k))
                    else cls._redact(v))
                for k, v in node.items()
            }
        if isinstance(node, list):
            return [cls._redact(v) for v in node]
        return node

    def _status_config(self, mode: str) -> dict:
        """Running config as a dict (secrets redacted); mode=defaults
        shows the built-in defaults, mode=diff only the changed keys."""
        import dataclasses

        def to_dict(cfg):
            return self._redact(dataclasses.asdict(cfg))

        from tempo_tpu.modules import AppConfig

        current = to_dict(self.app.cfg)
        if mode == "defaults":
            return to_dict(AppConfig())
        if mode == "diff":
            def diff(cur, dfl):
                out = {}
                for k, cv in cur.items():
                    dv = dfl.get(k) if isinstance(dfl, dict) else None
                    if isinstance(cv, dict) and isinstance(dv, dict):
                        sub = diff(cv, dv)
                        if sub:
                            out[k] = sub
                    elif cv != dv:
                        out[k] = cv
                return out

            return diff(current, to_dict(AppConfig()))
        return current


# every /debug route: path -> handler(api, query) -> (code, body).
# Adding a route HERE is all it takes — the server.debug_endpoints gate
# in _route and the tier-1 JSON/gating contract test iterate this map.
DEBUG_ROUTES = {
    "/debug/threads": HTTPApi._debug_threads_route,
    "/debug/scan": HTTPApi._debug_scan_route,
    "/debug/profile": HTTPApi._debug_profile_route,
    "/debug/planner": HTTPApi._debug_planner_route,
    "/debug/querystats": HTTPApi._debug_querystats_route,
    "/debug/ingest": HTTPApi._debug_ingest_route,
    "/debug/faults": HTTPApi._debug_faults_route,
    "/debug/ownership": HTTPApi._debug_ownership_route,
    "/debug/flightrecorder": HTTPApi._debug_flightrecorder_route,
}


def _accepts_gzip(header: str | None) -> bool:
    """RFC 9110 Accept-Encoding: gzip only when listed with q > 0 —
    `gzip;q=0` is an explicit refusal, not a match."""
    for token in (header or "").lower().split(","):
        parts = [p.strip() for p in token.split(";")]
        if parts[0] != "gzip":
            continue
        for p in parts[1:]:
            if p.startswith("q="):
                try:
                    return float(p[2:]) > 0
                except ValueError:
                    return False
        return True
    return False


def serve_http(api: HTTPApi, host: str = "0.0.0.0", port: int = 3200):
    """Blocking stdlib server; returns the server object when used via
    threading (tests call .shutdown())."""

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — stdlib API
            u = urlparse(self.path)
            query = {k: v[0] for k, v in parse_qs(u.query).items()}
            code, body = api.handle("GET", u.path, query, self.headers)
            self._reply(code, body)

        def do_POST(self):  # noqa: N802
            u = urlparse(self.path)
            query = {k: v[0] for k, v in parse_qs(u.query).items()}
            MAX_BODY = 64 << 20  # cap hostile/streaming bodies
            if self.headers.get("Transfer-Encoding", "").lower() == "chunked":
                chunks, total = [], 0
                try:
                    while True:
                        size_line = self.rfile.readline().split(b";")[0].strip()
                        size = int(size_line, 16)
                        if size < 0:
                            raise ValueError("negative chunk size")
                        if size == 0:
                            self.rfile.readline()  # trailing CRLF
                            break
                        total += size
                        if total > MAX_BODY:
                            raise ValueError("body too large")
                        chunks.append(self.rfile.read(size))
                        self.rfile.readline()  # chunk CRLF
                except ValueError as e:
                    return self._reply(400, {"error": f"bad chunked body: {e}"})
                body = b"".join(chunks)
            else:
                length = int(self.headers.get("Content-Length", 0))
                if length > MAX_BODY:
                    # reject, never truncate: a parseable prefix would be
                    # silently accepted while the tail spans are dropped
                    return self._reply(413, {"error": "body too large"})
                body = self.rfile.read(length) if length else b""
            code, out = api.handle("POST", u.path, query, self.headers, body)
            self._reply(code, out)

        def _reply(self, code, body):
            if isinstance(body, SSEBody):
                # streaming: no Content-Length, no gzip, flush per
                # event — buffering would defeat the route's purpose
                self.send_response(code)
                self.send_header("Content-Type", body.content_type)
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self.end_headers()
                try:
                    for frame in body.events:
                        self.wfile.write(frame.encode())
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client hung up; close() below cleans up
                finally:
                    body.close()
                return
            if isinstance(body, (bytes, bytearray)):
                # negotiated protobuf (Accept: application/protobuf on
                # the query routes) — reference frontend.go:121-127
                data = bytes(body)
                ctype = "application/protobuf"
            elif isinstance(body, (dict, list)):
                data = json.dumps(body).encode()
                ctype = "application/json"
            else:
                data = str(body).encode()
                # TextBody carries its negotiated type (/metrics)
                ctype = getattr(body, "content_type", "text/plain")
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            # the body varies on negotiation headers — shared caches
            # must key on them or serve the wrong representation
            self.send_header("Vary", "Accept, Accept-Encoding")
            # response compression (reference gzips frontend responses);
            # tiny payloads skip it — the header+CPU outweighs the bytes
            if _accepts_gzip(self.headers.get("Accept-Encoding")) \
                    and len(data) >= 256:
                import gzip as _gzip

                data = _gzip.compress(data, compresslevel=5)
                self.send_header("Content-Encoding", "gzip")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, *a):  # quiet
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    return server
