"""HTTP query-param ↔ proto request round-trips.

Role-equivalent to the reference's pkg/api/http.go (path constants,
BuildSearchRequest/ParseSearchRequest etc.) — the frontend job sharder
builds sub-request URLs from these and queriers parse them back, so the
round-trip must be lossless.
"""

from __future__ import annotations

import urllib.parse

from tempo_tpu import tempopb

PATH_TRACES = "/api/traces"
PATH_SEARCH = "/api/search"
PATH_SEARCH_STREAM = "/api/search/stream"
PATH_SEARCH_TAGS = "/api/search/tags"
PATH_SEARCH_TAG_VALUES = "/api/search/tag"
PATH_TAIL = "/api/tail"
PATH_ECHO = "/api/echo"

HEADER_TENANT = "X-Scope-OrgID"
DEFAULT_TENANT = "single-tenant"

# tenant ids travel from an attacker-controllable header into object
# paths (LocalBackend: <root>/<tenant>/<block>/...), so they are
# validated at every boundary — same stance as the reference's
# weaveworks tenant rules (no separators, no relative components). The
# rule lives in utils/pathsafe so the backend's defense-in-depth check
# can never drift from this one.


class InvalidArgument(ValueError):
    """Client-data error: HTTP 400 / gRPC INVALID_ARGUMENT. A dedicated
    type so the gRPC layer can map ONLY genuine client mistakes to
    non-retryable INVALID_ARGUMENT — server-side data errors that also
    surface as ValueError (corrupt WAL entries, object framing) must
    stay INTERNAL, not be pinned on the caller (ADVICE r4)."""


def validate_tenant(tenant: str) -> str:
    """The tenant id, or InvalidArgument (HTTP 400 / gRPC
    INVALID_ARGUMENT)."""
    from tempo_tpu.utils.pathsafe import check_path_component

    try:
        return check_path_component(tenant, "tenant id")
    except ValueError as e:
        raise InvalidArgument(str(e)) from None


def _parse_tags(val: str) -> dict[str, str]:
    """logfmt-ish `k=v k2=v2` tag encoding (reference search tags param)."""
    out: dict[str, str] = {}
    for pair in val.split():
        if "=" in pair:
            k, v = pair.split("=", 1)
            out[k] = v
    return out


def _encode_tags(tags) -> str:
    return " ".join(f"{k}={v}" for k, v in sorted(tags.items()))


def parse_search_request(query: dict[str, str]) -> tempopb.SearchRequest:
    try:
        req = tempopb.SearchRequest()
        for k, v in _parse_tags(query.get("tags", "")).items():
            req.tags[k] = v
        if "minDuration" in query:
            req.min_duration_ms = _duration_ms(query["minDuration"])
        if "maxDuration" in query:
            req.max_duration_ms = _duration_ms(query["maxDuration"])
        req.limit = int(query.get("limit", 0) or 0)
        req.start = int(query.get("start", 0) or 0)
        req.end = int(query.get("end", 0) or 0)
        # per-query execution breakdown opt-in (docs/search-query-stats
        # .md); in the param set so the frontend↔querier URL round-trip
        # stays lossless. Same normalization as the X-Tempo-Explain
        # header path (api/http.py)
        if query.get("explain", "").strip().lower() in ("1", "true",
                                                        "yes"):
            req.explain = True
        if query.get("q"):
            # structural query (docs/search-structural-queries.md):
            # compact JSON IR in ?q=. Parsed HERE — a malformed tree is
            # a 400 carrying the node's JSON path, never a 500 from deep
            # in compile — then stowed canonically in the reserved tag
            # so it survives the frontend <-> querier round-trip.
            from tempo_tpu.search import ir as _ir
            from tempo_tpu.search.structural import attach_query

            try:
                attach_query(req, _ir.parse(query["q"]))
            except _ir.IRSyntaxError as e:
                raise InvalidArgument(
                    f"bad structural query: {e}") from None
        if query.get("agg"):
            # ?agg= aggregate opt-in (docs/search-analytics.md):
            # grammar validated HERE (a bad spec is a 400, never a deep
            # 500), then stowed canonically in the reserved tag so it
            # survives the frontend <-> querier round-trip
            from tempo_tpu.search.analytics import attach_agg

            try:
                attach_agg(req, query["agg"])
            except ValueError as e:
                raise InvalidArgument(str(e)) from None
        return req
    except InvalidArgument:
        # already the dedicated client-error type with its own message
        # (the structural-query path) — re-wrapping would double-prefix
        raise
    except ValueError as e:
        # query-param parse failures are CLIENT errors (400), never the
        # 500 a bare ValueError now maps to on the serving path
        raise InvalidArgument(f"bad search params: {e}") from None


def build_search_request(req: tempopb.SearchRequest) -> str:
    q: dict[str, str] = {}
    if req.tags:
        q["tags"] = _encode_tags(req.tags)
    if req.min_duration_ms:
        q["minDuration"] = f"{req.min_duration_ms}ms"
    if req.max_duration_ms:
        q["maxDuration"] = f"{req.max_duration_ms}ms"
    if req.limit:
        q["limit"] = str(req.limit)
    if req.start:
        q["start"] = str(req.start)
    if req.end:
        q["end"] = str(req.end)
    if req.explain:
        q["explain"] = "1"
    return urllib.parse.urlencode(q)


def _duration_ms(s: str) -> int:
    """'100ms', '1.5s', '250us', '2m', '0.5h'; bare numbers are ms.
    (Also the Jaeger-bridge duration syntax — keep the suffix table in
    one place.)"""
    s = s.strip()
    for suffix, mult in (("ms", 1), ("us", 0.001), ("µs", 0.001),
                         ("s", 1000), ("m", 60_000), ("h", 3_600_000)):
        if s.endswith(suffix) and s[: -len(suffix)].replace(".", "").isdigit():
            return max(0, int(float(s[: -len(suffix)]) * mult))
    return max(0, int(float(s)))


def parse_trace_by_id_params(query: dict[str, str]) -> tuple[str, str, str]:
    """(mode, blockStart, blockEnd)."""
    return (
        query.get("mode", "all"),
        query.get("blockStart", ""),
        query.get("blockEnd", ""),
    )
