"""gRPC services: the process boundary between modules.

Role-equivalent to the reference's tempo.proto services (SURVEY.md §2.6):
  - Pusher (distributor → ingester, PushBytes)
  - Querier (querier → ingester / frontend jobs → query workers:
    FindTraceByID, SearchRecent, SearchBlock, SearchTags, SearchTagValues)
  - OTLP TraceService/Export receiver: our Trace message is wire-compatible
    with ExportTraceServiceRequest (batches == resource_spans field 1), so
    standard OTLP gRPC exporters can push directly.

Stubs are hand-rolled over grpc generic handlers (no grpc_tools in this
image); client classes present the same duck-typed interface the
in-process wiring uses, so a multi-process deployment swaps transparently.
"""

from __future__ import annotations

import grpc

from tempo_tpu import tempopb

SERVICE_PUSHER = "tempopb.Pusher"
SERVICE_QUERIER = "tempopb.Querier"
OTLP_SERVICE = "opentelemetry.proto.collector.trace.v1.TraceService"
OTLP_EXPORT_METHOD = f"/{OTLP_SERVICE}/Export"


# ---------------------------------------------------------------------------
# server


def make_grpc_server(app, address: str = "0.0.0.0:9095",
                     max_workers: int = 16) -> grpc.Server:
    from concurrent import futures

    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))

    def push_bytes(request: tempopb.PushBytesRequest, context) -> tempopb.PushResponse:
        tenant = _tenant_from(context)
        for ing in app.ingesters.values():
            ing.push_bytes(tenant, request)
            break  # addressed ingester: the server IS one ingester process
        return tempopb.PushResponse()

    def find_trace(request: tempopb.TraceByIDRequest, context) -> tempopb.TraceByIDResponse:
        return app.queriers[0].find_trace_by_id(
            _tenant_from(context), request.trace_id,
            block_start=request.block_start, block_end=request.block_end,
            mode=request.query_mode or "all",
        )

    def search_recent(request: tempopb.SearchRequest, context) -> tempopb.SearchResponse:
        return app.queriers[0].search_recent(_tenant_from(context), request)

    def search_block(request: tempopb.SearchBlockRequest, context) -> tempopb.SearchResponse:
        return app.queriers[0].search_block(request)

    def search_tags(request, context) -> tempopb.SearchTagsResponse:
        return app.queriers[0].search_tags(_tenant_from(context))

    def search_tag_values(request, context) -> tempopb.SearchTagValuesResponse:
        return app.queriers[0].search_tag_values(
            _tenant_from(context), request.tag_name
        )

    def otlp_export(request: tempopb.Trace, context) -> tempopb.Trace:
        # request is wire-compatible ExportTraceServiceRequest; the empty
        # response reuses Trace (wire-compatible: zero fields set)
        app.push(_tenant_from(context), list(request.batches))
        return tempopb.Trace()

    server.add_generic_rpc_handlers((
        grpc.method_handlers_generic_handler(SERVICE_PUSHER, {
            "PushBytes": _unary(push_bytes, tempopb.PushBytesRequest,
                                tempopb.PushResponse),
        }),
        grpc.method_handlers_generic_handler(SERVICE_QUERIER, {
            "FindTraceByID": _unary(find_trace, tempopb.TraceByIDRequest,
                                    tempopb.TraceByIDResponse),
            "SearchRecent": _unary(search_recent, tempopb.SearchRequest,
                                   tempopb.SearchResponse),
            "SearchBlock": _unary(search_block, tempopb.SearchBlockRequest,
                                  tempopb.SearchResponse),
            "SearchTags": _unary(search_tags, tempopb.SearchTagsRequest,
                                 tempopb.SearchTagsResponse),
            "SearchTagValues": _unary(search_tag_values,
                                      tempopb.SearchTagValuesRequest,
                                      tempopb.SearchTagValuesResponse),
        }),
        grpc.method_handlers_generic_handler(OTLP_SERVICE, {
            "Export": _unary(otlp_export, tempopb.Trace, tempopb.Trace),
        }),
    ))
    server.add_insecure_port(address)
    return server


def _unary(fn, req_cls, resp_cls):
    return grpc.unary_unary_rpc_method_handler(
        fn,
        request_deserializer=req_cls.FromString,
        response_serializer=lambda m: m.SerializeToString(),
    )


def _tenant_from(context) -> str:
    from .params import DEFAULT_TENANT

    for k, v in context.invocation_metadata() or ():
        if k.lower() == "x-scope-orgid":
            return v
    return DEFAULT_TENANT


# ---------------------------------------------------------------------------
# clients (duck-typed like the in-process modules)


class _Base:
    def __init__(self, address: str, tenant: str | None = None):
        self.channel = grpc.insecure_channel(address)
        self.tenant = tenant

    def _md(self, tenant: str | None):
        t = tenant or self.tenant
        return (("x-scope-orgid", t),) if t else ()

    def _call(self, service, method, req, resp_cls, tenant=None):
        rpc = self.channel.unary_unary(
            f"/{service}/{method}",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=resp_cls.FromString,
        )
        return rpc(req, metadata=self._md(tenant))


class PusherClient(_Base):
    """Distributor-side stub: same interface as modules.Ingester."""

    def push_bytes(self, tenant: str, req: tempopb.PushBytesRequest) -> None:
        self._call(SERVICE_PUSHER, "PushBytes", req, tempopb.PushResponse,
                   tenant=tenant)


class QuerierClient(_Base):
    def find_trace_by_id(self, tenant, trace_id, block_start="", block_end="",
                         mode="all") -> tempopb.TraceByIDResponse:
        req = tempopb.TraceByIDRequest(
            trace_id=trace_id, block_start=block_start,
            block_end=block_end, query_mode=mode,
        )
        return self._call(SERVICE_QUERIER, "FindTraceByID", req,
                          tempopb.TraceByIDResponse, tenant=tenant)

    def search_recent(self, tenant, req) -> tempopb.SearchResponse:
        return self._call(SERVICE_QUERIER, "SearchRecent", req,
                          tempopb.SearchResponse, tenant=tenant)

    def search_block(self, req) -> tempopb.SearchResponse:
        return self._call(SERVICE_QUERIER, "SearchBlock", req,
                          tempopb.SearchResponse)

    def search_tags(self, tenant) -> tempopb.SearchTagsResponse:
        return self._call(SERVICE_QUERIER, "SearchTags",
                          tempopb.SearchTagsRequest(),
                          tempopb.SearchTagsResponse, tenant=tenant)

    def search_tag_values(self, tenant, tag) -> tempopb.SearchTagValuesResponse:
        return self._call(SERVICE_QUERIER, "SearchTagValues",
                          tempopb.SearchTagValuesRequest(tag_name=tag),
                          tempopb.SearchTagValuesResponse, tenant=tenant)
